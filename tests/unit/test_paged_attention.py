"""Paged decode-attention kernel vs the gathered-lax reference.

The parity suite the serving tick's fused page gather rides on
(cloud_tpu/ops/paged_attention.py): the Pallas kernel in interpret
mode, the off-TPU lax page-walk form, and the gathered reference must
agree — across the plain seq=1 tick, the speculative seq=k+1 verify
window, shared/CoW donor pages, and the masking edge cases the engine
relies on (scratch page 0 never contributes; an evicted slot's rows
come out exact-zero from the kernel).

Interpret-mode pallas_call is orders of magnitude slower than lax, so
every shape here is tiny; the serving-scale behavior is pinned by the
smoke gates (serving/smoke.py) with CLOUD_TPU_PAGED_KERNEL=1.
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# The ops package re-exports the `paged_attention` FUNCTION under the
# same name as this module, shadowing the package attribute — go
# through sys.modules for the module itself.
import cloud_tpu.ops.paged_attention  # noqa: F401  (registers module)

pa = sys.modules["cloud_tpu.ops.paged_attention"]

TOL = 2e-5


def _scenario(slots=3, pages_per_slot=4, page_size=16, heads=2,
              head_dim=64, seq=1, dtype=jnp.float32, seed=0):
    """A miniature engine cache: page 0 is the scratch page, slot i
    owns `pages_per_slot` distinct pages, per-slot positions stagger so
    the causal frontier crosses page boundaries."""
    rng = np.random.default_rng(seed)
    num_pages = slots * pages_per_slot + 1
    cache_len = pages_per_slot * page_size
    shape = (num_pages, page_size, heads, head_dim)
    key_pages = jnp.asarray(rng.normal(size=shape), dtype)
    value_pages = jnp.asarray(rng.normal(size=shape), dtype)
    q = jnp.asarray(rng.normal(size=(slots, seq, heads, head_dim)),
                    dtype)
    page_table = jnp.asarray(
        1 + np.arange(slots * pages_per_slot).reshape(
            slots, pages_per_slot), jnp.int32)
    # Slot s decodes at position pos_s; verify-window row t may attend
    # through pos_s + t (the engine's causal contract).
    pos = np.array([(7 + 11 * s) % (cache_len - seq) for s in
                    range(slots)])
    allowed = (np.arange(cache_len)[None, None, :]
               <= (pos[:, None] + np.arange(seq))[:, :, None])
    return q, key_pages, value_pages, page_table, jnp.asarray(allowed)


def _all_impls(q, kp, vp, pt, allowed):
    ref = pa.paged_attention_reference(q, kp, vp, pt, allowed)
    walk = pa._paged_walk_lax(q, kp, vp, pt, allowed,
                              1.0 / np.sqrt(q.shape[-1]))
    kern = pa.paged_decode_attention(q, kp, vp, pt, allowed,
                                     interpret=True)
    return ref, walk, kern


def test_plain_tick_parity():
    """seq=1 — the shape every non-speculative serving tick runs."""
    ref, walk, kern = _all_impls(*_scenario(seq=1))
    np.testing.assert_allclose(kern, ref, atol=TOL, rtol=TOL)
    np.testing.assert_allclose(walk, ref, atol=TOL, rtol=TOL)


def test_verify_window_parity():
    """seq=k+1 (speculative verify window, here k=3): per-row causal
    frontier; rows are sublane-padded inside the kernel (4 -> 8) and
    the pad rows must never leak into the sliced output."""
    ref, walk, kern = _all_impls(*_scenario(seq=4))
    np.testing.assert_allclose(kern, ref, atol=TOL, rtol=TOL)
    np.testing.assert_allclose(walk, ref, atol=TOL, rtol=TOL)


def test_walk_matches_interpret_kernel_tightly():
    """The lax page-walk is the kernel's off-TPU execution: same math,
    same page order, same online-softmax update sequence. It must track
    the interpret-mode kernel much tighter than either tracks the
    reference (which softmaxes in one pass)."""
    q, kp, vp, pt, allowed = _scenario(seq=4)
    walk = pa._paged_walk_lax(q, kp, vp, pt, allowed,
                              1.0 / np.sqrt(q.shape[-1]))
    kern = pa.paged_decode_attention(q, kp, vp, pt, allowed,
                                     interpret=True)
    np.testing.assert_allclose(np.asarray(walk), np.asarray(kern),
                               atol=1e-6, rtol=1e-6)


def test_bf16_parity():
    """bf16 pages (the serving dtype): kernel within bf16 resolution of
    the reference."""
    ref, walk, kern = _all_impls(*_scenario(seq=1, dtype=jnp.bfloat16))
    np.testing.assert_allclose(
        np.asarray(kern, np.float32), np.asarray(ref, np.float32),
        atol=2e-2, rtol=2e-2)
    np.testing.assert_allclose(
        np.asarray(walk, np.float32), np.asarray(ref, np.float32),
        atol=2e-2, rtol=2e-2)


def test_shared_donor_pages():
    """graftshare CoW: a prefix-cache hit leaves multiple slots'
    page tables pointing at the SAME donor pages. The gather-free
    kernel must read shared pages identically to the reference."""
    q, kp, vp, pt, allowed = _scenario(slots=3, seq=1)
    pt = np.asarray(pt).copy()
    pt[1, :2] = pt[0, :2]  # slots 0 and 1 share two donor pages
    pt[2, 0] = pt[0, 0]    # three-way share of the first page
    pt = jnp.asarray(pt)
    ref, walk, kern = _all_impls(q, kp, vp, pt, allowed)
    np.testing.assert_allclose(kern, ref, atol=TOL, rtol=TOL)
    np.testing.assert_allclose(walk, ref, atol=TOL, rtol=TOL)


@pytest.mark.parametrize("impl_name", ["reference", "walk", "kernel"])
def test_scratch_page_never_contributes(impl_name):
    """Page 0 is the pool's scratch page: unallocated page-table tail
    entries point at it and their positions are always masked. Filling
    it with large finite garbage (NOT NaN — 0 * NaN = NaN would poison
    any impl) must not move a single output bit."""
    q, kp, vp, pt, allowed = _scenario(slots=2, pages_per_slot=3,
                                       seq=1)
    pt = np.asarray(pt).copy()
    pt[:, -1] = 0  # tail entries parked on the scratch page
    pt = jnp.asarray(pt)
    # Mask off everything the scratch page would back.
    allowed = np.asarray(allowed).copy()
    allowed[:, :, -16:] = False
    allowed = jnp.asarray(allowed)

    def run(kp):
        if impl_name == "reference":
            return pa.paged_attention_reference(q, kp, vp, pt, allowed)
        if impl_name == "walk":
            return pa._paged_walk_lax(q, kp, vp, pt, allowed,
                                      1.0 / np.sqrt(q.shape[-1]))
        return pa.paged_decode_attention(q, kp, vp, pt, allowed,
                                         interpret=True)

    clean = run(kp)
    garbage = run(kp.at[0].set(1e30))
    np.testing.assert_array_equal(np.asarray(clean),
                                  np.asarray(garbage))


def test_evicted_slot_outputs_exact_zeros():
    """An evicted/inactive slot has `allowed` all-False. The kernel and
    walk output EXACT zeros there (explicit p=where(mask,...,0)); the
    reference's one-pass softmax instead averages garbage uniformly.
    The engine never consumes those rows — this pins the intentional
    divergence so a refactor can't silently change it."""
    q, kp, vp, pt, allowed = _scenario(slots=3, seq=1)
    allowed = np.asarray(allowed).copy()
    allowed[1] = False  # slot 1 evicted
    allowed = jnp.asarray(allowed)
    walk = pa._paged_walk_lax(q, kp, vp, pt, allowed,
                              1.0 / np.sqrt(q.shape[-1]))
    kern = pa.paged_decode_attention(q, kp, vp, pt, allowed,
                                     interpret=True)
    np.testing.assert_array_equal(np.asarray(walk)[1],
                                  np.zeros_like(np.asarray(walk)[1]))
    np.testing.assert_array_equal(np.asarray(kern)[1],
                                  np.zeros_like(np.asarray(kern)[1]))
    # Live slots still match the reference exactly as usual.
    ref = pa.paged_attention_reference(q, kp, vp, pt, allowed)
    for s in (0, 2):
        np.testing.assert_allclose(np.asarray(kern)[s],
                                   np.asarray(ref)[s],
                                   atol=TOL, rtol=TOL)


def test_impl_selection_off_tpu():
    """On CPU, impl='reference' (and 'auto'/'flash') is bitwise the
    gathered reference; impl='paged' is bitwise the lax page-walk."""
    q, kp, vp, pt, allowed = _scenario(seq=1)
    ref = pa.paged_attention_reference(q, kp, vp, pt, allowed)
    walk = pa._paged_walk_lax(q, kp, vp, pt, allowed,
                              1.0 / np.sqrt(q.shape[-1]))
    for impl in ("reference", "auto", "flash"):
        got = pa.paged_attention(q, kp, vp, pt, allowed, impl=impl)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    got = pa.paged_attention(q, kp, vp, pt, allowed, impl="paged")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(walk))


def test_env_override_beats_impl(monkeypatch):
    """CLOUD_TPU_PAGED_KERNEL is the deployment A/B switch: '0' forces
    the reference even under impl='paged'; '1' forces the kernel path
    even under impl='reference'."""
    q, kp, vp, pt, allowed = _scenario(seq=1)
    ref = pa.paged_attention_reference(q, kp, vp, pt, allowed)
    walk = pa._paged_walk_lax(q, kp, vp, pt, allowed,
                              1.0 / np.sqrt(q.shape[-1]))
    monkeypatch.setenv("CLOUD_TPU_PAGED_KERNEL", "0")
    got = pa.paged_attention(q, kp, vp, pt, allowed, impl="paged")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    monkeypatch.setenv("CLOUD_TPU_PAGED_KERNEL", "1")
    got = pa.paged_attention(q, kp, vp, pt, allowed, impl="reference")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(walk))


def test_shape_validation():
    q, kp, vp, pt, allowed = _scenario(seq=1)
    with pytest.raises(ValueError, match="allowed must be"):
        pa.paged_decode_attention(q, kp, vp, pt, allowed[:, :, :-1])
    with pytest.raises(ValueError, match="identical shapes"):
        pa.paged_decode_attention(q, kp, vp[:-1], pt, allowed)


# -- int8 quantized pages (graftpack, ISSUE 17) -----------------------


def _quantize_pages(pages):
    """Per-page per-head symmetric int8 quantization — the same
    contract the engine's page-write paths use: scale = amax / 127 over
    the page's (positions, head_dim) block, dequant = int8 * scale. An
    all-zero (never-written) page gets scale 0 so it dequantizes to
    exact zeros."""
    arr = np.asarray(pages, np.float32)
    amax = np.max(np.abs(arr), axis=(1, 3))          # [num_pages, H]
    scale = (amax / 127.0).astype(np.float32)
    safe = np.where(scale > 0, scale, 1.0)
    q = np.clip(np.rint(arr / safe[:, None, :, None]), -127, 127)
    return jnp.asarray(q, jnp.int8), jnp.asarray(scale)


def _int8_scenario(**kwargs):
    """A `_scenario` whose K/V pages are quantized to int8 + scales,
    plus the dequantized f32 pages every impl's output must match."""
    q, kp, vp, pt, allowed = _scenario(**kwargs)
    kq, ks = _quantize_pages(kp)
    vq, vs = _quantize_pages(vp)
    kp_deq = jnp.asarray(np.asarray(kq, np.float32)
                         * np.asarray(ks)[:, None, :, None])
    vp_deq = jnp.asarray(np.asarray(vq, np.float32)
                         * np.asarray(vs)[:, None, :, None])
    return q, (kq, ks, kp_deq), (vq, vs, vp_deq), pt, allowed


def _all_impls_int8(q, k3, v3, pt, allowed):
    kq, ks, _ = k3
    vq, vs, _ = v3
    ref = pa.paged_attention_reference(q, kq, vq, pt, allowed,
                                       key_scales=ks, value_scales=vs)
    walk = pa._paged_walk_lax(q, kq, vq, pt, allowed,
                              1.0 / np.sqrt(q.shape[-1]),
                              key_scales=ks, value_scales=vs)
    kern = pa.paged_decode_attention(q, kq, vq, pt, allowed,
                                     interpret=True, key_scales=ks,
                                     value_scales=vs)
    return ref, walk, kern


@pytest.mark.parametrize("seq", [1, 4])
def test_int8_parity_across_impls(seq):
    """Quantized pages: reference/walk/kernel must agree with each
    other AND with the fp reference run on the explicitly dequantized
    pages — the dequant must be mathematically inside the attention,
    not an approximation of it."""
    q, k3, v3, pt, allowed = _int8_scenario(seq=seq)
    ref, walk, kern = _all_impls_int8(q, k3, v3, pt, allowed)
    oracle = pa.paged_attention_reference(q, k3[2], v3[2], pt, allowed)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(oracle),
                               atol=TOL, rtol=TOL)
    np.testing.assert_allclose(np.asarray(walk), np.asarray(oracle),
                               atol=TOL, rtol=TOL)
    np.testing.assert_allclose(np.asarray(kern), np.asarray(oracle),
                               atol=TOL, rtol=TOL)


def test_int8_shared_donor_pages():
    """CoW-shared donor pages carry ONE scale row per page — slots
    sharing a page must dequantize it identically."""
    q, k3, v3, pt, allowed = _int8_scenario(slots=3, seq=1)
    pt = np.asarray(pt).copy()
    pt[1, :2] = pt[0, :2]
    pt[2, 0] = pt[0, 0]
    pt = jnp.asarray(pt)
    ref, walk, kern = _all_impls_int8(q, k3, v3, pt, allowed)
    oracle = pa.paged_attention_reference(q, k3[2], v3[2], pt, allowed)
    np.testing.assert_allclose(np.asarray(kern), np.asarray(oracle),
                               atol=TOL, rtol=TOL)
    np.testing.assert_allclose(np.asarray(walk), np.asarray(oracle),
                               atol=TOL, rtol=TOL)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(oracle),
                               atol=TOL, rtol=TOL)


def test_int8_zero_scale_page_is_exact_zero():
    """A never-written page carries scale 0: whatever int8 garbage the
    pool left in it must dequantize to exact zeros and (masked) move no
    output bit — the promote path relies on this for the scratch-padded
    page-table tail."""
    q, k3, v3, pt, allowed = _int8_scenario(slots=2, pages_per_slot=3,
                                            seq=1)
    kq, ks, _ = k3
    vq, vs, _ = v3
    pt = np.asarray(pt).copy()
    pt[:, -1] = 0  # tail parked on scratch page 0
    pt = jnp.asarray(pt)
    allowed = np.asarray(allowed).copy()
    allowed[:, :, -16:] = False
    allowed = jnp.asarray(allowed)

    def run(kq, ks):
        return pa.paged_decode_attention(q, kq, vq, pt, allowed,
                                         interpret=True, key_scales=ks,
                                         value_scales=vs)

    clean = run(kq, ks)
    garbage = run(kq.at[0].set(127), ks.at[0].set(0.0))
    np.testing.assert_array_equal(np.asarray(clean),
                                  np.asarray(garbage))


def test_int8_evicted_slot_outputs_exact_zeros():
    """The kernel/walk all-False-mask contract survives quantization:
    an evicted slot's rows are exact zeros, not dequant noise."""
    q, k3, v3, pt, allowed = _int8_scenario(slots=3, seq=1)
    allowed = np.asarray(allowed).copy()
    allowed[1] = False
    allowed = jnp.asarray(allowed)
    _, walk, kern = _all_impls_int8(q, k3, v3, pt, allowed)
    np.testing.assert_array_equal(np.asarray(walk)[1],
                                  np.zeros_like(np.asarray(walk)[1]))
    np.testing.assert_array_equal(np.asarray(kern)[1],
                                  np.zeros_like(np.asarray(kern)[1]))


def test_int8_scale_validation():
    """Both-or-neither scales; int8 pages required; [N, H] f32 shape."""
    q, kp, vp, pt, allowed = _scenario(seq=1)
    kq, ks = _quantize_pages(kp)
    vq, vs = _quantize_pages(vp)
    with pytest.raises(ValueError, match="given together"):
        pa.paged_decode_attention(q, kq, vq, pt, allowed,
                                  interpret=True, key_scales=ks)
    with pytest.raises(ValueError, match="int8 pages"):
        pa.paged_decode_attention(q, kp, vp, pt, allowed,
                                  interpret=True, key_scales=ks,
                                  value_scales=vs)
    with pytest.raises(ValueError, match="num_pages, heads"):
        pa.paged_decode_attention(q, kq, vq, pt, allowed,
                                  interpret=True, key_scales=ks[:-1],
                                  value_scales=vs)


def test_int8_dispatch_through_public_entrypoint():
    """paged_attention() forwards scales to whichever impl it picks."""
    q, k3, v3, pt, allowed = _int8_scenario(seq=1)
    kq, ks, _ = k3
    vq, vs, _ = v3
    ref = pa.paged_attention_reference(q, kq, vq, pt, allowed,
                                       key_scales=ks, value_scales=vs)
    walk = pa._paged_walk_lax(q, kq, vq, pt, allowed,
                              1.0 / np.sqrt(q.shape[-1]),
                              key_scales=ks, value_scales=vs)
    got = pa.paged_attention(q, kq, vq, pt, allowed, impl="reference",
                             key_scales=ks, value_scales=vs)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
    got = pa.paged_attention(q, kq, vq, pt, allowed, impl="paged",
                             key_scales=ks, value_scales=vs)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(walk))


def test_cost_hook():
    """The telemetry row: positive flops and bytes, and the fused
    bytes figure stays below the dense-gather materialization (the
    whole point of the kernel)."""
    cost = pa.paged_attention_cost(slots=8, seq=1, heads=8,
                                   head_dim=64, page_size=16,
                                   pages_per_slot=4)
    assert cost["flops"] > 0
    assert cost["bytes_moved"] > 0
    cache_len = 16 * 4
    dense_gather = 2 * 8 * cache_len * 8 * 64 * 2  # K+V, bf16
    assert cost["bytes_moved"] < 2 * dense_gather
