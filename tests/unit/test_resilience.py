"""graftguard: typed faults, rollback, and warm bit-identical resume.

What's pinned here is the ISSUE 9 acceptance contract: an injected
preemption at an arbitrary mid-epoch step auto-resumes to a
bit-identical final state with ZERO new compiles after re-entry, and
every answered fault leaves retry/rollback/resume-latency breadcrumbs
in `guard_stats()` (and the "graftguard" JSONL stream when enabled).
The deterministic injections come from the chaos harness
(analysis/chaos.py) — the same rig the chaos-smoke CI job drives.
"""

import json
import os
import random

import jax
import numpy as np
import optax
import pytest

from cloud_tpu.analysis import chaos
from cloud_tpu.models import MLP
from cloud_tpu.parallel import runtime
from cloud_tpu.training import (ArrayDataset, TerminateOnNaN, Trainer,
                                resilient_fit)
from cloud_tpu.training import checkpoint as checkpoint_lib
from cloud_tpu.training import resilience
from cloud_tpu.utils import events as events_lib


@pytest.fixture(autouse=True)
def _guard_isolation(monkeypatch):
    """No chaos plan, counters, runtime state, or knob env leaks
    between tests; backoff is zeroed so retries are instant."""
    for key in ("CLOUD_TPU_CHAOS", "CLOUD_TPU_RETRIES",
                "CLOUD_TPU_RESUME_DIR", "CLOUD_TPU_EVENT_LOG",
                "CLOUD_TPU_WATCH"):
        monkeypatch.delenv(key, raising=False)
    monkeypatch.setenv("CLOUD_TPU_RETRY_BACKOFF", "0")
    runtime.reset()
    chaos.uninstall()
    resilience.reset_guard_stats()
    yield
    chaos.uninstall()
    resilience.reset_guard_stats()
    runtime.reset()


def _toy_data(n=64, d=8, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.integers(0, classes, size=n).astype(np.int32)
    return x, y


def _trainer(**kwargs):
    return Trainer(MLP(hidden=16, num_classes=4),
                   optimizer=optax.sgd(1e-2), seed=3, **kwargs)


def _params_equal(a, b):
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree_util.tree_leaves(a),
                               jax.tree_util.tree_leaves(b)))


class TestTaxonomy:
    def test_fault_kinds(self):
        assert resilience.fault_kind(resilience.Preemption("x")) == \
            "preemption"
        assert resilience.fault_kind(
            resilience.CheckpointCorrupt("x", path="/p", step=4)) == \
            "checkpoint_corrupt"
        assert resilience.fault_kind(resilience.DataStall("x")) == \
            "data_stall"
        assert resilience.fault_kind(
            resilience.NaNLoss("x", epoch=2, monitor="loss")) == "nan_loss"
        assert resilience.fault_kind(
            runtime.BackendUnavailable("x")) == "backend_unavailable"
        assert resilience.fault_kind(ValueError("x")) == "unknown"

    def test_all_faults_are_catchable_as_fault_types(self):
        for exc in (resilience.Preemption("x"),
                    resilience.CheckpointCorrupt("x"),
                    resilience.DataStall("x"), resilience.NaNLoss("x"),
                    runtime.BackendUnavailable("x")):
            assert isinstance(exc, resilience.FAULT_TYPES)
        assert not isinstance(ValueError("x"), resilience.FAULT_TYPES)

    def test_attrs_survive(self):
        corrupt = resilience.CheckpointCorrupt("torn", path="/c/8", step=8)
        assert (corrupt.path, corrupt.step) == ("/c/8", 8)
        nan = resilience.NaNLoss("bad", epoch=3, monitor="loss",
                                 value=float("nan"))
        assert (nan.epoch, nan.monitor) == (3, "loss")


class TestBackoff:
    def test_deterministic_with_seeded_rng(self):
        rng_a, rng_b = random.Random(7), random.Random(7)
        delays_a = [resilience.backoff_delay(k, rng=rng_a)
                    for k in range(6)]
        delays_b = [resilience.backoff_delay(k, rng=rng_b)
                    for k in range(6)]
        assert delays_a == delays_b

    def test_exponential_capped_and_jittered(self):
        rng = random.Random(0)
        for attempt in range(10):
            delay = resilience.backoff_delay(attempt, base=1.0, cap=30.0,
                                             rng=rng)
            raw = min(30.0, 2.0 ** attempt)
            assert 0.5 * raw <= delay < raw

    def test_attempt_zero_is_the_jittered_base(self):
        rng = random.Random(1)
        for _ in range(16):
            delay = resilience.backoff_delay(0, base=2.0, cap=30.0,
                                             rng=rng)
            assert 1.0 <= delay < 2.0

    def test_base_beyond_cap_clamps_immediately(self):
        rng = random.Random(2)
        delay = resilience.backoff_delay(0, base=100.0, cap=30.0,
                                         rng=rng)
        assert 15.0 <= delay < 30.0

    def test_huge_attempt_saturates_instead_of_overflowing(self):
        # 2.0**attempt overflows a float past attempt 1023; a retry
        # loop gone wild must still get the cap, not an OverflowError.
        rng = random.Random(3)
        for attempt in (64, 1024, 10**6):
            delay = resilience.backoff_delay(attempt, base=1.0,
                                             cap=30.0, rng=rng)
            assert 15.0 <= delay < 30.0

    def test_same_seed_same_schedule(self):
        delays_a = [resilience.backoff_delay(k, rng=random.Random(9))
                    for k in range(4)]
        delays_b = [resilience.backoff_delay(k, rng=random.Random(9))
                    for k in range(4)]
        assert delays_a == delays_b
        assert delays_a != [resilience.backoff_delay(k,
                                                     rng=random.Random(10))
                            for k in range(4)]


class TestCheckpointIntegrity:
    def _state(self):
        import jax.numpy as jnp

        return {"w": jnp.arange(16, dtype=jnp.float32),
                "b": jnp.ones((4,))}

    def test_metadata_sidecar_roundtrip(self, tmp_path):
        data_state = {"epoch": 1, "step_in_epoch": 3, "dataset_epoch": 2,
                      "data_seed": 7}
        checkpoint_lib.save(str(tmp_path), self._state(), step=5,
                            data_state=data_state)
        meta = checkpoint_lib.load_metadata(str(tmp_path), 5)
        assert meta["step"] == 5
        assert meta["data_state"] == data_state
        assert meta["digest"]  # content digest present
        # Sidecars are not checkpoints: step discovery skips them.
        assert checkpoint_lib.latest_step(str(tmp_path)) == 5

    def test_digest_tamper_raises_typed_corrupt(self, tmp_path):
        state = self._state()
        checkpoint_lib.save(str(tmp_path), state, step=5)
        files = []
        for root, _, names in os.walk(tmp_path / "5"):
            files.extend(os.path.join(root, n) for n in names)
        target = max(files, key=os.path.getsize)
        with open(target, "r+b") as f:
            data = f.read()
            f.seek(0)
            # Flip bytes without changing the size: whether orbax
            # deserializes garbage or chokes, restore must surface ONE
            # typed fault.
            f.write(bytes(b ^ 0xFF for b in data[:64]) + data[64:])
        with pytest.raises(resilience.CheckpointCorrupt) as info:
            checkpoint_lib.restore(str(tmp_path), state)
        assert info.value.step == 5

    def test_missing_sidecar_restores_unverified(self, tmp_path):
        # Pre-graftguard checkpoints have no sidecar: restore must not
        # refuse them.
        checkpoint_lib.save(str(tmp_path), self._state(), step=1)
        os.remove(str(tmp_path / "1.meta.json"))
        restored = checkpoint_lib.restore(str(tmp_path), self._state())
        assert np.asarray(restored["b"]).sum() == 4.0

    def test_quarantine_falls_back_to_previous(self, tmp_path):
        state = self._state()
        checkpoint_lib.save(str(tmp_path), state, step=2)
        checkpoint_lib.save(str(tmp_path), state, step=4)
        moved = checkpoint_lib.quarantine(str(tmp_path), 4)
        assert moved.endswith("4.corrupt")
        assert checkpoint_lib.latest_step(str(tmp_path)) == 2
        # The sidecar moved with it.
        assert os.path.exists(str(tmp_path / "4.corrupt.meta.json"))


class TestResumeBitIdentical:
    """The tentpole acceptance: kill mid-epoch at an arbitrary step,
    auto-resume, end bit-identical to the uninterrupted run with zero
    new compiles after re-entry."""

    EPOCHS, BATCH = 3, 8  # 8 steps/epoch over 64 examples, 24 total

    def _fit_clean(self, **fit_kwargs):
        x, y = _toy_data()
        trainer = _trainer()
        history = trainer.fit(x, y, epochs=self.EPOCHS,
                              batch_size=self.BATCH, verbose=False,
                              **fit_kwargs)
        return trainer, history

    def _fit_chaotic(self, spec, tmp_path, retries=3, **fit_kwargs):
        chaos.install(spec)
        x, y = _toy_data()
        trainer = _trainer()
        history = trainer.fit(x, y, epochs=self.EPOCHS,
                              batch_size=self.BATCH, verbose=False,
                              resume="auto", retries=retries,
                              resume_from=str(tmp_path / "ckpt"),
                              **fit_kwargs)
        return trainer, history

    def test_preemption_mid_epoch_resumes_bit_identical(self, tmp_path):
        clean, clean_hist = self._fit_clean()
        # Step 12 = epoch 1, batch 4 of 8: an arbitrary mid-epoch kill.
        chaotic, hist = self._fit_chaotic("preempt@12", tmp_path)
        assert _params_equal(clean.state.params, chaotic.state.params)
        assert int(chaotic.state.step) == self.EPOCHS * 8
        # The post-resume epochs' losses match the clean run exactly.
        assert hist["loss"][-1] == clean_hist["loss"][-1]
        stats = resilience.guard_stats()
        assert stats["faults"] == 1 and stats["retries"] == 1
        assert stats["resumes"] == 1
        assert stats["last_fault"] == "preemption"
        assert stats["last_resume_latency_seconds"] > 0
        # The warm re-entry invariant: restored state + cached
        # executables = nothing recompiles.
        assert stats["last_resume_new_compiles"] == 0
        assert stats["last_resume_new_traces"] == 0

    @pytest.mark.slow
    def test_device_resident_resumes_bit_identical(self, tmp_path):
        clean, _ = self._fit_clean(cache="device")
        chaotic, _ = self._fit_chaotic("preempt@12", tmp_path,
                                       cache="device")
        assert _params_equal(clean.state.params, chaotic.state.params)
        assert resilience.guard_stats()["last_resume_new_compiles"] == 0

    @pytest.mark.slow
    def test_grad_accum_mid_accumulation_resumes_bit_identical(
            self, tmp_path):
        # preempt@13 lands between micro-steps of an accumulation
        # window; MultiSteps state rides the checkpoint, so resume
        # continues the half-built accumulator exactly.
        x, y = _toy_data()
        clean = _trainer(gradient_accumulation_steps=2)
        clean.fit(x, y, epochs=self.EPOCHS, batch_size=self.BATCH,
                  verbose=False)
        chaos.install("preempt@13")
        chaotic = _trainer(gradient_accumulation_steps=2)
        chaotic.fit(x, y, epochs=self.EPOCHS, batch_size=self.BATCH,
                    verbose=False, resume="auto",
                    resume_from=str(tmp_path / "ckpt"))
        assert _params_equal(clean.state.params, chaotic.state.params)

    def test_corrupt_rescue_falls_back_and_completes(self, tmp_path,
                                                     monkeypatch):
        # preempt@20 forces a rescue save at 20; corrupt@18 tears that
        # very rescue. Attempt 2 must hit the typed CheckpointCorrupt,
        # quarantine step 20, fall back to the epoch-2 checkpoint at
        # 16, and still finish.
        log = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("CLOUD_TPU_EVENT_LOG", log)
        chaotic, _ = self._fit_chaotic("preempt@20,corrupt@18", tmp_path)
        assert int(chaotic.state.step) == self.EPOCHS * 8
        stats = resilience.guard_stats()
        assert stats["faults"] == 2
        assert stats["rollbacks"] == 1  # the quarantine
        quarantined = [n for n in os.listdir(tmp_path / "ckpt")
                       if n.endswith(".corrupt")]
        assert quarantined == ["20.corrupt"]
        guard = events_lib.read_job_events(log, kind="graftguard")
        sequence = [r["payload"]["event"] for r in guard]
        # ONE "resumed": attempt 1 dies during restore (before any
        # dispatch completes), so only attempt 2's probe fires.
        assert sequence == ["fault", "rescue_checkpoint", "retry",
                            "fault", "rollback", "retry", "resumed"]
        kinds = {r["payload"]["fault"] for r in guard
                 if r["payload"]["event"] == "fault"}
        assert kinds == {"preemption", "checkpoint_corrupt"}
        assert len(events_lib.read_job_events(log, kind="graftchaos")) == 2

    def test_nan_rolls_back_with_fresh_data_order(self, tmp_path):
        chaotic, _ = self._fit_chaotic("nan@12", tmp_path)
        # Rolled back to the last finite checkpoint and completed; the
        # replay uses a FRESH data seed so params legitimately differ
        # from the clean run — completion + rollback accounting is the
        # contract.
        assert int(chaotic.state.step) == self.EPOCHS * 8
        stats = resilience.guard_stats()
        assert stats["rollbacks"] == 1
        assert stats["last_fault"] == "nan_loss"

    def test_data_stall_is_transient(self, tmp_path):
        clean, _ = self._fit_clean()
        chaotic, _ = self._fit_chaotic("fetch@9", tmp_path)
        # A transient fetch error re-enters the SAME position: still
        # bit-identical.
        assert _params_equal(clean.state.params, chaotic.state.params)
        assert resilience.guard_stats()["last_fault"] == "data_stall"

    def test_budget_exhaustion_reraises_typed_fault(self, tmp_path):
        chaos.install("preempt@4,preempt@8")
        x, y = _toy_data()
        trainer = _trainer()
        with pytest.raises(resilience.Preemption):
            trainer.fit(x, y, epochs=self.EPOCHS, batch_size=self.BATCH,
                        verbose=False, resume="auto", retries=1,
                        resume_from=str(tmp_path / "ckpt"))
        stats = resilience.guard_stats()
        assert stats["giveups"] == 1
        assert stats["faults"] == 2 and stats["retries"] == 1

    def test_retries_without_resume_auto_rejected(self):
        x, y = _toy_data()
        with pytest.raises(ValueError, match="resume='auto'"):
            _trainer().fit(x, y, epochs=1, retries=2, verbose=False)

    def test_unguarded_fit_propagates_typed_fault(self, tmp_path):
        chaos.install("preempt@4")
        x, y = _toy_data()
        with pytest.raises(resilience.Preemption):
            _trainer().fit(x, y, epochs=self.EPOCHS,
                           batch_size=self.BATCH, verbose=False)


class TestTerminateOnNaN:
    def test_rollback_raises_typed_nan_loss(self):
        cb = TerminateOnNaN(rollback=True)
        with pytest.raises(resilience.NaNLoss) as info:
            cb.on_epoch_end(4, {"loss": float("nan")})
        assert info.value.epoch == 4
        assert info.value.monitor == "loss"

    def test_default_still_stops_without_raising(self):
        class Host:
            stop_training = False

        cb = TerminateOnNaN()
        cb.trainer = Host()
        cb.on_epoch_end(0, {"loss": float("inf")})
        assert cb.trainer.stop_training

    def test_finite_loss_is_untouched(self):
        cb = TerminateOnNaN(rollback=True)
        cb.on_epoch_end(0, {"loss": 0.5})  # must not raise


class TestAutoCheckpoint:
    def test_epoch_saves_carry_data_state(self, tmp_path):
        x, y = _toy_data()
        trainer = _trainer()
        cb = resilience.AutoCheckpoint(str(tmp_path))
        trainer.fit(x, y, epochs=2, batch_size=8, verbose=False,
                    callbacks=[cb])
        assert checkpoint_lib.latest_step(str(tmp_path)) == 16
        meta = checkpoint_lib.load_metadata(str(tmp_path), 16)
        state = meta["data_state"]
        # End of epoch 1 normalizes to the start of epoch 2.
        assert state["epoch"] == 2 and state["step_in_epoch"] == 0
        assert state["data_seed"] == 3
        # Earlier epochs' checkpoints are KEPT (corrupt fallback needs
        # one to fall back to).
        assert os.path.isdir(str(tmp_path / "8"))
