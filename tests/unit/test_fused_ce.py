"""Chunked LM-head cross-entropy vs the materializing oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # numeric-heavy: excluded from the fast tier

from cloud_tpu.ops import lm_head_loss, lm_head_loss_reference


def _inputs(n=24, d=16, v=50, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    h = jnp.asarray(rng.normal(size=(n, d)), dtype)
    w = jnp.asarray(rng.normal(size=(d, v)) * 0.1, dtype)
    y = jnp.asarray(rng.integers(0, v, n), jnp.int32)
    return h, w, y


class TestLmHeadLoss:

    @pytest.mark.parametrize("chunk", [7, 16, 50, 128])
    def test_matches_oracle(self, chunk):
        """Chunk widths that divide, exceed, and straddle the vocab."""
        h, w, y = _inputs()
        got = lm_head_loss(h, w, y, chunk=chunk)
        want = lm_head_loss_reference(h, w, y)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("chunk", [7, 16, 50, 128])
    def test_gradients_match_oracle(self, chunk):
        h, w, y = _inputs()

        def fused(h, w):
            return jnp.mean(lm_head_loss(h, w, y, chunk=chunk))

        def naive(h, w):
            return jnp.mean(lm_head_loss_reference(h, w, y))

        (gh, gw) = jax.grad(fused, argnums=(0, 1))(h, w)
        (oh, ow) = jax.grad(naive, argnums=(0, 1))(h, w)
        np.testing.assert_allclose(np.asarray(gh), np.asarray(oh),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(ow),
                                   rtol=1e-4, atol=1e-5)

    def test_bf16_inputs_f32_accumulation(self):
        h, w, y = _inputs(dtype=jnp.bfloat16)
        got = lm_head_loss(h, w, y, chunk=16)
        assert got.dtype == jnp.float32
        want = lm_head_loss_reference(h, w, y)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-2, atol=2e-2)
        # Grads keep the input dtypes.
        gh, gw = jax.grad(
            lambda h, w: jnp.mean(lm_head_loss(h, w, y, chunk=16)),
            argnums=(0, 1))(h, w)
        assert gh.dtype == jnp.bfloat16 and gw.dtype == jnp.bfloat16

    def test_jits_and_trains_a_tiny_lm_head(self):
        """End-to-end: gradient descent on the fused loss learns."""
        import optax

        h, w, y = _inputs(n=64, d=8, v=32, seed=1)
        tx = optax.adam(5e-2)
        opt = tx.init(w)

        @jax.jit
        def step(w, opt):
            loss, gw = jax.value_and_grad(
                lambda w: jnp.mean(lm_head_loss(h, w, y, chunk=8)))(w)
            up, opt = tx.update(gw, opt, w)
            return optax.apply_updates(w, up), opt, loss

        first = None
        for _ in range(30):
            w, opt, loss = step(w, opt)
            first = first if first is not None else float(loss)
        assert float(loss) < first * 0.5

    def test_huge_chunk_degenerates_to_single_block(self):
        h, w, y = _inputs(v=33)
        a = lm_head_loss(h, w, y, chunk=1 << 20)
        b = lm_head_loss_reference(h, w, y)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)

    def test_ignore_index_semantics(self):
        """Out-of-range labels (-1 padding) carry zero loss and zero
        gradient; in-range positions are unaffected."""
        h, w, y = _inputs()
        y_masked = y.at[::3].set(-1)
        loss = np.asarray(lm_head_loss(h, w, y_masked, chunk=16))
        assert (loss[::3] == 0.0).all()
        ref = np.asarray(lm_head_loss_reference(h, w, y))
        keep = np.ones(len(ref), bool)
        keep[::3] = False
        np.testing.assert_allclose(loss[keep], ref[keep], rtol=1e-5,
                                   atol=1e-5)
        gh = jax.grad(lambda h: jnp.sum(
            lm_head_loss(h, w, y_masked, chunk=16)))(h)
        gh_ref = jax.grad(lambda h: jnp.sum(jnp.where(
            jnp.asarray(keep),
            lm_head_loss_reference(h, w, y), 0.0)))(h)
        np.testing.assert_allclose(np.asarray(gh), np.asarray(gh_ref),
                                   rtol=1e-4, atol=1e-5)

    def test_custom_vjp_composes_under_outer_scan(self):
        """The realistic training composition: the op inside an outer
        lax.scan (e.g. a microbatch loop), differentiated through."""
        h, w, y = _inputs(n=32)
        hs = h.reshape(4, 8, -1)
        ys = y.reshape(4, 8)

        def scanned(w):
            def body(acc, xs):
                hb, yb = xs
                return acc + jnp.sum(
                    lm_head_loss(hb, w, yb, chunk=16)), None
            total, _ = jax.lax.scan(body, 0.0, (hs, ys))
            return total

        def naive(w):
            return jnp.sum(lm_head_loss_reference(h, w, y))

        np.testing.assert_allclose(float(scanned(w)), float(naive(w)),
                                   rtol=1e-5)
        gw = jax.grad(scanned)(w)
        gw_ref = jax.grad(naive)(w)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_ref),
                                   rtol=1e-4, atol=1e-5)
