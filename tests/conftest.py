"""Test configuration: force an 8-device virtual CPU mesh.

Tests exercise multi-chip sharding logic without TPU hardware by running
JAX on 8 virtual CPU devices — the TPU-native analogue of the reference's
fake-cluster trick (reference cloud_fit/tests/unit/remote_test.py:80-127,
which fabricates TF_CONFIG with bogus worker addresses). Must run before
jax initializes its backends, hence the env mutation at import time.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The host environment pins JAX_PLATFORMS to the TPU tunnel via a site
# hook; an explicit config update is the only override that sticks.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
