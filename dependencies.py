"""Dependency specification for the cloud-tpu framework.

Mirrors the reference's standalone dependency module
(reference src/python/dependencies.py:19-29) with the TPU-native stack:
jax/flax/optax replace `tensorflow>=1.15.0,<3.0`, orbax replaces the
SavedModel checkpoint path, and the GCP client libraries are optional
extras because every cloud boundary in the framework takes an injectable
transport (the library imports and unit-tests cleanly without them).
"""


def make_required_install_packages():
    return [
        "absl-py",
        # Floor set by jax.shard_map + the jax_num_cpu_devices config
        # (used by the driver dry-run's virtual-device fallback).
        "jax>=0.6",
        "flax",
        "optax",
        "numpy",
    ]


def make_required_extra_packages():
    return {
        "gcp": [
            "google-api-python-client",
            "google-auth",
            "google-cloud-storage",
        ],
        "docker": ["docker"],
        "checkpoint": ["orbax-checkpoint"],
        "tests": ["pytest"],
    }
