"""Benchmark harness: ResNet50 training throughput on one TPU chip.

BASELINE.md target: Keras `model.fit` steps/sec via the launch API on
v5e-8 matching 8xV100 wall-clock. The reference publishes no numbers
(BASELINE.md "Published reference numbers: None"), so the recorded
baseline is the 8xV100 side of the driver's target: ResNet50 mixed
precision at ~2800 images/sec across 8 V100s = 350 images/sec per
V100-equivalent. This harness measures our per-chip ResNet50 train-step
throughput (bf16, NHWC) through the framework's own jitted Trainer
step; vs_baseline > 1.0 means one v5e chip beats one V100, i.e. v5e-8
beats 8xV100 wall-clock for config 2.

Structure: the top-level process never touches the accelerator backend
directly — the TPU on this host sits behind an experimental tunnel
whose init can hang indefinitely, so (1) backend health is probed in a
bounded subprocess, (2) the measurement itself runs in a bounded
subprocess, (3) both are retried, and (4) persistent failure produces a
diagnostic JSON line instead of a traceback or a hang.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N,
     "method": "median_chunk", ...}
or, when the backend is unreachable after all retries:
    {"metric": ..., "value": 0.0, ..., "error": "<diagnosis>"}
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

BATCH = int(os.environ.get("BENCH_BATCH", 256))
IMAGE = int(os.environ.get("BENCH_IMAGE", 224))
WARMUP_STEPS = int(os.environ.get("BENCH_WARMUP", 3))
TIMED_STEPS = int(os.environ.get("BENCH_STEPS", 20))
CHUNK = min(int(os.environ.get("BENCH_CHUNK", 5)), TIMED_STEPS)
BASELINE_IMAGES_PER_SEC = 350.0  # one V100, fp16 ResNet50 (8xV100 / 8)

ATTEMPTS = int(os.environ.get("BENCH_ATTEMPTS", 3))
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_PROBE_TIMEOUT", 120))
RETRY_DELAY_S = float(os.environ.get("BENCH_RETRY_DELAY", 20))
# Overall wall-clock budget: whatever happens, the JSON line appears
# within roughly this many seconds, so an outer `timeout` on the driver
# side never fires first and the result is always recorded. The
# per-attempt worker timeout is additionally clamped to the remaining
# deadline — raise BENCH_DEADLINE together with BENCH_TIMEOUT for a
# slow-but-healthy backend.
DEADLINE_S = float(os.environ.get("BENCH_DEADLINE", 600))
WORKER_TIMEOUT_S = float(os.environ.get("BENCH_TIMEOUT", 480))

METRIC = "resnet50_train_images_per_sec_per_chip"


def _metric_name():
    # The s2d stem is an architecture variant: suffix it so recorded
    # numbers (including failed runs) stay apples-to-apples per series.
    if os.environ.get("BENCH_S2D", "0") == "1":
        return METRIC + "_s2d"
    return METRIC


def _probe_backend(timeout=None):
    """Compile-and-run a trivial jit in a fresh bounded process.

    Returns (ok, diagnosis). A healthy backend answers in a few seconds
    (first-compile overhead aside); a stalled tunnel hits the timeout
    without ever returning — which must not take the harness down with
    it, hence the subprocess.
    """
    timeout = PROBE_TIMEOUT_S if timeout is None else timeout
    # A site hook can pin JAX_PLATFORMS to the tunnel, so the CPU
    # override (used by CI to test this harness end-to-end) must be an
    # explicit config update, not an env var.
    code = ("import os, jax; "
            "os.environ.get('BENCH_FORCE_CPU') == '1' and "
            "jax.config.update('jax_platforms', 'cpu'); "
            "x = jax.jit(lambda v: v + 1)(1.0); x.block_until_ready(); "
            "print('PROBE_OK', jax.default_backend(), len(jax.devices()))")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout, cwd=os.path.dirname(__file__) or ".")
    except subprocess.TimeoutExpired:
        return False, "backend probe hung past {:.0f}s".format(timeout)
    except OSError as e:
        return False, "backend probe failed to launch: {}".format(e)
    for line in proc.stdout.splitlines():
        if line.startswith("PROBE_OK"):
            return True, line.strip()
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return False, "backend init failed: {}".format(tail[-1] if tail else
                                                   "rc={}".format(proc.returncode))


def _run_worker(timeout=None):
    """Run the measurement in a bounded subprocess; returns (record, err)."""
    timeout = WORKER_TIMEOUT_S if timeout is None else timeout
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(__file__) or ".")
    except subprocess.TimeoutExpired:
        return None, "measurement hung past {:.0f}s".format(timeout)
    except OSError as e:
        return None, "measurement failed to launch: {}".format(e)
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), None
            except ValueError:
                break
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return None, "measurement died: {}".format(tail[-1] if tail else
                                               "rc={}".format(proc.returncode))


def main():
    start = time.monotonic()

    def remaining():
        return DEADLINE_S - (time.monotonic() - start)

    last_err = "no attempts made"
    attempt = 0
    while attempt < ATTEMPTS and remaining() > 10:
        if attempt:
            time.sleep(min(RETRY_DELAY_S, max(remaining() - 10, 0)))
        attempt += 1
        ok, diag = _probe_backend(timeout=min(PROBE_TIMEOUT_S, remaining()))
        print("# probe attempt {}: {}".format(attempt, diag),
              file=sys.stderr)
        if not ok:
            last_err = diag
            continue
        if remaining() < 30:
            last_err = "backend healthy but <30s of budget left for " \
                       "measurement"
            break
        record, err = _run_worker(timeout=min(WORKER_TIMEOUT_S, remaining()))
        if record is not None:
            print(json.dumps(record))
            return
        last_err = err
        print("# measurement attempt {} failed: {}".format(attempt, err),
              file=sys.stderr)
    print(json.dumps({
        "metric": _metric_name(),
        "value": 0.0,
        "unit": "images/sec",
        "vs_baseline": 0.0,
        "error": last_err,
        "attempts": attempt,
    }))


def worker():
    import jax
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    import optax

    from cloud_tpu.models import ResNet50
    from cloud_tpu.training import Trainer

    rng = np.random.default_rng(0)
    x = rng.normal(size=(BATCH, IMAGE, IMAGE, 3)).astype(np.float32)
    y = rng.integers(0, 1000, size=BATCH).astype(np.int32)

    s2d = os.environ.get("BENCH_S2D", "0") == "1"
    trainer = Trainer(
        ResNet50(num_classes=1000, conv0_space_to_depth=s2d),
        optimizer=optax.sgd(0.1, momentum=0.9),
        train_kwargs={"train": True},
        eval_kwargs={"train": False},
        metrics=())
    trainer.build(x)
    step_fn = trainer._make_train_step()

    batch = trainer._feed((x, y))
    state = trainer.state

    def sync(logs):
        """True barrier: fetch the loss VALUE to host.

        The tunneled TPU backend on this host acks block_until_ready()
        before execution finishes (measured: an 8192^3 matmul "completes"
        in 36us = 30 PFLOP/s), so only a device->host value fetch is an
        honest sync point. Costs one ~66ms tunnel round-trip per call —
        paid once per chunk, amortized over CHUNK steps.
        """
        return float(jax.device_get(logs["loss"]))

    for _ in range(WARMUP_STEPS):
        state, logs = step_fn(state, batch)
    if WARMUP_STEPS:
        sync(logs)

    # Median contiguous chunk: robust to one-off stalls of the shared
    # chip tunnel (which measure the tunnel, not the step) while still
    # reporting sustained — not peak — throughput, comparable with the
    # sustained-average baseline.
    chunk_times = []
    for _ in range(max(TIMED_STEPS // CHUNK, 1)):
        t0 = time.perf_counter()
        for _ in range(CHUNK):
            state, logs = step_fn(state, batch)
        sync(logs)
        chunk_times.append(time.perf_counter() - t0)
    median_elapsed = sorted(chunk_times)[len(chunk_times) // 2]

    images_per_sec = BATCH * CHUNK / median_elapsed
    record = {
        "metric": _metric_name(),
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / BASELINE_IMAGES_PER_SEC, 3),
        "method": "median_chunk",
        "chunk": CHUNK,
        "steps": max(TIMED_STEPS // CHUNK, 1) * CHUNK,
        "batch": BATCH,
        "image": IMAGE,
        "platform": jax.default_backend(),
    }
    if s2d:
        record["stem"] = "space_to_depth"
    print(json.dumps(record))


if __name__ == "__main__":
    if "--worker" in sys.argv[1:]:
        worker()
    else:
        main()
