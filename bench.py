"""Benchmark harness: ResNet50 training throughput on one TPU chip.

BASELINE.md target: Keras `model.fit` steps/sec via the launch API on
v5e-8 matching 8xV100 wall-clock. The reference publishes no numbers
(BASELINE.md "Published reference numbers: None"), so the recorded
baseline is the 8xV100 side of the driver's target: ResNet50 mixed
precision at ~2800 images/sec across 8 V100s = 350 images/sec per
V100-equivalent. This harness measures our per-chip ResNet50 train-step
throughput (bf16, NHWC) through the framework's own jitted Trainer
step; vs_baseline > 1.0 means one v5e chip beats one V100, i.e. v5e-8
beats 8xV100 wall-clock for config 2.

Structure: the top-level process never touches the accelerator backend
directly — the TPU on this host sits behind an experimental tunnel
whose init can hang indefinitely, so (1) backend health is probed in a
bounded subprocess, (2) the measurement itself runs in a bounded
subprocess, and (3) the probe loop keeps running for the WHOLE
BENCH_DEADLINE window: any ~3-minute tunnel-up window is enough to
capture a number (the persistent XLA compilation cache under
benchmarks/.jax_cache makes retries skip the multi-minute ResNet50
compile). Every green measurement is cached to
benchmarks/last_green.json; on persistent tunnel failure the cached
record is emitted with "stale": true so the record is never empty.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N,
     "method": "median_chunk", "kernel_parity": "ok", ...}
or, when the backend stayed unreachable and no cached green run exists:
    {"metric": ..., "value": 0.0, ..., "error": "<diagnosis>"}
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

# Sweep-derived operating point: benchmarks/best_pin.json (written by
# `sweep.py --write-pin` from the best measured config) supplies
# defaults for the FAIR-GAME knobs — batch size, steps_per_execution,
# bf16 input feeding — that don't change the model being measured
# (space-to-depth does, so it is never pinned). Explicit env always
# wins; applied before the constants below so main(), the worker
# subprocess, and the green-cache metric naming all agree.
_PIN_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "benchmarks", "best_pin.json")
_PINNABLE = ("BENCH_BATCH", "BENCH_SPE", "BENCH_BF16_INPUT")
_IS_WORKER = "--worker" in sys.argv[1:]

# `--cpu`: force the CPU backend end-to-end (probe, worker, kernel
# smoke) and — unless the caller overrode them via env — shrink the
# measurement to CPU-tractable sizes. The point of the flag is a fast
# full-pipeline smoke on a laptop/CI box, not a CPU throughput
# contest. Placed BEFORE the pin block so a TPU operating point from
# best_pin.json never sizes a CPU smoke.
if "--cpu" in sys.argv[1:]:
    os.environ["BENCH_FORCE_CPU"] = "1"
    for _k, _v in (("BENCH_BATCH", "8"), ("BENCH_IMAGE", "64"),
                   ("BENCH_WARMUP", "1"), ("BENCH_STEPS", "4"),
                   ("BENCH_CHUNK", "2")):
        os.environ.setdefault(_k, _v)

# Named bench configs: the fair-game ResNet variants that keep
# resurfacing in sweeps get first-class names, so
# `BENCH_CONFIG=bf16_input python bench.py` reproduces the exact knob
# set a recorded series claims instead of a hand-typed env pile.
# Expanded (setdefault) BEFORE the pin block: a named config is
# explicit user intent, so its keys look explicitly-set to the pin
# loop and are never overridden by best_pin.json; explicit env still
# beats the named config.
NAMED_CONFIGS = {
    "bf16_input": {"BENCH_BF16_INPUT": "1"},
    "space_to_depth": {"BENCH_S2D": "1"},
    "bf16_s2d": {"BENCH_BF16_INPUT": "1", "BENCH_S2D": "1"},
}
_CFG_NAME = os.environ.get("BENCH_CONFIG", "")
if _CFG_NAME:
    if _CFG_NAME not in NAMED_CONFIGS:
        sys.exit("BENCH_CONFIG=%r unknown (choose from: %s)"
                 % (_CFG_NAME, ", ".join(sorted(NAMED_CONFIGS))))
    for _k, _v in NAMED_CONFIGS[_CFG_NAME].items():
        os.environ.setdefault(_k, _v)

# BENCH_* keys whose values came from the pin file. BENCH_PIN_APPLIED
# is a parent->worker handoff, not user configuration: the worker
# subprocess inherits the parent's post-pin env (so every pinned key
# looks "explicitly set" to it) and needs the marker to record honest
# pin provenance. The PARENT, however, must never trust an inherited
# value — a stale marker leaking in from an outer shell or driver
# would mislabel explicitly-set knobs as pinned — so it clears the
# variable at startup and rebuilds it from its own pin loop below.
if not _IS_WORKER:
    os.environ.pop("BENCH_PIN_APPLIED", None)
_PIN_APPLIED = [k for k in
                os.environ.get("BENCH_PIN_APPLIED", "").split(",") if k]
try:
    if os.environ.get("BENCH_IGNORE_PIN", "0") != "1":
        with open(_PIN_PATH) as _f:
            _pin = json.load(_f)
        if isinstance(_pin, dict):
            for _k in _PINNABLE:
                if _k in _pin and _k not in os.environ:
                    os.environ[_k] = str(int(_pin[_k]))
                    _PIN_APPLIED.append(_k)
                    # Export per-iteration: a later malformed key
                    # aborts the loop, but keys already applied to
                    # os.environ must still reach the worker with
                    # their provenance marker.
                    os.environ["BENCH_PIN_APPLIED"] = ",".join(
                        _PIN_APPLIED)
except (OSError, ValueError, TypeError):
    # A malformed pin must degrade to defaults, never kill the
    # harness (its contract: the JSON line is never empty).
    pass


def _env_int(key, default):
    """os.environ int with the harness's never-crash contract: a
    malformed value degrades to the default (the fallback path calls
    this — an uncaught ValueError there would violate 'the JSON line
    is never empty')."""
    try:
        return int(os.environ.get(key, default))
    except (TypeError, ValueError):
        return default


def _env_float(key, default):
    """`_env_int`'s float sibling (BENCH_SERVE_PREFIX_SHARE etc.)."""
    try:
        return float(os.environ.get(key, default))
    except (TypeError, ValueError):
        return default

BATCH = _env_int("BENCH_BATCH", 256)
IMAGE = _env_int("BENCH_IMAGE", 224)
WARMUP_STEPS = _env_int("BENCH_WARMUP", 3)
TIMED_STEPS = _env_int("BENCH_STEPS", 20)
CHUNK = min(_env_int("BENCH_CHUNK", 5), TIMED_STEPS)
BASELINE_IMAGES_PER_SEC = 350.0  # one V100, fp16 ResNet50 (8xV100 / 8)

# ResNet50 fwd+bwd+update FLOPs per image at 224^2 (PERF.md roofline
# sanity check) and v5e bf16 peak, for the %-of-peak line in the JSON.
RESNET50_GFLOPS_PER_IMAGE = 12.3
V5E_PEAK_TFLOPS = 197.0

# Probe cadence: a 1-op jit in a bounded subprocess. Healthy tunnel
# answers in ~5s; a stalled one eats the whole timeout, so the loop's
# worst-case period is PROBE_TIMEOUT + PROBE_INTERVAL.
PROBE_TIMEOUT_S = float(os.environ.get("BENCH_PROBE_TIMEOUT", 60))
PROBE_INTERVAL_S = float(os.environ.get("BENCH_PROBE_INTERVAL", 15))
# Overall wall-clock budget. Round-2 lesson: 600s gave up while the
# tunnel stayed down for the driver's whole capture window; the probe
# loop is cheap, so default to most of the driver's budget and measure
# the moment the tunnel comes up. INVARIANT: the JSON line appears
# within ~DEADLINE_S + a few seconds — every probe/worker timeout is
# clamped to the remaining budget, so a driver-side outer timeout must
# simply exceed BENCH_DEADLINE (set BENCH_DEADLINE below the driver's
# budget when that budget is under the 2400s default).
DEADLINE_S = float(os.environ.get("BENCH_DEADLINE", 2400))
WORKER_TIMEOUT_S = float(os.environ.get("BENCH_TIMEOUT", 480))
# Cap on full measurement launches (probes are uncapped — they're the
# cheap part): a worker that fails for a non-tunnel reason (bad env,
# import error) must not be relaunched in a tight loop all window.
MAX_MEASUREMENTS = int(os.environ.get("BENCH_ATTEMPTS", 5))
RETRY_DELAY_S = float(os.environ.get("BENCH_RETRY_DELAY", 10))
# Consecutive probe failures before the run declares the backend down
# and emits a fast clean `skipped` record. Round-5 lesson inverted:
# waiting out the window only pays when the backend has answered at
# least once this run (a flap); a backend that NEVER answers gets a
# typed skip in ~3 probe periods, not an 11-hour stale re-serve.
PROBE_ATTEMPTS = _env_int("BENCH_PROBE_ATTEMPTS", 3)

METRIC = "resnet50_train_images_per_sec_per_chip"

# The in-flight probe/worker child and the emitted-record flag, shared
# with the SIGTERM handler: on early termination the child must die
# with us (an orphaned worker would keep the shared tunnel busy), and
# exactly one JSON line may ever be printed.
_INFLIGHT = None
_EMITTED = False
_CHIP_LOCK = None  # held for the process lifetime once acquired


def _bounded_run(args, timeout):
    """subprocess.run equivalent that records the child for the SIGTERM
    handler. Raises subprocess.TimeoutExpired like subprocess.run."""
    global _INFLIGHT
    proc = subprocess.Popen(args, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, cwd=_HERE)
    _INFLIGHT = proc
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        stdout, stderr = proc.communicate()
        raise subprocess.TimeoutExpired(args, timeout, output=stdout,
                                        stderr=stderr)
    finally:
        _INFLIGHT = None
    return subprocess.CompletedProcess(args, proc.returncode, stdout,
                                       stderr)


def _print_record(record):
    global _EMITTED
    if _EMITTED:
        return
    _EMITTED = True
    print(json.dumps(record), flush=True)

_HERE = os.path.dirname(os.path.abspath(__file__))
LAST_GREEN_PATH = os.environ.get(
    "BENCH_LAST_GREEN", os.path.join(_HERE, "benchmarks",
                                     "last_green.json"))
COMPILE_CACHE_DIR = os.path.join(_HERE, "benchmarks", ".jax_cache")


def _metric_name():
    if os.environ.get("BENCH_SWEEP", "0") == "1":
        # graftsweep series: trial throughput of a warm-cache ASHA
        # sweep (tuner/sweep.py), with the cold-vs-warm compile split
        # and guard fault census in the record. Foreign metric name ->
        # its own cache slot; never pin-eligible (best_pin.json only
        # carries the flagship training knobs, none of which this
        # worker reads).
        return "graftsweep_trials_per_hour"
    if os.environ.get("BENCH_SERVE_LOAD", "0") == "1":
        # graftlens open-loop load series: goodput (fraction of offered
        # requests meeting the TTFT+TPOT SLOs) at the highest swept
        # arrival rate, with the full offered-vs-achieved curve in the
        # record. Checked before BENCH_SERVE: the load series drives a
        # Scheduler too, but measures the SLO envelope, not raw
        # tokens/sec.
        return "graftserve_loadgen_goodput"
    if os.environ.get("BENCH_SERVE", "0") == "1":
        # A different measurement entirely (continuous-batching decode,
        # not training throughput): its own metric name, its own cache
        # slot (_series_path gives foreign names their own file). A
        # CLOUD_TPU_PAGED_KERNEL force-override is an A/B contrast
        # series — suffixed so kernel-on/off records never share a
        # cache slot with each other or with the auto flagship.
        name = "graftserve_decode_tokens_per_sec"
        forced = os.environ.get("CLOUD_TPU_PAGED_KERNEL", "")
        if forced == "1":
            name += "_pk_on"
        elif forced == "0":
            name += "_pk_off"
        # graftpack contrast series: int8 KV pages (and/or the host
        # page tier) change what a token costs, so their records get
        # their own cache slot — suffixed, never pin-eligible, same as
        # the kernel A/B above.
        if os.environ.get("BENCH_SERVE_KV_DTYPE",
                          "").strip().lower() == "int8":
            name += "_kvq"
        if os.environ.get("BENCH_SERVE_HOST_TIER", "0") == "1":
            name += "_host"
        return name
    # Architecture/feeding variants are suffixed so recorded numbers
    # (including failed runs) stay apples-to-apples per series.
    name = METRIC
    if os.environ.get("BENCH_S2D", "0") == "1":
        name += "_s2d"
    if os.environ.get("BENCH_BF16_INPUT", "0") == "1":
        name += "_bf16in"
    if os.environ.get("BENCH_RESIDENT", "0") == "1":
        name += "_res"
    if os.environ.get("BENCH_ASYNC_LOG", "0") == "1":
        # Async-host-loop contrast series: the timed loop hands its
        # per-chunk loss to the background metric reader instead of
        # sync-fetching it, so the sync-elimination win is its own
        # metric. Never pinned (like _res: a different host-loop
        # regime, not a fair-game knob of the flagship series).
        name += "_async"
    if os.environ.get("BENCH_WARM", "0") == "1":
        # Warm-start contrast series: same measurement, but the record
        # is its own series so its compile-census fields (time to
        # first step, persistent-cache hits) are tracked against other
        # warm runs — a cold run's multi-minute compile would otherwise
        # look like a throughput regression. Never pinned.
        name += "_warm"
    return name


def _unit():
    if os.environ.get("BENCH_SWEEP", "0") == "1":
        return "trials/hour"
    if os.environ.get("BENCH_SERVE_LOAD", "0") == "1":
        return "goodput_frac"
    return ("tokens/sec" if os.environ.get("BENCH_SERVE", "0") == "1"
            else "images/sec")


def _probe_backend(timeout=None):
    """Compile-and-run a trivial jit in a fresh bounded process.

    Returns (ok, diagnosis). Thin wrapper over the shared
    `runtime.probe_backend` (the same probe the graftwatch stall
    handler runs, so bench and watchdog diagnose a dead tunnel with
    identical words); this shim only adds the harness's concerns —
    the BENCH_FORCE_CPU contract and registering the child with the
    SIGTERM handler's `_INFLIGHT` slot so early termination kills it.
    """
    timeout = PROBE_TIMEOUT_S if timeout is None else timeout

    def register(proc):
        global _INFLIGHT
        _INFLIGHT = proc

    try:
        from cloud_tpu.parallel import runtime as _runtime
    except Exception as e:  # partial checkout: diagnose, don't crash
        return False, ("backend probe unavailable (cloud_tpu import "
                       "failed: {})".format(e))
    return _runtime.probe_backend(
        deadline=timeout,
        force_cpu=os.environ.get("BENCH_FORCE_CPU") == "1",
        register=register)


def _run_worker(timeout=None):
    """Run the measurement in a bounded subprocess; returns (record, err)."""
    timeout = WORKER_TIMEOUT_S if timeout is None else timeout
    def parse(stdout):
        for line in reversed((stdout or "").splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)
                except ValueError:
                    # A record cut mid-write (killed during the
                    # enriched print): keep scanning for the intact
                    # pre-smoke line.
                    continue
        return None

    try:
        proc = _bounded_run(
            [sys.executable, os.path.abspath(__file__), "--worker"],
            timeout)
    except subprocess.TimeoutExpired as e:
        # The worker prints the throughput record BEFORE the kernel
        # smoke: a smoke that hangs on the tunnel must not discard a
        # completed measurement.
        stdout = e.stdout
        if isinstance(stdout, bytes):
            stdout = stdout.decode("utf-8", "replace")
        record = parse(stdout)
        if record is not None:
            record.setdefault("kernel_parity",
                              "timeout past {:.0f}s".format(timeout))
            # The measurement (and possibly the smoke) completed, but
            # the process had to be killed: worker_rc demotes the
            # record to the annotated cache tier (_cache_rank), same
            # as the rc!=0 path.
            record["worker_rc"] = "killed after {:.0f}s timeout".format(
                timeout)
            return record, None
        return None, "measurement hung past {:.0f}s".format(timeout)
    except OSError as e:
        return None, "measurement failed to launch: {}".format(e)
    record = parse(proc.stdout)
    if record is not None:
        if proc.returncode != 0:
            # Throughput line landed but the process then aborted —
            # on TPU that's the Mosaic-compile failure class the
            # kernel smoke exists to surface; don't report it green.
            # OVERWRITE any kernel_parity the worker printed: even a
            # passing smoke followed by a teardown crash must not be
            # REPORTED as parity-ok; the crash annotation also demotes
            # the record to the annotated cache tier (_cache_rank).
            tail = (proc.stderr or "").strip().splitlines()
            record["kernel_parity"] = "crashed rc={}: {}".format(
                proc.returncode, tail[-1][:160] if tail else "")
            record["worker_rc"] = proc.returncode
        return record, None
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()
    return None, "measurement died: {}".format(tail[-1] if tail else
                                               "rc={}".format(proc.returncode))


def _cache_rank(record):
    """Cache precedence for a record, from its own fields:

    2 — harness capture, parity ok, clean worker exit (fully green);
    1 — harness capture with honest annotations (kernel_parity failure
        or worker_rc): the throughput number is real and was measured
        by this code, but something around it went wrong — cacheable,
        served stale WITH its annotations, so it can never be mistaken
        for a fully-green run (ADVICE r3's actual concern);
    0 — self-reported hand number (the round-2 seed).

    A new record replaces the cache iff its rank >= the cached rank, so
    a real-but-annotated capture outranks the hand seed and a fresh
    fully-green run outranks everything, while an annotated run can
    never shadow an existing fully-green one.
    """
    if record.get("self_reported"):
        return 0
    if (record.get("kernel_parity", "ok") == "ok"
            and "worker_rc" not in record):
        return 2
    return 1


def _series_path(metric):
    """One cache slot PER METRIC SERIES (base, _s2d, _bf16in, ...):
    a variant run's record must never evict another series' only
    fallback record. LAST_GREEN_PATH names the base-series slot;
    variant slots insert the metric suffix before the extension."""
    base, ext = os.path.splitext(LAST_GREEN_PATH)
    if metric.startswith(METRIC):
        suffix = metric[len(METRIC):]
    else:  # foreign metric name: still give it its own slot
        suffix = "_" + metric
    return base + suffix + ext


def _read_slot(path):
    """The slot's record, or None (missing/corrupt/non-object JSON —
    a truncated write can still parse as a bare list/string)."""
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, ValueError):
        return None
    return record if isinstance(record, dict) else None


def _maybe_cache(record):
    """Cache a real-TPU harness capture if it outranks its series' slot.

    Only a real-TPU number is worth serving stale later; a forced-CPU
    CI run must not shadow the last green TPU run. Rank (above) keeps
    the slot honest: annotated captures carry their annotations into
    any later stale emission."""
    if record.get("platform") != "tpu" or not record.get("value"):
        return False
    path = _series_path(record.get("metric", METRIC))
    cached = _read_slot(path)
    if cached is not None and _cache_rank(record) < _cache_rank(cached):
        return False
    _save_last_green(record, path)
    return True


def _save_last_green(record, path=None):
    path = path or _series_path(record.get("metric", METRIC))
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
    except OSError as e:
        print("# could not cache green record: {}".format(e),
              file=sys.stderr)


def _load_last_green():
    """Most recent cached record for this run's metric series, or None.

    The metric guard stays even with per-series slots: a legacy
    single-slot cache file (pre-round-4 code wrote every series to
    LAST_GREEN_PATH) may hold a variant record at the base path, and a
    cross-series number must never be replayed as this series' stale
    fallback."""
    record = _read_slot(_series_path(_metric_name()))
    if record is None or not record.get("value"):
        return None
    if record.get("metric") != _metric_name():
        return None
    return record


def _requested_config():
    """The fair-game measurement knobs THIS invocation was asked for.

    Attached to every emission so a consumer can always tell which
    configuration the number claims to describe — and, on a stale
    re-serve, whether the cached green was captured under a DIFFERENT
    config (round-4 gap: captures/bench_spe5.json served the flagship
    number under an SPE-contrast filename with nothing marking the
    mismatch). Values reflect the post-pin environment; `pinned` lists
    the keys best_pin.json supplied.
    """
    if os.environ.get("BENCH_SWEEP", "0") == "1":
        # The sweep series' fair-game knobs: trial budget and the ASHA
        # ladder geometry. The chaos spec is recorded when set so a
        # fault-census record is self-describing.
        cfg = {
            "sweep": True,
            "trials": _env_int("BENCH_SWEEP_TRIALS", 12),
            "min_budget": _env_int("BENCH_SWEEP_MIN_BUDGET", 1),
            "eta": _env_int("BENCH_SWEEP_ETA", 3),
            "max_budget": _env_int("BENCH_SWEEP_MAX_BUDGET", 9),
        }
        if os.environ.get("CLOUD_TPU_CHAOS"):
            cfg["chaos"] = os.environ["CLOUD_TPU_CHAOS"]
        return cfg
    if os.environ.get("BENCH_SERVE_LOAD", "0") == "1":
        # The loadgen series' fair-game knobs: the arrival process and
        # the SLO envelope the goodput number is measured against.
        return {
            "serve_load": True,
            "slots": _env_int("BENCH_SERVE_LOAD_SLOTS", 8),
            "requests": _env_int("BENCH_SERVE_LOAD_REQUESTS", 24),
            "rates": os.environ.get("BENCH_SERVE_LOAD_RATES", "2,4,8"),
            "process": os.environ.get("BENCH_SERVE_LOAD_PROCESS",
                                      "poisson"),
            "shared_prefix_ratio": _env_float(
                "BENCH_SERVE_LOAD_SHARE", 0.5),
            "slo_ttft_s": _env_float("BENCH_SLO_TTFT", 0.5),
            "slo_tpot_s": _env_float("BENCH_SLO_TPOT", 0.1),
        }
    if os.environ.get("BENCH_SERVE", "0") == "1":
        # The serve series' fair-game knobs — none of the training
        # knobs apply (it measures the decode engine, not the Trainer).
        return {
            "serve": True,
            "slots": _env_int("BENCH_SERVE_SLOTS", 8),
            "waves": _env_int("BENCH_SERVE_WAVES", 0),
            # graftshare knob: fraction of short requests sharing one
            # prompt prefix (0 = no sharing, the pre-ISSUE-11 shape;
            # the sweep runs 0 / 0.5 / 0.9).
            "prefix_share": _env_float("BENCH_SERVE_PREFIX_SHARE", 0.0),
            # Paged decode-attention impl the serve series ran under
            # (ops/paged_attention.py): "on"/"off" when
            # CLOUD_TPU_PAGED_KERNEL force-overrides, else "auto"
            # (kernel on TPU, reference elsewhere). Recorded so an
            # A/B pair of serve records is self-describing.
            "paged_kernel": {"1": "on", "0": "off"}.get(
                os.environ.get("CLOUD_TPU_PAGED_KERNEL", ""), "auto"),
            # graftpack knobs: KV page dtype ("" = compute dtype) and
            # the host page tier. Each flips the record onto its own
            # suffixed series (_kvq / _host).
            "kv_dtype": os.environ.get("BENCH_SERVE_KV_DTYPE",
                                       "").strip().lower(),
            "host_tier": _env_int("BENCH_SERVE_HOST_TIER", 0),
        }
    cfg = {
        "batch": BATCH,
        "image": IMAGE,
        "steps_per_execution": max(_env_int("BENCH_SPE", 1), 1),
        "bf16_input": os.environ.get("BENCH_BF16_INPUT", "0") == "1",
        "space_to_depth": os.environ.get("BENCH_S2D", "0") == "1",
    }
    # Only when on: legacy cached records predate the key, and an
    # absent-vs-False diff must not flag a spurious config mismatch on
    # a base-series stale re-serve.
    if os.environ.get("BENCH_RESIDENT", "0") == "1":
        cfg["resident"] = True
    if os.environ.get("BENCH_ASYNC_LOG", "0") == "1":
        cfg["async_log"] = True
    if os.environ.get("BENCH_WARM", "0") == "1":
        cfg["warm"] = True
    for key in ("CLOUD_TPU_FLASH_BLOCK_Q", "CLOUD_TPU_FLASH_BLOCK_K"):
        if os.environ.get(key):
            cfg[key.lower()] = _env_int(key, 0)
    if _CFG_NAME:
        # Provenance only (the expanded knobs above are what the run
        # measured); absent on legacy records, so only set when used.
        cfg["named_config"] = _CFG_NAME
    if _PIN_APPLIED:
        cfg["pinned"] = list(_PIN_APPLIED)
    return cfg


def _captured_config(record):
    """The config a (possibly pre-round-5) record was captured under.

    New records carry `requested_config` verbatim; legacy cached
    records are reconstructed from the fields the worker has always
    emitted (spe/stem/input_dtype are written only when non-default).
    """
    if isinstance(record.get("requested_config"), dict):
        return record["requested_config"]
    return {
        "batch": record.get("batch"),
        "image": record.get("image"),
        "steps_per_execution": record.get("steps_per_execution", 1),
        "bf16_input": record.get("input_dtype") == "bfloat16",
        "space_to_depth": record.get("stem") == "space_to_depth",
    }


def _config_mismatch(requested, captured):
    """True iff any knob differs. `pinned` and `named_config` are
    provenance, not knobs (a named config expands to the same env
    knobs an explicit run would set); a key absent on one side
    compares as its absent-default (None for sizes, which only happens
    on hand-seeded records — an honest mismatch)."""
    keys = (set(requested) | set(captured)) - {"pinned", "named_config"}
    return any(requested.get(k) != captured.get(k) for k in keys)


def _emit_fallback(last_err, extra=None):
    """The never-empty exit: cached green (marked stale) or error JSON.

    A stale re-serve is self-describing: it carries the config THIS
    run requested and, when the cached green was captured under a
    different config, `config_mismatch: true` plus that cached config
    — a consumer diffing e.g. SPE-on vs SPE-off can no longer read a
    never-measured 0% delta off two re-serves of the same capture.
    """
    requested = _requested_config()
    cached = _load_last_green()
    if cached is not None:
        stale = dict(cached)
        stale["stale"] = True
        stale["stale_reason"] = last_err
        stale["requested_config"] = requested
        captured = _captured_config(cached)
        if _config_mismatch(requested, captured):
            stale["config_mismatch"] = True
            stale["captured_config"] = captured
        if stale.get("self_reported"):
            # A hand measurement must fail safe for consumers that read
            # `value` without checking provenance flags: move the number
            # to last_green_* keys and zero the headline fields. A
            # harness-captured green (no self_reported marker) is served
            # at face value — it was measured by this code.
            stale["last_green_value"] = stale.get("value", 0.0)
            stale["last_green_vs_baseline"] = stale.get(
                "vs_baseline", 0.0)
            stale["value"] = 0.0
            stale["vs_baseline"] = 0.0
        _print_record(stale)
        return
    record = {
        "metric": _metric_name(),
        "value": 0.0,
        "unit": _unit(),
        "vs_baseline": 0.0,
        "error": last_err,
        "requested_config": requested,
    }
    # Counter fields ride every emission (worker records carry the
    # timed loop's real census; this error path reports the driver's
    # own — honestly zero, nothing was fetched in this process).
    try:
        from cloud_tpu.parallel import runtime as _runtime
        stats = _runtime.transfer_stats()
        record["d2h_fetches"] = stats["d2h_fetches"]
        record["d2h_bytes"] = stats["d2h_bytes"]
        cstats = _runtime.compile_stats()
        record["n_traces"] = cstats["n_traces"]
        record["n_compiles"] = cstats["n_compiles"]
        record["compile_seconds"] = round(cstats["compile_seconds"], 3)
        record["compile_cache_hits"] = cstats["cache_hits"]
    except Exception:  # partial checkout must not sink the fallback
        pass
    record.update(extra or {})
    _print_record(record)


def _emit_skipped(diagnosis, probes):
    """The probe-failure exit: a fast, clean, typed skip.

    Distinct from `_emit_fallback`'s stale re-serve on purpose: a
    stale record answers "the measurement broke mid-run, serve the
    last green" — but when the backend never answered a single probe
    there IS no measurement to be stale about, and re-serving an old
    green taught consumers to read numbers through an 11-hour outage
    (the round-5 lesson). A skip says so in its own fields: value 0.0,
    `skipped: true`, the probe diagnosis, never `stale`.
    """
    _print_record({
        "metric": _metric_name(),
        "value": 0.0,
        "unit": _unit(),
        "vs_baseline": 0.0,
        "skipped": True,
        "skip_reason": diagnosis,
        "probes": probes,
        "requested_config": _requested_config(),
    })


def main():
    start = time.monotonic()

    def remaining():
        return DEADLINE_S - (time.monotonic() - start)

    last_err = "no attempts made"
    probes = 0
    probe_failures = 0  # consecutive, reset by any successful probe
    backend_seen = False  # any probe answered this run
    measurements = 0

    # A driver whose outer `timeout` is SHORTER than BENCH_DEADLINE
    # sends SIGTERM before the loop's own fallback would print — the
    # one path that could leave the record empty. Catch it, emit the
    # fallback JSON, exit clean.
    import signal

    def _terminated(signum, frame):
        del signum, frame
        child = _INFLIGHT
        if child is not None:
            try:
                child.kill()
            except OSError:
                pass
        if not _EMITTED:
            reason = (last_err + " (terminated by outer timeout at "
                      "t+{:.0f}s)".format(time.monotonic() - start))
            if probes and not backend_seen:
                # The backend never answered a single probe: the honest
                # record is a typed skip, not a stale re-serve of a
                # green the outage had nothing to do with.
                _emit_skipped(reason, probes)
            else:
                _emit_fallback(reason)
        os._exit(0)

    try:
        signal.signal(signal.SIGTERM, _terminated)
    except (ValueError, OSError):  # non-main thread / exotic platform
        pass
    # One measurement driver on the chip at a time: a concurrent
    # capture (e.g. the auto-capture watcher mid-sweep) would contend
    # through the tunnel and corrupt both timings. Advisory — a
    # timeout proceeds anyway (never deadlock the harness); the wait
    # spends this run's own deadline budget. Acquired for the process
    # lifetime: the kernel releases the flock when this process (or a
    # crash) closes the fd, so no explicit release path is needed.
    try:
        sys.path.insert(0, os.path.join(_HERE, "benchmarks"))
        from _subproc import hold_chip_lock
        global _CHIP_LOCK  # keep the fd referenced for process lifetime
        _CHIP_LOCK = hold_chip_lock(
            timeout=min(900.0, max(remaining() - 120.0, 0.0)))
    except ImportError:  # partial checkout: measure unlocked
        pass
    while True:
        if measurements >= MAX_MEASUREMENTS:
            # No further measurement can ever launch; don't burn the
            # rest of the window probing for one.
            last_err = "{} (after {} measurement attempts)".format(
                last_err, measurements)
            break
        if probes and remaining() <= 10:
            break
        # The first probe always runs — even under a tiny deadline the
        # contract is a diagnosed error, not "no attempts made".
        ok, diag = _probe_backend(
            timeout=min(PROBE_TIMEOUT_S, max(remaining(), 0.1)))
        probes += 1
        print("# probe {} (t+{:.0f}s): {}".format(
            probes, time.monotonic() - start, diag), file=sys.stderr)
        if not ok:
            last_err = diag
            probe_failures += 1
            if not backend_seen and probe_failures >= PROBE_ATTEMPTS:
                # The backend never answered this run: emit the typed
                # skip NOW (fast, clean, never `stale`) instead of
                # probing out the window. A backend that answered once
                # is a flap — those keep the patient retry loop.
                _emit_skipped(diag, probes)
                return
            if remaining() <= 10:
                break
            time.sleep(min(PROBE_INTERVAL_S, max(remaining() - 10, 0)))
            continue
        backend_seen = True
        probe_failures = 0
        if remaining() < 30:
            last_err = "backend healthy but <30s of budget left for " \
                       "measurement"
            break
        measurements += 1
        record, err = _run_worker(timeout=min(WORKER_TIMEOUT_S, remaining()))
        if record is not None:
            # Tiered green cache (_cache_rank): a fully-green record
            # (parity ok, clean exit) replaces anything; a capture with
            # honest annotations (parity failure, worker_rc) replaces
            # the hand seed or an older annotated capture but never a
            # fully-green one, and its annotations travel into any
            # later stale emission.
            _maybe_cache(record)
            _print_record(record)
            return
        last_err = err
        print("# measurement attempt {} failed: {}".format(
            measurements, err), file=sys.stderr)
        # The compile cache makes a tunnel-flap retry cheap, but pause
        # before re-probing so a deterministically-failing worker can't
        # spin the whole window.
        time.sleep(min(RETRY_DELAY_S, max(remaining() - 10, 0)))
    if probes and not backend_seen:
        # Same honesty as the PROBE_ATTEMPTS exit: the window closed
        # with the backend never having answered — skip, don't stale.
        _emit_skipped(last_err, probes)
        return
    _emit_fallback(last_err, extra={
        "probes": probes, "measurement_attempts": measurements})


def _kernel_parity_smoke(jax):
    """Flash-attention parity vs the jnp oracle, non-interpreted.

    Round-2 gap: every kernel test ran in interpret mode off-TPU, so a
    Mosaic compile/layout failure would first surface during the
    benchmark itself. This runs the real kernel (forward AND grad) on
    whatever backend the worker measured on; on TPU that is the
    compiled Mosaic kernel. ~30s budget, [2,256,4,64] shapes, four
    configs: causal MHA, masked non-causal MHA, causal+masked GQA,
    and softcapped causal MHA (the Gemma2 tanh-capping path).
    Returns "ok", or "fail: ..."/"error: ..." without sinking the
    throughput record.
    """
    import jax.numpy as jnp

    from cloud_tpu.ops import flash_attention, mha_reference

    try:
        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        b, s, h, d = 2, 256, 4, 64
        q = jax.random.normal(kq, (b, s, h, d), dtype=jnp.float32)
        # Contiguous-prefix key mask (valid lengths 256 and 192).
        # (Fully-masked rows would also agree now — both conventions
        # are zeros since round 4 — but valid rows are what the smoke
        # is about.)
        mask = (np.arange(s)[None, :] <
                np.array([[s], [192]])).astype(bool)
        mask = jnp.asarray(mask)
        configs = [
            ("causal", h, True, None, None),
            ("masked", h, False, mask, None),
            ("gqa", h // 2, True, mask, None),
            # Gemma2-style tanh capping: exercises the softcap forward
            # + backward kernel paths under real Mosaic lowering
            # (interpret mode never checks layout/shape legality).
            ("softcap", h, True, None, 30.0),
        ]
        for name, h_kv, causal, m, cap in configs:
            k = jax.random.normal(kk, (b, s, h_kv, d), dtype=jnp.float32)
            v = jax.random.normal(kv, (b, s, h_kv, d), dtype=jnp.float32)

            def loss_flash(q, k, v, causal=causal, m=m, cap=cap):
                return flash_attention(q, k, v, causal=causal,
                                       mask=m, logit_softcap=cap).sum()

            def loss_ref(q, k, v, causal=causal, m=m, cap=cap):
                return mha_reference(q, k, v, causal=causal,
                                     mask=m, logit_softcap=cap).sum()

            out = jax.jit(lambda q, k, v: flash_attention(
                q, k, v, causal=causal, mask=m,
                logit_softcap=cap))(q, k, v)
            ref = mha_reference(q, k, v, causal=causal, mask=m,
                                logit_softcap=cap)
            fwd_err = float(jax.device_get(
                jnp.max(jnp.abs(out - ref))))
            g_flash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(
                q, k, v)
            g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
            grad_err = max(
                float(jax.device_get(jnp.max(jnp.abs(a - b_))))
                for a, b_ in zip(g_flash, g_ref))
            if fwd_err > 5e-2 or grad_err > 5e-2:
                return ("fail: {} fwd_err={:.2e} grad_err={:.2e}"
                        .format(name, fwd_err, grad_err))
        return "ok"
    except Exception as e:  # noqa: BLE001 - report, don't sink the bench
        return "error: {}: {}".format(type(e).__name__, str(e)[:200])


def _pct(snapshot, key):
    """Percentile from a host Histogram snapshot, None when the
    histogram is empty (p50 of nothing reads 0.0, which would record a
    fake perfect latency)."""
    return round(snapshot[key], 5) if snapshot.get("count") else None


def _serve_worker():
    """BENCH_SERVE=1: the graftserve continuous-batching series.

    Measures the decode engine the way the serving smoke does — a
    mixed-length request fleet through the Scheduler vs the
    batch-synchronous `generate()` baseline at the SAME slot count —
    but reports the numbers instead of enforcing a floor: tokens/sec
    (the `value`), speedup as `vs_baseline`, requests/sec, TTFT and
    per-token latency p50/p95/p99, plus the standard compile/transfer
    census every bench record carries.
    """
    import jax

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    from cloud_tpu.parallel import compile_cache
    compile_cache.enable(COMPILE_CACHE_DIR, min_compile_time_secs=1.0)
    import jax.numpy as jnp

    from cloud_tpu.parallel import runtime as runtime_lib
    from cloud_tpu.serving import Scheduler
    from cloud_tpu.serving.smoke import (build_model, build_requests,
                                         run_baseline, run_serve)

    slots = _env_int("BENCH_SERVE_SLOTS", 8)
    waves = _env_int("BENCH_SERVE_WAVES", 0) or None
    prefix_share = _env_float("BENCH_SERVE_PREFIX_SHARE", 0.0)
    kv_dtype = os.environ.get("BENCH_SERVE_KV_DTYPE",
                              "").strip().lower()
    host_tier = os.environ.get("BENCH_SERVE_HOST_TIER", "0") == "1"
    model = build_model()
    requests = build_requests(slots, waves, prefix_share=prefix_share)
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]

    run_baseline(model, params, requests, slots, timed=False)  # warm
    base_tokens, base_secs = run_baseline(model, params, requests,
                                          slots, timed=True)

    t_cold = time.perf_counter()
    pages_per_slot = model.max_seq_len // 16
    scheduler = Scheduler(model, params, slots=slots, page_size=16,
                          num_pages=(slots + 4) * pages_per_slot + 1,
                          admission_window=len(requests),
                          strict_no_retrace=True,
                          kv_dtype=kv_dtype,
                          host_tier=host_tier).start()
    try:
        buckets = sorted({scheduler._bucket(r) for r in requests})
        scheduler.warmup(buckets,
                         sampling_configs=[(("temperature", 0.0),)])
        # Serve's time-to-first-step analog: engine build + the whole
        # compile surface (prefill buckets, insert, tick, evict) to
        # the first warm-servable state.
        first_step_seconds = time.perf_counter() - t_cold
        warm = runtime_lib.compile_stats()
        _d2h_before = runtime_lib.transfer_stats()
        _, serve_tokens, serve_secs = run_serve(scheduler, requests)
        _d2h_after = runtime_lib.transfer_stats()
        after = runtime_lib.compile_stats()
        stats = scheduler.stats()
        # Model-exact per-tick cost of the paged decode-attention op
        # (ops/paged_attention.py cost hook; what the scheduler feeds
        # the kernel pct_peak/bytes gauges every tick).
        kernel_costs = scheduler.engine.kernel_costs()
    finally:
        scheduler.close()

    base_tps = base_tokens / base_secs
    serve_tps = serve_tokens / serve_secs
    _pstats = compile_cache.stats()
    record = {
        "metric": _metric_name(),
        "value": round(serve_tps, 2),
        "unit": "tokens/sec",
        # For this series the honest baseline is the run's own
        # batch-synchronous measurement: vs_baseline IS the
        # continuous-batching speedup.
        "vs_baseline": round(serve_tps / base_tps, 3),
        "method": "continuous_vs_batch_synchronous",
        "requests": len(requests),
        "slots": slots,
        "baseline_tokens_per_sec": round(base_tps, 2),
        "requests_per_sec": round(stats["requests_per_sec"], 3),
        "ttft_p50_s": round(stats["ttft"]["p50"], 4),
        "ttft_p95_s": round(stats["ttft"]["p95"], 4),
        "ttft_p99_s": round(stats["ttft"]["p99"], 4),
        "token_latency_p50_s": round(stats["token_latency"]["p50"], 5),
        "token_latency_p95_s": round(stats["token_latency"]["p95"], 5),
        "token_latency_p99_s": round(stats["token_latency"]["p99"], 5),
        # Paged decode-attention A/B field (ops/paged_attention.py):
        # which impl served this record's token latencies.
        "paged_kernel": {"1": "on", "0": "off"}.get(
            os.environ.get("CLOUD_TPU_PAGED_KERNEL", ""), "auto"),
        "paged_attention_flops_per_tick": kernel_costs[
            "paged_attention"]["flops"],
        "paged_attention_bytes_per_tick": kernel_costs[
            "paged_attention"]["bytes_moved"],
        # Chunked-prefill A/B fields (ISSUE 16): chunk size 0 = off;
        # the dispatch count and decode-gap tail make a chunked record
        # self-describing next to an unchunked one.
        "prefill_chunk": stats["prefill_chunk_size"],
        "prefill_chunks_dispatched": stats["prefill_chunks_dispatched"],
        "decode_gap_p99_s": _pct(stats["decode_gap"], "p99"),
        # graftshare census: hit/miss TTFT split + cache effectiveness.
        # Hit percentiles are None at prefix_share=0 (empty histogram).
        "prefix_share": prefix_share,
        "prefix_hit_rate": round(stats["prefix_hit_rate"], 4),
        "prefix_hits": stats["prefix_hits"],
        "prefix_misses": stats["prefix_misses"],
        "prefix_tokens_served": stats["prefix_tokens_served"],
        "ttft_hit_p50_s": _pct(stats["ttft_hit"], "p50"),
        "ttft_hit_p95_s": _pct(stats["ttft_hit"], "p95"),
        "ttft_hit_p99_s": _pct(stats["ttft_hit"], "p99"),
        "ttft_miss_p50_s": _pct(stats["ttft_miss"], "p50"),
        "ttft_miss_p95_s": _pct(stats["ttft_miss"], "p95"),
        "ttft_miss_p99_s": _pct(stats["ttft_miss"], "p99"),
        "cow_copies": stats["pool"]["cow_copies"],
        "ticks": stats["ticks"],
        # graftpack KV-hierarchy census: page dtype + per-page cost,
        # resident-session capacity at the pool's byte budget, and the
        # demote/promote traffic when the host tier is on.
        "kv_dtype": stats["kv"]["page_dtype"] or "fp",
        "kv_page_bytes": stats["kv"]["page_bytes"],
        "kv_capacity_sessions": stats["kv"]["capacity_sessions"],
        "host_tier_pages": stats["kv"]["host_tier_pages"],
        "page_demotes": stats["kv"]["page_demotes"],
        "page_promotes": stats["kv"]["page_promotes"],
        "digest_failures": stats["kv"]["digest_failures"],
        # The zero-retrace contract as numbers (also enforced live by
        # strict_no_retrace — a violation kills the run, not the lint).
        "new_traces_post_warmup": after["n_traces"] - warm["n_traces"],
        "new_compiles_post_warmup": (after["n_compiles"]
                                     - warm["n_compiles"]),
        "d2h_fetches": (_d2h_after["d2h_fetches"]
                        - _d2h_before["d2h_fetches"]),
        "d2h_bytes": _d2h_after["d2h_bytes"] - _d2h_before["d2h_bytes"],
        "n_traces": after["n_traces"],
        "n_compiles": after["n_compiles"],
        "compile_seconds": round(after["compile_seconds"], 3),
        "compile_cache_hits": after["cache_hits"],
        "persistent_cache_hits": _pstats["persistent_hits"],
        "persistent_cache_misses": _pstats["persistent_misses"],
        "time_to_first_step_seconds": round(first_step_seconds, 3),
        "platform": jax.default_backend(),
        "requested_config": _requested_config(),
    }
    if compile_cache.is_enabled():
        record["compile_cache_dir"] = compile_cache.cache_dir()
    print(json.dumps(record))


def _serve_load_worker():
    """BENCH_SERVE_LOAD=1: the graftlens open-loop goodput series.

    Unlike BENCH_SERVE (a closed-loop fleet: the driver submits the
    next request when the previous finishes, so the system sets its
    own arrival rate), this series offers load on an independent clock
    — Poisson arrivals at 2-3 fixed rates from serving/loadgen.py —
    and records the SLO envelope: `value` is goodput (fraction of
    OFFERED requests completing within --slo-ttft/--slo-tpot) at the
    HIGHEST swept rate, `vs_baseline` is goodput at the lowest (the
    underload sanity point; a healthy stack reads ~1.0 there), and
    `load_curve` carries the full offered-vs-achieved sweep.
    """
    import jax

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    from cloud_tpu.parallel import compile_cache
    compile_cache.enable(COMPILE_CACHE_DIR, min_compile_time_secs=1.0)
    import jax.numpy as jnp

    from cloud_tpu.parallel import runtime as runtime_lib
    from cloud_tpu.serving import Scheduler
    from cloud_tpu.serving import loadgen
    from cloud_tpu.serving.smoke import build_model

    slots = _env_int("BENCH_SERVE_LOAD_SLOTS", 8)
    n_requests = _env_int("BENCH_SERVE_LOAD_REQUESTS", 24)
    rates = [float(r) for r in os.environ.get(
        "BENCH_SERVE_LOAD_RATES", "2,4,8").split(",") if r.strip()]
    process = os.environ.get("BENCH_SERVE_LOAD_PROCESS", "poisson")
    share = _env_float("BENCH_SERVE_LOAD_SHARE", 0.5)
    slo_ttft = _env_float("BENCH_SLO_TTFT", 0.5)
    slo_tpot = _env_float("BENCH_SLO_TPOT", 0.1)

    model = build_model()
    params = model.init(jax.random.PRNGKey(1),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    specs = [loadgen.LoadSpec(rate=rate, n_requests=n_requests,
                              process=process,
                              shared_prefix_ratio=share, seed=i)
             for i, rate in enumerate(rates)]

    t_cold = time.perf_counter()
    pages_per_slot = model.max_seq_len // 16
    scheduler = Scheduler(model, params, slots=slots, page_size=16,
                          num_pages=(slots + 4) * pages_per_slot + 1,
                          admission_window=slots,
                          strict_no_retrace=True).start()
    try:
        all_requests = []
        for spec in specs:
            all_requests.extend(loadgen.build_requests(
                spec, model.vocab_size, model.max_seq_len))
        buckets = sorted({scheduler._bucket(r) for r in all_requests})
        scheduler.warmup(buckets,
                         sampling_configs=[(("temperature", 0.0),)])
        first_step_seconds = time.perf_counter() - t_cold
        warm = runtime_lib.compile_stats()
        runs = [loadgen.run_load(scheduler, spec, slo_ttft=slo_ttft,
                                 slo_tpot=slo_tpot)
                for spec in specs]
        after = runtime_lib.compile_stats()
        stats = scheduler.stats()
    finally:
        scheduler.close()

    # Sweep order is the env-var order; value/vs_baseline key on the
    # rate extremes so a reordered RATES list still records the same
    # contrast.
    lowest = min(runs, key=lambda r: r["spec"]["rate"])
    highest = max(runs, key=lambda r: r["spec"]["rate"])
    _pstats = compile_cache.stats()
    record = {
        "metric": _metric_name(),
        "value": round(highest["goodput"], 4),
        "unit": "goodput_frac",
        # Goodput under the lightest offered load: the run's own
        # underload control, not a cached foreign number.
        "vs_baseline": round(lowest["goodput"], 4),
        "method": "open_loop_loadgen",
        "slots": slots,
        "requests_per_rate": n_requests,
        "process": process,
        "shared_prefix_ratio": share,
        "slo_ttft_s": slo_ttft,
        "slo_tpot_s": slo_tpot,
        "load_curve": [{
            "rate": run["spec"]["rate"],
            "offered_rps": round(run["offered_rps"], 3),
            "achieved_rps": round(run["achieved_rps"], 3),
            "goodput": round(run["goodput"], 4),
            "completed": run["completed"],
            "rejected": run["rejected"],
            "failed": run["failed"],
            "ttft_p95_s": _pct(run["ttft"], "p95"),
            "tpot_p95_s": _pct(run["tpot"], "p95"),
            "hit_rate": round(run["hit_rate"], 4),
        } for run in runs],
        "prefix_hit_rate": round(stats["prefix_hit_rate"], 4),
        "queue_wait_p95_s": _pct(stats["queue_wait"], "p95"),
        "reserve_wait_p95_s": _pct(stats["reserve_wait"], "p95"),
        "prefill_chunk": stats["prefill_chunk_size"],
        "prefill_chunks_dispatched": stats["prefill_chunks_dispatched"],
        "decode_gap_p99_s": _pct(stats["decode_gap"], "p99"),
        "ticks": stats["ticks"],
        "new_traces_post_warmup": after["n_traces"] - warm["n_traces"],
        "new_compiles_post_warmup": (after["n_compiles"]
                                     - warm["n_compiles"]),
        "n_traces": after["n_traces"],
        "n_compiles": after["n_compiles"],
        "compile_seconds": round(after["compile_seconds"], 3),
        "compile_cache_hits": after["cache_hits"],
        "persistent_cache_hits": _pstats["persistent_hits"],
        "persistent_cache_misses": _pstats["persistent_misses"],
        "time_to_first_step_seconds": round(first_step_seconds, 3),
        "platform": jax.default_backend(),
        "requested_config": _requested_config(),
    }
    if compile_cache.is_enabled():
        record["compile_cache_dir"] = compile_cache.cache_dir()
    print(json.dumps(record))


def _sweep_worker():
    """BENCH_SWEEP=1: the graftsweep trial-throughput series.

    Runs the CI smoke's sweep shape — an ASHA ladder over a
    runtime-only learning-rate axis on the CPU-scale MLP, so every
    trial after the first rides the cold trial's warm executables —
    and reports trials/hour as the `value`. `vs_baseline` is the run's
    own cold-vs-warm contrast (cold trial wall over mean warm trial
    wall: the multiplicative win the shared compile cache buys per
    trial), and the guard fault/retry census fields make a
    CLOUD_TPU_CHAOS run self-describing. Foreign metric name -> own
    cache slot; never pin-eligible.
    """
    import tempfile

    import jax

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    from cloud_tpu.parallel import compile_cache
    compile_cache.enable(COMPILE_CACHE_DIR, min_compile_time_secs=1.0)
    import optax

    from cloud_tpu.models.mnist import MLP
    from cloud_tpu.parallel import runtime as runtime_lib
    from cloud_tpu.training import Trainer
    from cloud_tpu.tuner import (ASHA, HyperParameters, Objective,
                                 RandomOracle, Sweep)

    trials = _env_int("BENCH_SWEEP_TRIALS", 12)
    min_budget = _env_int("BENCH_SWEEP_MIN_BUDGET", 1)
    eta = _env_int("BENCH_SWEEP_ETA", 3)
    max_budget = _env_int("BENCH_SWEEP_MAX_BUDGET", 9)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 16)).astype(np.float32)
    y = rng.integers(0, 8, size=256).astype(np.int32)
    hp = HyperParameters()
    hp.Float("learning_rate", 1e-3, 1e-1, sampling="log")

    def build(hp):
        return Trainer(
            MLP(hidden=32, num_classes=8),
            optimizer=optax.inject_hyperparams(optax.sgd)(
                learning_rate=hp.get("learning_rate")),
            metrics=())

    objective = Objective("loss", "min")
    sweep = Sweep(build, hp, objective,
                  directory=tempfile.mkdtemp(prefix="bench_sweep_"),
                  oracle=RandomOracle(hp, trials, seed=7),
                  scheduler=ASHA(objective, min_budget=min_budget,
                                 eta=eta, max_budget=max_budget),
                  shape_keys=(), seed=0, name="bench")
    result = sweep.run(x, y, batch_size=64, verbose=False)

    rows = result["trials"]
    cold_walls = [t["wall_s"] for t in rows if t["cold"]]
    warm_walls = [t["wall_s"] for t in rows if not t["cold"]]
    mean_warm = (sum(warm_walls) / len(warm_walls)) if warm_walls else None
    trials_per_hour = (len(rows) / (result["wall_s"] / 3600.0)
                       if result["wall_s"] else 0.0)
    _pstats = compile_cache.stats()
    compile_stats = runtime_lib.compile_stats()
    record = {
        "metric": _metric_name(),
        "value": round(trials_per_hour, 2),
        "unit": "trials/hour",
        "vs_baseline": (round(cold_walls[0] / mean_warm, 3)
                        if cold_walls and mean_warm else None),
        "method": "warm_vs_cold_trial_wall",
        "trials": len(rows),
        "statuses": result["statuses"],
        "best_score": (result["best"] or {}).get("score"),
        "budgets": list(sweep.scheduler.budgets),
        "sweep_wall_s": result["wall_s"],
        "train_s": result["train_s"],
        # The multiplicative compile win, as numbers: ONE cold start
        # for the whole sweep, zero compiles on every warm trial.
        "cold_trials": result["compile"]["cold_trials"],
        "warm_trials": result["compile"]["warm_trials"],
        "cold_compile_seconds": result["compile"]["cold_seconds"],
        "warm_compile_seconds": result["compile"]["warm_seconds"],
        "warm_new_compiles": result["compile"]["warm_new_compiles"],
        "warm_new_traces": result["compile"]["warm_new_traces"],
        "cold_trial_wall_s": (round(cold_walls[0], 4)
                              if cold_walls else None),
        "mean_warm_trial_wall_s": (round(mean_warm, 4)
                                   if mean_warm else None),
        # Guard census (zeros on a clean run; the CLOUD_TPU_CHAOS
        # contrast shows the recovery-path tax per series).
        "faults": result["census"]["faults"],
        "retries": result["census"]["retries"],
        "rollbacks": result["census"]["rollbacks"],
        "resumes": result["census"]["resumes"],
        "fault_kinds": result["census"]["by_kind"],
        "lost_trials": len(result["census"]["lost_trials"]),
        "n_traces": compile_stats["n_traces"],
        "n_compiles": compile_stats["n_compiles"],
        "compile_seconds": round(compile_stats["compile_seconds"], 3),
        "compile_cache_hits": compile_stats["cache_hits"],
        "persistent_cache_hits": _pstats["persistent_hits"],
        "persistent_cache_misses": _pstats["persistent_misses"],
        "platform": jax.default_backend(),
        "requested_config": _requested_config(),
    }
    if compile_cache.is_enabled():
        record["compile_cache_dir"] = compile_cache.cache_dir()
    print(json.dumps(record))


def worker():
    if os.environ.get("BENCH_SWEEP", "0") == "1":
        _sweep_worker()
        return
    if os.environ.get("BENCH_SERVE_LOAD", "0") == "1":
        _serve_load_worker()
        return
    if os.environ.get("BENCH_SERVE", "0") == "1":
        _serve_worker()
        return
    import jax

    if os.environ.get("BENCH_FORCE_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    # Persistent compilation cache: a tunnel-flap retry (or the sweep's
    # next config) skips the multi-minute ResNet50 compile entirely.
    # Enablement (version-scoped dir, size-floor lift, hit counting)
    # lives in parallel/compile_cache; CLOUD_TPU_COMPILE_CACHE in the
    # env overrides this default location or disables it.
    from cloud_tpu.parallel import compile_cache
    compile_cache.enable(COMPILE_CACHE_DIR, min_compile_time_secs=1.0)
    import optax

    from cloud_tpu.models import ResNet50
    from cloud_tpu.training import Trainer

    rng = np.random.default_rng(0)
    x = rng.normal(size=(BATCH, IMAGE, IMAGE, 3)).astype(np.float32)
    y = rng.integers(0, 1000, size=BATCH).astype(np.int32)
    bf16_input = os.environ.get("BENCH_BF16_INPUT", "0") == "1"
    if bf16_input:
        # Feed bf16. In THIS bench the batch is device-resident and
        # reused every step, so steady-state H2D is zero either way —
        # the measured effect is the stem's input HBM read width (the
        # model casts to compute dtype at the stem regardless,
        # cloud_tpu/models/resnet.py). A real input pipeline feeding
        # fresh batches additionally halves its per-step H2D bytes.
        import ml_dtypes
        x = x.astype(ml_dtypes.bfloat16)

    s2d = os.environ.get("BENCH_S2D", "0") == "1"
    trainer = Trainer(
        ResNet50(num_classes=1000, conv0_space_to_depth=s2d),
        optimizer=optax.sgd(0.1, momentum=0.9),
        train_kwargs={"train": True},
        eval_kwargs={"train": False},
        metrics=())
    trainer.build(x)

    # In-graph multi-step (steps_per_execution): BENCH_SPE optimizer
    # steps per dispatch via lax.scan over the SAME resident batch —
    # on the tunneled chip every dispatch costs a ~66ms round-trip
    # (PERF.md), so amortizing it across the chunk measures the chip,
    # not the tunnel. BENCH_SPE=1 preserves the round-2 methodology.
    spe = max(_env_int("BENCH_SPE", 1), 1)
    resident_mode = os.environ.get("BENCH_RESIDENT", "0") == "1"
    async_log = os.environ.get("BENCH_ASYNC_LOG", "0") == "1"
    resident = None
    from cloud_tpu.parallel import runtime as runtime_lib
    if resident_mode:
        # _res series: measure the Trainer's actual device-resident
        # executable — per-epoch threefry permutation + in-graph
        # gather over a multi-batch uploaded dataset — instead of
        # re-feeding one host batch. The H2D counter fields attached
        # to the record prove the pipeline's claim: one upload, zero
        # steady-state host->device bytes.
        import jax.numpy as jnp

        from cloud_tpu.training.data import (ArrayDataset,
                                             DeviceResidentDataset)
        n_examples = max(
            _env_int("BENCH_RESIDENT_EXAMPLES", BATCH * 2) // BATCH,
            1) * BATCH
        reps = -(-n_examples // BATCH)
        xr = np.concatenate([x] * reps, axis=0)[:n_examples]
        yr = np.concatenate([y] * reps, axis=0)[:n_examples]
        dataset = ArrayDataset(xr, yr, batch_size=BATCH, shuffle=True,
                               seed=0)
        runtime_lib.reset_transfer_stats()
        resident = DeviceResidentDataset(dataset)
        step_fn = trainer._make_resident_run(
            spe, resident.steps_per_epoch, resident, weighted=False)
        # Fixed device scalars: position wraps modulo steps_per_epoch
        # as state.step advances, cycling the uploaded epoch.
        step_inputs = (resident.data,
                       jnp.array(trainer.state.step, copy=True),
                       jnp.asarray(0, dtype=jnp.int32))
    elif spe > 1:
        inner = trainer._make_train_step_body()

        def chunk_fn(state, batch):
            def body(s, _):
                s, logs = inner(s, batch)
                return s, logs

            state, logs = jax.lax.scan(body, state, None, length=spe)
            return state, {k: v[-1] for k, v in logs.items()}

        step_fn = runtime_lib.instrumented_jit(chunk_fn, donate_argnums=0)
    else:
        step_fn = trainer._make_train_step()

    if not resident_mode:
        step_inputs = (trainer._feed((x, y)),)
    state = trainer.state

    # Time-to-first-step: everything between "step function exists"
    # and "step 1's loss is on the host" — trace + XLA compile (or a
    # persistent-cache hit) + the first dispatch. THE warm-vs-cold
    # contrast number: on a cache-hit restart it collapses from the
    # multi-minute ResNet50 compile to one dispatch.
    first_step_seconds = None
    _t_cold = time.perf_counter()

    # XLA's own FLOP count for one compiled step: turns the roofline
    # line from a hand constant (12.3 GFLOPs/image) into a
    # compiler-derived number. AOT-compile once and reuse the
    # executable for the timed loop (no second trace/compile).
    xla_flops = None
    try:
        compiled = step_fn.lower(state, *step_inputs).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returns [dict]
            cost = cost[0] if cost else {}
        flops = cost.get("flops")
        if flops and flops > 0:
            xla_flops = float(flops)
            step_fn = compiled
    except Exception as e:  # noqa: BLE001 - analysis is best-effort
        print("# cost_analysis unavailable: {}".format(e),
              file=sys.stderr)

    def sync(logs):
        """True barrier: fetch the loss VALUE to host.

        The tunneled TPU backend on this host acks block_until_ready()
        before execution finishes (measured: an 8192^3 matmul "completes"
        in 36us = 30 PFLOP/s), so only a device->host value fetch is an
        honest sync point. Costs one ~66ms tunnel round-trip per call —
        paid once per chunk, amortized over CHUNK steps. Routed through
        runtime.device_fetch so the record's d2h counters census every
        fetch the timed loop performs.
        """
        return float(runtime_lib.device_fetch(logs["loss"]))

    for _i in range(WARMUP_STEPS):
        state, logs = step_fn(state, *step_inputs)
        if _i == 0:
            sync(logs)
            first_step_seconds = time.perf_counter() - _t_cold
    if WARMUP_STEPS:
        sync(logs)

    # Steady-state d2h census covers the timed loop only: delta against
    # this snapshot, NOT a reset — the _res series' h2d fields need the
    # counters running since their pre-upload reset.
    _d2h_before = runtime_lib.transfer_stats()
    n_chunks = max(TIMED_STEPS // CHUNK, 1)
    if async_log:
        # _async series: the chunk loop never sync-fetches — each
        # chunk's loss goes to the background metric reader
        # (one coalesced off-thread fetch per chunk, the Trainer's
        # async_logging regime) and the loop runs on. Timing the WHOLE
        # loop through drain() is honest despite the early-acking
        # tunnel: the last chunk's fetched VALUE depends on the entire
        # donated-state chain, so the clock can't stop before every
        # step has truly executed. Median-chunk doesn't apply (there is
        # no per-chunk barrier to time against) — method says so.
        from cloud_tpu.training.async_logs import AsyncMetricReader

        reader = AsyncMetricReader()
        futures = []
        t0 = time.perf_counter()
        for _ in range(n_chunks):
            for _ in range(CHUNK):
                state, logs = step_fn(state, *step_inputs)
            futures.append(reader.submit({"loss": logs["loss"]}))
        reader.drain()
        futures[-1].result()
        total_elapsed = time.perf_counter() - t0
        reader.close()
        method = "async_total"
        images_per_sec = BATCH * CHUNK * n_chunks * spe / total_elapsed
    else:
        # Median contiguous chunk: robust to one-off stalls of the
        # shared chip tunnel (which measure the tunnel, not the step)
        # while still reporting sustained — not peak — throughput,
        # comparable with the sustained-average baseline.
        chunk_times = []
        for _ in range(n_chunks):
            t0 = time.perf_counter()
            for _ in range(CHUNK):
                state, logs = step_fn(state, *step_inputs)
            sync(logs)
            chunk_times.append(time.perf_counter() - t0)
        median_elapsed = sorted(chunk_times)[len(chunk_times) // 2]
        method = "median_chunk"
        images_per_sec = BATCH * CHUNK * spe / median_elapsed
    tflops = images_per_sec * RESNET50_GFLOPS_PER_IMAGE / 1000.0
    if xla_flops is not None:
        # cost_analysis counts a lax.scan/while body ONCE (verified on
        # this jax: scan(8) reports the same flops as one step), so the
        # spe>1 executable's true work is body_flops * spe. ResNet50
        # itself has no internal loops, so this is the only scaling
        # needed. dispatches/sec * per-dispatch flops = honest rate.
        dispatches_per_sec = images_per_sec / (BATCH * spe)
        tflops = dispatches_per_sec * (xla_flops * spe) / 1e12
    _d2h_after = runtime_lib.transfer_stats()
    # Compile census (whole worker process, not just the timed loop —
    # the timed loop's own invariant is "zero", which the steady-state
    # tests pin; the record's job is cold-vs-warm provenance).
    _cstats = runtime_lib.compile_stats()
    _pstats = compile_cache.stats()
    record = {
        "metric": _metric_name(),
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / BASELINE_IMAGES_PER_SEC, 3),
        "method": method,
        "chunk": CHUNK,
        "steps": n_chunks * CHUNK * spe,
        # The async-host-loop claim as numbers: device->host round
        # trips the timed loop performed (one coalesced fetch per
        # chunk in both regimes; _async just takes them off-thread).
        "d2h_fetches": (_d2h_after["d2h_fetches"]
                        - _d2h_before["d2h_fetches"]),
        "d2h_bytes": _d2h_after["d2h_bytes"] - _d2h_before["d2h_bytes"],
        "batch": BATCH,
        "image": IMAGE,
        "platform": jax.default_backend(),
        "tflops": round(tflops, 3),
        "pct_peak": round(100.0 * tflops / V5E_PEAK_TFLOPS, 1),
        "flops_source": ("xla_cost_analysis" if xla_flops is not None
                         else "estimate_12.3gflops_per_image"),
        # The compile-as-a-counted-resource claim, as numbers
        # (runtime.compile_stats doctrine): what this process traced
        # and compiled, what the persistent cache absorbed.
        "n_traces": _cstats["n_traces"],
        "n_compiles": _cstats["n_compiles"],
        "compile_seconds": round(_cstats["compile_seconds"], 3),
        "compile_cache_hits": _cstats["cache_hits"],
        "persistent_cache_hits": _pstats["persistent_hits"],
        "persistent_cache_misses": _pstats["persistent_misses"],
        # Self-describing capture: lets a later stale re-serve compare
        # what it is asked for against what this record measured.
        "requested_config": _requested_config(),
    }
    # graftscope: the census MFU number IS the telemetry MFU gauge —
    # one denominator (V5E_PEAK_TFLOPS == telemetry's default peak),
    # one value, surfaced both as `pct_peak` here and as
    # cloud_tpu_mfu_pct_peak in the Prometheus textfile when a
    # telemetry session is live. sys.modules.get keeps the disabled
    # bench import-free.
    _telemetry = sys.modules.get("cloud_tpu.monitoring.telemetry")
    if _telemetry is not None and _telemetry.enabled():
        _tele = _telemetry.get()
        _tele.registry.gauge(_telemetry.MFU_GAUGE).set(
            record["pct_peak"])
        _tele.flush()
    if first_step_seconds is not None:
        record["time_to_first_step_seconds"] = round(first_step_seconds, 3)
    if compile_cache.is_enabled():
        record["compile_cache_dir"] = compile_cache.cache_dir()
    if os.environ.get("BENCH_WARM", "0") == "1":
        record["warm"] = True
    if xla_flops is not None:
        record["xla_flops_per_dispatch"] = xla_flops
    if spe > 1:
        record["steps_per_execution"] = spe
    if async_log:
        record["async_log"] = True
    if s2d:
        record["stem"] = "space_to_depth"
    if bf16_input:
        record["input_dtype"] = "bfloat16"
    if resident_mode:
        stats = runtime_lib.transfer_stats()
        record["resident"] = True
        record["resident_examples"] = resident.num_examples
        record["h2d_upload_bytes"] = resident.upload_bytes
        # The pipeline's whole claim, as a number: counted bytes past
        # the one-time upload (0 when the resident path holds).
        record["h2d_steady_bytes"] = (stats["h2d_bytes"]
                                      - resident.upload_bytes)
        record["h2d_transfers"] = stats["h2d_transfers"]
    if os.environ.get("BENCH_LOCK_CONTENDED") == "1":
        # Another measurement driver may have shared the chip during
        # this run (the chip-lock wait timed out upstream).
        record["lock_contended"] = True
    # graftguard provenance: a record produced by a run that survived
    # faults is not the same measurement as a clean one — retries mean
    # the wall clock includes backoff and re-entry. Only stamped when
    # the resilience module is live AND saw at least one fault
    # (sys.modules.get keeps the common no-fault bench import-free).
    _resilience = sys.modules.get("cloud_tpu.training.resilience")
    if _resilience is not None:
        _gstats = _resilience.guard_stats()
        if _gstats["faults"]:
            record["guard_faults"] = _gstats["faults"]
            record["guard_retries"] = _gstats["retries"]
            record["guard_rollbacks"] = _gstats["rollbacks"]
            record["guard_last_fault"] = _gstats["last_fault"]
    if os.environ.get("BENCH_SKIP_KERNEL_PARITY", "0") != "1":
        # Emit the throughput record FIRST: if the kernel smoke hangs
        # the tunnel, the parent salvages this line from the killed
        # process's stdout instead of losing the measurement. The
        # enriched record below (last JSON line) wins when the smoke
        # completes.
        print(json.dumps(record), flush=True)
        record["kernel_parity"] = _kernel_parity_smoke(jax)
    print(json.dumps(record))


if __name__ == "__main__":
    if _IS_WORKER:
        worker()
    else:
        main()
