"""Benchmark harness: ResNet50 training throughput on one TPU chip.

BASELINE.md target: Keras `model.fit` steps/sec via the launch API on
v5e-8 matching 8xV100 wall-clock. The reference publishes no numbers
(BASELINE.md "Published reference numbers: None"), so the recorded
baseline is the 8xV100 side of the driver's target: ResNet50 mixed
precision at ~2800 images/sec across 8 V100s = 350 images/sec per
V100-equivalent. This harness measures our per-chip ResNet50 train-step
throughput (bf16, NHWC, batch 256) through the framework's own jitted
Trainer step; vs_baseline > 1.0 means one v5e chip beats one V100, i.e.
v5e-8 beats 8xV100 wall-clock for config 2.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": "images/sec", "vs_baseline": N}
"""

import json
import os
import time

import numpy as np

BATCH = int(os.environ.get("BENCH_BATCH", 256))
IMAGE = int(os.environ.get("BENCH_IMAGE", 224))
WARMUP_STEPS = int(os.environ.get("BENCH_WARMUP", 3))
TIMED_STEPS = int(os.environ.get("BENCH_STEPS", 20))
CHUNK = min(int(os.environ.get("BENCH_CHUNK", 5)), TIMED_STEPS)
BASELINE_IMAGES_PER_SEC = 350.0  # one V100, fp16 ResNet50 (8xV100 / 8)


def main():
    import jax
    import optax

    from cloud_tpu.models import ResNet50
    from cloud_tpu.training import Trainer

    rng = np.random.default_rng(0)
    x = rng.normal(size=(BATCH, IMAGE, IMAGE, 3)).astype(np.float32)
    y = rng.integers(0, 1000, size=BATCH).astype(np.int32)

    s2d = os.environ.get("BENCH_S2D", "0") == "1"
    trainer = Trainer(
        ResNet50(num_classes=1000, conv0_space_to_depth=s2d),
        optimizer=optax.sgd(0.1, momentum=0.9),
        train_kwargs={"train": True},
        eval_kwargs={"train": False},
        metrics=())
    trainer.build(x)
    step_fn = trainer._make_train_step()

    batch = trainer._feed((x, y))
    state = trainer.state
    for _ in range(WARMUP_STEPS):
        state, logs = step_fn(state, batch)
    jax.block_until_ready(logs["loss"])

    # Median contiguous chunk: robust to one-off stalls of the shared
    # chip tunnel (which measure the tunnel, not the step) while still
    # reporting sustained — not peak — throughput, comparable with the
    # sustained-average baseline.
    chunk_times = []
    for _ in range(max(TIMED_STEPS // CHUNK, 1)):
        t0 = time.perf_counter()
        for _ in range(CHUNK):
            state, logs = step_fn(state, batch)
        jax.block_until_ready(logs["loss"])
        chunk_times.append(time.perf_counter() - t0)
    median_elapsed = sorted(chunk_times)[len(chunk_times) // 2]

    images_per_sec = BATCH * CHUNK / median_elapsed
    record = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / BASELINE_IMAGES_PER_SEC, 3),
    }
    if s2d:
        # Architecture variant: mark it so recorded numbers stay
        # apples-to-apples with the standard stem.
        record["metric"] += "_s2d"
        record["stem"] = "space_to_depth"
    print(json.dumps(record))


if __name__ == "__main__":
    main()
