#include "http_transport.h"

#include <dlfcn.h>

#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <mutex>
#include <string>

#include "config.h"

namespace cloud_tpu {
namespace monitoring {

namespace {

// Minimal libcurl C ABI surface, resolved at runtime. The option values
// are part of curl's stable public ABI (curl/curl.h).
typedef void CURL;
struct curl_slist;

constexpr int kCurloptUrl = 10002;
constexpr int kCurloptHttpHeader = 10023;
constexpr int kCurloptPostFields = 10015;
constexpr int kCurloptWriteFunction = 20011;
constexpr int kCurloptWriteData = 10001;
constexpr int kCurloptTimeout = 13;
constexpr int kCurloptNoSignal = 99;
constexpr int kCurloptPost = 47;
constexpr int kCurlinfoResponseCode = 0x200002;

constexpr long kCurlGlobalAll = 3;

struct CurlApi {
  CURL* (*easy_init)() = nullptr;
  int (*easy_setopt)(CURL*, int, ...) = nullptr;
  int (*easy_perform)(CURL*) = nullptr;
  void (*easy_cleanup)(CURL*) = nullptr;
  int (*easy_getinfo)(CURL*, int, ...) = nullptr;
  curl_slist* (*slist_append)(curl_slist*, const char*) = nullptr;
  void (*slist_free_all)(curl_slist*) = nullptr;
  int (*global_init)(long) = nullptr;

  bool ok() const {
    return easy_init && easy_setopt && easy_perform && easy_cleanup &&
           easy_getinfo && slist_append && slist_free_all;
  }
};

const CurlApi* GetCurl() {
  static CurlApi* api = [] {
    const char* names[] = {"libcurl.so.4", "libcurl-gnutls.so.4",
                           "libcurl.so"};
    void* handle = nullptr;
    for (const char* name : names) {
      handle = dlopen(name, RTLD_NOW | RTLD_LOCAL);
      if (handle != nullptr) break;
    }
    if (handle == nullptr) return static_cast<CurlApi*>(nullptr);
    auto* out = new CurlApi();
    out->easy_init = reinterpret_cast<CURL* (*)()>(
        dlsym(handle, "curl_easy_init"));
    out->easy_setopt = reinterpret_cast<int (*)(CURL*, int, ...)>(
        dlsym(handle, "curl_easy_setopt"));
    out->easy_perform = reinterpret_cast<int (*)(CURL*)>(
        dlsym(handle, "curl_easy_perform"));
    out->easy_cleanup = reinterpret_cast<void (*)(CURL*)>(
        dlsym(handle, "curl_easy_cleanup"));
    out->easy_getinfo = reinterpret_cast<int (*)(CURL*, int, ...)>(
        dlsym(handle, "curl_easy_getinfo"));
    out->slist_append = reinterpret_cast<curl_slist* (*)(
        curl_slist*, const char*)>(dlsym(handle, "curl_slist_append"));
    out->slist_free_all = reinterpret_cast<void (*)(curl_slist*)>(
        dlsym(handle, "curl_slist_free_all"));
    out->global_init = reinterpret_cast<int (*)(long)>(
        dlsym(handle, "curl_global_init"));
    if (!out->ok()) {
      delete out;
      return static_cast<CurlApi*>(nullptr);
    }
    // Implicit global init from curl_easy_init is not thread-safe;
    // the exporter thread and a main-thread flush() can race first
    // use. Init once here, under this static's own init lock.
    if (out->global_init != nullptr) out->global_init(kCurlGlobalAll);
    return out;
  }();
  return api;
}

size_t AppendToString(char* data, size_t size, size_t nmemb,
                      void* userdata) {
  static_cast<std::string*>(userdata)->append(data, size * nmemb);
  return size * nmemb;
}

// One bounded HTTP round trip. GET when body is nullptr.
bool Perform(const std::string& url, const std::string* body,
             curl_slist* headers, std::string* response) {
  const CurlApi* curl = GetCurl();
  if (curl == nullptr) return false;
  CURL* handle = curl->easy_init();
  if (handle == nullptr) return false;
  curl->easy_setopt(handle, kCurloptUrl, url.c_str());
  curl->easy_setopt(handle, kCurloptNoSignal, 1L);
  curl->easy_setopt(handle, kCurloptTimeout, 15L);
  if (body != nullptr) {
    curl->easy_setopt(handle, kCurloptPost, 1L);
    curl->easy_setopt(handle, kCurloptPostFields, body->c_str());
  }
  if (headers != nullptr) {
    curl->easy_setopt(handle, kCurloptHttpHeader, headers);
  }
  curl->easy_setopt(handle, kCurloptWriteFunction, AppendToString);
  curl->easy_setopt(handle, kCurloptWriteData,
                    static_cast<void*>(response));
  int rc = curl->easy_perform(handle);
  long status = 0;
  if (rc == 0) curl->easy_getinfo(handle, kCurlinfoResponseCode, &status);
  curl->easy_cleanup(handle);
  return rc == 0 && status >= 200 && status < 300;
}

// Crude but dependency-free: pull "access_token":"..." out of the
// metadata server's JSON reply.
std::string ParseAccessToken(const std::string& json) {
  const std::string key = "\"access_token\"";
  size_t pos = json.find(key);
  if (pos == std::string::npos) return "";
  pos = json.find('"', json.find(':', pos + key.size()));
  if (pos == std::string::npos) return "";
  size_t end = json.find('"', pos + 1);
  if (end == std::string::npos) return "";
  return json.substr(pos + 1, end - pos - 1);
}

std::mutex g_token_mu;
std::string g_cached_token;
std::chrono::steady_clock::time_point g_token_expiry;

std::string AccessToken() {
  // Explicit token beats the metadata server (tests, off-GCP runs).
  const char* env_token = std::getenv("CLOUD_TPU_MONITORING_TOKEN");
  if (env_token != nullptr && env_token[0] != '\0') return env_token;

  std::lock_guard<std::mutex> lock(g_token_mu);
  auto now = std::chrono::steady_clock::now();
  // May be empty: failures are negatively cached so an off-GCP host
  // doesn't block every export tick on a metadata round trip.
  if (now < g_token_expiry) return g_cached_token;
  // Default-credentials path on GCE/TPU-VM (the REST analogue of the
  // reference's GoogleDefaultCredentials, stackdriver_client.cc:56-58).
  const CurlApi* curl = GetCurl();
  if (curl == nullptr) return "";
  curl_slist* headers =
      curl->slist_append(nullptr, "Metadata-Flavor: Google");
  std::string response;
  bool ok = Perform(
      "http://metadata.google.internal/computeMetadata/v1/instance/"
      "service-accounts/default/token",
      nullptr, headers, &response);
  curl->slist_free_all(headers);
  if (!ok) {
    g_cached_token.clear();
    g_token_expiry = now + std::chrono::seconds(30);
    return "";
  }
  g_cached_token = ParseAccessToken(response);
  // Tokens last ~1h; refresh well before that.
  g_token_expiry = now + std::chrono::minutes(5);
  return g_cached_token;
}

}  // namespace

// The request builders synthesize gRPC-shaped wrappers
// ({"name":"projects/p","metricDescriptor"/"timeSeries":...}) — the
// canonical form the golden tests and FileTransport record. The REST
// bindings put the project in the URL instead: metricDescriptors.create
// takes the bare MetricDescriptor as its body, timeSeries.create takes
// {"timeSeries":[...]}. Re-shape here (the wrappers are our own output,
// so positional extraction is safe — no JSON parser needed).
std::string RestBody(const std::string& method, const std::string& json) {
  if (method == "CreateMetricDescriptor") {
    const std::string key = "\"metricDescriptor\":";
    size_t pos = json.find(key);
    if (pos != std::string::npos && !json.empty() &&
        json.back() == '}') {
      size_t start = pos + key.size();
      return json.substr(start, json.size() - start - 1);
    }
  } else {
    const std::string key = "\"timeSeries\":";
    size_t pos = json.find(key);
    if (pos != std::string::npos) {
      return "{" + json.substr(pos);
    }
  }
  return json;
}

bool HttpTransportAvailable() { return GetCurl() != nullptr; }

bool HttpSend(const std::string& endpoint, const std::string& project_id,
              const std::string& method, const std::string& json) {
  const CurlApi* curl = GetCurl();
  if (curl == nullptr) {
    static bool warned = [] {
      std::fprintf(stderr,
                   "cloud_tpu_monitoring: http transport requested but "
                   "libcurl is not loadable; dropping metrics.\n");
      return true;
    }();
    (void)warned;
    return false;
  }
  std::string path = (method == "CreateMetricDescriptor")
                         ? "/metricDescriptors"
                         : "/timeSeries";
  std::string url =
      endpoint + "/v3/projects/" + project_id + path;
  curl_slist* headers =
      curl->slist_append(nullptr, "Content-Type: application/json");
  std::string token = AccessToken();
  if (!token.empty()) {
    headers = curl->slist_append(
        headers, ("Authorization: Bearer " + token).c_str());
  }
  std::string body = RestBody(method, json);
  std::string response;
  bool ok = Perform(url, &body, headers, &response);
  curl->slist_free_all(headers);
  if (!ok) {
    std::fprintf(stderr,
                 "cloud_tpu_monitoring: %s POST to %s failed%s%s\n",
                 method.c_str(), url.c_str(),
                 response.empty() ? "" : ": ", response.c_str());
  }
  return ok;
}

}  // namespace monitoring
}  // namespace cloud_tpu
