#include "metrics_registry.h"

#include <algorithm>
#include <chrono>

namespace cloud_tpu {
namespace monitoring {

namespace {
int64_t NowMicros() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}
}  // namespace

MetricsRegistry* MetricsRegistry::Get() {
  static MetricsRegistry* registry = new MetricsRegistry();
  return registry;
}

void MetricsRegistry::IncrementCounter(const std::string& name,
                                       int64_t delta) {
  std::lock_guard<std::mutex> lock(mu_);
  auto& metric = metrics_[name];
  if (metric.start_time_micros == 0) metric.start_time_micros = NowMicros();
  metric.kind = MetricKind::kCounter;
  metric.counter += delta;
}

void MetricsRegistry::SetGauge(const std::string& name, double value) {
  std::lock_guard<std::mutex> lock(mu_);
  auto& metric = metrics_[name];
  if (metric.start_time_micros == 0) metric.start_time_micros = NowMicros();
  metric.kind = MetricKind::kGauge;
  metric.gauge = value;
}

void MetricsRegistry::ObserveHistogram(const std::string& name,
                                       double value,
                                       const std::vector<double>& bounds) {
  std::lock_guard<std::mutex> lock(mu_);
  auto& metric = metrics_[name];
  if (metric.start_time_micros == 0) metric.start_time_micros = NowMicros();
  if (metric.histogram.bucket_bounds.empty()) {
    metric.kind = MetricKind::kHistogram;
    metric.histogram.bucket_bounds = bounds;
    metric.histogram.bucket_counts.assign(bounds.size() + 1, 0);
  }
  auto& h = metric.histogram;
  // First bucket whose upper bound is > value; last bucket overflows.
  size_t idx = std::upper_bound(h.bucket_bounds.begin(),
                                h.bucket_bounds.end(), value) -
               h.bucket_bounds.begin();
  h.bucket_counts[idx] += 1;
  h.sum += value;
  h.sum_squares += value * value;
  h.count += 1;
}

void MetricsRegistry::SetDescription(const std::string& name,
                                     const std::string& description) {
  std::lock_guard<std::mutex> lock(mu_);
  metrics_[name].description = description;
}

std::vector<MetricSnapshot> MetricsRegistry::Snapshot() const {
  std::lock_guard<std::mutex> lock(mu_);
  std::vector<MetricSnapshot> out;
  out.reserve(metrics_.size());
  const int64_t now = NowMicros();
  for (const auto& entry : metrics_) {
    MetricSnapshot snap;
    snap.name = entry.first;
    snap.description = entry.second.description;
    snap.kind = entry.second.kind;
    snap.counter_value = entry.second.counter;
    snap.gauge_value = entry.second.gauge;
    snap.histogram = entry.second.histogram;
    snap.timestamp_micros = now;
    snap.start_time_micros = entry.second.start_time_micros;
    out.push_back(std::move(snap));
  }
  return out;
}

void MetricsRegistry::Reset() {
  std::lock_guard<std::mutex> lock(mu_);
  metrics_.clear();
}

}  // namespace monitoring
}  // namespace cloud_tpu
