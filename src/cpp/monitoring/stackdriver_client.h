// Cloud Monitoring request builder + pluggable transport.
//
// Reference parity: src/cpp/monitoring/stackdriver_client.{h,cc} — the
// singleton client that converts runtime metrics into Cloud Monitoring
// `CreateTimeSeries` / `CreateMetricDescriptor` requests (histogram ->
// Distribution with mean/sum-of-squared-deviation/bucket bounds,
// client.cc:69-98; kind/value-type mapping, client.cc:138-183; metric
// type prefix `custom.googleapis.com`, client.cc:46). The reference
// serializes to googleapis protos over gRPC; this implementation
// produces the canonical JSON encodings of the same protos and hands
// them to a pluggable Transport (file/stderr by default, a gRPC or REST
// sender in deployment) so the conversion layer is fully testable with
// golden JSON (the reference pins golden protos the same way,
// stackdriver_client_test.cc:79-156).

#ifndef CLOUD_TPU_MONITORING_STACKDRIVER_CLIENT_H_
#define CLOUD_TPU_MONITORING_STACKDRIVER_CLIENT_H_

#include <functional>
#include <string>
#include <vector>

#include "metrics_registry.h"

namespace cloud_tpu {
namespace monitoring {

// Sends one serialized request; returns false on failure.
// method is "CreateTimeSeries" or "CreateMetricDescriptor".
using Transport =
    std::function<bool(const std::string& method, const std::string& json)>;

// C-ABI transport override (registered via the C API so an embedding
// process — e.g. Python with an authenticated google client — does the
// send). Non-zero return = success.
using TransportCallback = int (*)(const char* method, const char* json);
void SetTransportCallback(TransportCallback callback);

// The default transport used by the singleton: a registered callback
// wins; else CLOUD_TPU_MONITORING_TRANSPORT=http selects the libcurl
// REST sender; else FileTransport (tests/offline).
Transport DispatchTransport();

class StackdriverClient {
 public:
  // Singleton wired to the env-configured project and the default
  // transport (reference client.cc:45-61).
  static StackdriverClient* Get();

  StackdriverClient(std::string project_id, Transport transport);

  // Builds + sends a CreateTimeSeries request for the snapshots.
  // Returns the serialized request (empty when there was nothing to
  // send).
  std::string CreateTimeSeries(
      const std::vector<MetricSnapshot>& snapshots);

  // Builds + sends a CreateMetricDescriptor request for one metric.
  std::string CreateMetricDescriptor(const MetricSnapshot& snapshot);

  // Conversion helpers (exposed for golden tests).
  static std::string TimeSeriesJson(const std::string& project_id,
                                    const std::vector<MetricSnapshot>& s);
  static std::string MetricDescriptorJson(const std::string& project_id,
                                          const MetricSnapshot& s);

 private:
  std::string project_id_;
  Transport transport_;
};

// Default transport: appends JSONL records to
// $CLOUD_TPU_MONITORING_EXPORT_PATH (or stderr when unset). The
// deployment gRPC sender plugs in here without touching conversion.
Transport FileTransport(const std::string& path);

}  // namespace monitoring
}  // namespace cloud_tpu

#endif  // CLOUD_TPU_MONITORING_STACKDRIVER_CLIENT_H_
