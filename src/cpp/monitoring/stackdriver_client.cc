#include "stackdriver_client.h"

#include <atomic>
#include <cstdio>
#include <sstream>

#include "config.h"
#include "http_transport.h"

namespace cloud_tpu {
namespace monitoring {

namespace {

// Metric type prefix (reference stackdriver_client.cc:46). Metric names
// already carry their /cloud_tpu/... namespace, so the prefix is the
// bare custom-metrics domain.
const char kMetricTypePrefix[] = "custom.googleapis.com";

std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      default: out += c;
    }
  }
  return out;
}

std::string FormatDouble(double v) {
  char buf[64];
  std::snprintf(buf, sizeof(buf), "%g", v);
  return buf;
}

// Interval from snapshot timestamps (reference converts timestamps at
// client.cc:63-67). CUMULATIVE kinds must carry a startTime strictly
// earlier than endTime, so pass start_micros > 0 for counters and
// histograms; GAUGE intervals are end-only (start_micros == 0).
std::string IntervalJson(int64_t micros, int64_t start_micros = 0) {
  std::stringstream out;
  out << "{";
  if (start_micros > 0) {
    if (micros <= start_micros) micros = start_micros + 1;
    out << "\"startTime\":{\"seconds\":" << start_micros / 1000000
        << ",\"nanos\":" << (start_micros % 1000000) * 1000 << "},";
  }
  out << "\"endTime\":{\"seconds\":" << micros / 1000000
      << ",\"nanos\":" << (micros % 1000000) * 1000 << "}}";
  return out.str();
}

// Histogram -> Cloud Monitoring Distribution (reference
// client.cc:69-98: mean, sum-of-squared-deviation, explicit bounds).
std::string DistributionJson(const HistogramData& h) {
  const double mean = h.count > 0 ? h.sum / h.count : 0.0;
  // sum((x - mean)^2) = sum(x^2) - n*mean^2.
  const double ssd =
      h.count > 0 ? h.sum_squares - h.count * mean * mean : 0.0;
  std::stringstream out;
  out << "{\"count\":" << h.count << ",\"mean\":" << FormatDouble(mean)
      << ",\"sumOfSquaredDeviation\":" << FormatDouble(ssd)
      << ",\"bucketOptions\":{\"explicitBuckets\":{\"bounds\":[";
  for (size_t i = 0; i < h.bucket_bounds.size(); ++i) {
    if (i) out << ",";
    out << FormatDouble(h.bucket_bounds[i]);
  }
  out << "]}},\"bucketCounts\":[";
  for (size_t i = 0; i < h.bucket_counts.size(); ++i) {
    if (i) out << ",";
    out << h.bucket_counts[i];
  }
  out << "]}";
  return out.str();
}

// One TimeSeries entry. Only the latest point is sent per series
// (reference keeps the first point only, client.cc:133-135 — one point
// per CreateTimeSeries call is a service requirement).
std::string OneSeriesJson(const std::string& project_id,
                          const MetricSnapshot& s) {
  std::stringstream out;
  out << "{\"metric\":{\"type\":\"" << kMetricTypePrefix
      << JsonEscape(s.name) << "\"},\"resource\":{\"type\":\"global\","
      << "\"labels\":{\"project_id\":\"" << JsonEscape(project_id)
      << "\"}},";
  switch (s.kind) {
    case MetricKind::kCounter:
      out << "\"metricKind\":\"CUMULATIVE\",\"valueType\":\"INT64\","
          << "\"points\":[{\"interval\":"
          << IntervalJson(s.timestamp_micros, s.start_time_micros)
          << ",\"value\":{\"int64Value\":" << s.counter_value << "}}]";
      break;
    case MetricKind::kGauge:
      out << "\"metricKind\":\"GAUGE\",\"valueType\":\"DOUBLE\","
          << "\"points\":[{\"interval\":"
          << IntervalJson(s.timestamp_micros)
          << ",\"value\":{\"doubleValue\":"
          << FormatDouble(s.gauge_value) << "}}]";
      break;
    case MetricKind::kHistogram:
      out << "\"metricKind\":\"CUMULATIVE\",\"valueType\":"
          << "\"DISTRIBUTION\",\"points\":[{\"interval\":"
          << IntervalJson(s.timestamp_micros, s.start_time_micros)
          << ",\"value\":{\"distributionValue\":"
          << DistributionJson(s.histogram) << "}}]";
      break;
  }
  out << "}";
  return out.str();
}

}  // namespace

std::string StackdriverClient::TimeSeriesJson(
    const std::string& project_id,
    const std::vector<MetricSnapshot>& snapshots) {
  if (snapshots.empty()) return "";
  std::stringstream out;
  out << "{\"name\":\"projects/" << JsonEscape(project_id)
      << "\",\"timeSeries\":[";
  for (size_t i = 0; i < snapshots.size(); ++i) {
    if (i) out << ",";
    out << OneSeriesJson(project_id, snapshots[i]);
  }
  out << "]}";
  return out.str();
}

std::string StackdriverClient::MetricDescriptorJson(
    const std::string& project_id, const MetricSnapshot& s) {
  // Kind/value-type mapping (reference client.cc:138-183).
  const char* kind = s.kind == MetricKind::kGauge ? "GAUGE" : "CUMULATIVE";
  const char* value_type =
      s.kind == MetricKind::kCounter
          ? "INT64"
          : (s.kind == MetricKind::kGauge ? "DOUBLE" : "DISTRIBUTION");
  std::stringstream out;
  out << "{\"name\":\"projects/" << JsonEscape(project_id)
      << "\",\"metricDescriptor\":{\"type\":\"" << kMetricTypePrefix
      << JsonEscape(s.name) << "\",\"metricKind\":\"" << kind
      << "\",\"valueType\":\"" << value_type << "\",\"description\":\""
      << JsonEscape(s.description) << "\"}}";
  return out.str();
}

StackdriverClient::StackdriverClient(std::string project_id,
                                     Transport transport)
    : project_id_(std::move(project_id)),
      transport_(std::move(transport)) {}

namespace {

// Host-process override: a C-ABI callback registered through the C API
// (cloud_tpu_set_transport). Lets an embedding Python process send with
// its own authenticated client while the C++ exporter keeps owning
// collection, filtering, and request synthesis.
std::atomic<TransportCallback> g_transport_callback{nullptr};

}  // namespace

void SetTransportCallback(TransportCallback callback) {
  g_transport_callback.store(callback);
}

Transport DispatchTransport() {
  // Resolved per send (not per process): respects a callback registered
  // after startup and Config::ResetForTesting re-reads of the env.
  return [](const std::string& method, const std::string& json) {
    TransportCallback callback = g_transport_callback.load();
    if (callback != nullptr) {
      return callback(method.c_str(), json.c_str()) != 0;
    }
    const Config* config = Config::Get();
    if (config->transport() == "http") {
      // Real Cloud Monitoring REST sends (the reference's gRPC channel
      // equivalent, stackdriver_client.cc:45-61).
      return HttpSend(config->endpoint(), config->project_id(), method,
                      json);
    }
    return FileTransport(config->export_path())(method, json);
  };
}

StackdriverClient* StackdriverClient::Get() {
  static StackdriverClient* client = [] {
    const Config* config = Config::Get();
    return new StackdriverClient(config->project_id(),
                                 DispatchTransport());
  }();
  return client;
}

std::string StackdriverClient::CreateTimeSeries(
    const std::vector<MetricSnapshot>& snapshots) {
  std::string request = TimeSeriesJson(project_id_, snapshots);
  if (!request.empty() && transport_) {
    transport_("CreateTimeSeries", request);
  }
  return request;
}

std::string StackdriverClient::CreateMetricDescriptor(
    const MetricSnapshot& snapshot) {
  std::string request = MetricDescriptorJson(project_id_, snapshot);
  if (transport_) transport_("CreateMetricDescriptor", request);
  return request;
}

Transport FileTransport(const std::string& path) {
  return [path](const std::string& method, const std::string& json) {
    FILE* out = path.empty() ? stderr : std::fopen(path.c_str(), "a");
    if (out == nullptr) return false;
    std::fprintf(out, "{\"method\":\"%s\",\"request\":%s}\n",
                 method.c_str(), json.c_str());
    if (!path.empty()) std::fclose(out);
    return true;
  };
}

}  // namespace monitoring
}  // namespace cloud_tpu
