// Metrics registry for the cloud_tpu native runtime.
//
// The reference's exporter reads TensorFlow's global CollectionRegistry
// (reference src/cpp/monitoring/stackdriver_exporter.cc:86-89). This
// framework owns its metric source: a process-global, thread-safe
// registry of int64 counters, double gauges, and histograms with
// explicit bucket bounds — the shapes the Cloud Monitoring conversion
// layer (stackdriver_client.{h,cc}) understands.

#ifndef CLOUD_TPU_MONITORING_METRICS_REGISTRY_H_
#define CLOUD_TPU_MONITORING_METRICS_REGISTRY_H_

#include <cstdint>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

namespace cloud_tpu {
namespace monitoring {

struct HistogramData {
  std::vector<double> bucket_bounds;  // ascending upper bounds
  std::vector<int64_t> bucket_counts;  // size = bounds + 1 (overflow)
  double sum = 0.0;
  double sum_squares = 0.0;
  int64_t count = 0;
};

enum class MetricKind { kCounter, kGauge, kHistogram };

struct MetricSnapshot {
  std::string name;
  std::string description;
  MetricKind kind = MetricKind::kCounter;
  int64_t counter_value = 0;
  double gauge_value = 0.0;
  HistogramData histogram;
  int64_t timestamp_micros = 0;
  // Creation time of the underlying metric; CUMULATIVE time series must
  // report an interval start earlier than the end.
  int64_t start_time_micros = 0;
};

// Process-global registry. All operations are thread-safe.
class MetricsRegistry {
 public:
  static MetricsRegistry* Get();

  void IncrementCounter(const std::string& name, int64_t delta);
  void SetGauge(const std::string& name, double value);
  // Creates the histogram on first observation with the given bounds
  // (subsequent bounds arguments are ignored).
  void ObserveHistogram(const std::string& name, double value,
                        const std::vector<double>& bounds);
  void SetDescription(const std::string& name,
                      const std::string& description);

  std::vector<MetricSnapshot> Snapshot() const;
  void Reset();  // test isolation

 private:
  struct Metric {
    MetricKind kind;
    std::string description;
    int64_t counter = 0;
    double gauge = 0.0;
    HistogramData histogram;
    int64_t start_time_micros = 0;
  };

  mutable std::mutex mu_;
  std::map<std::string, Metric> metrics_;
};

}  // namespace monitoring
}  // namespace cloud_tpu

#endif  // CLOUD_TPU_MONITORING_METRICS_REGISTRY_H_
