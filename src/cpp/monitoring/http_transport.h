// Production network transport: HTTPS POST to the Cloud Monitoring REST
// API via libcurl (loaded at runtime with dlopen, so the library builds
// and tests without curl development headers installed).
//
// Reference parity: src/cpp/monitoring/stackdriver_client.cc:45-61 — the
// reference opens a gRPC channel to monitoring.googleapis.com with
// GoogleDefaultCredentials. The equivalent here speaks the same API's
// canonical JSON/REST surface:
//   POST {endpoint}/v3/projects/{project}/timeSeries
//   POST {endpoint}/v3/projects/{project}/metricDescriptors
// with a Bearer token from CLOUD_TPU_MONITORING_TOKEN or (the on-GCP
// default-credentials path) the GCE/TPU-VM metadata server.

#ifndef CLOUD_TPU_MONITORING_HTTP_TRANSPORT_H_
#define CLOUD_TPU_MONITORING_HTTP_TRANSPORT_H_

#include <string>

#include "stackdriver_client.h"

namespace cloud_tpu {
namespace monitoring {

// True when libcurl could be loaded on this host.
bool HttpTransportAvailable();

// Sends one request; returns true on HTTP 2xx. `endpoint` has no
// trailing slash (default "https://monitoring.googleapis.com"). The
// Bearer token comes from CLOUD_TPU_MONITORING_TOKEN when set, else
// from the metadata server (cached; failures negatively cached).
bool HttpSend(const std::string& endpoint, const std::string& project_id,
              const std::string& method, const std::string& json);

// Re-shapes a builder wrapper into the REST request body (bare
// MetricDescriptor / {"timeSeries":[...]}). Exposed for tests.
std::string RestBody(const std::string& method, const std::string& json);

}  // namespace monitoring
}  // namespace cloud_tpu

#endif  // CLOUD_TPU_MONITORING_HTTP_TRANSPORT_H_
