// CHECK-based tests (gtest is not in this image). Mirrors the
// reference's golden-request tests (stackdriver_client_test.cc:86-212):
// exact serialized-request matching for both RPC builders, plus
// registry/whitelist/exporter behavior with a capturing transport.
// CHECK (below) is always-on — unlike assert, which -DNDEBUG compiles
// out, silently skipping every test in a Release build; the reference's
// gtest assertions survive any build type, so must these.

#include <cstdio>
#include <cstdlib>
#include <string>
#include <vector>

#include "config.h"
#include "exporter.h"
#include "http_transport.h"
#include "metrics_registry.h"
#include "stackdriver_client.h"

using cloud_tpu::monitoring::Config;
using cloud_tpu::monitoring::Exporter;
using cloud_tpu::monitoring::HistogramData;
using cloud_tpu::monitoring::MetricKind;
using cloud_tpu::monitoring::MetricSnapshot;
using cloud_tpu::monitoring::MetricsRegistry;
using cloud_tpu::monitoring::StackdriverClient;

#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      std::fprintf(stderr, "CHECK failed %s:%d: %s\n", __FILE__,      \
                   __LINE__, #cond);                                  \
      std::exit(1);                                                   \
    }                                                                 \
  } while (0)

#define CHECK_CONTAINS(haystack, needle)                              \
  do {                                                                \
    if ((haystack).find(needle) == std::string::npos) {               \
      std::fprintf(stderr, "FAIL %s:%d: %s not found in:\n%s\n",      \
                   __FILE__, __LINE__, needle, (haystack).c_str());   \
      std::exit(1);                                                   \
    }                                                                 \
  } while (0)

namespace {

MetricSnapshot CounterSnap(const std::string& name, int64_t value) {
  MetricSnapshot s;
  s.name = name;
  s.kind = MetricKind::kCounter;
  s.counter_value = value;
  s.timestamp_micros = 1500000000000000;  // fixed for golden output
  s.start_time_micros = 1400000000000000;
  return s;
}

void TestTimeSeriesGolden() {
  MetricSnapshot s = CounterSnap("/cloud_tpu/training/steps", 42);
  std::string json = StackdriverClient::TimeSeriesJson("proj", {s});
  // Golden request (reference pins exact protos,
  // stackdriver_client_test.cc:97-156).
  const std::string expected =
      "{\"name\":\"projects/proj\",\"timeSeries\":[{\"metric\":{\"type\":"
      "\"custom.googleapis.com/cloud_tpu/training/steps\"},"
      "\"resource\":{\"type\":\"global\",\"labels\":{\"project_id\":"
      "\"proj\"}},\"metricKind\":\"CUMULATIVE\",\"valueType\":\"INT64\","
      "\"points\":[{\"interval\":{\"startTime\":{\"seconds\":1400000000,"
      "\"nanos\":0},\"endTime\":{\"seconds\":1500000000,"
      "\"nanos\":0}},\"value\":{\"int64Value\":42}}]}]}";
  CHECK(json == expected);
}

void TestDistributionConversion() {
  MetricsRegistry::Get()->Reset();
  std::vector<double> bounds = {1.0, 10.0, 100.0};
  MetricsRegistry::Get()->ObserveHistogram("/h", 0.5, bounds);
  MetricsRegistry::Get()->ObserveHistogram("/h", 5.0, bounds);
  MetricsRegistry::Get()->ObserveHistogram("/h", 500.0, bounds);
  auto snaps = MetricsRegistry::Get()->Snapshot();
  CHECK(snaps.size() == 1);
  const HistogramData& h = snaps[0].histogram;
  CHECK(h.count == 3);
  CHECK(h.bucket_counts.size() == 4);
  CHECK(h.bucket_counts[0] == 1);  // 0.5 <= 1
  CHECK(h.bucket_counts[1] == 1);  // 5 <= 10
  CHECK(h.bucket_counts[3] == 1);  // 500 overflow
  std::string json = StackdriverClient::TimeSeriesJson("p", snaps);
  CHECK_CONTAINS(json, "\"distributionValue\"");
  CHECK_CONTAINS(json, "\"count\":3");
  // mean = 505.5/3 = 168.5
  CHECK_CONTAINS(json, "\"mean\":168.5");
  CHECK_CONTAINS(json, "\"bounds\":[1,10,100]");
  CHECK_CONTAINS(json, "\"bucketCounts\":[1,1,0,1]");
}

void TestDescriptorGolden() {
  MetricSnapshot s = CounterSnap("/cloud_tpu/training/steps", 1);
  s.description = "Completed training steps";
  std::string json = StackdriverClient::MetricDescriptorJson("proj", s);
  const std::string expected =
      "{\"name\":\"projects/proj\",\"metricDescriptor\":{\"type\":"
      "\"custom.googleapis.com/cloud_tpu/training/steps\","
      "\"metricKind\":\"CUMULATIVE\",\"valueType\":\"INT64\","
      "\"description\":\"Completed training steps\"}}";
  CHECK(json == expected);
}

void TestWhitelistAndGate() {
  Config::ResetForTesting();
  unsetenv(cloud_tpu::monitoring::kWhitelistEnvVar);
  unsetenv(cloud_tpu::monitoring::kEnabledEnvVar);
  const Config* config = Config::Get();
  CHECK(config->IsWhitelisted("/cloud_tpu/training/steps"));
  CHECK(!config->IsWhitelisted("/not/registered"));
  CHECK(!config->enabled());

  Config::ResetForTesting();
  setenv(cloud_tpu::monitoring::kWhitelistEnvVar, "/a,/b", 1);
  setenv(cloud_tpu::monitoring::kEnabledEnvVar, "true", 1);
  config = Config::Get();
  CHECK(config->IsWhitelisted("/a"));
  CHECK(config->IsWhitelisted("/b"));
  CHECK(!config->IsWhitelisted("/cloud_tpu/training/steps"));
  CHECK(config->enabled());
  Config::ResetForTesting();
  unsetenv(cloud_tpu::monitoring::kWhitelistEnvVar);
  unsetenv(cloud_tpu::monitoring::kEnabledEnvVar);
}

void TestExporterFiltersAndDedups() {
  Config::ResetForTesting();
  setenv(cloud_tpu::monitoring::kWhitelistEnvVar,
         "/cloud_tpu/training/steps", 1);
  MetricsRegistry::Get()->Reset();
  MetricsRegistry::Get()->IncrementCounter("/cloud_tpu/training/steps", 3);
  MetricsRegistry::Get()->IncrementCounter("/not/whitelisted", 7);

  std::vector<std::pair<std::string, std::string>> sent;
  StackdriverClient client("proj",
                           [&sent](const std::string& method,
                                   const std::string& json) {
                             sent.emplace_back(method, json);
                             return true;
                           });
  Exporter exporter(&client);
  exporter.ExportMetrics();
  exporter.ExportMetrics();

  // Pass 1: descriptor + series; pass 2: series only (descriptor
  // dedup, reference exporter.cc:105-126).
  CHECK(sent.size() == 3);
  CHECK(sent[0].first == "CreateMetricDescriptor");
  CHECK(sent[1].first == "CreateTimeSeries");
  CHECK(sent[2].first == "CreateTimeSeries");
  CHECK_CONTAINS(sent[1].second, "/cloud_tpu/training/steps");
  // The non-whitelisted metric never leaves the process.
  CHECK(sent[1].second.find("/not/whitelisted") == std::string::npos);
  CHECK(exporter.export_count() == 2);

  Config::ResetForTesting();
  unsetenv(cloud_tpu::monitoring::kWhitelistEnvVar);
}

void TestPeriodicGate() {
  Config::ResetForTesting();
  unsetenv(cloud_tpu::monitoring::kEnabledEnvVar);
  StackdriverClient client("proj", nullptr);
  Exporter exporter(&client);
  // Gate off -> refuses to start (reference exporter.cc:31-36).
  bool started = exporter.PeriodicallyExportMetrics();
  CHECK(!started);
  Config::ResetForTesting();
}

std::vector<std::pair<std::string, std::string>>* g_callback_sent =
    nullptr;

int CapturingCallback(const char* method, const char* json) {
  g_callback_sent->emplace_back(method, json);
  return 1;
}

void TestTransportDispatch() {
  using cloud_tpu::monitoring::DispatchTransport;
  using cloud_tpu::monitoring::SetTransportCallback;

  // A registered callback wins over env selection.
  std::vector<std::pair<std::string, std::string>> sent;
  g_callback_sent = &sent;
  SetTransportCallback(&CapturingCallback);
  auto transport = DispatchTransport();
  // The call under test stays OUTSIDE the check macro: even though
  // CHECK is always-on, the action must read as an action.
  bool dispatched = transport("CreateTimeSeries", "{\"k\":1}");
  CHECK(dispatched);
  CHECK(sent.size() == 1);
  CHECK(sent[0].first == "CreateTimeSeries");
  CHECK(sent[0].second == "{\"k\":1}");

  // Clearing it restores the env-selected (file) transport.
  SetTransportCallback(nullptr);
  Config::ResetForTesting();
  const char* path = "/tmp/cloud_tpu_monitoring_dispatch_test.jsonl";
  std::remove(path);
  setenv(cloud_tpu::monitoring::kExportPathEnvVar, path, 1);
  unsetenv(cloud_tpu::monitoring::kTransportEnvVar);
  dispatched = transport("CreateTimeSeries", "{\"k\":2}");
  CHECK(dispatched);
  std::FILE* f = std::fopen(path, "r");
  CHECK(f != nullptr);
  char buf[256] = {0};
  char* line_read = std::fgets(buf, sizeof(buf), f);
  CHECK(line_read != nullptr);
  std::fclose(f);
  CHECK_CONTAINS(std::string(buf), "\"k\":2");
  std::remove(path);
  unsetenv(cloud_tpu::monitoring::kExportPathEnvVar);
  Config::ResetForTesting();
}

void TestRestBodyShapes() {
  using cloud_tpu::monitoring::RestBody;
  // metricDescriptors.create takes the bare MetricDescriptor; the
  // project rides in the URL.
  std::string descriptor_wrapper =
      "{\"name\":\"projects/p\",\"metricDescriptor\":{\"type\":\"t\","
      "\"metricKind\":\"CUMULATIVE\"}}";
  CHECK(RestBody("CreateMetricDescriptor", descriptor_wrapper) ==
         "{\"type\":\"t\",\"metricKind\":\"CUMULATIVE\"}");
  // timeSeries.create takes {"timeSeries": [...]}.
  std::string series_wrapper =
      "{\"name\":\"projects/p\",\"timeSeries\":[{\"metric\":1}]}";
  CHECK(RestBody("CreateTimeSeries", series_wrapper) ==
         "{\"timeSeries\":[{\"metric\":1}]}");
}

void TestHttpSendFailsFastWhenUnreachable() {
  if (!cloud_tpu::monitoring::HttpTransportAvailable()) {
    std::printf("(libcurl not loadable; http transport test skipped)\n");
    return;
  }
  // Explicit token: keeps the test off the metadata-server path.
  setenv("CLOUD_TPU_MONITORING_TOKEN", "test-token", 1);
  // Port 9 (discard) refuses connections: a clean false, no crash/hang.
  bool ok = cloud_tpu::monitoring::HttpSend(
      "http://127.0.0.1:9", "proj", "CreateTimeSeries", "{}");
  CHECK(!ok);
  unsetenv("CLOUD_TPU_MONITORING_TOKEN");
}

}  // namespace

int main() {
  TestTimeSeriesGolden();
  TestDistributionConversion();
  TestDescriptorGolden();
  TestWhitelistAndGate();
  TestExporterFiltersAndDedups();
  TestPeriodicGate();
  TestTransportDispatch();
  TestRestBodyShapes();
  TestHttpSendFailsFastWhenUnreachable();
  std::printf("ALL MONITORING TESTS PASSED\n");
  return 0;
}
