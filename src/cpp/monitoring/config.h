// Env-driven exporter configuration (whitelist + gates).
//
// Reference parity: src/cpp/monitoring/stackdriver_config.{h,cc} — a
// singleton metric whitelist parsed from a comma-separated env var with
// compiled-in defaults (reference stackdriver_config.cc:26-50), plus the
// enable/project env contract read by the exporter and client
// (stackdriver_exporter.cc:31-36, stackdriver_client.cc:38-43).

#ifndef CLOUD_TPU_MONITORING_CONFIG_H_
#define CLOUD_TPU_MONITORING_CONFIG_H_

#include <set>
#include <string>

namespace cloud_tpu {
namespace monitoring {

// Env vars (the CLOUD_TPU_* analogue of the reference's
// TF_MONITORING_STACKDRIVER_* contract).
extern const char kEnabledEnvVar[];      // CLOUD_TPU_MONITORING_ENABLED
extern const char kProjectIdEnvVar[];    // CLOUD_TPU_MONITORING_PROJECT_ID
extern const char kWhitelistEnvVar[];    // CLOUD_TPU_MONITORING_METRICS_WHITELIST
extern const char kExportPathEnvVar[];   // CLOUD_TPU_MONITORING_EXPORT_PATH
extern const char kTransportEnvVar[];    // CLOUD_TPU_MONITORING_TRANSPORT
extern const char kEndpointEnvVar[];     // CLOUD_TPU_MONITORING_ENDPOINT

class Config {
 public:
  // Parses env on first use (singleton, like reference
  // stackdriver_config.cc:20-24).
  static const Config* Get();
  // Re-parses env (test isolation; the reference's singleton is
  // unresettable, which its tests work around with process isolation).
  static void ResetForTesting();

  bool IsWhitelisted(const std::string& metric_name) const;
  bool enabled() const { return enabled_; }
  const std::string& project_id() const { return project_id_; }
  const std::string& export_path() const { return export_path_; }
  // "file" (default) or "http" (real Cloud Monitoring REST sends).
  const std::string& transport() const { return transport_; }
  // REST endpoint base, overridable for tests/emulators.
  const std::string& endpoint() const { return endpoint_; }
  std::string DebugString() const;

 private:
  Config();

  bool enabled_ = false;
  std::string project_id_;
  std::string export_path_;
  std::string transport_ = "file";
  std::string endpoint_ = "https://monitoring.googleapis.com";
  std::set<std::string> whitelist_;
};

}  // namespace monitoring
}  // namespace cloud_tpu

#endif  // CLOUD_TPU_MONITORING_CONFIG_H_
