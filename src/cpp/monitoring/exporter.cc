#include "exporter.h"

#include <chrono>

#include "config.h"
#include "metrics_registry.h"

namespace cloud_tpu {
namespace monitoring {

namespace {

bool IsEmpty(const MetricSnapshot& s) {
  switch (s.kind) {
    case MetricKind::kCounter:
      return s.counter_value == 0;
    case MetricKind::kGauge:
      return false;  // a set gauge is always a point
    case MetricKind::kHistogram:
      return s.histogram.count == 0;
  }
  return true;
}

}  // namespace

Exporter::Exporter(StackdriverClient* client, int64_t interval_micros)
    : client_(client), interval_micros_(interval_micros) {}

Exporter::~Exporter() { Stop(); }

bool Exporter::PeriodicallyExportMetrics() {
  if (!Config::Get()->enabled()) return false;  // exporter.cc:31-36
  std::lock_guard<std::mutex> lock(mu_);
  if (started_) return true;
  started_ = true;
  thread_ = std::thread([this] {
    std::unique_lock<std::mutex> lock(mu_);
    while (!stopping_) {
      lock.unlock();
      ExportMetrics();
      lock.lock();
      cv_.wait_for(lock,
                   std::chrono::microseconds(interval_micros_),
                   [this] { return stopping_; });
    }
  });
  return true;
}

void Exporter::ExportMetrics() {
  const Config* config = Config::Get();
  std::vector<MetricSnapshot> snapshots =
      MetricsRegistry::Get()->Snapshot();
  // Whitelist + non-empty filter (reference exporter.cc:38-68).
  std::vector<MetricSnapshot> filtered;
  for (auto& s : snapshots) {
    if (config->IsWhitelisted(s.name) && !IsEmpty(s)) {
      filtered.push_back(std::move(s));
    }
  }
  if (filtered.empty()) return;
  ExportMetricDescriptors(filtered);
  client_->CreateTimeSeries(filtered);
  export_count_++;
}

void Exporter::ExportMetricDescriptors(
    const std::vector<MetricSnapshot>& snapshots) {
  for (const auto& s : snapshots) {
    bool is_new;
    {
      std::lock_guard<std::mutex> lock(mu_);
      is_new = registered_descriptors_.insert(s.name).second;
    }
    if (is_new) client_->CreateMetricDescriptor(s);
  }
}

void Exporter::Stop() {
  {
    std::lock_guard<std::mutex> lock(mu_);
    stopping_ = true;
  }
  cv_.notify_all();
  if (thread_.joinable()) thread_.join();
  // Allow a later restart (start->stop->start must actually export).
  std::lock_guard<std::mutex> lock(mu_);
  started_ = false;
  stopping_ = false;
}

}  // namespace monitoring
}  // namespace cloud_tpu
