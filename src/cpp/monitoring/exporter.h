// Periodic metrics exporter.
//
// Reference parity: src/cpp/monitoring/stackdriver_exporter.{h,cc} — a
// background thread that every 10s (kIntervalMicros, reference
// exporter.cc:28) collects from the registry, filters to whitelisted
// non-empty metrics (exporter.cc:38-68), lazily registers each metric's
// descriptor exactly once (exporter.cc:105-126), and pushes time series;
// gated by an env var (exporter.cc:31-36); mutex-guarded state
// (exporter.h:43-46).

#ifndef CLOUD_TPU_MONITORING_EXPORTER_H_
#define CLOUD_TPU_MONITORING_EXPORTER_H_

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <mutex>
#include <set>
#include <string>
#include <thread>

#include "stackdriver_client.h"

namespace cloud_tpu {
namespace monitoring {

constexpr int64_t kDefaultIntervalMicros = 10 * 1000 * 1000;  // 10s

class Exporter {
 public:
  explicit Exporter(StackdriverClient* client,
                    int64_t interval_micros = kDefaultIntervalMicros);
  ~Exporter();

  // Starts the periodic thread if the env gate is on (reference
  // exporter.cc:72-84). Returns whether it started.
  bool PeriodicallyExportMetrics();

  // One export pass (also used by the periodic thread).
  void ExportMetrics();

  void Stop();

  int64_t export_count() const { return export_count_; }

 private:
  void ExportMetricDescriptors(
      const std::vector<MetricSnapshot>& snapshots);

  StackdriverClient* client_;
  int64_t interval_micros_;

  std::mutex mu_;
  std::condition_variable cv_;
  bool stopping_ = false;
  std::thread thread_;
  bool started_ = false;
  // Descriptor dedup (reference exporter.cc:105-126).
  std::set<std::string> registered_descriptors_;
  std::atomic<int64_t> export_count_{0};
};

}  // namespace monitoring
}  // namespace cloud_tpu

#endif  // CLOUD_TPU_MONITORING_EXPORTER_H_
