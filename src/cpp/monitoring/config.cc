#include "config.h"

#include <cstdlib>
#include <mutex>
#include <sstream>

namespace cloud_tpu {
namespace monitoring {

const char kEnabledEnvVar[] = "CLOUD_TPU_MONITORING_ENABLED";
const char kProjectIdEnvVar[] = "CLOUD_TPU_MONITORING_PROJECT_ID";
const char kWhitelistEnvVar[] = "CLOUD_TPU_MONITORING_METRICS_WHITELIST";
const char kExportPathEnvVar[] = "CLOUD_TPU_MONITORING_EXPORT_PATH";
const char kTransportEnvVar[] = "CLOUD_TPU_MONITORING_TRANSPORT";
const char kEndpointEnvVar[] = "CLOUD_TPU_MONITORING_ENDPOINT";

namespace {

// Default whitelist: the runtime metrics the framework emits on the hot
// path (the analogue of the reference's TF graph/data defaults,
// stackdriver_config.cc:37-44).
const char* const kDefaultWhitelist[] = {
    "/cloud_tpu/training/steps",
    "/cloud_tpu/training/examples",
    "/cloud_tpu/training/step_time_usecs_histogram",
    "/cloud_tpu/data/bytes_fetched",
    "/cloud_tpu/data/batch_latency_usecs_histogram",
    "/cloud_tpu/compile/compile_time_usecs_histogram",
};

Config* g_config = nullptr;
std::mutex g_mu;

}  // namespace

Config::Config() {
  const char* enabled = std::getenv(kEnabledEnvVar);
  enabled_ = enabled != nullptr && std::string(enabled) == "true";
  const char* project = std::getenv(kProjectIdEnvVar);
  if (project != nullptr) project_id_ = project;
  const char* path = std::getenv(kExportPathEnvVar);
  if (path != nullptr) export_path_ = path;
  const char* transport = std::getenv(kTransportEnvVar);
  if (transport != nullptr && transport[0] != '\0') {
    transport_ = transport;
  }
  const char* endpoint = std::getenv(kEndpointEnvVar);
  if (endpoint != nullptr && endpoint[0] != '\0') endpoint_ = endpoint;

  const char* raw = std::getenv(kWhitelistEnvVar);
  if (raw == nullptr || std::string(raw).empty()) {
    for (const char* name : kDefaultWhitelist) whitelist_.insert(name);
    return;
  }
  // Comma-split (reference stackdriver_config.cc:26-35).
  std::stringstream stream(raw);
  std::string item;
  while (std::getline(stream, item, ',')) {
    if (!item.empty()) whitelist_.insert(item);
  }
}

const Config* Config::Get() {
  std::lock_guard<std::mutex> lock(g_mu);
  if (g_config == nullptr) g_config = new Config();
  return g_config;
}

void Config::ResetForTesting() {
  std::lock_guard<std::mutex> lock(g_mu);
  // Intentionally leaked: Exporter threads and callers hold raw const
  // pointers from Get(); deleting here would be a use-after-free.
  g_config = nullptr;
}

bool Config::IsWhitelisted(const std::string& metric_name) const {
  return whitelist_.count(metric_name) > 0;
}

std::string Config::DebugString() const {
  std::stringstream out;
  out << "enabled=" << (enabled_ ? "true" : "false")
      << " project_id=" << project_id_
      << " transport=" << transport_ << " whitelist=[";
  bool first = true;
  for (const auto& name : whitelist_) {
    if (!first) out << ",";
    out << name;
    first = false;
  }
  out << "]";
  return out.str();
}

}  // namespace monitoring
}  // namespace cloud_tpu
