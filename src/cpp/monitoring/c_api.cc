// C API: the ctypes boundary for cloud_tpu.monitoring (pybind11 is not
// available in this image; plain extern "C" + ctypes is the binding).

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "config.h"
#include "exporter.h"
#include "http_transport.h"
#include "metrics_registry.h"
#include "stackdriver_client.h"

namespace {

cloud_tpu::monitoring::Exporter* g_exporter = nullptr;
std::mutex g_exporter_mu;

cloud_tpu::monitoring::Exporter* GetExporter(
    int64_t interval_micros =
        cloud_tpu::monitoring::kDefaultIntervalMicros) {
  // ctypes calls release the GIL; creation must be synchronized.
  std::lock_guard<std::mutex> lock(g_exporter_mu);
  if (g_exporter == nullptr) {
    g_exporter = new cloud_tpu::monitoring::Exporter(
        cloud_tpu::monitoring::StackdriverClient::Get(), interval_micros);
  }
  return g_exporter;
}

char* CopyString(const std::string& s) {
  char* out = static_cast<char*>(std::malloc(s.size() + 1));
  std::memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

}  // namespace

extern "C" {

void cloud_tpu_counter_increment(const char* name, int64_t delta) {
  cloud_tpu::monitoring::MetricsRegistry::Get()->IncrementCounter(name,
                                                                  delta);
}

void cloud_tpu_gauge_set(const char* name, double value) {
  cloud_tpu::monitoring::MetricsRegistry::Get()->SetGauge(name, value);
}

void cloud_tpu_histogram_observe(const char* name, double value,
                                 const double* bounds, int num_bounds) {
  std::vector<double> bound_vec(bounds, bounds + num_bounds);
  cloud_tpu::monitoring::MetricsRegistry::Get()->ObserveHistogram(
      name, value, bound_vec);
}

void cloud_tpu_metric_set_description(const char* name,
                                      const char* description) {
  cloud_tpu::monitoring::MetricsRegistry::Get()->SetDescription(
      name, description);
}

// Serialized CreateTimeSeries request for the current registry contents
// (caller frees with cloud_tpu_free).
char* cloud_tpu_snapshot_json() {
  auto snapshots =
      cloud_tpu::monitoring::MetricsRegistry::Get()->Snapshot();
  const cloud_tpu::monitoring::Config* config =
      cloud_tpu::monitoring::Config::Get();
  return CopyString(
      cloud_tpu::monitoring::StackdriverClient::TimeSeriesJson(
          config->project_id(), snapshots));
}

char* cloud_tpu_config_debug_string() {
  return CopyString(
      cloud_tpu::monitoring::Config::Get()->DebugString());
}

void cloud_tpu_free(char* ptr) { std::free(ptr); }

// Starts the periodic exporter (no-op unless
// CLOUD_TPU_MONITORING_ENABLED=true). Returns 1 if running.
int cloud_tpu_exporter_start(int64_t interval_micros) {
  return GetExporter(interval_micros)->PeriodicallyExportMetrics() ? 1 : 0;
}

// One synchronous export pass (also what the periodic thread runs).
void cloud_tpu_exporter_flush() { GetExporter()->ExportMetrics(); }

int64_t cloud_tpu_exporter_export_count() {
  std::lock_guard<std::mutex> lock(g_exporter_mu);
  return g_exporter == nullptr ? 0 : g_exporter->export_count();
}

void cloud_tpu_exporter_stop() {
  std::lock_guard<std::mutex> lock(g_exporter_mu);
  if (g_exporter != nullptr) g_exporter->Stop();
}

// Registers a host-process transport (e.g. a Python callback holding an
// authenticated google client). NULL restores env-selected transports.
void cloud_tpu_set_transport(
    int (*callback)(const char* method, const char* json)) {
  cloud_tpu::monitoring::SetTransportCallback(callback);
}

// 1 when the libcurl REST sender can be used on this host.
int cloud_tpu_http_transport_available() {
  return cloud_tpu::monitoring::HttpTransportAvailable() ? 1 : 0;
}

void cloud_tpu_registry_reset() {
  cloud_tpu::monitoring::MetricsRegistry::Get()->Reset();
}

void cloud_tpu_config_reset() {
  cloud_tpu::monitoring::Config::ResetForTesting();
}

}  // extern "C"
