"""MNIST with a custom training loop and a user-managed mesh.

The escape hatch: `distribution_strategy=None` launches user code
unwrapped (reference run.py:79-83; CTL example
core/tests/testdata/mnist_example_using_ctl.py, which builds its own
MultiWorkerMirroredStrategy). The JAX form: build your own Mesh, place
params and batches yourself, jit your own step.

Run: python examples/mnist_example_using_ctl.py
"""

import jax
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cloud_tpu.models import MLP


def main():
    # User-managed mesh over all local devices: pure data parallelism.
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    replicate = NamedSharding(mesh, P())
    shard_batch = NamedSharding(mesh, P("dp"))

    model = MLP(hidden=256, num_classes=10)
    optimizer = optax.sgd(0.1, momentum=0.9)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2048, 28, 28)).astype(np.float32)
    y = rng.integers(0, 10, size=2048).astype(np.int32)

    params = model.init(jax.random.PRNGKey(0), x[:1])
    params = jax.device_put(params, replicate)
    opt_state = jax.device_put(optimizer.init(params), replicate)

    @jax.jit
    def train_step(params, opt_state, bx, by):
        def loss_fn(p):
            logits = model.apply(p, bx)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, by).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    batch_size = 256
    # Round down to a whole number of batches; the dp axis requires the
    # batch dim to divide evenly across devices.
    steps = len(x) // batch_size
    for epoch in range(2):
        epoch_loss = 0.0
        for i in range(steps):
            bx = jax.device_put(
                x[i * batch_size:(i + 1) * batch_size], shard_batch)
            by = jax.device_put(
                y[i * batch_size:(i + 1) * batch_size], shard_batch)
            params, opt_state, loss = train_step(params, opt_state, bx, by)
            epoch_loss += float(loss)
        print("epoch %d loss: %.4f" % (epoch, epoch_loss / steps))


if __name__ == "__main__":
    main()
