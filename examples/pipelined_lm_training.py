"""Train a decoder LM with pipeline + data parallelism in one mesh.

The pp flagship: `PipelinedLM`'s transformer blocks run as GPipe stages
over the "pp" mesh axis (stage params sharded, activations hop
stage-to-stage via ppermute inside a lax.scan schedule —
cloud_tpu/parallel/pipeline.py), while microbatches shard over "dp".
The standard Trainer drives it: `pipelined_lm_rules()` lays the stacked
stage params out on "pp" and XLA inserts the dp gradient psum.

Run (8 virtual CPU devices):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/pipelined_lm_training.py
On a real slice the same code runs unchanged; pick pp_stages to match
the mesh and num_microbatches >= 2*pp_stages to keep the GPipe bubble
((n-1)/(M+n-1)) small.
"""

import numpy as np
import optax

from cloud_tpu.models import PipelinedLM, pipelined_lm_rules
from cloud_tpu.parallel import runtime
from cloud_tpu.training import Trainer

SEQ_LEN = 64
VOCAB = 256
D_MODEL = 64


def main():
    import jax
    import jax.numpy as jnp

    n = len(jax.devices())
    pp = 4 if n % 4 == 0 else (2 if n % 2 == 0 else 1)
    dp = max(n // pp, 1)
    runtime.initialize(strategy="tpu_slice", axis_names=("dp", "pp"),
                       mesh_shape=(dp, pp))

    model = PipelinedLM(
        vocab_size=VOCAB, d_model=D_MODEL, num_heads=4,
        pp_stages=pp, layers_per_stage=2, max_seq_len=SEQ_LEN,
        num_microbatches=max(2 * pp, 2), compute_dtype=jnp.float32)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, VOCAB, size=(dp * 32, SEQ_LEN)).astype(
        np.int32)
    targets = np.roll(tokens, -1, axis=1)

    trainer = Trainer((model.init, model.apply),
                      optimizer=optax.adam(3e-3),
                      param_sharding_rules=pipelined_lm_rules(),
                      metrics=())
    history = trainer.fit(tokens, targets, epochs=2,
                          batch_size=dp * 16, verbose=False)
    print("pp={} dp={} final loss {:.4f}".format(
        pp, dp, history["loss"][-1]))
    return history


if __name__ == "__main__":
    main()
