"""Long-context Transformer LM with ring attention (sequence parallel).

The long-context flagship — capability the reference never had (SURVEY §5
"Long-context / sequence parallelism: Absent"). The sequence axis is
sharded over the mesh's "sp" axis; K/V chunks rotate around the ring on
ICI neighbor links (cloud_tpu/parallel/ring_attention.py), so per-device
activation memory is O(S / sp) and context length scales with the slice.

Run (8 virtual CPU devices):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/transformer_long_context.py
On a v5e-8 the same code runs unchanged over the real chips.
"""

import numpy as np
import optax

from cloud_tpu.models import TransformerLM
from cloud_tpu.parallel import runtime
from cloud_tpu.training import Trainer

SEQ_LEN = 1024
VOCAB = 512


def main():
    import jax

    n = len(jax.devices())
    sp = 4 if n % 4 == 0 else 1
    dp = n // sp
    # dp x sp mesh: batches split over dp, sequences split over sp.
    runtime.initialize(strategy="tpu_slice", axis_names=("dp", "sp"),
                       mesh_shape=(dp, sp))

    model = TransformerLM(
        vocab_size=VOCAB, num_layers=2, num_heads=4, d_model=128,
        d_ff=256, max_seq_len=SEQ_LEN, attention_impl="ring")

    def lm_loss(logits, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean(axis=-1)

    trainer = Trainer(model, optimizer=optax.adam(3e-4), loss=lm_loss,
                      metrics=())

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, VOCAB, size=(4 * dp, SEQ_LEN)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=1)

    history = trainer.fit(tokens, targets, epochs=2, batch_size=2 * dp)
    print("final loss: %.4f" % history["loss"][-1])


if __name__ == "__main__":
    main()
