"""MNIST with `Trainer.fit` — the framework's hello world.

The counterpart of the reference README's `mnist_example.py` (reference
core/tests/testdata/mnist_example_using_fit.py): a dense net trained with
a Keras-style `fit`. This script is a valid `entry_point` for
`cloud_tpu.run()` — launched remotely, the generated runner initializes
the ambient mesh first and the same code runs data-parallel over the TPU
slice with no changes.

Run locally:     python examples/mnist_example_using_fit.py
Launch on cloud: ctc.run(entry_point="examples/mnist_example_using_fit.py")

Uses synthetic MNIST-shaped data so the example is hermetic; swap in any
(N, 28, 28) array source.
"""

import numpy as np
import optax

from cloud_tpu.models import MLP
from cloud_tpu.training import Trainer


def load_synthetic_mnist(n=4096, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 28, 28)).astype(np.float32)
    y = rng.integers(0, 10, size=n).astype(np.int32)
    return x, y


def main():
    x, y = load_synthetic_mnist()

    trainer = Trainer(
        model=MLP(hidden=512, num_classes=10),
        optimizer=optax.adam(1e-3),
        loss="sparse_categorical_crossentropy",
        metrics=("accuracy",),
    )
    history = trainer.fit(x, y, epochs=2, batch_size=128)
    print("final loss: %.4f" % history["loss"][-1])

    logs = trainer.evaluate(x[:512], y[:512], batch_size=128)
    print("eval loss: %.4f, accuracy: %.4f" % (logs["loss"],
                                               logs["accuracy"]))
    return history


if __name__ == "__main__":
    main()
