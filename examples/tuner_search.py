"""Hyperparameter search with CloudTuner.

Reference parity: the KerasTuner-on-Vizier flow (reference
tuner/tuner.py:333-381 and tuner/tests/examples) — define a search
space, build a Trainer per trial, let the oracle drive suggestions. The
Vizier boundary is injectable (`CloudOracle(client=...)`), so this
example runs offline with a random-search fake while the whole
trial-loop machinery (suggest -> train -> report-per-epoch -> complete)
executes for real. Drop the `client` kwarg (with GCP credentials) to
search against real Vizier.

Run: python examples/tuner_search.py
"""

import numpy as np
import optax

from cloud_tpu.models import MLP
from cloud_tpu.training import Trainer
from cloud_tpu.tuner import CloudTuner, HyperParameters


class FakeVizier:
    """Random-search stand-in implementing the OptimizerClient surface
    (cloud_tpu/tuner/optimizer_client.py)."""

    def __init__(self, hps):
        self.hps = hps
        self.trials = []
        self.measurements = {}

    def get_suggestions(self, client_id):
        hp = self.hps.random_sample(seed=len(self.trials))
        # Vizier wire format: typed value keys per parameter.
        params = []
        for name, value in hp.values.items():
            if isinstance(value, bool) or isinstance(value, str):
                params.append({"parameter": name,
                               "stringValue": str(value)})
            elif isinstance(value, int):
                params.append({"parameter": name, "intValue": value})
            else:
                params.append({"parameter": name, "floatValue": value})
        trial = {"name": "trials/%d" % (len(self.trials) + 1),
                 "parameters": params, "state": "ACTIVE"}
        self.trials.append(trial)
        return {"trials": [trial]}

    def list_trials(self):
        return list(self.trials)

    def report_intermediate_objective_value(self, step, elapsed_secs,
                                            metric_list, trial_id):
        self.measurements.setdefault(trial_id, []).append(
            {"stepCount": step, "metrics": metric_list})

    def should_trial_stop(self, trial_id):
        return False

    def complete_trial(self, trial_id, trial_infeasible=False,
                       infeasibility_reason=None):
        trial = self.trials[int(trial_id) - 1]
        trial["state"] = ("INFEASIBLE" if trial_infeasible
                          else "COMPLETED")
        reported = self.measurements.get(trial_id)
        if reported:
            trial["finalMeasurement"] = reported[-1]
        return trial


def build_trainer(hp):
    """Model-per-trial factory, KerasTuner `build(hp)` style."""
    return Trainer(
        model=MLP(hidden=hp.get("hidden"), num_classes=10),
        optimizer=optax.adam(hp.get("learning_rate")),
        loss="sparse_categorical_crossentropy",
        metrics=("accuracy",),
    )


def main():
    hps = HyperParameters()
    hps.Choice("hidden", [64, 128, 256])
    hps.Float("learning_rate", 1e-4, 1e-2, sampling="log")

    tuner = CloudTuner(
        build_trainer,
        directory="/tmp/cloud_tpu_tuner_demo",
        project_id="my-project",
        region="us-central1",
        objective="accuracy",
        hyperparameters=hps,
        max_trials=3,
        study_id="demo_study",
        client=FakeVizier(hps),
    )

    rng = np.random.default_rng(0)
    x = rng.normal(size=(1024, 28, 28)).astype(np.float32)
    y = rng.integers(0, 10, size=1024).astype(np.int32)

    tuner.search(x=x, y=y, epochs=1, batch_size=128, verbose=False)
    best = tuner.get_best_hyperparameters()[0]
    print("best hidden=%s lr=%.5f" % (best.get("hidden"),
                                      best.get("learning_rate")))


if __name__ == "__main__":
    main()
