"""Launching training on Cloud TPU with `cloud_tpu.run()`.

The reference README's headline flow ("High level overview":
`tfc.run(entry_point="mnist_example.py")`), TPU-first: validate ->
generate the mesh-runner -> containerize -> submit. The cloud boundaries
(docker daemon, AI-Platform REST) are injectable seams on `run()`, so
this example demonstrates the full pipeline offline with fakes; drop the
two injection kwargs (with real GCP credentials + a docker daemon) to
launch for real.

Run: python examples/launch_with_run.py
"""

import os

import cloud_tpu as ctc
from cloud_tpu.core import run as run_module


class FakeBuilder:
    """Stands in for LocalContainerBuilder (docker daemon) offline."""

    def __init__(self, *args, **kwargs):
        self.entry_point = args[0]

    def get_docker_image(self):
        print("[fake] built docker image for", self.entry_point)
        return "gcr.io/my-project/tpu_train:demo"

    def get_generated_files(self):
        return []


class _Executable:
    def __init__(self, body):
        self.body = body

    def execute(self):
        print("[fake] submitted CAIP request for",
              self.body["trainingInput"]["masterConfig"]["imageUri"])
        return {}


class FakeJobsApi:
    """googleapiclient-shaped fake: projects().jobs().create().execute()."""

    def projects(self):
        return self

    def jobs(self):
        return self

    def create(self, parent, body):
        print("[fake] create job under", parent)
        return _Executable(body)


def main():
    os.environ.setdefault("GOOGLE_CLOUD_PROJECT", "my-project")
    job_id = run_module.run(
        entry_point="examples/mnist_example_using_fit.py",
        chief_config=ctc.COMMON_MACHINE_CONFIGS["CPU"],
        worker_config=ctc.COMMON_MACHINE_CONFIGS["TPU_V5E_8"],
        worker_count=1,
        container_builder_cls=FakeBuilder,
        api_client=FakeJobsApi(),
    )
    print("job id:", job_id)


if __name__ == "__main__":
    main()
