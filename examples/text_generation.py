"""Every decoding entry point on one tiny trained LM.

Trains a small `TransformerLM` on a synthetic copy task (the model
learns to echo a repeating pattern, so decode quality is checkable),
then decodes with the full inference surface:

- `generate`: greedy, then temperature/top-k/top-p sampling, then a
  left-padded variable-length batch (`prompt_mask`).
- `generate_beam`: batched beam search (B prompts x W beams on the
  cache batch dimension, on-device ranking).
- `generate_speculative`: a 1-layer draft proposing for the trained
  target — greedy (token-identical to the target's greedy decode) and
  stochastic (Leviathan accept/reject; prints the acceptance rate).

Run: python examples/text_generation.py
(sizes are module constants so the example tests can shrink them).
"""

import numpy as np

SEQ_LEN = 48
VOCAB = 32
EPOCHS = 25
DRAFT_EPOCHS = 6
PATTERN = 7  # the copy task's period


def _dataset(rng, n=512):
    """Sequences that repeat a random PATTERN-length motif: the LM can
    learn next-token prediction almost perfectly, so greedy decode is
    checkable against the motif."""
    x = np.zeros((n, SEQ_LEN), np.int32)
    for i in range(n):
        motif = rng.integers(1, VOCAB, size=PATTERN)
        x[i] = np.tile(motif, SEQ_LEN // PATTERN + 1)[:SEQ_LEN]
    return x


def main():
    import jax
    import jax.numpy as jnp
    import optax

    from cloud_tpu.models import (TransformerLM, generate,
                                  generate_beam, generate_speculative)
    from cloud_tpu.training import Trainer

    rng = np.random.default_rng(0)
    data = _dataset(rng)
    inputs, targets = data[:, :-1], data[:, 1:]

    target = TransformerLM(vocab_size=VOCAB, num_layers=2, num_heads=4,
                           d_model=64, d_ff=128, max_seq_len=SEQ_LEN,
                           compute_dtype=jnp.float32)

    # Default loss (sparse categorical cross-entropy) handles the
    # [B, S, V]-vs-[B, S] next-token shapes directly.
    trainer = Trainer(target, optimizer=optax.adam(1e-3), metrics=())
    history = trainer.fit(inputs, targets, epochs=EPOCHS,
                          batch_size=64, verbose=False)
    params = jax.device_get(trainer.state.params)
    print("final loss: {:.4f}".format(history["loss"][-1]))

    prompt = jnp.asarray(data[:1, :PATTERN * 2], jnp.int32)
    new = PATTERN * 2

    greedy = generate(target, params, prompt, new, temperature=0.0)
    print("greedy continuation:", np.asarray(greedy)[0, prompt.shape[1]:])

    sampled = generate(target, params, prompt, new,
                       rng=jax.random.PRNGKey(1), temperature=0.7,
                       top_k=8, top_p=0.95)
    print("sampled continuation:",
          np.asarray(sampled)[0, prompt.shape[1]:])

    # Variable-length batch: left-pad a shorter prompt beside a longer
    # one; each row generates exactly as it would alone.
    s = prompt.shape[1]
    batch = np.zeros((2, s), np.int32)
    mask = np.zeros((2, s), bool)
    batch[0], mask[0] = np.asarray(prompt)[0], True
    short = np.asarray(prompt)[0, :PATTERN]
    batch[1, s - PATTERN:], mask[1, s - PATTERN:] = short, True
    padded = generate(target, params, jnp.asarray(batch), new,
                      temperature=0.0, prompt_mask=jnp.asarray(mask))
    print("padded-batch rows:", np.asarray(padded)[:, s:])

    beams, scores = generate_beam(target, params, jnp.asarray(batch),
                                  new, beam_width=4,
                                  prompt_mask=jnp.asarray(mask))
    print("beam rows:", np.asarray(beams)[:, s:],
          "scores:", np.round(np.asarray(scores), 3))

    # A briefly-trained 1-layer draft: the realistic speculative setup
    # (a random draft would propose near-uniformly and the trained
    # target would reject almost everything).
    draft = TransformerLM(vocab_size=VOCAB, num_layers=1, num_heads=4,
                          d_model=64, d_ff=128, max_seq_len=SEQ_LEN,
                          compute_dtype=jnp.float32)
    draft_trainer = Trainer(draft, optimizer=optax.adam(1e-3),
                            metrics=())
    draft_trainer.fit(inputs, targets, epochs=DRAFT_EPOCHS,
                      batch_size=64, verbose=False)
    draft_params = jax.device_get(draft_trainer.state.params)
    spec = generate_speculative(target, params, draft, draft_params,
                                prompt, new, num_draft=3)
    assert (np.asarray(spec) == np.asarray(greedy)).all(), \
        "greedy speculative must be token-identical to greedy decode"
    _, stats = generate_speculative(
        target, params, draft, draft_params, prompt, new, num_draft=3,
        rng=jax.random.PRNGKey(3), temperature=0.7, top_p=0.95,
        return_stats=True)
    print("speculative ok; stochastic acceptance rate: {:.2f}".format(
        stats["acceptance_rate"]))
    return history


if __name__ == "__main__":
    main()
