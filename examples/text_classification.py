"""Text classification with TransformerEncoder — padded batches done
right.

The encoder-side counterpart of `transformer_long_context.py`: a
BERT-style bidirectional encoder classifying variable-length token
sequences. Demonstrates the two things padded-text workloads need from
the framework:

1. a [B, S] validity mask that excludes pad positions from attention
   keys AND from the pooled classification features, and
2. the same model running unchanged under `cloud_tpu.run()` on a TPU
   slice (the generated runner initializes the mesh; fit is
   data-parallel automatically).

Synthetic data keeps it hermetic: each "sentence" is classified by its
first token's bucket — learnable only if masking is correct, because
the pad tail is deliberately filled with misleading tokens.

Run locally:  python examples/text_classification.py
"""

import numpy as np
import optax

from cloud_tpu.models import TransformerEncoder
from cloud_tpu.training import Trainer

VOCAB = 128
NUM_CLASSES = 4
MAX_LEN = 24


def load_synthetic_text(n=2048, seed=0):
    """Variable-length "sentences" labeled by the first token's bucket.

    The pad tail is deliberately adversarial: it repeats a token whose
    bucket is a RANDOM WRONG class (uncorrelated with the label), so a
    model that attends to or pools over padding trains on contradictory
    signal — measured at this budget: ~0.79 accuracy unmasked vs ~1.0
    masked, so masking correctness is observable in the metric. (Real
    pipelines usually pad with a fixed id like 0; only the mask
    matters, not the fill value.)
    """
    rng = np.random.default_rng(seed)
    lengths = rng.integers(4, MAX_LEN + 1, size=n)
    tokens = np.zeros((n, MAX_LEN), np.int32)
    labels = np.zeros((n,), np.int32)
    for i, ln in enumerate(lengths):
        body = rng.integers(1, VOCAB, size=ln)
        tokens[i, :ln] = body
        labels[i] = body[0] % NUM_CLASSES
        wrong = (labels[i] + rng.integers(1, NUM_CLASSES)) % NUM_CLASSES
        tokens[i, ln:] = wrong + NUM_CLASSES  # in-vocab, bucket=wrong
    mask = (np.arange(MAX_LEN)[None, :] < lengths[:, None])
    return tokens, mask.astype(np.int32), labels


def main():
    tokens, mask, labels = load_synthetic_text()

    model = TransformerEncoder(
        vocab_size=VOCAB, num_layers=2, num_heads=4, d_model=64,
        d_ff=256, max_seq_len=MAX_LEN, num_classes=NUM_CLASSES,
        head="classify")
    # Masks are part of the input: pack (tokens, mask) pairs via a
    # model wrapper so fit's (x, y) protocol stays unchanged.
    class MaskedEncoder:
        def init(self, rng, x, **kw):
            toks, m = x[..., 0], x[..., 1]
            return model.init(rng, toks, m, **kw)

        def apply(self, variables, x, **kw):
            toks, m = x[..., 0], x[..., 1]
            return model.apply(variables, toks, m, **kw)

    packed = np.stack([tokens, mask], axis=-1)
    trainer = Trainer(MaskedEncoder(), optimizer=optax.adam(1e-3),
                      loss="sparse_categorical_crossentropy",
                      metrics=("accuracy",))
    history = trainer.fit(packed, labels, epochs=4, batch_size=64)
    print("final accuracy: %.3f" % history["accuracy"][-1])

    logs = trainer.evaluate(packed[:512], labels[:512], batch_size=64)
    print("eval accuracy: %.3f" % logs["accuracy"])
    return history


if __name__ == "__main__":
    main()
