"""Vizier-backed hyperparameter tuning.

Reference parity: tuner/tuner.py:40-606 — `CloudOracle` (trial lifecycle
against the Vizier service), `CloudTuner` (local trial execution), and
`DistributingCloudTuner` (every trial trains remotely via cloud_fit and
metrics are read back from storage). Differences, TPU-native:

- No KerasTuner dependency: the Oracle/Tuner loop, Trial, and
  HyperParameters are this package's own (cloud_tpu/tuner/
  hyperparameters.py), so the tuner drives `cloud_tpu.training.Trainer`
  directly.
- The remote metric return channel is the structured history/JSONL file
  written by the trainer (reference tuner.py:532-560 parses TensorBoard
  event files and splits epochs on `epoch_*` tag conventions — SURVEY
  §7.4 item 6 calls out that fragility).
- `load_trainer` (the analogue of the reference's NotImplementedError
  `load_model`, tuner.py:562-567) restores the per-trial checkpoint.
"""

import json
import logging
import time

from cloud_tpu.tuner import utils as tuner_utils

# The GCP/cloud_fit/storage machinery (googleapiclient discovery, the
# remote-trial channel, gs:// IO) is imported INSIDE the methods that
# reach for it: importing this module — e.g. for `CloudOracle` with an
# injected offline client, or from a local graftsweep process — must
# never touch google-api plumbing or pull jax via cloud_fit.remote.
#
# `tuner.cloud_fit_client` etc. stay reachable as module attributes
# (tests patch the seams through them) via PEP 562 — resolving one
# imports only that dependency, on first touch.

_LAZY_MODULES = {
    "cloud_fit_client": ("cloud_tpu.cloud_fit", "client"),
    "cloud_fit_remote": ("cloud_tpu.cloud_fit", "remote"),
    "storage": ("cloud_tpu.utils", "storage"),
    "gcp": ("cloud_tpu.core", "gcp"),
    "google_api_client": ("cloud_tpu.utils", "google_api_client"),
    "optimizer_client": ("cloud_tpu.tuner", "optimizer_client"),
}


def __getattr__(name):
    try:
        package, attr = _LAZY_MODULES[name]
    except KeyError:
        raise AttributeError(
            "module {!r} has no attribute {!r}".format(__name__, name))
    import importlib

    module = getattr(importlib.import_module(package), attr)
    globals()[name] = module
    return module


logger = logging.getLogger("cloud_tpu")


class TrialStatus:
    RUNNING = "RUNNING"
    COMPLETED = "COMPLETED"
    INVALID = "INVALID"
    STOPPED = "STOPPED"


class Trial:
    """One hyperparameter evaluation."""

    def __init__(self, trial_id, hyperparameters, status=TrialStatus.RUNNING):
        self.trial_id = trial_id
        self.hyperparameters = hyperparameters
        self.status = status
        self.score = None
        self.best_step = None

    def __repr__(self):
        return "Trial(id={!r}, status={!r}, score={!r})".format(
            self.trial_id, self.status, self.score)


class CloudOracle:
    """Trial source backed by the Vizier service
    (reference tuner.py:40-330)."""

    def __init__(self,
                 project_id=None,
                 region=None,
                 objective=None,
                 hyperparameters=None,
                 study_config=None,
                 max_trials=None,
                 study_id=None,
                 service_client=None,
                 client=None):
        # With an injected `client` the GCP identity is cosmetic: don't
        # force credential/project resolution offline.
        if client is not None:
            self.project_id = project_id
            self.region = region
        else:
            from cloud_tpu.core import gcp

            self.project_id = project_id or gcp.get_project_name()
            self.region = region or gcp.get_region()

        if study_config is not None:
            if objective is not None or hyperparameters is not None:
                raise ValueError(
                    "Pass either study_config or "
                    "(objective, hyperparameters), not both.")
            self.objective = tuner_utils.convert_study_config_to_objective(
                study_config)[0]
            self.hyperparameters = tuner_utils.convert_study_config_to_hps(
                study_config)
            self.study_config = study_config
        else:
            if objective is None or hyperparameters is None:
                raise ValueError(
                    "Provide (objective, hyperparameters) or a "
                    "study_config.")
            if not hyperparameters.space:
                raise ValueError("The hyperparameter search space is empty.")
            self.objective = tuner_utils.format_objective(objective)[0]
            self.hyperparameters = hyperparameters
            self.study_config = tuner_utils.make_study_config(
                self.objective, hyperparameters)

        self.max_trials = max_trials
        self.study_id = study_id or "cloud_tpu_tuner_{}".format(
            int(time.time()))
        # Two injection seams: `service_client` fakes the REST transport
        # under the real OptimizerClient; `client` replaces the
        # OptimizerClient surface wholesale (offline demos, unit tests).
        if client is not None:
            self.client = client
        else:
            from cloud_tpu.tuner import optimizer_client

            self.client = optimizer_client.create_or_load_study(
                self.project_id, self.region, self.study_id,
                self.study_config, service_client=service_client)

        self.trials = {}
        self._start_times = {}

    def create_trial(self, tuner_id):
        """Suggest the next trial, or a STOPPED sentinel when the budget
        is exhausted (reference tuner.py:129-200)."""
        if self.max_trials is not None:
            completed = [
                t for t in self.client.list_trials()
                if t.get("state") in ("COMPLETED", "INFEASIBLE")]
            if len(completed) >= self.max_trials:
                return Trial(tuner_id, self.hyperparameters.copy(),
                             status=TrialStatus.STOPPED)

        suggestions = self.client.get_suggestions(tuner_id)
        if not suggestions.get("trials"):
            # Search space or trial budget exhausted service-side.
            return Trial(tuner_id, self.hyperparameters.copy(),
                         status=TrialStatus.STOPPED)

        optimizer_trial = suggestions["trials"][0]
        trial_id = tuner_utils.get_trial_id(optimizer_trial)
        hps = tuner_utils.convert_optimizer_trial_to_hps(
            self.hyperparameters, optimizer_trial)
        trial = Trial(trial_id, hps)
        self.trials[trial_id] = trial
        self._start_times[trial_id] = time.time()
        return trial

    def update_trial(self, trial_id, metrics, step=0):
        """Report intermediate metrics; poll early stopping
        (reference tuner.py:202-240)."""
        elapsed = time.time() - self._start_times.get(trial_id, time.time())
        metric_list = [
            {"metric": k, "value": float(v)} for k, v in metrics.items()
            if k == self.objective.name]
        self.client.report_intermediate_objective_value(
            step, elapsed, metric_list, trial_id)
        trial = self.trials[trial_id]
        if self.client.should_trial_stop(trial_id):
            trial.status = TrialStatus.STOPPED
        return trial.status

    def end_trial(self, trial_id, status=TrialStatus.COMPLETED):
        """Complete (or mark infeasible) a trial
        (reference tuner.py:242-280)."""
        trial = self.trials[trial_id]
        infeasible = status == TrialStatus.INVALID
        optimizer_trial = self.client.complete_trial(
            trial_id, trial_infeasible=infeasible,
            infeasibility_reason=status if infeasible else None)
        if not infeasible:
            final = optimizer_trial.get("finalMeasurement")
            if final and final.get("metrics"):
                trial.score = final["metrics"][0].get("value")
                trial.best_step = int(final.get("stepCount", 0))
        trial.status = (TrialStatus.COMPLETED if not infeasible
                        else TrialStatus.INVALID)
        return trial

    def get_best_trials(self, num_trials=1):
        """Best completed trials by final measurement
        (reference tuner.py:282-330)."""
        maximizing = self.objective.direction == "max"
        completed = [
            t for t in self.client.list_trials()
            if t.get("state") == "COMPLETED" and t.get("finalMeasurement")]
        sorted_trials = sorted(
            completed,
            key=lambda t: t["finalMeasurement"]["metrics"][0].get(
                "value", float("-inf") if maximizing else float("inf")),
            reverse=maximizing)
        best = []
        for optimizer_trial in sorted_trials[:num_trials]:
            trial_id = tuner_utils.get_trial_id(optimizer_trial)
            trial = Trial(
                trial_id,
                tuner_utils.convert_optimizer_trial_to_hps(
                    self.hyperparameters, optimizer_trial),
                status=TrialStatus.COMPLETED)
            trial.score = optimizer_trial[
                "finalMeasurement"]["metrics"][0].get("value")
            trial.best_step = int(optimizer_trial[
                "finalMeasurement"].get("stepCount", 0))
            best.append(trial)
        return best


class _VizierReporter:
    """Trainer callback streaming the objective to Vizier each epoch and
    halting training when the service recommends early stopping (the
    reference achieves this through KerasTuner's per-epoch
    `on_epoch_end` -> oracle.update_trial wiring)."""

    def __init__(self, oracle, trial):
        self.oracle = oracle
        self.trial = trial

    def set_trainer(self, trainer):
        self.trainer = trainer

    def on_train_begin(self):
        pass

    def on_epoch_begin(self, epoch):
        pass

    def on_epoch_end(self, epoch, logs):
        objective = self.oracle.objective.name
        if objective not in logs:
            return
        status = self.oracle.update_trial(
            self.trial.trial_id, {objective: logs[objective]}, step=epoch)
        if status == TrialStatus.STOPPED:
            self.trainer.stop_training = True

    def on_train_end(self, history):
        pass


class CloudTuner:
    """Tuner running trials locally, trial selection by Vizier
    (reference tuner.py:333-381).

    Args:
        hypermodel: callable(hp: HyperParameters) -> Trainer.
        All other args forwarded to `CloudOracle`.
    """

    def __init__(self, hypermodel, directory="tuner_output",
                 tuner_id="tuner0", **oracle_kwargs):
        self.hypermodel = hypermodel
        self.directory = directory
        self.tuner_id = tuner_id
        self.oracle = CloudOracle(**oracle_kwargs)

    def search(self, x=None, y=None, **fit_kwargs):
        """The search loop: suggest -> run -> report, until exhausted."""
        while True:
            trial = self.oracle.create_trial(self.tuner_id)
            if trial.status == TrialStatus.STOPPED:
                logger.info("Search ended (budget or space exhausted).")
                break
            logger.info("Running trial %s: %s", trial.trial_id,
                        trial.hyperparameters.values)
            try:
                # Early-stopped trials still complete with their partial
                # measurements (reference tuner.py:261-272 reserves
                # INVALID for failures).
                self.run_trial(trial, x=x, y=y, **fit_kwargs)
                status = TrialStatus.COMPLETED
            except Exception:
                logger.exception("Trial %s failed; marking INVALID.",
                                 trial.trial_id)
                status = TrialStatus.INVALID
            self.oracle.end_trial(trial.trial_id, status)

    def run_trial(self, trial, x=None, y=None, **fit_kwargs):
        """Build + fit locally; stream per-epoch objective values to
        Vizier DURING training (so early stopping actually saves compute)
        with per-trial checkpoints (reference tuner.py:470-487,
        576-605)."""
        from cloud_tpu.training import callbacks as callbacks_lib
        from cloud_tpu.utils import storage

        trainer = self.hypermodel(trial.hyperparameters)
        trial_dir = storage.join(self.directory, str(trial.trial_id))
        callbacks = list(fit_kwargs.pop("callbacks", []))
        # Per-trial channels replace any user-supplied equivalents —
        # the reference's callback surgery (tuner.py:470-487): strip,
        # then re-add rooted at <dir>/<trial_id>/.
        callbacks = [c for c in callbacks
                     if not isinstance(c, (callbacks_lib.MetricsLogger,
                                           callbacks_lib.TensorBoard))]
        if not storage.is_gcs_path(trial_dir):
            callbacks.append(callbacks_lib.ModelCheckpoint(
                storage.join(trial_dir, "checkpoint")))
        callbacks.append(callbacks_lib.MetricsLogger(
            storage.join(trial_dir, "logs", "metrics.jsonl")))
        # Event-file compat beside the JSONL channel: TensorBoard
        # pointed at <dir>/<trial_id>/logs shows the trial's curves
        # (the reference's only channel, tuner.py:581-593).
        callbacks.append(callbacks_lib.TensorBoard(
            storage.join(trial_dir, "logs")))
        callbacks.append(_VizierReporter(self.oracle, trial))

        return trainer.fit(x, y, callbacks=callbacks, **fit_kwargs)

    def _report_history(self, trial, history):
        objective = self.oracle.objective.name
        values = history.get(objective, [])
        for epoch, value in enumerate(values):
            status = self.oracle.update_trial(
                trial.trial_id, {objective: value}, step=epoch)
            if status == TrialStatus.STOPPED:
                break

    def get_best_trials(self, num_trials=1):
        return self.oracle.get_best_trials(num_trials)

    def get_best_hyperparameters(self, num_trials=1):
        return [t.hyperparameters
                for t in self.get_best_trials(num_trials)]

    def results_summary(self, num_trials=10):
        """Logs the top trials (KerasTuner's `results_summary` shape):
        rank, trial id, objective value, and hyperparameter values."""
        objective = self.oracle.objective
        trials = self.get_best_trials(num_trials)
        lines = ["Results summary ({} best of study {!r}, "
                 "objective {} [{}]):".format(
                     len(trials), self.oracle.study_id,
                     objective.name, objective.direction)]
        for rank, trial in enumerate(trials, start=1):
            lines.append("  #{} trial {}: {} = {}".format(
                rank, trial.trial_id, objective.name,
                getattr(trial, "score", None)))
            for name, value in sorted(
                    trial.hyperparameters.values.items()):
                lines.append("      {}: {}".format(name, value))
        text = "\n".join(lines)
        logger.info("%s", text)
        return text


class DistributingCloudTuner(CloudTuner):
    """Tuner whose trials each train remotely on a TPU slice via
    cloud_fit (reference tuner.py:384-606).

    Args:
        remote_dir: Durable storage root; trial assets/outputs live at
            `<remote_dir>/<trial_id>` (reference tuner.py:595-605 layout).
        image_uri: Container image for remote trials.
        distribution_strategy: runtime strategy for remote workers.
    """

    def __init__(self, hypermodel, remote_dir, image_uri=None,
                 distribution_strategy="tpu_slice", job_api_client=None,
                 **kwargs):
        super().__init__(hypermodel, directory=remote_dir, **kwargs)
        self.remote_dir = remote_dir
        self.image_uri = image_uri
        self.distribution_strategy = distribution_strategy
        self._job_api_client = job_api_client

    def run_trial(self, trial, x=None, y=None, **fit_kwargs):
        from cloud_tpu.cloud_fit import client as cloud_fit_client
        from cloud_tpu.utils import google_api_client
        from cloud_tpu.utils import storage

        trainer = self.hypermodel(trial.hyperparameters)
        trial_dir = storage.join(self.remote_dir, str(trial.trial_id))
        job_id = "{}_{}".format(self.oracle.study_id, trial.trial_id)

        cloud_fit_client.cloud_fit(
            trainer, trial_dir,
            image_uri=self.image_uri,
            distribution_strategy=self.distribution_strategy,
            job_id=job_id,
            x=x, y=y,
            api_client=self._job_api_client,
            **fit_kwargs)

        # Block until the remote job finishes (reference tuner.py:512-516),
        # then read the structured history back (vs event-file parsing,
        # reference tuner.py:532-560).
        if not google_api_client.wait_for_api_training_job_success(
                job_id, self.oracle.project_id,
                api_client=self._job_api_client):
            raise RuntimeError(
                "AIP Training job failed: {}".format(job_id))
        history = self._get_remote_training_metrics(trial_dir)
        self._report_history(trial, history)
        return history

    def _get_remote_training_metrics(self, trial_dir):
        from cloud_tpu.cloud_fit import remote as cloud_fit_remote
        from cloud_tpu.utils import storage

        history_path = storage.join(trial_dir, cloud_fit_remote.OUTPUT_DIR,
                                    cloud_fit_remote.HISTORY_FILE)
        return json.loads(storage.read_bytes(history_path))

    def load_trainer(self, trial, sample_x):
        """Re-hydrates the trial's trained Trainer (the reference leaves
        this NotImplemented, tuner.py:562-567).

        Args:
            trial: A completed `Trial`.
            sample_x: A sample input batch used to build congruent state
                before restoring the checkpoint into it.
        """
        import pickle

        from cloud_tpu.cloud_fit import client as cloud_fit_client
        from cloud_tpu.cloud_fit import remote as cloud_fit_remote
        from cloud_tpu.training import checkpoint as checkpoint_lib
        from cloud_tpu.utils import storage

        trial_dir = storage.join(self.remote_dir, str(trial.trial_id))
        spec = pickle.loads(storage.read_bytes(
            storage.join(trial_dir, cloud_fit_client.SPEC_FILE)))
        trainer = cloud_fit_remote.build_trainer(spec)
        output_dir = storage.join(trial_dir, cloud_fit_remote.OUTPUT_DIR)
        trainer.build(sample_x)
        # gs:// works as-is: checkpoint.restore hands the URI straight
        # to orbax, whose tensorstore backend reads GCS directly — the
        # per-trial layout real distributed trials write (the reference
        # leaves remote restore NotImplemented, tuner.py:562-567).
        trainer.state = checkpoint_lib.restore(output_dir, trainer.state)
        return trainer
