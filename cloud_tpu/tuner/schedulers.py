"""graftsweep trial sources and the ASHA rung scheduler.

The sweep engine (cloud_tpu/tuner/sweep.py) separates WHAT to try from
WHEN to stop trying it:

- An **oracle** proposes hyperparameter assignments from the existing
  `hyperparameters.py` search space: `RandomOracle` (seeded i.i.d.
  samples, deterministic per trial index) and `GridOracle` (the full
  cartesian product over discrete axes, mixed-radix enumeration so
  trial k is a pure function of k). Both are offline/local — the
  Vizier-backed `CloudOracle` stays in tuner.py for the hosted path.

- A **scheduler** decides budgets and early stopping. `ASHA` is
  asynchronous successive halving (Li et al., "A System for Massively
  Parallel Hyperparameter Tuning"): rung r runs trials to
  `min_budget * eta**r` epochs; whenever any rung holds at least
  `eta * (promotions so far + 1)` reported trials, its best unpromoted
  top-1/eta trial is promoted to the next rung — no synchronization
  barrier, so one worker (or many) always has a job. Trials that reach
  the top rung COMPLETE; trials still paused at a lower rung when the
  sweep drains are PRUNED (terminal, never lost).

Scores flow through `report(trial_id, rung, score)` in the objective's
raw units; direction ("min"/"max") comes from the `Objective` so the
promotion math never sees negated values.
"""

import logging

logger = logging.getLogger("cloud_tpu")


# --------------------------------------------------------------------------
# Oracles: trial index -> HyperParameters (or None when exhausted)
# --------------------------------------------------------------------------


class RandomOracle:
    """Seeded random search over a `HyperParameters` space.

    Trial k samples with seed `seed * 1_000_003 + k`, so a proposal is
    a pure function of (seed, k): a re-run — or a bit-identity control
    re-running one trial of a finished sweep — reproduces the exact
    assignment without replaying the sweep.
    """

    name = "random"

    def __init__(self, hyperparameters, max_trials, seed=0):
        if not hyperparameters.space:
            raise ValueError("The hyperparameter search space is empty.")
        if max_trials < 1:
            raise ValueError("max_trials must be >= 1; got {}."
                             .format(max_trials))
        self.hyperparameters = hyperparameters
        self.max_trials = int(max_trials)
        self.seed = int(seed)

    def propose(self, index):
        if index >= self.max_trials:
            return None
        return self.hyperparameters.random_sample(
            self.seed * 1_000_003 + index)


class GridOracle:
    """Exhaustive cartesian product over discrete axes.

    Axis values per parameter kind: Choice -> its values, Boolean ->
    (False, True), Fixed -> its single value, Int/Float -> the stepped
    range (both require `step`; an unstepped continuous axis has no
    finite grid and raises up front rather than silently subsampling).
    Trial k decodes k in mixed radix over the axes in space-insertion
    order — last axis fastest, like itertools.product.
    """

    name = "grid"

    def __init__(self, hyperparameters):
        if not hyperparameters.space:
            raise ValueError("The hyperparameter search space is empty.")
        self.hyperparameters = hyperparameters
        self.axes = [(name, self._axis_values(param))
                     for name, param in hyperparameters.space.items()]
        self.max_trials = 1
        for _, values in self.axes:
            self.max_trials *= len(values)

    @staticmethod
    def _axis_values(param):
        kind = getattr(param, "kind", None)
        if kind == "choice":
            return list(param.values)
        if kind == "boolean":
            return [False, True]
        if kind == "fixed":
            return [param.value]
        if kind == "int":
            if param.step:
                return list(range(param.min_value, param.max_value + 1,
                                  int(param.step)))
            return list(range(param.min_value, param.max_value + 1))
        if kind == "float":
            if not param.step:
                raise ValueError(
                    "GridOracle needs a finite axis for {!r}: give the "
                    "Float a step= or use Choice.".format(param.name))
            n = int(round((param.max_value - param.min_value)
                          / param.step))
            return [param.min_value + i * param.step
                    for i in range(n + 1)]
        raise ValueError("GridOracle cannot enumerate parameter kind "
                         "{!r} ({!r}).".format(kind, param.name))

    def propose(self, index):
        if index >= self.max_trials:
            return None
        hp = self.hyperparameters.copy()
        rem = index
        for name, values in reversed(self.axes):
            rem, digit = divmod(rem, len(values))
            hp.values[name] = values[digit]
        return hp


# --------------------------------------------------------------------------
# ASHA: asynchronous successive halving
# --------------------------------------------------------------------------


class ASHA:
    """Asynchronous successive-halving rung scheduler.

    Rung budgets are `min_budget * eta**r` epochs, capped at
    `max_budget` (which always terminates the ladder, so a trial that
    reaches the top rung is COMPLETE). `next_rung()` is checked before
    every new proposal — the async rule: promote whenever some rung's
    top 1/eta holds an unpromoted trial, scanning the highest rung
    first so near-finished trials finish ahead of fresh starts.
    """

    name = "asha"

    def __init__(self, objective, min_budget=1, eta=3, max_budget=None):
        if eta < 2:
            raise ValueError("eta must be >= 2; got {}.".format(eta))
        if min_budget < 1:
            raise ValueError("min_budget must be >= 1; got {}."
                             .format(min_budget))
        if max_budget is None:
            max_budget = min_budget * eta ** 2
        if max_budget < min_budget:
            raise ValueError(
                "max_budget {} < min_budget {}.".format(max_budget,
                                                        min_budget))
        self.objective = objective
        self.eta = int(eta)
        self.budgets = []
        budget = int(min_budget)
        while budget < int(max_budget):
            self.budgets.append(budget)
            budget *= self.eta
        self.budgets.append(int(max_budget))
        # rung index -> {trial_id: score}; promotions out of each rung.
        self.results = [dict() for _ in self.budgets]
        self.promoted = [set() for _ in self.budgets]

    @property
    def top_rung(self):
        return len(self.budgets) - 1

    def report(self, trial_id, rung, score):
        """Records a trial's score at rung `rung` (its budget's epoch
        count reached). Re-reports overwrite — the score at a rung is
        the trial's value AT that budget, whatever path got it there."""
        self.results[rung][trial_id] = float(score)

    def _ranked(self, rung):
        reverse = self.objective.direction == "max"
        return sorted(self.results[rung].items(),
                      key=lambda item: item[1], reverse=reverse)

    def next_promotion(self):
        """(trial_id, next_rung) for the best promotable trial, or
        None. A rung can promote its i-th trial once it holds at least
        `eta * i` reports — the top-1/eta rule applied online."""
        for rung in range(self.top_rung - 1, -1, -1):
            quota = len(self.results[rung]) // self.eta
            if quota <= len(self.promoted[rung]):
                continue
            for trial_id, _ in self._ranked(rung)[:quota]:
                if trial_id not in self.promoted[rung]:
                    return trial_id, rung + 1
        return None

    def promote(self, trial_id, next_rung):
        """Commits a promotion returned by `next_promotion`."""
        self.promoted[next_rung - 1].add(trial_id)

    def paused(self):
        """Trial ids reported at some rung but neither promoted out of
        it nor at the top rung — the set a draining sweep prunes."""
        out = []
        for rung in range(self.top_rung):
            for trial_id, score in self.results[rung].items():
                if trial_id not in self.promoted[rung]:
                    out.append((trial_id, rung, score))
        # A trial sits unpromoted in at most one rung (reporting at
        # rung r+1 implies promotion out of r), so no dedup needed.
        return sorted(out)

    def cutoff(self, rung):
        """The score a trial must beat to sit in rung `rung`'s current
        top 1/eta (None while the rung holds fewer than eta reports) —
        recorded in prune events so a pruned trial's event row shows
        what it lost to."""
        quota = len(self.results[rung]) // self.eta
        if quota == 0:
            return None
        return self._ranked(rung)[quota - 1][1]


__all__ = ["RandomOracle", "GridOracle", "ASHA"]
