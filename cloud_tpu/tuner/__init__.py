from cloud_tpu.tuner.hyperparameters import HyperParameters, Objective
from cloud_tpu.tuner.optimizer_client import (OptimizerClient,
                                              SuggestionInactiveError,
                                              create_or_load_study)
from cloud_tpu.tuner.tuner import (CloudOracle, CloudTuner,
                                   DistributingCloudTuner, Trial,
                                   TrialStatus)
