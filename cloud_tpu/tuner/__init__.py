"""Hyperparameter search: local-first graftsweep + Vizier-backed tuner.

Every name resolves lazily (PEP 562): `import cloud_tpu.tuner` touches
nothing — not googleapiclient, not cloud_fit (whose remote module pulls
jax), not the sweep engine. The hosted-path classes
(CloudOracle/CloudTuner/DistributingCloudTuner) import their GCP
machinery only inside the methods that reach the service, so an
offline process pays for exactly what it uses.
"""

_LAZY = {
    # The search-space / objective surface (pure python).
    "HyperParameters": "cloud_tpu.tuner.hyperparameters",
    "Objective": "cloud_tpu.tuner.hyperparameters",
    # graftsweep: local-first supervised sweeps.
    "Sweep": "cloud_tpu.tuner.sweep",
    "SweepTrial": "cloud_tpu.tuner.sweep",
    "SweepTrialStatus": "cloud_tpu.tuner.sweep",
    "RandomOracle": "cloud_tpu.tuner.schedulers",
    "GridOracle": "cloud_tpu.tuner.schedulers",
    "ASHA": "cloud_tpu.tuner.schedulers",
    # The Vizier-backed hosted path.
    "CloudOracle": "cloud_tpu.tuner.tuner",
    "CloudTuner": "cloud_tpu.tuner.tuner",
    "DistributingCloudTuner": "cloud_tpu.tuner.tuner",
    "Trial": "cloud_tpu.tuner.tuner",
    "TrialStatus": "cloud_tpu.tuner.tuner",
    "OptimizerClient": "cloud_tpu.tuner.optimizer_client",
    "SuggestionInactiveError": "cloud_tpu.tuner.optimizer_client",
    "create_or_load_study": "cloud_tpu.tuner.optimizer_client",
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    module_name = _LAZY.get(name)
    if module_name is None:
        raise AttributeError(
            "module {!r} has no attribute {!r}".format(__name__, name))
    import importlib

    value = getattr(importlib.import_module(module_name), name)
    globals()[name] = value  # cache: resolve once per process
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
