"""graftsweep: fault-tolerant local-first hyperparameter sweeps.

ROADMAP item 5. The Vizier-backed `CloudTuner` (tuner.py) round-trips
a hosted service per trial and predates every piece of machinery from
PRs 1-14; this engine is the local-first rebuild that actually reaches
it all:

- **Trials are graftguard-supervised.** Every trial segment runs under
  `resilience.resilient_fit` with a per-trial checkpoint directory
  (`<directory>/<trial_id>/`), so the typed fault taxonomy (Preemption,
  CheckpointCorrupt, NaNLoss, BackendUnavailable) is answered per kind
  exactly as in training: a preempted trial RESUMES mid-epoch
  bit-identical instead of being re-scored from scratch, and the
  deterministic `CLOUD_TPU_CHAOS` injector exercises it in CI. Per-
  trial fault/retry/rollback attribution comes from
  `resilience.guard_scope()` deltas — the process-global counters
  never bleed between trials.

- **Trials of one shape signature share one warm Trainer.** The first
  trial of a signature builds via the user's `build(hp)` and pays the
  cold compile; every later same-signature trial REUSES that Trainer —
  state nulled and re-initialized from the trial's seed (plain
  jax.random + optimizer init: the instrumented compile census does
  not move), runtime-only hyperparameters applied to the live
  `opt_state` (optax `inject_hyperparams` — the traced graph reads
  them from state, so no retrace) or via a user `apply(trainer, hp)`
  hook. The step executables live in the Trainer's per-shape caches
  and the AOT warm table, so trial N>1 reports
  `new_traces == new_compiles == 0` — the compile census pins it.

- **ASHA rungs early-stop via the metric stream.** With an `ASHA`
  scheduler (schedulers.py), rung-0 trials run `min_budget` epochs;
  promotions literally resume the trial's checkpoint through the warm
  executables (`initial_epoch`/`resume_from`) up to the next rung's
  budget. Paused trials that never promote are PRUNED at drain —
  every trial ends terminal (COMPLETED / PRUNED / FAILED), never lost.

- **Everything lands in the JSONL job-event log** (`kind="graftsweep"`
  via CLOUD_TPU_EVENT_LOG): sweep_start, trial_start, rung_report
  (per epoch), promote, prune, fault, resume, complete,
  sweep_complete. `python -m cloud_tpu.monitoring.collect --sweep`
  rolls the log into `sweep_report.json`
  (`cloud_tpu.sweep_report.v1`); `cloud_tpu_sweep_*` telemetry
  counters/gauges ride the graftscope registry when one is active.

Usage::

    hp = HyperParameters()
    hp.Float("learning_rate", 1e-3, 1e-1, sampling="log")

    def build(hp):
        opt = optax.inject_hyperparams(optax.sgd)(
            learning_rate=hp.get("learning_rate"))
        return Trainer(MLP(hidden=32, num_classes=4), optimizer=opt)

    sweep = Sweep(build, hp, Objective("loss", "min"),
                  directory="/tmp/sweep",
                  oracle=RandomOracle(hp, max_trials=12),
                  scheduler=ASHA(Objective("loss", "min"),
                                 min_budget=1, eta=3, max_budget=9))
    result = sweep.run(x, y, batch_size=32)
"""

import json
import logging
import os
import sys
import time

from cloud_tpu.parallel import runtime
from cloud_tpu.training import callbacks as callbacks_lib
from cloud_tpu.training import resilience
from cloud_tpu.tuner import schedulers as schedulers_lib

logger = logging.getLogger("cloud_tpu")


class SweepTrialStatus:
    RUNNING = "RUNNING"
    PAUSED = "PAUSED"
    COMPLETED = "COMPLETED"
    PRUNED = "PRUNED"
    FAILED = "FAILED"

    TERMINAL = (COMPLETED, PRUNED, FAILED)


# --------------------------------------------------------------------------
# Telemetry / events (graftguard's soft-dependency discipline)
# --------------------------------------------------------------------------


def _registry():
    telemetry = sys.modules.get("cloud_tpu.monitoring.telemetry")
    if telemetry is None:
        return None
    try:
        tele = telemetry.get()
        if tele is None or not tele.active:
            return None
        return tele.registry
    except Exception:
        return None


def _count(name, delta=1):
    reg = _registry()
    if reg is None or not delta:
        return
    try:
        reg.counter(name).inc(delta)
    except Exception:
        logger.debug("graftsweep: counter %s export failed", name,
                     exc_info=True)


def _gauge(name, value):
    reg = _registry()
    if reg is None or value is None:
        return
    try:
        reg.gauge(name).set(value)
    except Exception:
        logger.debug("graftsweep: gauge %s export failed", name,
                     exc_info=True)


def _log_event(payload):
    try:
        from cloud_tpu.utils import events

        events.log_job_event("graftsweep", payload)
    except Exception:
        logger.debug("graftsweep: job event export failed",
                     exc_info=True)


# --------------------------------------------------------------------------
# Trial record
# --------------------------------------------------------------------------


class SweepTrial:
    """One hyperparameter evaluation and its full lifecycle ledger."""

    def __init__(self, index, trial_id, hp, seed, signature):
        self.index = index
        self.trial_id = trial_id
        self.hp = hp
        self.seed = seed
        self.signature = signature
        self.status = SweepTrialStatus.RUNNING
        self.score = None
        self.history = {}
        self.rungs = []          # [{"rung", "budget_epochs", "score"}]
        self.epochs = 0          # highest budget reached
        self.cold = False        # this trial built its signature's Trainer
        self.error = None
        # Guard census, accumulated across segments.
        self.faults = 0
        self.retries = 0
        self.rollbacks = 0
        self.resumes = 0
        self.fault_kinds = []
        # Compile census, accumulated across segments.
        self.new_traces = 0
        self.new_compiles = 0
        self.compile_seconds = 0.0
        self.wall_s = 0.0

    def spec(self):
        return {
            "trial": self.trial_id,
            "index": self.index,
            "hp": dict(self.hp.values),
            "seed": self.seed,
            "signature": self.signature,
            "status": self.status,
            "score": self.score,
            "rungs": list(self.rungs),
            "epochs": self.epochs,
            "cold": self.cold,
            "error": self.error,
            "faults": self.faults,
            "retries": self.retries,
            "rollbacks": self.rollbacks,
            "resumes": self.resumes,
            "fault_kinds": list(self.fault_kinds),
            "new_traces": self.new_traces,
            "new_compiles": self.new_compiles,
            "compile_seconds": round(self.compile_seconds, 6),
            "wall_s": round(self.wall_s, 6),
        }


class _RungReporter(callbacks_lib.Callback):
    """Per-epoch rung_report events off the (async) metric stream —
    the score a rung decision will read, visible while the trial is
    still running, not only at its end."""

    def __init__(self, sweep, trial, rung):
        self.sweep = sweep
        self.trial = trial
        self.rung = rung

    def on_epoch_end(self, epoch, logs):
        name = self.sweep.objective.name
        value = (logs or {}).get(name)
        if value is None:
            return
        try:
            value = float(value)
        except (TypeError, ValueError):
            return
        self.trial.score = value
        _log_event({"event": "rung_report", "sweep": self.sweep.name,
                    "trial": self.trial.trial_id, "rung": self.rung,
                    "epoch": int(epoch), "score": value})


# --------------------------------------------------------------------------
# The engine
# --------------------------------------------------------------------------


class Sweep:
    """Local-first, graftguard-supervised hyperparameter sweep.

    Args:
        build: callable(hp: HyperParameters) -> Trainer (the
            hypermodel). Called once per SHAPE SIGNATURE, not once per
            trial — same-signature trials reuse the warm Trainer.
        hyperparameters: The search space (used for the default
            signature keys; proposals come from the oracle).
        objective: `Objective(name, direction)` — the history metric
            rung decisions and best-trial selection read.
        directory: Sweep root; trial t's checkpoints live at
            `<directory>/<trial_id>/`.
        oracle: Trial source (`RandomOracle` / `GridOracle` /
            anything with `.propose(index)` and `.max_trials`).
            Defaults to `RandomOracle(hyperparameters, max_trials)`.
        scheduler: Optional `ASHA`. None runs every trial to `epochs`
            in one segment (plain random/grid search).
        max_trials: Budget for the default oracle (ignored when an
            oracle is passed).
        epochs: Per-trial epochs WITHOUT a scheduler (with one, rung
            budgets rule).
        seed: Base seed; trial k trains with seed `seed + k` (param
            init AND shuffle stream — the bit-identity control re-runs
            a trial from its recorded seed alone).
        shape_keys: Names of hyperparameters that change compiled
            shapes (model width, batch geometry, ...). Default None
            treats EVERY non-Fixed parameter as shape-affecting —
            correct for any build(), no cross-trial reuse unless
            values collide. Pass an explicit tuple (often `()`) to
            declare the rest runtime-only and unlock Trainer sharing;
            runtime-only values are applied to a reused Trainer via
            optax `inject_hyperparams` state (or `apply`).
        apply: Optional callable(trainer, hp) applying runtime-only
            hyperparameters to a REUSED warm Trainer. Default edits
            `state.opt_state.hyperparams` entries matching hp names
            (optax.inject_hyperparams).
        retries: graftguard retry budget per segment (default:
            `CLOUD_TPU_RETRIES`).
        name: Sweep id stamped on every event (default "sweep").
    """

    def __init__(self, build, hyperparameters, objective, directory,
                 oracle=None, scheduler=None, max_trials=None, epochs=1,
                 seed=0, shape_keys=None, apply=None, retries=None,
                 name="sweep"):
        if oracle is None:
            if max_trials is None:
                raise ValueError("Pass an oracle or max_trials.")
            oracle = schedulers_lib.RandomOracle(
                hyperparameters, max_trials, seed=seed)
        self.build = build
        self.hyperparameters = hyperparameters
        self.objective = objective
        self.directory = str(directory)
        self.oracle = oracle
        self.scheduler = scheduler
        self.epochs = int(epochs)
        self.seed = int(seed)
        self.shape_keys = (None if shape_keys is None
                           else tuple(shape_keys))
        self.apply = apply
        self.retries = retries
        self.name = str(name)

        self.trials = []
        self._by_id = {}
        self._trainers = {}       # signature -> warm Trainer
        self._warned_inert = set()
        self._wall_s = 0.0
        self._train_s = 0.0

    # -- signatures / trainer cache -------------------------------------

    def signature(self, hp):
        """Stable identity of the compiled-shape-affecting values."""
        keys = self.shape_keys
        if keys is None:
            keys = [n for n, p in hp.space.items()
                    if getattr(p, "kind", None) != "fixed"]
        sig = {k: hp.values[k] for k in sorted(keys) if k in hp.values}
        return json.dumps(sig, sort_keys=True, default=repr)

    def _apply_hp(self, trainer, hp):
        """Applies runtime-only hyperparameters to a reused Trainer's
        freshly initialized state. The default path targets optax
        `inject_hyperparams`: those live in `opt_state.hyperparams`,
        which the traced step reads as state — a host-side dict edit,
        never a retrace."""
        if self.apply is not None:
            self.apply(trainer, hp)
            return
        applied = set()
        state = getattr(trainer, "state", None)
        hyperparams = getattr(getattr(state, "opt_state", None),
                              "hyperparams", None)
        if isinstance(hyperparams, dict):
            import jax.numpy as jnp

            for pname, value in hp.values.items():
                if pname in hyperparams:
                    old = hyperparams[pname]
                    hyperparams[pname] = jnp.asarray(
                        value, getattr(old, "dtype", None))
                    applied.add(pname)
        sig_keys = (set(hp.space) if self.shape_keys is None
                    else set(self.shape_keys))
        inert = [n for n, p in hp.space.items()
                 if n not in sig_keys and n not in applied
                 and getattr(p, "kind", None) != "fixed"]
        for pname in inert:
            if pname not in self._warned_inert:
                self._warned_inert.add(pname)
                logger.warning(
                    "graftsweep: hyperparameter %r is neither a "
                    "shape_key nor applied to the reused Trainer "
                    "(no opt_state.hyperparams entry and no apply= "
                    "hook) — its values have no effect on warm "
                    "trials.", pname)

    def _trainer_for(self, trial, sample_x):
        """The signature's warm Trainer; builds it on first ask (the
        cold trial). A reused Trainer gets fresh state from the
        trial's seed — plain init calls on the host path, so the
        instrumented compile census does not move — and keeps its
        step executables (state is an argument; they close over model
        and optimizer only)."""
        trainer = self._trainers.get(trial.signature)
        if trainer is None:
            trainer = self.build(trial.hp.copy())
            trainer.seed = trial.seed
            self._trainers[trial.signature] = trainer
            trial.cold = True
            return trainer
        trainer.state = None
        trainer.seed = trial.seed
        trainer.build(sample_x)
        self._apply_hp(trainer, trial.hp)
        return trainer

    # -- segments --------------------------------------------------------

    def _trial_dir(self, trial):
        return os.path.join(self.directory, trial.trial_id)

    def _run_segment(self, trial, rung, initial_epoch, epochs, x, y,
                     sample_x, fit_kwargs):
        """One supervised segment: [initial_epoch, epochs) under
        graftguard, scored at its end. Returns the score, or None when
        the trial FAILED (terminal; the complete event is emitted)."""
        trainer = self._trainer_for(trial, sample_x)
        reporter = _RungReporter(self, trial, rung)
        kwargs = dict(fit_kwargs)
        kwargs["callbacks"] = (tuple(kwargs.get("callbacks") or ())
                               + (reporter,))
        kwargs.setdefault("verbose", False)
        kwargs.setdefault("warm_start", True)
        cs0 = runtime.compile_stats()
        t0 = time.monotonic()
        error = None
        with resilience.guard_scope() as guard:
            try:
                resilience.resilient_fit(
                    trainer, directory=self._trial_dir(trial),
                    retries=self.retries, x=x, y=y, epochs=epochs,
                    initial_epoch=initial_epoch, history=trial.history,
                    **kwargs)
            except (KeyboardInterrupt, SystemExit):
                raise
            except Exception as exc:  # noqa: BLE001 - trial isolation
                error = exc
            census = guard.stats()
        cs1 = runtime.compile_stats()
        wall = time.monotonic() - t0
        trial.wall_s += wall
        self._train_s += wall
        trial.faults += census["faults"]
        trial.retries += census["retries"]
        trial.rollbacks += census["rollbacks"]
        trial.resumes += census["resumes"]
        if census["last_fault"]:
            trial.fault_kinds.append(census["last_fault"])
        new_traces = cs1["n_traces"] - cs0["n_traces"]
        new_compiles = cs1["n_compiles"] - cs0["n_compiles"]
        trial.new_traces += new_traces
        trial.new_compiles += new_compiles
        trial.compile_seconds += (cs1["compile_seconds"]
                                  - cs0["compile_seconds"])
        _count("cloud_tpu_sweep_faults_total", census["faults"])
        _count("cloud_tpu_sweep_resumes_total", census["resumes"])
        if census["faults"]:
            _log_event({"event": "fault", "sweep": self.name,
                        "trial": trial.trial_id, "rung": rung,
                        "faults": census["faults"],
                        "retries": census["retries"],
                        "rollbacks": census["rollbacks"],
                        "last_fault": census["last_fault"]})
        if census["resumes"]:
            _log_event({
                "event": "resume", "sweep": self.name,
                "trial": trial.trial_id, "rung": rung,
                "resumes": census["resumes"],
                "resume_latency_seconds":
                    census["last_resume_latency_seconds"],
                "new_traces": census["last_resume_new_traces"],
                "new_compiles": census["last_resume_new_compiles"]})
        if error is not None:
            trial.error = "{}: {}".format(type(error).__name__, error)
            logger.warning("graftsweep: trial %s failed terminally: %s",
                           trial.trial_id, trial.error, exc_info=error)
            self._finish(trial, SweepTrialStatus.FAILED)
            return None
        trial.epochs = epochs
        score = self._score(trial)
        if score is None:
            trial.error = ("objective {!r} never appeared in the "
                           "history (keys: {})".format(
                               self.objective.name,
                               sorted(trial.history)))
            self._finish(trial, SweepTrialStatus.FAILED)
            return None
        trial.score = score
        trial.rungs.append({"rung": rung, "budget_epochs": epochs,
                            "score": score})
        return score

    def _score(self, trial):
        values = trial.history.get(self.objective.name) or []
        if not values:
            return None
        try:
            return float(values[-1])
        except (TypeError, ValueError):
            return None

    def _finish(self, trial, status):
        trial.status = status
        _count("cloud_tpu_sweep_trials_total")
        if status == SweepTrialStatus.PRUNED:
            _count("cloud_tpu_sweep_trials_pruned_total")
        elif status == SweepTrialStatus.FAILED:
            _count("cloud_tpu_sweep_trials_failed_total")
        if not trial.cold and trial.new_compiles == 0:
            _count("cloud_tpu_sweep_warm_trials_total")
        payload = dict(trial.spec())
        payload["event"] = "complete"
        payload["sweep"] = self.name
        _log_event(payload)

    # -- the sweep loop --------------------------------------------------

    def run(self, x=None, y=None, **fit_kwargs):
        """Runs the sweep to drain; returns the result dict (also the
        shape `collect --sweep` reconstructs from the event log).
        Extra kwargs forward to every trial's fit (batch_size,
        shuffle, steps_per_epoch, ...)."""
        import jax

        from cloud_tpu.analysis import chaos

        t_start = time.monotonic()
        plan = chaos.active_plan()
        if plan is not None:
            # Trial-local step counters restart at 0 every trial; the
            # cumulative dispatch index makes `preempt@N` land at one
            # deterministic point of the SWEEP, whichever trial covers
            # it.
            plan.set_step_mode("cumulative")
        batch_size = fit_kwargs.get("batch_size", 32)
        if hasattr(x, "shape") or isinstance(x, (dict, list, tuple)):
            sample_x = jax.tree_util.tree_map(
                lambda a: a[:batch_size], x)
        else:
            sample = next(iter(x))
            sample_x = sample[0] if isinstance(sample, tuple) else sample

        budgets = (list(self.scheduler.budgets) if self.scheduler
                   else [self.epochs])
        _log_event({
            "event": "sweep_start", "sweep": self.name,
            "oracle": getattr(self.oracle, "name",
                              type(self.oracle).__name__),
            "scheduler": (getattr(self.scheduler, "name", None)
                          if self.scheduler else None),
            "objective": {"name": self.objective.name,
                          "direction": self.objective.direction},
            "max_trials": getattr(self.oracle, "max_trials", None),
            "budgets": budgets,
            "directory": self.directory,
            "space": {n: getattr(p, "kind", "?")
                      for n, p in self.hyperparameters.space.items()},
        })

        index = 0
        while True:
            promo = (self.scheduler.next_promotion()
                     if self.scheduler else None)
            if promo is not None:
                trial_id, rung = promo
                self.scheduler.promote(trial_id, rung)
                trial = self._by_id[trial_id]
                budget = self.scheduler.budgets[rung]
                start = self.scheduler.budgets[rung - 1]
                _log_event({"event": "promote", "sweep": self.name,
                            "trial": trial_id, "rung": rung,
                            "budget_epochs": budget,
                            "score": trial.score})
                trial.status = SweepTrialStatus.RUNNING
                score = self._run_segment(trial, rung, start, budget,
                                          x, y, sample_x, fit_kwargs)
                if score is not None:
                    self.scheduler.report(trial_id, rung, score)
                    if rung == self.scheduler.top_rung:
                        self._finish(trial, SweepTrialStatus.COMPLETED)
                    else:
                        trial.status = SweepTrialStatus.PAUSED
                continue
            hp = self.oracle.propose(index)
            if hp is None:
                break
            trial = SweepTrial(
                index, "t{:04d}".format(index), hp,
                seed=self.seed + index, signature=self.signature(hp))
            index += 1
            self.trials.append(trial)
            self._by_id[trial.trial_id] = trial
            budget = budgets[0]
            _log_event({"event": "trial_start", "sweep": self.name,
                        "trial": trial.trial_id, "hp": dict(hp.values),
                        "seed": trial.seed,
                        "signature": trial.signature,
                        "rung": 0, "budget_epochs": budget})
            score = self._run_segment(trial, 0, 0, budget, x, y,
                                      sample_x, fit_kwargs)
            if score is None:
                continue
            if self.scheduler is None:
                self._finish(trial, SweepTrialStatus.COMPLETED)
            else:
                self.scheduler.report(trial.trial_id, 0, score)
                if self.scheduler.top_rung == 0:
                    self._finish(trial, SweepTrialStatus.COMPLETED)
                else:
                    trial.status = SweepTrialStatus.PAUSED

        # Drain: paused trials that never earned a promotion are
        # pruned — terminal, with the cutoff they lost to on record.
        if self.scheduler is not None:
            for trial_id, rung, score in self.scheduler.paused():
                trial = self._by_id[trial_id]
                if trial.status in SweepTrialStatus.TERMINAL:
                    continue
                _log_event({"event": "prune", "sweep": self.name,
                            "trial": trial_id, "rung": rung,
                            "score": score,
                            "cutoff": self.scheduler.cutoff(rung)})
                self._finish(trial, SweepTrialStatus.PRUNED)

        self._wall_s = time.monotonic() - t_start
        result = self.result()
        _log_event({
            "event": "sweep_complete", "sweep": self.name,
            "trials": len(self.trials),
            "statuses": result["statuses"],
            "best": (result["best"] or {}).get("trial"),
            "best_score": (result["best"] or {}).get("score"),
            "census": result["census"],
            "compile": result["compile"],
            "wall_s": round(self._wall_s, 6),
            "train_s": round(self._train_s, 6),
        })
        if result["best"] is not None:
            _gauge("cloud_tpu_sweep_best_score",
                   result["best"]["score"])
        _gauge("cloud_tpu_sweep_compile_seconds",
               result["compile"]["total_seconds"])
        return result

    # -- rollups ---------------------------------------------------------

    def best_trial(self):
        """Best terminal COMPLETED trial by the objective (falls back
        to any scored trial when nothing completed)."""
        scored = [t for t in self.trials
                  if t.status == SweepTrialStatus.COMPLETED
                  and t.score is not None]
        if not scored:
            scored = [t for t in self.trials if t.score is not None]
        if not scored:
            return None
        best = (max if self.objective.direction == "max" else min)(
            scored, key=lambda t: t.score)
        return best

    def result(self):
        statuses = {}
        for trial in self.trials:
            statuses[trial.status] = statuses.get(trial.status, 0) + 1
        fault_kind_census = {}
        for trial in self.trials:
            for kind in trial.fault_kinds:
                fault_kind_census[kind] = (
                    fault_kind_census.get(kind, 0) + 1)
        cold = [t for t in self.trials if t.cold]
        warm = [t for t in self.trials if not t.cold]
        best = self.best_trial()
        return {
            "format": "cloud_tpu.sweep_result.v1",
            "sweep": self.name,
            "objective": {"name": self.objective.name,
                          "direction": self.objective.direction},
            "trials": [t.spec() for t in self.trials],
            "statuses": statuses,
            "best": best.spec() if best is not None else None,
            "census": {
                "faults": sum(t.faults for t in self.trials),
                "retries": sum(t.retries for t in self.trials),
                "rollbacks": sum(t.rollbacks for t in self.trials),
                "resumes": sum(t.resumes for t in self.trials),
                "by_kind": fault_kind_census,
                "lost_trials": [
                    t.trial_id for t in self.trials
                    if t.status not in SweepTrialStatus.TERMINAL],
            },
            "compile": {
                "cold_trials": len(cold),
                "warm_trials": len(warm),
                "cold_seconds": round(
                    sum(t.compile_seconds for t in cold), 6),
                "warm_seconds": round(
                    sum(t.compile_seconds for t in warm), 6),
                "warm_new_compiles": sum(t.new_compiles for t in warm),
                "warm_new_traces": sum(t.new_traces for t in warm),
                "total_seconds": round(
                    sum(t.compile_seconds for t in self.trials), 6),
            },
            "wall_s": round(self._wall_s, 6),
            "train_s": round(self._train_s, 6),
        }


__all__ = ["Sweep", "SweepTrial", "SweepTrialStatus"]
