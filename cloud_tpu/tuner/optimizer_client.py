"""Vizier (CAIP Optimizer) REST client.

Reference parity: tuner/optimizer_client.py:40-496 — trial suggestion
with idempotency by client_id, intermediate measurements, early-stopping
checks, completion, listing/deletion, long-running-operation polling with
1.41^n bounded backoff, and the race-safe create-or-load study bootstrap
(409 -> load) designed for many tuner processes sharing one study.

The service client is injectable (tests use mocks; production builds a
googleapiclient service against the regional endpoint).
"""

import datetime
import http
import json
import logging
import os
import time

try:
    from googleapiclient import discovery
    from googleapiclient import errors
except ImportError:
    discovery = None
    errors = None

from cloud_tpu.tuner import constants
from cloud_tpu.utils import google_api_client

logger = logging.getLogger("cloud_tpu")


class SuggestionInactiveError(Exception):
    """Indicates that a suggestion was requested from an inactive study
    (reference optimizer_client.py:31)."""


def _http_status(err):
    resp = getattr(err, "resp", None)
    return getattr(resp, "status", None)


class OptimizerClient:
    """Client for a single Vizier study."""

    def __init__(self, service_client, project_id, region, study_id=None):
        """Use `create_or_load_study()` unless the study already exists
        (reference optimizer_client.py:40-65)."""
        self.service_client = service_client
        self.project_id = project_id
        self.region = region
        if not study_id:
            raise ValueError(
                "Use create_or_load_study() instead of constructing the "
                "OptimizerClient class directly.")
        self.study_id = study_id

    # -- trials ---------------------------------------------------------

    def get_suggestions(self, client_id):
        """Suggests trials; idempotent per client_id (reference
        optimizer_client.py:68-134). Returns {} when the trial budget or
        search space is exhausted (429)."""
        try:
            resp = (self._trials()
                    .suggest(parent=self._make_study_name(),
                             body={
                                 "client_id": client_id,
                                 "suggestion_count":
                                     constants.SUGGESTION_COUNT_PER_REQUEST,
                             })
                    .execute())
        except Exception as e:
            if _http_status(e) == 429:
                logger.info("Reached max number of trials.")
                return {}
            logger.info("SuggestTrial failed.")
            raise

        operation = self._obtain_long_running_operation(resp)
        suggestions = operation.get("response", {})
        if "trials" not in suggestions:
            if suggestions.get("studyState") == "INACTIVE":
                raise SuggestionInactiveError(
                    "The study is stopped due to an internal error.")
        return suggestions

    def report_intermediate_objective_value(self, step, elapsed_secs,
                                            metric_list, trial_id):
        """AddMeasurement (reference optimizer_client.py:136-164)."""
        measurement = {
            "stepCount": step,
            "elapsedTime": {"seconds": int(elapsed_secs)},
            "metrics": metric_list,
        }
        self._trials().addMeasurement(
            name=self._make_trial_name(trial_id),
            body={"measurement": measurement}).execute()

    def should_trial_stop(self, trial_id):
        """checkEarlyStoppingState + stop (reference
        optimizer_client.py:166-202)."""
        trial_name = self._make_trial_name(trial_id)
        resp = (self._trials()
                .checkEarlyStoppingState(name=trial_name)
                .execute())
        operation = self._obtain_long_running_operation(resp)
        if operation.get("response", {}).get("shouldStop"):
            logger.info("Stopping trial %s early.", trial_id)
            self._trials().stop(name=trial_name).execute()
            return True
        return False

    def complete_trial(self, trial_id, trial_infeasible=False,
                       infeasibility_reason=None):
        """Marks COMPLETED (reference optimizer_client.py:204-237)."""
        return (self._trials()
                .complete(name=self._make_trial_name(trial_id),
                          body={
                              "trial_infeasible": trial_infeasible,
                              "infeasible_reason": infeasibility_reason,
                          })
                .execute())

    def get_trial(self, trial_id):
        return self._trials().get(
            name=self._make_trial_name(trial_id)).execute()

    def list_trials(self):
        resp = self._trials().list(
            parent=self._make_study_name()).execute()
        return resp.get("trials", [])

    # -- studies --------------------------------------------------------

    def list_studies(self):
        resp = self._studies().list(
            parent=self._make_parent_name()).execute()
        return resp.get("studies", [])

    def delete_study(self, study_name=None):
        if study_name is None:
            study_name = self._make_study_name()
        try:
            self._studies().delete(name=study_name).execute()
        except Exception as e:
            if _http_status(e) == http.HTTPStatus.NOT_FOUND.value:
                raise ValueError(
                    "DeleteStudy failed. Study not found: {}.".format(
                        study_name))
            raise

    # -- plumbing -------------------------------------------------------

    def _studies(self):
        return self.service_client.projects().locations().studies()

    def _trials(self):
        return self._studies().trials()

    def _obtain_long_running_operation(self, resp):
        """Polls an LRO with 1.41^n backoff, <=30 attempts (~10 min)
        (reference optimizer_client.py:294-348)."""
        op_id = resp["name"].split("/")[-1]
        operation_name = "projects/{}/locations/{}/operations/{}".format(
            self.project_id, self.region, op_id)
        get_op = (self.service_client.projects()
                  .locations()
                  .operations()
                  .get(name=operation_name))
        operation = get_op.execute()

        polling_secs = 1
        num_attempts = 0
        while not operation.get("done"):
            sleep_time = self._polling_delay(num_attempts, polling_secs)
            num_attempts += 1
            logger.info("Waiting for operation; attempt %d; sleeping %s",
                        num_attempts, sleep_time)
            time.sleep(sleep_time.total_seconds())
            if num_attempts > 30:
                raise RuntimeError("GetLongRunningOperations timeout.")
            operation = get_op.execute()
        if "error" in operation:
            # LROs report failure via an `error` field, not `response`.
            raise RuntimeError(
                "Operation {} failed: {}".format(
                    operation.get("name"), operation["error"]))
        return operation

    @staticmethod
    def _polling_delay(num_attempts, time_scale):
        """Bounded exponential backoff (reference
        optimizer_client.py:350-361)."""
        small_interval = 0.3
        interval = max(time_scale,
                       small_interval) * 1.41 ** min(num_attempts, 9)
        return datetime.timedelta(seconds=interval)

    def _make_study_name(self):
        return "projects/{}/locations/{}/studies/{}".format(
            self.project_id, self.region, self.study_id)

    def _make_trial_name(self, trial_id):
        return "{}/trials/{}".format(self._make_study_name(), trial_id)

    def _make_parent_name(self):
        return "projects/{}/locations/{}".format(self.project_id,
                                                 self.region)


#: Bundled pinned Vizier REST surface. The reference ships the full
#: discovery document (tuner/constants.py:20-22 +
#: optimizer_client.py:404-411); ours is hand-authored but covers every
#: method the reference's document exposes (projects.operations.* and
#: projects.locations.{operations,studies,studies.trials}.*, plus
#: locations-level operations.list which the reference's doc lacks), so
#: no client call can fall off the offline path. The pinned-surface
#: test (tests/unit/test_tuner.py::TestPinnedDiscoverySurface) holds a
#: reflection guard over OptimizerClient to keep it that way.
PINNED_DISCOVERY_PATH = os.path.join(
    os.path.dirname(__file__), "api", "vizier_v1_discovery.json")


def _discovery_fallback_errors():
    """Transport-shaped failures that justify the offline fallback.

    Credential misconfiguration or client bugs must fail loudly at
    build time instead of resurfacing mid-tuning-run, so only network
    and HTTP errors trigger the pinned document.
    """
    errs = (OSError,)
    if errors is not None:
        errs = errs + (errors.HttpError,)
    return errs


def load_pinned_discovery_doc(endpoint):
    """Loads the bundled discovery doc, pointed at a regional endpoint.

    The document is endpoint-agnostic on disk; rootUrl/baseUrl are
    patched here so one file serves every region.
    """
    with open(PINNED_DISCOVERY_PATH) as f:
        doc = json.load(f)
    root = endpoint.rstrip("/") + "/"
    doc["rootUrl"] = root
    doc["baseUrl"] = root
    return doc


def build_service_client(region):
    """Builds a googleapiclient service against the regional Vizier
    endpoint.

    Live discovery first (avoids the stale-document problem), falling
    back to the bundled pinned document when discovery is unreachable —
    air-gapped workers and flaky egress still get a working client, the
    same guarantee the reference's bundled document provides
    (tuner/constants.py:20-22). Set CLOUD_TPU_PINNED_DISCOVERY=1 to skip
    the live attempt entirely.
    """
    if discovery is None:
        raise RuntimeError(
            "google-api-python-client is required for the Vizier tuner.")
    endpoint = constants.OPTIMIZER_API_ENDPOINT.format(region=region)
    if os.environ.get("CLOUD_TPU_PINNED_DISCOVERY", "") != "1":
        try:
            return discovery.build(
                "ml", "v1", cache_discovery=False,
                discoveryServiceUrl=(
                    "{}/$discovery/rest?version=v1".format(endpoint)),
                requestBuilder=google_api_client.CloudTpuHttpRequest)
        except _discovery_fallback_errors() as e:
            logger.warning(
                "Live Vizier discovery against %s failed (%s); "
                "falling back to the pinned discovery document.",
                endpoint, e)
    return discovery.build_from_document(
        load_pinned_discovery_doc(endpoint),
        requestBuilder=google_api_client.CloudTpuHttpRequest)


def create_or_load_study(project_id, region, study_id, study_config=None,
                         service_client=None):
    """Race-safe factory (reference optimizer_client.py:364-448):
    create; on 409 (someone else won the race) load instead."""
    if service_client is None:
        service_client = build_service_client(region)

    study_parent = "projects/{}/locations/{}".format(project_id, region)
    studies = service_client.projects().locations().studies()

    if study_config is None:
        _get_study(service_client, study_parent, study_id,
                   study_should_exist=True)
    else:
        request = studies.create(
            body={"study_config": study_config},
            parent=study_parent,
            studyId=study_id)
        try:
            logger.info(request.execute())
        except Exception as e:
            if _http_status(e) != 409:
                raise
            _get_study(service_client, study_parent, study_id)

    return OptimizerClient(service_client, project_id, region, study_id)


def _get_study(service_client, study_parent, study_id,
               study_should_exist=False):
    """GET with bounded retry (reference optimizer_client.py:451-496)."""
    study_name = "{}/studies/{}".format(study_parent, study_id)
    num_tries = 0
    while True:
        try:
            (service_client.projects().locations().studies()
             .get(name=study_name).execute())
            return
        except Exception as e:
            status = _http_status(e)
            if status == http.HTTPStatus.NOT_FOUND.value:
                if study_should_exist:
                    raise ValueError(
                        "GetStudy failed. Study not found: {}.".format(
                            study_id))
                # Created by another process moments ago; retry.
            num_tries += 1
            if num_tries >= constants.MAX_NUM_TRIES_FOR_STUDIES:
                raise RuntimeError(
                    "GetStudy wasn't successful after {} tries: {}".format(
                        constants.MAX_NUM_TRIES_FOR_STUDIES, e))
            time.sleep(1)
