"""Native hyperparameter search-space API.

The reference rides KerasTuner's `HyperParameters`/`Objective`
(reference tuner/tuner.py imports kerastuner throughout; converters in
tuner/utils.py:220-282 handle Choice/Int/Float/Boolean/Fixed). This
framework is self-contained: the same five parameter kinds, defined
declaratively and convertible to/from Vizier study configs
(cloud_tpu/tuner/utils.py).

Usage:
    hp = HyperParameters()
    hp.Int("units", 32, 512, step=32)
    hp.Float("lr", 1e-4, 1e-1, sampling="log")
    ...
    build(hp)  # reads hp.get("units") / hp.values
"""

import random


class HyperParameter:
    """Base spec: name + default."""

    kind = "base"

    def __init__(self, name, default=None):
        self.name = name
        self.default = default

    def random_sample(self, rng):
        raise NotImplementedError

    def __repr__(self):
        return "{}(name={!r}, default={!r})".format(
            type(self).__name__, self.name, self.default)


class Choice(HyperParameter):
    kind = "choice"

    def __init__(self, name, values, default=None):
        if not values:
            raise ValueError("Choice {!r} needs at least one value."
                             .format(name))
        super().__init__(name, default if default is not None else values[0])
        self.values = list(values)

    def random_sample(self, rng):
        return rng.choice(self.values)


class Int(HyperParameter):
    kind = "int"

    def __init__(self, name, min_value, max_value, step=None,
                 sampling="linear", default=None):
        super().__init__(name,
                         default if default is not None else min_value)
        self.min_value = int(min_value)
        self.max_value = int(max_value)
        self.step = step
        self.sampling = sampling

    def random_sample(self, rng):
        if self.step:
            choices = list(range(self.min_value, self.max_value + 1,
                                 int(self.step)))
            return rng.choice(choices)
        return rng.randint(self.min_value, self.max_value)


class Float(HyperParameter):
    kind = "float"

    def __init__(self, name, min_value, max_value, step=None,
                 sampling="linear", default=None):
        super().__init__(name,
                         default if default is not None else min_value)
        self.min_value = float(min_value)
        self.max_value = float(max_value)
        self.step = step
        self.sampling = sampling

    def random_sample(self, rng):
        if self.step:
            n = int((self.max_value - self.min_value) / self.step)
            return self.min_value + self.step * rng.randint(0, n)
        if self.sampling == "log":
            import math
            lo, hi = math.log(self.min_value), math.log(self.max_value)
            return math.exp(rng.uniform(lo, hi))
        return rng.uniform(self.min_value, self.max_value)


class Boolean(HyperParameter):
    kind = "boolean"

    def __init__(self, name, default=False):
        super().__init__(name, default)

    def random_sample(self, rng):
        return rng.random() < 0.5


class Fixed(HyperParameter):
    kind = "fixed"

    def __init__(self, name, value):
        super().__init__(name, value)
        self.value = value

    def random_sample(self, rng):
        return self.value


class HyperParameters:
    """A search space plus current values."""

    def __init__(self):
        self.space = {}
        self.values = {}

    def _register(self, param):
        if param.name not in self.space:
            self.space[param.name] = param
        if param.name not in self.values:
            self.values[param.name] = param.default
        return self.values[param.name]

    def Choice(self, name, values, default=None):
        return self._register(Choice(name, values, default))

    def Int(self, name, min_value, max_value, step=None, sampling="linear",
            default=None):
        return self._register(Int(name, min_value, max_value, step,
                                  sampling, default))

    def Float(self, name, min_value, max_value, step=None,
              sampling="linear", default=None):
        return self._register(Float(name, min_value, max_value, step,
                                    sampling, default))

    def Boolean(self, name, default=False):
        return self._register(Boolean(name, default))

    def Fixed(self, name, value):
        return self._register(Fixed(name, value))

    def get(self, name):
        if name not in self.values:
            raise KeyError("Unknown hyperparameter {!r}.".format(name))
        return self.values[name]

    def copy(self):
        hp = HyperParameters()
        hp.space = dict(self.space)
        hp.values = dict(self.values)
        return hp

    def random_sample(self, seed=None):
        """A copy with every parameter randomly sampled."""
        rng = random.Random(seed)
        hp = self.copy()
        for name, param in hp.space.items():
            hp.values[name] = param.random_sample(rng)
        return hp

    def __repr__(self):
        return "HyperParameters({})".format(self.values)


class Objective:
    """A metric name + optimization direction ('min' or 'max')."""

    def __init__(self, name, direction="min"):
        if direction not in ("min", "max"):
            raise ValueError("direction must be 'min' or 'max', got {!r}."
                             .format(direction))
        self.name = name
        self.direction = direction

    def __eq__(self, other):
        return (isinstance(other, Objective) and self.name == other.name
                and self.direction == other.direction)

    def __repr__(self):
        return "Objective(name={!r}, direction={!r})".format(
            self.name, self.direction)


def default_objective_direction(name):
    """Infers direction from a metric name ('accuracy' -> max)."""
    return "max" if ("acc" in name or name.endswith("auc")) else "min"
