"""Converters: HyperParameters/Objective <-> Vizier study configs.

Reference parity: tuner/utils.py:47-399 — bidirectional conversion
between the tuner's search-space API and the CAIP Optimizer (Vizier)
`study_config`/trial wire format, including step->DISCRETE flattening
and log-scale mapping.
"""

from cloud_tpu.tuner import hyperparameters as hp_module

_SCALE_MAP = {
    "linear": "UNIT_LINEAR_SCALE",
    "log": "UNIT_LOG_SCALE",
    "reverse_log": "UNIT_REVERSE_LOG_SCALE",
}

_GOAL_MAP = {"max": "MAXIMIZE", "min": "MINIMIZE"}


def format_goal(direction):
    """'min'/'max' <-> Vizier goal (reference utils.py:318-346)."""
    if direction in _GOAL_MAP:
        return _GOAL_MAP[direction]
    for k, v in _GOAL_MAP.items():
        if direction == v:
            return k
    raise ValueError("Unknown goal/direction: {!r}".format(direction))


def format_objective(objective, direction=None):
    """Normalizes objective input to a list of `Objective`
    (reference utils.py:285-316)."""
    if isinstance(objective, hp_module.Objective):
        return [objective]
    if isinstance(objective, str):
        return [hp_module.Objective(
            objective,
            direction or hp_module.default_objective_direction(objective))]
    if isinstance(objective, (list, tuple)):
        out = []
        for obj in objective:
            out.extend(format_objective(obj, direction))
        return out
    raise TypeError(
        "Objective must be a string, Objective, or list; got {!r}."
        .format(objective))


def _convert_parameter(param):
    """One HyperParameter -> Vizier ParameterSpec
    (reference utils.py:220-282)."""
    spec = {"parameter": param.name}
    if param.kind == "choice":
        if all(isinstance(v, str) for v in param.values):
            spec["type"] = "CATEGORICAL"
            spec["categorical_value_spec"] = {"values": list(param.values)}
        else:
            spec["type"] = "DISCRETE"
            spec["discrete_value_spec"] = {
                "values": [float(v) for v in param.values]}
    elif param.kind == "int":
        if param.step:
            spec["type"] = "DISCRETE"
            spec["discrete_value_spec"] = {
                "values": [float(v) for v in range(
                    param.min_value, param.max_value + 1,
                    int(param.step))]}
        else:
            spec["type"] = "INTEGER"
            spec["integer_value_spec"] = {
                "min_value": param.min_value,
                "max_value": param.max_value,
            }
            spec["scale_type"] = _SCALE_MAP[param.sampling]
    elif param.kind == "float":
        if param.step:
            values, v = [], param.min_value
            while v <= param.max_value + 1e-12:
                values.append(round(v, 12))
                v += param.step
            spec["type"] = "DISCRETE"
            spec["discrete_value_spec"] = {"values": values}
        else:
            spec["type"] = "DOUBLE"
            spec["double_value_spec"] = {
                "min_value": param.min_value,
                "max_value": param.max_value,
            }
            spec["scale_type"] = _SCALE_MAP[param.sampling]
    elif param.kind == "boolean":
        spec["type"] = "CATEGORICAL"
        spec["categorical_value_spec"] = {"values": ["True", "False"]}
    elif param.kind == "fixed":
        if isinstance(param.value, str):
            spec["type"] = "CATEGORICAL"
            spec["categorical_value_spec"] = {"values": [param.value]}
        else:
            spec["type"] = "DISCRETE"
            spec["discrete_value_spec"] = {
                "values": [float(param.value)]}
    else:
        raise ValueError("Unknown parameter kind {!r}.".format(param.kind))
    return spec


def make_study_config(objective, hyperparams):
    """HyperParameters + objective -> Vizier study_config
    (reference utils.py:47-81: default algorithm + decay-curve automated
    stopping)."""
    objectives = format_objective(objective)
    return {
        "algorithm": "ALGORITHM_UNSPECIFIED",
        "automatedStoppingConfig": {
            "decayCurveStoppingConfig": {"useElapsedTime": True}},
        "metrics": [{"metric": o.name, "goal": format_goal(o.direction)}
                    for o in objectives],
        "parameters": [_convert_parameter(p)
                       for p in hyperparams.space.values()],
    }


def convert_study_config_to_objective(study_config):
    """study_config -> [Objective] (reference utils.py:84-110)."""
    metrics = study_config.get("metrics") or []
    if not metrics:
        raise ValueError("Study config has no metrics.")
    return [hp_module.Objective(m["metric"], format_goal(m["goal"]))
            for m in metrics]


def convert_study_config_to_hps(study_config):
    """study_config -> HyperParameters (reference utils.py:112-158)."""
    hps = hp_module.HyperParameters()
    for spec in study_config.get("parameters", []):
        name = spec["parameter"]
        if spec["type"] == "CATEGORICAL":
            values = spec["categorical_value_spec"]["values"]
            if set(values) == {"True", "False"}:
                hps.Boolean(name)
            else:
                hps.Choice(name, values)
        elif spec["type"] == "DISCRETE":
            hps.Choice(name, spec["discrete_value_spec"]["values"])
        elif spec["type"] == "INTEGER":
            value_spec = spec["integer_value_spec"]
            hps.Int(name, int(value_spec["min_value"]),
                    int(value_spec["max_value"]))
        elif spec["type"] == "DOUBLE":
            value_spec = spec["double_value_spec"]
            sampling = "linear"
            for k, v in _SCALE_MAP.items():
                if spec.get("scale_type") == v:
                    sampling = k
            hps.Float(name, value_spec["min_value"],
                      value_spec["max_value"], sampling=sampling)
        else:
            raise ValueError("Unknown parameter type {!r}."
                             .format(spec["type"]))
    return hps


def get_trial_id(optimizer_trial):
    """Full Vizier trial name -> short trial id
    (reference utils.py:360-371)."""
    return optimizer_trial["name"].split("/")[-1]


def convert_optimizer_trial_to_hps(base_hps, optimizer_trial):
    """Vizier trial params -> HyperParameters values
    (reference utils.py:374-388)."""
    hps = base_hps.copy()
    for param in optimizer_trial.get("parameters", []):
        name = param["parameter"]
        if "floatValue" in param:
            value = float(param["floatValue"])
            spec = hps.space.get(name)
            if spec is not None and spec.kind == "int":
                value = int(value)
            if (spec is not None and spec.kind == "choice"
                    and all(isinstance(v, int) for v in spec.values)):
                value = int(value)
        elif "intValue" in param:
            value = int(param["intValue"])
        else:
            value = param["stringValue"]
            spec = hps.space.get(name)
            if spec is not None and spec.kind == "boolean":
                value = value == "True"
        hps.values[name] = value
    return hps
