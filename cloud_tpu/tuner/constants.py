"""Tuner constants (reference tuner/constants.py:20-30)."""

# Number of trials requested per suggest call
# (reference constants.py:27).
SUGGESTION_COUNT_PER_REQUEST = 1

# Bounded retries for the race-safe study bootstrap
# (reference constants.py:30).
MAX_NUM_TRIES_FOR_STUDIES = 3

# Regional service endpoint template (the reference bundles a discovery
# document pinned to us-central1, constants.py:20-22).
OPTIMIZER_API_ENDPOINT = "https://{region}-ml.googleapis.com"
