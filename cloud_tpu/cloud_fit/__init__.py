from cloud_tpu.cloud_fit.client import cloud_fit, serialize_assets
from cloud_tpu.cloud_fit.remote import run as remote_run
