"""cloud_fit shared constants.

Reference parity: experimental/cloud_fit/utils.py:24-39 — the strategy
registry the client validates against and the remote worker re-creates
from. TPU-native: names map onto `cloud_tpu.parallel.runtime` strategies
instead of `tf.distribute` classes; the TF1-detection shim is meaningless
for JAX and intentionally absent.
"""

# Client-validated, worker-recreated strategy names
# (reference utils.py:24-28 lists MirroredStrategy / MWMS only).
SUPPORTED_DISTRIBUTION_STRATEGIES = (
    "one_device",
    "mirrored",
    "multi_worker",
    "tpu_slice",
    "tpu_pod",
    "multi_slice",
)
