"""cloud_fit remote worker: re-hydrate and fit inside the training job.

Reference parity: experimental/cloud_fit/remote.py:40-169 — the
container's `__main__`, flag-driven (`--remote_dir`,
`--distribution_strategy`, reference remote.py:40-52,166-169): recreate
the distribution setup, load the serialized assets, `fit`, and save
outputs with chief-only writes (the reference's decoy-dir MWMS
workaround, remote.py:130-145, is replaced by orbax single-writer
semantics plus explicit process-0 gating for the history file).
"""

import io
import json
import logging
import pickle

import numpy as np

from cloud_tpu.cloud_fit import client as client_lib
from cloud_tpu.cloud_fit import utils
from cloud_tpu.parallel import runtime
from cloud_tpu.utils import storage

logger = logging.getLogger("cloud_tpu")

OUTPUT_DIR = "output"
HISTORY_FILE = "history.json"


def build_trainer(spec, mesh=None):
    """Reconstructs a Trainer from a serialized spec dict."""
    from cloud_tpu.training import trainer as trainer_lib

    def _resolve(ref):
        if ref["kind"] == "name":
            return ref["value"]
        return client_lib.resolve_dotted(ref["value"])

    return trainer_lib.Trainer(
        model=spec["model"],
        optimizer=_resolve(spec["optimizer"]),
        loss=_resolve(spec["loss"]),
        metrics=[_resolve(m) for m in spec["metrics"]],
        mesh=mesh,
        param_sharding_rules=spec.get("param_sharding_rules"),
        train_kwargs=spec.get("train_kwargs"),
        eval_kwargs=spec.get("eval_kwargs"),
        rng_keys=spec.get("rng_keys", ()),
        seed=spec.get("seed", 0),
        aux_loss_weight=spec.get("aux_loss_weight", 0.01),
        gradient_accumulation_steps=spec.get(
            "gradient_accumulation_steps", 1),
        remat=spec.get("remat", False),
        zero1=spec.get("zero1", False),
        fsdp=spec.get("fsdp", False),
        ema_decay=spec.get("ema_decay"),
    )


def run(remote_dir, distribution_strategy="tpu_slice"):
    """Loads assets from `remote_dir`, trains, saves outputs.

    Reference parity: `run()` (remote.py:55-146). Returns the history
    dict.
    """
    if distribution_strategy not in utils.SUPPORTED_DISTRIBUTION_STRATEGIES:
        raise ValueError(
            "{} is not supported. Must be one of {}.".format(
                distribution_strategy,
                utils.SUPPORTED_DISTRIBUTION_STRATEGIES))

    if not runtime.is_initialized():
        runtime.initialize(strategy=distribution_strategy)

    spec = pickle.loads(
        storage.read_bytes(storage.join(remote_dir, client_lib.SPEC_FILE)))
    fit_kwargs = pickle.loads(storage.read_bytes(
        storage.join(remote_dir, client_lib.FIT_KWARGS_FILE)))

    trainer = build_trainer(spec, mesh=runtime.global_mesh())

    ds_spec_path = storage.join(remote_dir, client_lib.DATASET_SPEC_FILE)
    data_path = storage.join(remote_dir, client_lib.DATA_FILE)
    arrays = None
    if storage.exists(ds_spec_path):
        # Dataset transport: rebuild the generator/shard pipeline from
        # its JSON spec — the data itself never crossed in the assets
        # (reference ships live tf.data datasets, client.py:151-189;
        # this is the reference-free equivalent). The npz, if present,
        # carries only validation arrays.
        x = client_lib.build_dataset(
            json.loads(storage.read_bytes(ds_spec_path)))
        y = None
        if storage.exists(data_path):
            arrays = np.load(io.BytesIO(storage.read_bytes(data_path)))
    else:
        arrays = np.load(io.BytesIO(storage.read_bytes(data_path)))
        x = arrays["x"]
        y = arrays["y"] if "y" in arrays.files else None
    if arrays is not None and "val_x" in arrays.files:
        val = (arrays["val_x"], arrays["val_y"])
        if "val_w" in arrays.files:
            val = val + (arrays["val_w"],)
        fit_kwargs.setdefault("validation_data", val)

    history = trainer.fit(x, y, **fit_kwargs)

    _save_outputs(remote_dir, trainer, history)
    return history


def _save_outputs(remote_dir, trainer, history):
    """Final state + history under `<remote_dir>/output`
    (reference remote.py:130-145: chief-only real write)."""
    import jax

    from cloud_tpu.training import checkpoint as checkpoint_lib

    output_dir = storage.join(remote_dir, OUTPUT_DIR)
    # The trained state is the job's product: always save it, local or
    # gs:// (orbax/tensorstore writes both; the reference likewise always
    # saves, remote.py:130-145). orbax owns the multi-process write
    # protocol; the JSON history is chief-written only.
    checkpoint_lib.save(output_dir, trainer.state,
                        step=int(trainer.state.step))
    if jax.process_index() == 0:
        storage.write_bytes(
            storage.join(remote_dir, OUTPUT_DIR, HISTORY_FILE),
            json.dumps(history).encode("utf-8"))
    logger.info("cloud_fit outputs saved under %s", output_dir)


def main(argv=None):
    """Flag-driven entry point (reference remote.py:40-52, 166-169)."""
    import argparse

    parser = argparse.ArgumentParser(description="cloud_fit remote worker")
    parser.add_argument("--remote_dir", required=True,
                        help="Storage directory with serialized assets.")
    parser.add_argument("--distribution_strategy", default="tpu_slice",
                        choices=utils.SUPPORTED_DISTRIBUTION_STRATEGIES)
    args = parser.parse_args(argv)
    run(args.remote_dir, args.distribution_strategy)


if __name__ == "__main__":
    main()
