"""cloud_fit client: train an in-memory Trainer remotely.

Reference parity: experimental/cloud_fit/client.py:45-287 — serialize a
live model + data + callbacks to durable storage, submit a training job
whose entry point re-hydrates and fits. The reference's TF-specific
transport (datasets as tf.functions inside a tf.Module SavedModel,
client.py:151-189) becomes a JAX-native asset layout:

    <remote_dir>/spec.pkl        trainer construction spec (pickle)
    <remote_dir>/data.npz        training arrays (+ optional validation)
    <remote_dir>/fit_kwargs.pkl  fit arguments + pickled callbacks
    <remote_dir>/state/<step>/   optional pre-built TrainState (orbax)

Pickling constraints are surfaced, not hidden: optax transforms hold
closures that stdlib pickle rejects, so optimizers/losses cross the wire
as registry names or dotted factory paths (the analogue of the
reference's "serializable callbacks only" caveat, client.py:73-75).
"""

import datetime
import io
import logging
import pickle

import numpy as np

try:
    from googleapiclient import discovery
except ImportError:
    discovery = None

from cloud_tpu.cloud_fit import utils
from cloud_tpu.core import gcp
from cloud_tpu.core import machine_config
from cloud_tpu.utils import google_api_client
from cloud_tpu.utils import storage

logger = logging.getLogger("cloud_tpu")

SPEC_FILE = "spec.pkl"
DATA_FILE = "data.npz"
FIT_KWARGS_FILE = "fit_kwargs.pkl"


def _dotted_path(obj):
    """Returns 'module:qualname' for a module-level callable, or None."""
    module = getattr(obj, "__module__", None)
    qualname = getattr(obj, "__qualname__", None)
    if module and qualname and "<locals>" not in qualname:
        return "{}:{}".format(module, qualname)
    return None


def resolve_dotted(path):
    """Resolves 'module:qualname' back to the object."""
    import importlib

    module_name, _, qualname = path.partition(":")
    obj = importlib.import_module(module_name)
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj


def _serializable_ref(obj, registry, kind):
    """An object -> cross-process reference (name | dotted path)."""
    if isinstance(obj, str):
        return {"kind": "name", "value": obj}
    path = _dotted_path(obj)
    if path is not None:
        return {"kind": "path", "value": path}
    raise ValueError(
        "The {} {!r} cannot be shipped to a remote worker: pass a "
        "registry name ({}) or a module-level function.".format(
            kind, obj, sorted(registry)))


def serialize_assets(remote_dir, trainer, x, y=None, validation_data=None,
                     **fit_kwargs):
    """Writes the trainer spec + data + fit kwargs under `remote_dir`.

    Reference parity: `_serialize_assets` (client.py:138-192), with
    explicit picklability rules instead of SavedModel tracing.
    """
    from cloud_tpu.training import trainer as trainer_lib

    spec = {
        "model": trainer.model,
        "optimizer": _serializable_ref(
            trainer.optimizer_spec, trainer_lib.OPTIMIZERS, "optimizer"),
        "loss": _serializable_ref(
            trainer.loss_spec, trainer_lib.LOSSES, "loss"),
        "metrics": [
            _serializable_ref(m, trainer_lib.METRICS, "metric")
            for m in trainer.metric_specs],
        "param_sharding_rules": trainer.param_sharding_rules,
        "train_kwargs": trainer.train_kwargs,
        "eval_kwargs": trainer.eval_kwargs,
        "rng_keys": trainer.rng_keys,
        "seed": trainer.seed,
        "aux_loss_weight": trainer.aux_loss_weight,
        "gradient_accumulation_steps": trainer.gradient_accumulation_steps,
        "remat": trainer.remat,
        "zero1": trainer.zero1,
        "fsdp": trainer.fsdp,
        "ema_decay": trainer.ema_decay,
    }
    storage.write_bytes(storage.join(remote_dir, SPEC_FILE),
                        pickle.dumps(spec))

    arrays = {"x": np.asarray(x)}
    if y is not None:
        arrays["y"] = np.asarray(y)
    if validation_data is not None:
        arrays["val_x"] = np.asarray(validation_data[0])
        arrays["val_y"] = np.asarray(validation_data[1])
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    storage.write_bytes(storage.join(remote_dir, DATA_FILE),
                        buf.getvalue())

    # Callbacks ride pickle like the reference's (client.py:173-180).
    storage.write_bytes(storage.join(remote_dir, FIT_KWARGS_FILE),
                        pickle.dumps(fit_kwargs))
    logger.info("Serialized cloud_fit assets to %s", remote_dir)


def cloud_fit(trainer,
              remote_dir,
              region=None,
              project_id=None,
              image_uri=None,
              distribution_strategy="tpu_slice",
              job_spec=None,
              job_id=None,
              x=None,
              y=None,
              validation_data=None,
              api_client=None,
              **fit_kwargs):
    """Fits a Trainer remotely; returns the submitted job id.

    Reference parity: `cloud_fit()` (client.py:45-135): validate strategy
    name, serialize assets, submit the job whose container entry point is
    `python -m cloud_tpu.cloud_fit.remote`.

    Args:
        trainer: A `cloud_tpu.training.Trainer`. Its optimizer/loss/
            metrics must be registry names or module-level callables.
        remote_dir: Durable directory (`gs://...` in real use) for assets
            and outputs.
        region / project_id / image_uri: Job placement; defaulted from
            the environment like the reference.
        distribution_strategy: One of
            `utils.SUPPORTED_DISTRIBUTION_STRATEGIES` (reference
            client.py:87-93 validates against its registry).
        job_spec: Optional full trainingInput override.
        job_id: Optional job id; default `cloud_fit_<timestamp>`.
        x / y / validation_data: Training data arrays.
        api_client: Injectable platform client (tests).
        **fit_kwargs: Forwarded to `Trainer.fit` remotely (epochs,
            batch_size, callbacks, ...).

    Returns:
        The job id string.
    """
    if distribution_strategy not in utils.SUPPORTED_DISTRIBUTION_STRATEGIES:
        raise ValueError(
            "{} is not supported. Must be one of {}.".format(
                distribution_strategy,
                utils.SUPPORTED_DISTRIBUTION_STRATEGIES))

    serialize_assets(remote_dir, trainer, x, y, validation_data,
                     **fit_kwargs)

    project_id = project_id or gcp.get_project_name()
    region = region or gcp.get_region()
    job_id = job_id or "cloud_fit_{}".format(
        datetime.datetime.now().strftime("%Y%m%d_%H%M%S"))

    request = {
        "jobId": job_id,
        "trainingInput": job_spec or default_job_spec(
            region, image_uri,
            ["--remote_dir", str(remote_dir),
             "--distribution_strategy", distribution_strategy]),
    }
    _submit_job(request, project_id, api_client=api_client)
    return job_id


def default_job_spec(region, image_uri, args):
    """Default single v5e-8 TPU-VM pool (vs the reference's
    n1-standard-4 master+worker pair, client.py:195-224)."""
    config = machine_config.COMMON_MACHINE_CONFIGS["TPU_V5E_8"]
    return {
        "region": region,
        "scaleTier": "custom",
        "masterType": gcp.get_machine_type(
            config.cpu_cores, config.memory, config.accelerator_type),
        "masterConfig": {
            "imageUri": image_uri,
            "acceleratorConfig": {
                "count": str(config.accelerator_count),
                "type": gcp.get_tpu_slice_type(config.accelerator_type,
                                               config.accelerator_count),
            },
            "tpuRuntimeVersion": gcp.get_tpu_runtime_versions()[0],
        },
        "workerCount": "0",
        "args": list(args),
        "use_chief_in_tf_config": True,
    }


def _submit_job(request, project_id, api_client=None):
    """Submits to the training service (reference client.py:227-287)."""
    if api_client is None:
        if discovery is None:
            raise RuntimeError(
                "google-api-python-client is required to submit cloud_fit "
                "jobs.")
        api_client = discovery.build(
            "ml", "v1", cache_discovery=False,
            requestBuilder=google_api_client.CloudTpuHttpRequest)
    (api_client.projects()
     .jobs()
     .create(parent="projects/{}".format(project_id), body=request)
     .execute())
    logger.info("cloud_fit job %s submitted.", request["jobId"])
