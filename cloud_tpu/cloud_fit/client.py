"""cloud_fit client: train an in-memory Trainer remotely.

Reference parity: experimental/cloud_fit/client.py:45-287 — serialize a
live model + data + callbacks to durable storage, submit a training job
whose entry point re-hydrates and fits. The reference's TF-specific
transport (datasets as tf.functions inside a tf.Module SavedModel,
client.py:151-189) becomes a JAX-native asset layout:

    <remote_dir>/spec.pkl        trainer construction spec (pickle)
    <remote_dir>/data.npz        training arrays (+ optional validation)
    <remote_dir>/fit_kwargs.pkl  fit arguments + pickled callbacks
    <remote_dir>/state/<step>/   optional pre-built TrainState (orbax)

Pickling constraints are surfaced, not hidden: optax transforms hold
closures that stdlib pickle rejects, so optimizers/losses cross the wire
as registry names or dotted factory paths (the analogue of the
reference's "serializable callbacks only" caveat, client.py:73-75).
"""

import datetime
import io
import logging
import pickle
import json

import numpy as np

try:
    from googleapiclient import discovery
except ImportError:
    discovery = None

from cloud_tpu.cloud_fit import utils
from cloud_tpu.core import gcp
from cloud_tpu.core import machine_config
from cloud_tpu.utils import google_api_client
from cloud_tpu.utils import storage

logger = logging.getLogger("cloud_tpu")

SPEC_FILE = "spec.pkl"
DATA_FILE = "data.npz"
DATASET_SPEC_FILE = "dataset_spec.json"
FIT_KWARGS_FILE = "fit_kwargs.pkl"


def _dotted_path(obj):
    """Returns 'module:qualname' for a module-level callable, or None."""
    module = getattr(obj, "__module__", None)
    qualname = getattr(obj, "__qualname__", None)
    if module and qualname and "<locals>" not in qualname:
        return "{}:{}".format(module, qualname)
    return None


def resolve_dotted(path):
    """Resolves 'module:qualname' back to the object."""
    import importlib

    module_name, _, qualname = path.partition(":")
    obj = importlib.import_module(module_name)
    for part in qualname.split("."):
        obj = getattr(obj, part)
    return obj


def _serializable_ref(obj, registry, kind):
    """An object -> cross-process reference (name | dotted path)."""
    if isinstance(obj, str):
        return {"kind": "name", "value": obj}
    path = _dotted_path(obj)
    if path is not None:
        return {"kind": "path", "value": path}
    raise ValueError(
        "The {} {!r} cannot be shipped to a remote worker: pass a "
        "registry name ({}) or a module-level function.".format(
            kind, obj, sorted(registry)))


def make_spec(trainer):
    """The picklable trainer spec dict `remote.build_trainer` rebuilds
    from (names/dotted-paths for registry objects, pickle for the
    rest)."""
    from cloud_tpu.training import trainer as trainer_lib

    return {
        "model": trainer.model,
        "optimizer": _serializable_ref(
            trainer.optimizer_spec, trainer_lib.OPTIMIZERS, "optimizer"),
        "loss": _serializable_ref(
            trainer.loss_spec, trainer_lib.LOSSES, "loss"),
        "metrics": [
            _serializable_ref(m, trainer_lib.METRICS, "metric")
            for m in trainer.metric_specs],
        "param_sharding_rules": trainer.param_sharding_rules,
        "train_kwargs": trainer.train_kwargs,
        "eval_kwargs": trainer.eval_kwargs,
        "rng_keys": trainer.rng_keys,
        "seed": trainer.seed,
        "aux_loss_weight": trainer.aux_loss_weight,
        "gradient_accumulation_steps": trainer.gradient_accumulation_steps,
        "remat": trainer.remat,
        "zero1": trainer.zero1,
        "fsdp": trainer.fsdp,
        "ema_decay": trainer.ema_decay,
    }


def dataset_spec(x):
    """A JSON-able spec for dataset-typed `x`, or None for arrays.

    The dataset transport (the JAX-native analogue of the reference
    shipping live tf.data datasets as tf.function closures inside a
    SavedModel, reference cloud_fit/client.py:151-189): what crosses
    the wire is a REFERENCE — a dotted factory path + kwargs, or a
    shard-path manifest — never the data itself.
    """
    from cloud_tpu.training import data as data_lib

    spec = {"threaded": False, "buffer_size": None}
    ds = x
    if isinstance(ds, data_lib.ThreadedDataset):
        spec["threaded"] = True
        spec["buffer_size"] = ds.buffer_size
        ds = ds.dataset
    if isinstance(ds, data_lib.GeneratorDataset):
        path = _dotted_path(ds.factory)
        if path is None:
            raise ValueError(
                "GeneratorDataset factories shipped through cloud_fit "
                "must be module-level functions (the remote worker "
                "re-imports them by dotted path); got {!r}. Hoist the "
                "factory to module scope and parameterize it via "
                "factory_kwargs.".format(ds.factory))
        try:
            roundtrip = json.loads(json.dumps(ds.factory_kwargs))
        except (TypeError, ValueError):
            raise ValueError(
                "factory_kwargs must be JSON-serializable to ship "
                "through cloud_fit; got {!r}.".format(ds.factory_kwargs))
        if roundtrip != ds.factory_kwargs:
            # Values that *serialize* but come back different (tuples
            # -> lists) would make the factory behave differently on
            # the worker than in the local run the user validated.
            raise ValueError(
                "factory_kwargs must survive a JSON round-trip "
                "unchanged (tuples become lists); got {!r} -> {!r}. "
                "Use lists/dicts/scalars only.".format(
                    ds.factory_kwargs, roundtrip))
        spec.update(kind="generator", factory=path,
                    factory_kwargs=ds.factory_kwargs,
                    steps_per_epoch=ds.steps_per_epoch)
        return spec
    if isinstance(ds, data_lib.NpzShardDataset):
        spec.update(kind="npz_shards", paths=ds.shard_paths,
                    batch_size=ds.batch_size)
        return spec
    if spec["threaded"]:
        raise ValueError(
            "ThreadedDataset must wrap a GeneratorDataset or "
            "NpzShardDataset to ship through cloud_fit; it wraps "
            "{!r}.".format(type(ds)))
    return None


def build_dataset(spec):
    """Rebuilds the dataset a `dataset_spec` describes (worker side)."""
    from cloud_tpu.training import data as data_lib

    kind = spec["kind"]
    if kind == "generator":
        ds = data_lib.GeneratorDataset(
            resolve_dotted(spec["factory"]),
            steps_per_epoch=spec.get("steps_per_epoch"),
            factory_kwargs=spec.get("factory_kwargs"))
    elif kind == "npz_shards":
        ds = data_lib.NpzShardDataset(spec["paths"],
                                      batch_size=spec["batch_size"])
    else:
        raise ValueError("Unknown dataset spec kind {!r}.".format(kind))
    if spec.get("threaded"):
        ds = data_lib.ThreadedDataset(ds, buffer_size=spec["buffer_size"])
    return ds


def serialize_assets(remote_dir, trainer, x, y=None, validation_data=None,
                     **fit_kwargs):
    """Writes the trainer spec + data + fit kwargs under `remote_dir`.

    Reference parity: `_serialize_assets` (client.py:138-192), with
    explicit picklability rules instead of SavedModel tracing. Arrays
    ship as one compressed npz; GeneratorDataset / ThreadedDataset /
    NpzShardDataset ship as a JSON dataset spec (factory dotted path +
    kwargs, or shard manifest) with no data bytes in it.
    """
    storage.write_bytes(storage.join(remote_dir, SPEC_FILE),
                        pickle.dumps(make_spec(trainer)))

    ds_spec = dataset_spec(x)
    if ds_spec is not None:
        if y is not None:
            raise ValueError(
                "y must be None when x is a dataset (datasets yield "
                "(x, y) batches themselves).")
        if (ds_spec["kind"] == "npz_shards"
                and storage.is_gcs_path(remote_dir)):
            local = [p for p in ds_spec["paths"]
                     if not storage.is_gcs_path(p)]
            if local:
                # Fail before job submission, like the module-level
                # factory check — a remote worker can't read the
                # client's local filesystem.
                raise ValueError(
                    "NpzShardDataset shard paths must be gs:// for a "
                    "gs:// remote_dir (the worker cannot read local "
                    "paths); local: {}".format(local[:3]))
        storage.write_bytes(
            storage.join(remote_dir, DATASET_SPEC_FILE),
            json.dumps(ds_spec).encode("utf-8"))
        if validation_data is not None:
            arrays = {"val_x": np.asarray(validation_data[0]),
                      "val_y": np.asarray(validation_data[1])}
            if len(validation_data) == 3:
                arrays["val_w"] = np.asarray(validation_data[2])
            buf = io.BytesIO()
            np.savez_compressed(buf, **arrays)
            storage.write_bytes(storage.join(remote_dir, DATA_FILE),
                                buf.getvalue())
        storage.write_bytes(storage.join(remote_dir, FIT_KWARGS_FILE),
                            pickle.dumps(fit_kwargs))
        logger.info("Serialized cloud_fit assets (dataset spec: %s) "
                    "to %s", ds_spec["kind"], remote_dir)
        return

    arrays = {"x": np.asarray(x)}
    if y is not None:
        arrays["y"] = np.asarray(y)
    if validation_data is not None:
        arrays["val_x"] = np.asarray(validation_data[0])
        arrays["val_y"] = np.asarray(validation_data[1])
        if len(validation_data) == 3:
            # (x, y, sample_weight) validation triples survive the trip.
            arrays["val_w"] = np.asarray(validation_data[2])
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    storage.write_bytes(storage.join(remote_dir, DATA_FILE),
                        buf.getvalue())

    # Callbacks ride pickle like the reference's (client.py:173-180).
    storage.write_bytes(storage.join(remote_dir, FIT_KWARGS_FILE),
                        pickle.dumps(fit_kwargs))
    logger.info("Serialized cloud_fit assets to %s", remote_dir)


def cloud_fit(trainer,
              remote_dir,
              region=None,
              project_id=None,
              image_uri=None,
              distribution_strategy="tpu_slice",
              job_spec=None,
              job_id=None,
              x=None,
              y=None,
              validation_data=None,
              api_client=None,
              **fit_kwargs):
    """Fits a Trainer remotely; returns the submitted job id.

    Reference parity: `cloud_fit()` (client.py:45-135): validate strategy
    name, serialize assets, submit the job whose container entry point is
    `python -m cloud_tpu.cloud_fit.remote`.

    Args:
        trainer: A `cloud_tpu.training.Trainer`. Its optimizer/loss/
            metrics must be registry names or module-level callables.
        remote_dir: Durable directory (`gs://...` in real use) for assets
            and outputs.
        region / project_id / image_uri: Job placement; defaulted from
            the environment like the reference.
        distribution_strategy: One of
            `utils.SUPPORTED_DISTRIBUTION_STRATEGIES` (reference
            client.py:87-93 validates against its registry).
        job_spec: Optional full trainingInput override.
        job_id: Optional job id; default `cloud_fit_<timestamp>`.
        x / y / validation_data: Training data. Arrays ship inline
            (compressed npz); a GeneratorDataset / ThreadedDataset /
            NpzShardDataset `x` ships as a JSON dataset spec (dotted
            factory path + kwargs, or shard manifest) with no data
            bytes — for data that does not fit one array (y must be
            None then; validation_data stays array-typed).
        api_client: Injectable platform client (tests).
        **fit_kwargs: Forwarded to `Trainer.fit` remotely (epochs,
            batch_size, callbacks, ...).

    Returns:
        The job id string.
    """
    if distribution_strategy not in utils.SUPPORTED_DISTRIBUTION_STRATEGIES:
        raise ValueError(
            "{} is not supported. Must be one of {}.".format(
                distribution_strategy,
                utils.SUPPORTED_DISTRIBUTION_STRATEGIES))
    if (validation_data is not None and len(validation_data) == 3
            and distribution_strategy in ("tpu_pod", "multi_worker",
                                          "multi_slice")):
        # Trainer.fit would raise this on the pod AFTER provisioning —
        # fail at submission time instead (same pattern as the local
        # shard-path check below).
        raise NotImplementedError(
            "Weighted validation_data=(x, y, w) is single-process for "
            "now; drop the weights or evaluate separately.")

    serialize_assets(remote_dir, trainer, x, y, validation_data,
                     **fit_kwargs)

    project_id = project_id or gcp.get_project_name()
    region = region or gcp.get_region()
    job_id = job_id or "cloud_fit_{}".format(
        datetime.datetime.now().strftime("%Y%m%d_%H%M%S"))

    request = {
        "jobId": job_id,
        "trainingInput": job_spec or default_job_spec(
            region, image_uri,
            ["--remote_dir", str(remote_dir),
             "--distribution_strategy", distribution_strategy]),
    }
    _submit_job(request, project_id, api_client=api_client)
    return job_id


def default_job_spec(region, image_uri, args):
    """Default single v5e-8 TPU-VM pool (vs the reference's
    n1-standard-4 master+worker pair, client.py:195-224)."""
    config = machine_config.COMMON_MACHINE_CONFIGS["TPU_V5E_8"]
    return {
        "region": region,
        "scaleTier": "custom",
        "masterType": gcp.get_machine_type(
            config.cpu_cores, config.memory, config.accelerator_type),
        "masterConfig": {
            "imageUri": image_uri,
            "acceleratorConfig": {
                "count": str(config.accelerator_count),
                "type": gcp.get_tpu_slice_type(config.accelerator_type,
                                               config.accelerator_count),
            },
            "tpuRuntimeVersion": gcp.get_tpu_runtime_versions()[0],
        },
        "workerCount": "0",
        "args": list(args),
        "use_chief_in_tf_config": True,
    }


def _submit_job(request, project_id, api_client=None):
    """Submits to the training service (reference client.py:227-287)."""
    if api_client is None:
        if discovery is None:
            raise RuntimeError(
                "google-api-python-client is required to submit cloud_fit "
                "jobs.")
        api_client = discovery.build(
            "ml", "v1", cache_discovery=False,
            requestBuilder=google_api_client.CloudTpuHttpRequest)
    (api_client.projects()
     .jobs()
     .create(parent="projects/{}".format(project_id), body=request)
     .execute())
    logger.info("cloud_fit job %s submitted.", request["jobId"])
