"""Llama-family decoder LM: RMSNorm + RoPE + SwiGLU + grouped-query attention.

The modern-LLM counterpart of `TransformerLM` (which is GPT-2-shaped:
LayerNorm, learned positions, GELU, full MHA). No reference equivalent —
the reference stops at Keras models (SURVEY §0) — but a complete TPU
framework needs the architecture family that today's open checkpoints
(Llama/Mistral/Gemma-style) actually use:

- **RMSNorm** instead of LayerNorm: one fewer HBM pass (no mean
  subtraction / bias), fuses into the adjacent matmul under XLA.
- **Rotary position embeddings** instead of a learned table: positions
  are a closed-form rotation of q/k, so the KV cache carries them for
  free and long-context extension is a theta change, not a re-train.
- **SwiGLU MLP**: two column-parallel input projections (gate, up) and
  one row-parallel output — same two-collective Megatron layout as the
  GELU MLP, expressed in `llama_tensor_parallel_rules`.
- **GQA**: `num_kv_heads < num_heads` shrinks the KV cache (the decode
  memory bound) by H/H_kv while the q heads keep full MXU tiles. K/V
  are broadcast to the q-head grouping only at the attention op, never
  stored expanded.
- **Sliding-window attention** (`sliding_window=`): Mistral-style
  banded causal masking, mapped onto the flash kernel's tile-skip grid
  (ops.attention window=) in training and the cache band mask in
  decode.
- **RoPE frequency scaling** (`rope_scaling=RopeScaling(...)`):
  Llama-3.1 "llama3" banded scheme and plain linear compression for
  long-context checkpoints.
- **Decoupled head_dim** (`head_dim=`): attention width independent of
  d_model/num_heads (Mistral-Nemo-style checkpoints).
- **Family switches**: `qkv_bias=` (Qwen2), `mlp_activation=`
  ("gelu_tanh" GeGLU) + `scale_embed=` (Gemma), `post_block_norms=` +
  `attn_logit_softcap=`/`final_logit_softcap=` + `attn_scale=` +
  `attn_kinds=` local/global patterns (Gemma2), `qk_norm=` +
  `rope_theta_local=` (Gemma3) — one architecture serves the
  Llama/Mistral/Qwen/Gemma-1/2/3 checkpoint families via
  `models.hf_import`.

`LlamaLM` keeps `TransformerLM`'s module contract (same attribute
names, same "cache" collection shape conventions), so `generate()` —
the jitted prefill + `lax.scan` decode loop in
`cloud_tpu/models/transformer.py` — drives it unchanged.

RoPE convention: the default `rope_style="interleaved"` rotates
(even, odd) feature pairs — the GPT-NeoX layout. Real Llama/Mistral
checkpoints were trained against the rotate-half pairing (first half
vs second half); the two are related by a fixed permutation of
head_dim features, which from-scratch training absorbs into the
learned q/k projections. To run imported weights, build the model with
`rope_style="rotate_half"` — `models.hf_import.import_hf_llama` does
this for you and converts HF param layouts to this module's.
"""

from typing import NamedTuple, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from cloud_tpu.parallel import SEQUENCE_PARALLEL_IMPLS


class RopeScaling(NamedTuple):
    """Long-context RoPE frequency-scaling recipe (HF `rope_scaling`).

    kind selects the transform applied to the base inv-frequencies:
      - "linear": every frequency divided by `factor` (positions
        effectively compressed by `factor`).
      - "llama3": Llama-3.1's banded scheme — high frequencies (short
        wavelengths, local syntax) untouched, low frequencies (long
        wavelengths, past `original_max_len`) divided by `factor`, a
        smooth interpolation between the `high_freq_factor` and
        `low_freq_factor` wavelength cutoffs.
      - "yarn": NTK-by-parts (YaRN, arXiv 2309.00071): dimensions
        rotating faster than `beta_fast` turns over `original_max_len`
        keep their frequency (extrapolation), slower than `beta_slow`
        are divided by `factor` (interpolation), with a linear ramp
        between; the rotated vectors are additionally scaled by an
        attention factor (`attention_factor`, or derived from factor
        and the DeepSeek `mscale`/`mscale_all_dim` pair).

    A NamedTuple (not a dict) so flax module fields carrying it stay
    hashable/comparable; `models.hf_import` translates the HF config
    dict form.
    """
    kind: str
    factor: float
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_len: int = 8192
    # yarn-only fields:
    beta_fast: float = 32.0
    beta_slow: float = 1.0
    attention_factor: Optional[float] = None
    mscale: Optional[float] = None
    mscale_all_dim: Optional[float] = None
    truncate: bool = True


def _yarn_mscale(scale, mscale=1.0):
    if scale <= 1.0:
        return 1.0
    return 0.1 * mscale * float(np.log(scale)) + 1.0


def yarn_attention_factor(scaling: RopeScaling):
    """The cos/sin magnitude factor a yarn recipe applies to the
    rotated q/k (HF _compute_yarn_parameters attention_factor)."""
    if scaling.attention_factor is not None:
        return float(scaling.attention_factor)
    if scaling.mscale and scaling.mscale_all_dim:
        return (_yarn_mscale(scaling.factor, scaling.mscale)
                / _yarn_mscale(scaling.factor, scaling.mscale_all_dim))
    return _yarn_mscale(scaling.factor)


def _scale_rope_freqs(freqs, scaling: RopeScaling, theta, head_dim):
    """Applies a RopeScaling recipe to base inv-frequencies [D/2]."""
    if scaling.kind == "linear":
        return freqs / scaling.factor
    if scaling.kind == "llama3":
        wavelen = 2.0 * np.pi / freqs
        low_wl = scaling.original_max_len / scaling.low_freq_factor
        high_wl = scaling.original_max_len / scaling.high_freq_factor
        smooth = ((scaling.original_max_len / wavelen
                   - scaling.low_freq_factor)
                  / (scaling.high_freq_factor - scaling.low_freq_factor))
        blended = (1.0 - smooth) * freqs / scaling.factor + smooth * freqs
        return jnp.where(
            wavelen < high_wl, freqs,
            jnp.where(wavelen > low_wl, freqs / scaling.factor, blended))
    if scaling.kind == "yarn":
        # Dimension index below which a frequency completes `rot` turns
        # over the original context (HF find_correction_dim).
        def correction_dim(rot):
            return (head_dim * np.log(
                scaling.original_max_len / (rot * 2.0 * np.pi))
                / (2.0 * np.log(theta)))

        low = correction_dim(scaling.beta_fast)
        high = correction_dim(scaling.beta_slow)
        if scaling.truncate:
            low, high = np.floor(low), np.ceil(high)
        low = max(low, 0.0)
        high = min(high, head_dim - 1.0)
        if high == low:
            high += 0.001  # HF's singularity guard
        ramp = jnp.clip(
            (jnp.arange(head_dim // 2, dtype=jnp.float32) - low)
            / (high - low), 0.0, 1.0)
        extrapolation_factor = 1.0 - ramp
        return (freqs / scaling.factor * (1.0 - extrapolation_factor)
                + freqs * extrapolation_factor)
    raise ValueError(
        "Unknown RopeScaling kind {!r}; expected 'linear', 'llama3', "
        "or 'yarn'.".format(scaling.kind))


def apply_rope(x, positions, theta: float = 10000.0,
               style: str = "interleaved",
               scaling: Optional[RopeScaling] = None):
    """Rotary position embedding over the last (head_dim) axis.

    x: [B, S, H, D] (D even); positions: [S] or [B, S] int32.
    Rotates feature pairs by pos * theta^(-2i/D) — f32 rotation math
    regardless of input dtype (bf16 angles at position ~10k would
    quantize to whole radians).

    style selects which features pair up (the two conventions are
    related by a fixed permutation of head_dim features):
      - "interleaved": (even, odd) pairs — the GPT-NeoX layout, this
        framework's from-scratch default.
      - "rotate_half": (i, i + D/2) pairs — the Llama/HF layout;
        REQUIRED for weights imported from real Llama/Mistral
        checkpoints (`models.hf_import`), whose q/k projections were
        trained against this pairing.
    """
    head_dim = x.shape[-1]
    if head_dim % 2:
        raise ValueError("RoPE needs an even head_dim; got %d." % head_dim)
    freqs = theta ** (-jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                      / head_dim)
    if scaling is not None:
        freqs = _scale_rope_freqs(freqs, scaling, theta, head_dim)
    if positions.ndim == 1:
        positions = positions[None, :]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B,S,D/2]
    cos = jnp.cos(angles)[:, :, None, :]  # [B, S, 1, D/2]
    sin = jnp.sin(angles)[:, :, None, :]
    if style == "interleaved":
        x1 = x[..., 0::2].astype(jnp.float32)
        x2 = x[..., 1::2].astype(jnp.float32)
        rotated = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                            axis=-1).reshape(x.shape)
    elif style == "rotate_half":
        half = head_dim // 2
        x1 = x[..., :half].astype(jnp.float32)
        x2 = x[..., half:].astype(jnp.float32)
        rotated = jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    else:
        raise ValueError(
            "Unknown RoPE style {!r}; expected 'interleaved' or "
            "'rotate_half'.".format(style))
    if scaling is not None and scaling.kind == "yarn":
        # YaRN scales the rotary cos/sin magnitudes (both q and k, so
        # attention logits scale by the factor squared).
        rotated = rotated * yarn_attention_factor(scaling)
    return rotated.astype(x.dtype)


# Re-exported from ops (canonical home; the parallel layer uses it too
# without importing the models package).
from cloud_tpu.ops.attention import repeat_kv  # noqa: E402,F401


class GQAttention(nn.Module):
    """Grouped-query attention with RoPE and an H_kv-sized decode cache."""

    num_heads: int
    num_kv_heads: int
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"  # auto | flash | reference | ring | ulysses
    rope_theta: float = 10000.0
    rope_style: str = "interleaved"  # 'rotate_half' for HF-layout weights
    decode: bool = False
    cache_len: int = 0
    head_dim: Optional[int] = None  # None -> d_model // num_heads
    rope_scaling: Optional[RopeScaling] = None
    sliding_window: Optional[int] = None  # Mistral-style band width
    qkv_bias: bool = False  # Qwen2-style biased q/k/v (out stays bias-free)
    attn_scale: Optional[float] = None  # None -> 1/sqrt(head_dim)
    logit_softcap: Optional[float] = None  # Gemma2 tanh cap on logits
    qk_norm: bool = False  # Gemma3 per-head RMSNorm on q/k (pre-RoPE)
    norm_eps: float = 1e-6  # eps for the qk norms

    def _rope(self, x, positions):
        return apply_rope(x, positions, self.rope_theta, self.rope_style,
                          self.rope_scaling)

    @nn.compact
    def __call__(self, x, mask=None):
        from cloud_tpu import ops

        d_model = x.shape[-1]
        # Decoupled head_dim (Mistral-Nemo-style checkpoints): the
        # attention width need not be d_model/H; the out projection
        # maps H*head_dim back to d_model either way.
        head_dim = self.head_dim or d_model // self.num_heads
        dense = lambda feats, name: nn.DenseGeneral(
            feats, axis=-1, use_bias=self.qkv_bias,
            dtype=self.compute_dtype, name=name)
        q = dense((self.num_heads, head_dim), "query")(x)
        k = dense((self.num_kv_heads, head_dim), "key")(x)
        v = dense((self.num_kv_heads, head_dim), "value")(x)

        if self.qk_norm:
            # Gemma3: RMSNorm over head_dim (scale shared across heads),
            # applied BEFORE RoPE — replaces Gemma2's attention softcap
            # as the logit-magnitude control.
            q = nn.RMSNorm(epsilon=self.norm_eps, dtype=self.compute_dtype,
                           name="q_norm")(q)
            k = nn.RMSNorm(epsilon=self.norm_eps, dtype=self.compute_dtype,
                           name="k_norm")(k)

        if self.decode:
            # mask (optional [B, S]) marks REAL incoming tokens — the
            # left-padded-prompt contract (generate(prompt_mask=)):
            # padded slots are never attended and don't advance the
            # per-example logical position.
            out = self._decode_attention(q, k, v, mask)
        else:
            positions = jnp.arange(x.shape[1])
            q = self._rope(q, positions)
            k = self._rope(k, positions)
            if self.attention_impl in SEQUENCE_PARALLEL_IMPLS:
                if self.sliding_window or self.logit_softcap or \
                        self.attn_scale:
                    raise NotImplementedError(
                        "sliding_window / logit_softcap / attn_scale "
                        "are not supported by the sequence-parallel "
                        "impls ({}); use flash/reference/auto."
                        .format(self.attention_impl))
                # RoPE composes with sequence parallelism for free: the
                # rotation above ran on the *global* [B, S, H, D] arrays
                # (traced shapes under jit are global), so every shard
                # carries its true absolute positions into the SP path.
                # K/V stay at H_kv width: ulysses exchanges them grouped
                # (when H_kv divides sp), ring expands internally.
                from cloud_tpu.parallel import sp_attention
                out = sp_attention(self.attention_impl, q, k, v,
                                   causal=True, mask=mask)
            else:
                # flash/reference take the grouped H_kv layout natively.
                out = ops.attention(q, k, v, causal=True, mask=mask,
                                    sm_scale=self.attn_scale,
                                    window=self.sliding_window,
                                    logit_softcap=self.logit_softcap,
                                    impl=self.attention_impl)
        out = out.astype(self.compute_dtype)
        return nn.DenseGeneral(d_model, axis=(-2, -1), use_bias=False,
                               dtype=self.compute_dtype, name="out")(out)

    def _decode_attention(self, q, k, v, mask=None):
        """KV-cache attention at H_kv width (the point of GQA: the cache
        is num_heads/num_kv_heads times smaller than MHA's).

        Mirrors `CausalSelfAttention._decode_attention`
        (transformer.py): one path serves prefill (whole prompt, index
        0) and per-token steps (S=1). The cache is SLOT-addressed
        (write pointer `cache_index`), but RoPE angles and the sliding
        window band use per-example LOGICAL positions (`slot_pos`,
        counting only real tokens), so left-padded prompts rotate and
        band exactly like their unpadded equivalents; padded slots are
        marked invalid and never attended.
        """
        import jax.lax as lax

        from cloud_tpu.models.decoding import decode_slot_update

        batch, seq, _, head_dim = q.shape
        if not self.cache_len:
            raise ValueError("decode=True needs cache_len > 0.")
        cached_k = self.variable(
            "cache", "cached_key", jnp.zeros,
            (batch, self.cache_len, self.num_kv_heads, head_dim),
            self.compute_dtype)
        cached_v = self.variable(
            "cache", "cached_value", jnp.zeros,
            (batch, self.cache_len, self.num_kv_heads, head_dim),
            self.compute_dtype)

        idx, positions, allowed = decode_slot_update(
            self, mask, batch, seq, self.cache_len)
        q = self._rope(q, positions)
        k = self._rope(k, positions)

        cached_k.value = lax.dynamic_update_slice(
            cached_k.value, k.astype(self.compute_dtype), (0, idx, 0, 0))
        cached_v.value = lax.dynamic_update_slice(
            cached_v.value, v.astype(self.compute_dtype), (0, idx, 0, 0))

        if self.sliding_window:
            # Same band as the training-time kernel, on LOGICAL
            # positions: keys in (pos - window, pos]. Cached entries
            # older than the window are masked (not evicted — the
            # cache stays slot-addressed; rolling eviction is a memory
            # optimization this path doesn't need at cache_len scale).
            slot_pos = self.get_variable("cache", "slot_pos")
            allowed = allowed & (slot_pos[:, None, :]
                                 > positions[:, :, None]
                                 - self.sliding_window)
        scale = self.attn_scale or 1.0 / np.sqrt(head_dim)
        group = self.num_heads // self.num_kv_heads
        # Grouped einsum: q reshaped [B,S,H_kv,G,D] attends its own kv
        # head — no materialized repeat of the cache.
        qg = q.reshape(batch, seq, self.num_kv_heads, group, head_dim)
        logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, cached_k.value,
                            preferred_element_type=jnp.float32) * scale
        if self.logit_softcap:
            cap = float(self.logit_softcap)
            logits = cap * jnp.tanh(logits / cap)
        logits = jnp.where(allowed[:, None, None], logits, -1e30)
        weights = nn.softmax(logits, axis=-1).astype(self.compute_dtype)
        out = jnp.einsum("bhgqk,bkhd->bqhgd", weights, cached_v.value)
        return out.reshape(batch, seq, self.num_heads, head_dim)


_GATE_ACTIVATIONS = {
    "silu": nn.silu,  # Llama/Mistral/Qwen
    "gelu_tanh": lambda x: nn.gelu(x, approximate=True),  # Gemma
    "gelu": lambda x: nn.gelu(x, approximate=False),
}


class _DenseKernel(nn.Module):
    """Bare kernel-param holder: creates `<name>/kernel` exactly where
    `nn.Dense(use_bias=False)` would — same path, shape, param dtype,
    and initializer, so the param tree, checkpoints, AND path-derived
    init rng are unchanged when a fused op consumes the weight
    directly instead of calling the Dense module."""

    features: int

    @nn.compact
    def __call__(self, in_features):
        return self.param("kernel",
                          nn.linear.default_kernel_init,
                          (in_features, self.features), jnp.float32)


class SwiGLU(nn.Module):
    """Gated MLP: down(act(gate(x)) * up(x)), all bias-free.

    activation selects the gate nonlinearity: "silu" (the SwiGLU
    proper, Llama/Mistral/Qwen) or "gelu_tanh"/"gelu" (GeGLU, the
    Gemma family). The tail runs through `ops.fused_swiglu` — a
    single-VMEM-pass Pallas kernel on TPU, the bitwise lax reference
    elsewhere (`impl` follows the block's `attention_impl`,
    `CLOUD_TPU_FUSED_MLP` overriding) — with the gate/up/down kernel
    params exactly where the three `nn.Dense` modules kept them.
    """

    d_ff: int
    compute_dtype: jnp.dtype = jnp.bfloat16
    activation: str = "silu"
    impl: str = "auto"

    @nn.compact
    def __call__(self, x):
        if self.activation not in _GATE_ACTIVATIONS:
            raise ValueError(
                "Unknown mlp activation {!r}; expected one of {}."
                .format(self.activation, sorted(_GATE_ACTIVATIONS)))
        from cloud_tpu.ops import fused_swiglu
        features = x.shape[-1]
        w_gate = _DenseKernel(self.d_ff, name="gate")(features)
        w_up = _DenseKernel(self.d_ff, name="up")(features)
        w_down = _DenseKernel(features, name="down")(self.d_ff)
        impl = "reference" if self.impl == "reference" else "auto"
        return fused_swiglu(x, w_gate, w_up, w_down,
                            activation=self.activation,
                            compute_dtype=self.compute_dtype,
                            impl=impl)


class FusedRMSNorm(nn.Module):
    """`nn.RMSNorm` stand-in backed by the fused Pallas tail
    (ops/fused_norm.py): same param ("scale", [features] f32 — so
    checkpoints and hf_import layouts are unchanged), same f32
    statistics, bitwise the flax output wherever the lax reference is
    selected. Called with a `residual`, it ALSO returns the updated
    residual stream `h = x + residual` — the pre-norm block tail
    `x = x + y; y = norm(x)` collapses into one HBM pass.

    `impl` follows the block's `attention_impl` ("reference" forces the
    lax path; anything else auto-selects — Pallas on TPU, lax
    elsewhere, `CLOUD_TPU_FUSED_NORM` overriding)."""

    epsilon: float = 1e-6
    dtype: Optional[jnp.dtype] = None
    impl: str = "auto"

    @nn.compact
    def __call__(self, x, residual=None):
        from cloud_tpu.ops import fused_rmsnorm
        scale = self.param("scale", nn.initializers.ones,
                           (x.shape[-1],), jnp.float32)
        normed, h = fused_rmsnorm(x, scale, residual=residual,
                                  eps=self.epsilon,
                                  out_dtype=self.dtype, impl=self.impl)
        if residual is None:
            return normed
        return normed, h


class LlamaBlock(nn.Module):
    num_heads: int
    num_kv_heads: int
    d_ff: int
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"
    rope_theta: float = 10000.0
    rope_style: str = "interleaved"
    norm_eps: float = 1e-6
    dropout_rate: float = 0.0
    decode: bool = False
    cache_len: int = 0
    head_dim: Optional[int] = None
    rope_scaling: Optional[RopeScaling] = None
    sliding_window: Optional[int] = None
    qkv_bias: bool = False
    mlp_activation: str = "silu"
    post_norms: bool = False  # Gemma2/3: extra norm after attn and MLP
    attn_scale: Optional[float] = None
    logit_softcap: Optional[float] = None
    qk_norm: bool = False
    moe_experts: int = 0  # > 0: Mixtral-style top-k MoE replaces the MLP
    moe_top_k: int = 2
    moe_capacity_factor: Optional[float] = 2.0  # None = drop-free
    moe_norm_topk: bool = True  # False for some Qwen3-MoE checkpoints

    @nn.compact
    def __call__(self, x, mask=None, deterministic=True):
        norm = lambda name: nn.RMSNorm(
            epsilon=self.norm_eps, dtype=self.compute_dtype, name=name)
        fnorm = lambda name: FusedRMSNorm(
            epsilon=self.norm_eps, dtype=self.compute_dtype,
            impl=self.attention_impl, name=name)
        y = fnorm("norm_attn")(x)
        y = GQAttention(self.num_heads, self.num_kv_heads,
                        self.compute_dtype, self.attention_impl,
                        self.rope_theta, rope_style=self.rope_style,
                        decode=self.decode,
                        cache_len=self.cache_len,
                        head_dim=self.head_dim,
                        rope_scaling=self.rope_scaling,
                        sliding_window=self.sliding_window,
                        qkv_bias=self.qkv_bias,
                        attn_scale=self.attn_scale,
                        logit_softcap=self.logit_softcap,
                        qk_norm=self.qk_norm,
                        norm_eps=self.norm_eps,
                        name="attention")(y, mask)
        if self.post_norms:
            # Gemma2/3 sandwich norms: each sublayer's OUTPUT is
            # normalized before the residual add (the residual stream
            # itself stays un-normalized).
            y = norm("norm_attn_post")(y)
        if self.dropout_rate:
            # Dropout sits between the sublayer output and the residual
            # add, so the fused tail (add + norm in one pass) does not
            # apply; the param tree is identical either way.
            y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
            x = x + y
            y = norm("norm_mlp")(x)
        else:
            y, x = fnorm("norm_mlp")(y, residual=x)
        if self.moe_experts:
            from cloud_tpu.models.moe import TopKMoEMLP
            y, aux_loss = TopKMoEMLP(
                num_experts=self.moe_experts, top_k=self.moe_top_k,
                d_ff=self.d_ff,
                capacity_factor=self.moe_capacity_factor,
                compute_dtype=self.compute_dtype,
                activation=self.mlp_activation,
                norm_topk=self.moe_norm_topk, name="moe")(
                    y, deterministic)
            # Surfaced via mutable=["losses"] and summed into the
            # training loss by Trainer, same as TransformerBlock's
            # Switch-MoE path.
            self.sow("losses", "moe_aux_loss", aux_loss,
                     reduce_fn=lambda a, b: a + b, init_fn=lambda: 0.0)
        else:
            y = SwiGLU(self.d_ff, self.compute_dtype,
                       activation=self.mlp_activation,
                       impl=self.attention_impl, name="mlp")(y)
        if self.post_norms:
            y = norm("norm_mlp_post")(y)
        if self.dropout_rate:
            y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
        return x + y


class LlamaLM(nn.Module):
    """Llama-style decoder-only LM.

    Drop-in peer of `TransformerLM` for Trainer / `generate()` /
    checkpointing; differs in the block recipe (RMSNorm, RoPE, SwiGLU,
    GQA) and in having no learned position table.
    """

    vocab_size: int = 32000
    num_layers: int = 4
    num_heads: int = 8
    num_kv_heads: Optional[int] = None  # None -> num_heads (full MHA)
    d_model: int = 512
    d_ff: int = 1408  # ~2/3 * 4 * d_model, the SwiGLU convention
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    rope_style: str = "interleaved"  # 'rotate_half' for HF-layout weights
    norm_eps: float = 1e-6  # HF rms_norm_eps (Llama-2/Mistral use 1e-5)
    dropout_rate: float = 0.0
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"
    decode: bool = False
    head_dim: Optional[int] = None  # None -> d_model // num_heads
    rope_scaling: Optional[RopeScaling] = None  # long-context extension
    sliding_window: Optional[int] = None  # Mistral-style band width
    qkv_bias: bool = False  # Qwen2-style biased q/k/v projections
    mlp_activation: str = "silu"  # "gelu_tanh" for the Gemma family
    scale_embed: bool = False  # Gemma: hidden = embed * sqrt(d_model)
    # Gemma2/3 family switches (all default off):
    post_block_norms: bool = False  # extra norm after attn/MLP outputs
    attn_scale: Optional[float] = None  # query_pre_attn_scalar ** -0.5
    attn_logit_softcap: Optional[float] = None  # Gemma2: 50.0
    final_logit_softcap: Optional[float] = None  # Gemma2: 30.0
    qk_norm: bool = False  # Gemma3: per-head RMSNorm on q/k
    # Per-layer local/global attention pattern, cycled over layers:
    # e.g. ("local", "global") = Gemma2's alternating sliding/full;
    # ("local",)*5 + ("global",) = Gemma3's 5:1. "local" layers use the
    # sliding_window band and (rope_theta_local, rope_scaling_local);
    # "global" layers attend fully with (rope_theta, rope_scaling).
    # None = every layer identical (sliding_window applies to all).
    attn_kinds: Optional[Tuple[str, ...]] = None
    rope_theta_local: Optional[float] = None  # Gemma3: 10_000
    rope_scaling_local: Optional[RopeScaling] = None
    # Mixtral/Qwen3-MoE family: top-k routed MoE FFN in every block.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: Optional[float] = 2.0  # None = drop-free
    moe_norm_topk: bool = True

    def _layer_attn(self, i):
        """(window, theta, scaling) for layer i under attn_kinds."""
        if self.attn_kinds is None:
            return self.sliding_window, self.rope_theta, self.rope_scaling
        kind = self.attn_kinds[i % len(self.attn_kinds)]
        if kind == "global":
            return None, self.rope_theta, self.rope_scaling
        if kind != "local":
            raise ValueError(
                "attn_kinds entries must be 'local' or 'global'; got "
                "{!r}.".format(kind))
        if not self.sliding_window:
            raise ValueError(
                "attn_kinds includes 'local' layers but sliding_window "
                "is not set.")
        return (self.sliding_window,
                self.rope_theta_local or self.rope_theta,
                self.rope_scaling_local)

    @nn.compact
    def __call__(self, tokens, mask=None, deterministic=True):
        seq = tokens.shape[1]
        if seq > self.max_seq_len:
            raise ValueError(
                "Sequence length {} exceeds max_seq_len {}.".format(
                    seq, self.max_seq_len))
        num_kv = self.num_kv_heads or self.num_heads
        x = nn.Embed(self.vocab_size, self.d_model,
                     dtype=self.compute_dtype, name="embed")(tokens)
        if self.scale_embed:
            # Gemma convention: the normalizer is cast to the compute
            # dtype BEFORE multiplying (a bf16-rounded sqrt(d), matching
            # checkpoints trained that way).
            x = x * jnp.asarray(self.d_model ** 0.5, self.compute_dtype)
        for i in range(self.num_layers):
            window, theta, scaling = self._layer_attn(i)
            x = LlamaBlock(self.num_heads, num_kv, self.d_ff,
                           self.compute_dtype, self.attention_impl,
                           theta, self.rope_style,
                           self.norm_eps, self.dropout_rate,
                           decode=self.decode,
                           cache_len=self.max_seq_len,
                           head_dim=self.head_dim,
                           rope_scaling=scaling,
                           sliding_window=window,
                           qkv_bias=self.qkv_bias,
                           mlp_activation=self.mlp_activation,
                           post_norms=self.post_block_norms,
                           attn_scale=self.attn_scale,
                           logit_softcap=self.attn_logit_softcap,
                           qk_norm=self.qk_norm,
                           moe_experts=self.moe_experts,
                           moe_top_k=self.moe_top_k,
                           moe_capacity_factor=self.moe_capacity_factor,
                           moe_norm_topk=self.moe_norm_topk,
                           name="block_%d" % i)(x, mask, deterministic)
        x = FusedRMSNorm(epsilon=self.norm_eps,
                         dtype=self.compute_dtype,
                         impl=self.attention_impl,
                         name="norm_final")(x)
        logits = nn.Dense(self.vocab_size, use_bias=False,
                          dtype=self.compute_dtype, name="lm_head")(x)
        logits = logits.astype(jnp.float32)
        if self.final_logit_softcap:
            cap = float(self.final_logit_softcap)
            logits = cap * jnp.tanh(logits / cap)
        return logits


def llama_tensor_parallel_rules(tp_axis: str = "tp"):
    """Megatron layout for LlamaLM: same two-collective-per-block shape
    as `tensor_parallel_rules` (transformer.py), with SwiGLU's gate/up
    both column-parallel and kv projections head-sharded (requires
    num_kv_heads % tp == 0)."""
    return [
        (r"attention/(query|key|value)/kernel", P(None, tp_axis, None)),
        (r"attention/(query|key|value)/bias", P(tp_axis, None)),
        (r"attention/out/kernel", P(tp_axis, None, None)),
        (r"mlp/(gate|up)/kernel", P(None, tp_axis)),
        (r"mlp/down/kernel", P(tp_axis, None)),
        (r"(^|/)embed/embedding", P(tp_axis, None)),
        (r"lm_head/kernel", P(None, tp_axis)),
    ]
