from cloud_tpu.models.llama import (GQAttention, LlamaLM, RopeScaling,
                                    llama_tensor_parallel_rules)
from cloud_tpu.models.deepseek import (DeepseekLM, DeepseekMoE,
                                       MLAttention,
                                       deepseek_tensor_parallel_rules)
from cloud_tpu.models.mnist import MLP, ConvNet
from cloud_tpu.models.resnet import (ResNet, ResNet18, ResNet34, ResNet50,
                                     ResNet101, ResNet152)
from cloud_tpu.models.moe import (MoEMLP, TopKMoEMLP,
                                  expert_parallel_rules)
from cloud_tpu.models.pipelined import PipelinedLM, pipelined_lm_rules
from cloud_tpu.models.beam import generate_beam
from cloud_tpu.models.speculative import (SpeculativeBatchError,
                                          SpeculativeShardingError,
                                          generate_speculative)
from cloud_tpu.models.hf_import import (import_hf_deepseek,
                                        import_hf_gpt2, import_hf_llama)
from cloud_tpu.models.transformer import (TransformerEncoder,
                                          TransformerLM, generate,
                                          tensor_parallel_rules)
from cloud_tpu.models.vit import ViT, ViT_B16, ViT_L16, ViT_S16
