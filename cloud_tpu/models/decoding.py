"""Shared KV-cache slot bookkeeping for decode-mode attention.

Every decode attention (`CausalSelfAttention`, `GQAttention`,
`MLAttention`) appends incoming tokens at the cache write pointer and
attends over everything valid so far. The left-padded-prompt contract
(`generate(prompt_mask=)`) adds per-example bookkeeping on top: padded
slots must never be attended, and rotary angles / learned-position
lookups / sliding-window bands must count only REAL tokens. This
module holds that recipe ONCE so the three families cannot drift.

Cache variables created on the calling module ("cache" collection):
  cache_index  []       slot write pointer (shared across examples)
  slot_valid   [B, L]   True where a real token was written
  slot_pos     [B, L]   the slot's LOGICAL position (real tokens only)
  token_count  [B]      number of real tokens seen per example
"""

import functools
import re
import warnings

import jax
import jax.lax as lax
import jax.numpy as jnp


def decode_slot_update(module, mask, batch, seq, cache_len):
    """Advance the decode cache's slot bookkeeping for one call.

    module: the flax module (inside @nn.compact) owning the cache.
    mask: optional [B, S] marking REAL incoming tokens (None = all).

    Returns (idx, positions, allowed):
      idx        the write pointer BEFORE this call (callers write
                 their k/v tensors at slots [idx, idx+S));
      positions  [B, S] int32 logical position of each incoming token
                 (#real tokens before it, per example) — feed to RoPE
                 or a learned position table; padded entries carry a
                 harmless placeholder (their slots are invalid);
      allowed    [B, S, L] bool attention mask: slot-order causality
                 (append-only writes make slot index the causal order)
                 AND slot validity (padded + never-written slots
                 excluded).

    The sliding-window band is the caller's concern: compare the
    module's `slot_pos` cache variable (logical key positions) against
    `positions` — see `GQAttention._decode_attention`.
    """
    index = module.variable(
        "cache", "cache_index", lambda: jnp.zeros((), jnp.int32))
    slot_valid = module.variable(
        "cache", "slot_valid", jnp.zeros, (batch, cache_len), jnp.bool_)
    slot_pos = module.variable(
        "cache", "slot_pos", jnp.zeros, (batch, cache_len), jnp.int32)
    token_count = module.variable(
        "cache", "token_count", jnp.zeros, (batch,), jnp.int32)

    m = (jnp.ones((batch, seq), jnp.int32) if mask is None
         else mask.astype(jnp.int32))
    idx = index.value
    positions = token_count.value[:, None] + jnp.cumsum(m, 1) - m

    slot_valid.value = lax.dynamic_update_slice(
        slot_valid.value, m.astype(jnp.bool_), (0, idx))
    slot_pos.value = lax.dynamic_update_slice(
        slot_pos.value, positions.astype(jnp.int32), (0, idx))
    index.value = idx + seq
    token_count.value = token_count.value + m.sum(axis=1)

    key_slots = jnp.arange(cache_len)
    allowed = (slot_valid.value[:, None, :]
               & (key_slots[None, None, :]
                  <= idx + jnp.arange(seq)[None, :, None]))
    return idx, positions, allowed


def paged_slot_update(module, mask, slots, seq, cache_len):
    """The per-slot (continuous-batching) counterpart of
    `decode_slot_update`, for decode ticks over a paged pool.

    Where `decode_slot_update` advances ONE shared write pointer (all
    examples decode in lockstep), a serving tick advances each slot
    independently: slot s sits at its own depth `slot_steps[s]`, and an
    inactive slot (mask 0) must not move at all. Slot-order causality
    and validity masking are otherwise the recipe above, per row.

    `seq` may exceed 1: the speculative tick verifies a (k+1)-token
    window per slot in one call (serving/engine.py), writing each
    slot's tokens at consecutive positions from its own pointer. The
    single-token plain tick is the seq=1 specialization — the masks
    and pointer math reduce to exactly the PR 10 forms.

    Cache variables created on the calling module ("cache" collection):
      slot_steps  [S]      per-slot write pointer (tokens written)
      slot_valid  [S, L]   True where a real token was written
    (The page table itself is the attention module's variable — it owns
    the physical layout; this helper owns only the logical bookkeeping.)

    Returns (pos, allowed):
      pos      [S, seq] int32 per-token write positions — callers write
               token j of slot s at logical position pos[s, j] (the
               slot's pointer plus the real tokens before j);
      allowed  [S, seq, L] bool attention mask over each slot's LOGICAL
               cache view (validity AND slot-order causality up to each
               query's own write position), the exact mask
               `decode_slot_update` would produce for a solo decode at
               the same depth.
    """
    slot_steps = module.variable(
        "cache", "slot_steps", jnp.zeros, (slots,), jnp.int32)
    slot_valid = module.variable(
        "cache", "slot_valid", jnp.zeros, (slots, cache_len), jnp.bool_)

    m = (jnp.ones((slots, seq), jnp.int32) if mask is None
         else mask.reshape(slots, seq).astype(jnp.int32))
    idx = slot_steps.value
    pos = idx[:, None] + jnp.cumsum(m, 1) - m
    # Masked scatter: active slots validate their write positions; an
    # inactive slot OR-writes False at its (clamped) current position —
    # the identity, so it neither moves nor changes state.
    slot_valid.value = slot_valid.value.at[
        jnp.arange(slots)[:, None],
        jnp.clip(pos, 0, cache_len - 1)].max(m.astype(jnp.bool_))
    slot_steps.value = idx + m.sum(axis=1)

    key_slots = jnp.arange(cache_len)
    allowed = (slot_valid.value[:, None, :]
               & (key_slots[None, None, :] <= pos[:, :, None]))
    return pos, allowed


def paged_slot_rewind(cache_tree, delta, cache_len):
    """Rolls per-slot paged bookkeeping back by `delta[s]` positions:
    the speculative tick writes a full (k+1)-token verify window, then
    keeps only the accepted prefix — rejected positions become invalid
    and the pointer retreats, exactly `speculative._rewind_cache`'s
    bookkeeping-only rollback per slot. Physical page contents are NOT
    touched: an invalidated slot is masked to exact-zero attention
    weight and overwritten by the next real write.

    `cache_tree` is a plain-dict paged cache; attention subtrees are
    detected by their `key_pages` variable. Returns the rolled-back
    tree (functional update).
    """
    def rewind(att):
        out = dict(att)
        steps = att["slot_steps"] - delta
        out["slot_steps"] = steps
        out["slot_valid"] = (att["slot_valid"]
                             & (jnp.arange(cache_len)[None, :]
                                < steps[:, None]))
        return out

    def walk(tree):
        if isinstance(tree, dict):
            if "key_pages" in tree:
                return rewind(tree)
            return {k: walk(v) for k, v in tree.items()}
        return tree

    return walk(cache_tree)


# The load-bearing fragment of the warning jax emits when donated
# buffers can't alias (a plain `warnings.warn`, so category
# UserWarning; jax/_src/interpreters/mlir.py). Matching a FRAGMENT
# rather than jax 0.4.37's exact text ("Some donated buffers were not
# usable: ...") keeps the suppression armed across jax releases that
# reword the sentence around it — prefix AND suffix are free to
# change. Only if the core phrase itself disappears does the filter
# degrade to a no-op: the warning becomes visible again (fail open),
# never wrongly silenced.
_DONATION_FRAGMENT = "donated buffers were not usable"
# `warnings.filterwarnings` anchors its regex at the start of the
# message, so a leading wildcard makes this a substring match; the
# escape is future-proofing for fragments with regex metacharacters.
_DONATION_PATTERN = r".*" + re.escape(_DONATION_FRAGMENT)


def _arm_donation_filter():
    """Ensure ONE ignore entry for jax's donation warning is in the
    warnings filter list; re-installs after pytest's per-test filter
    resets wipe it. The scan compares the compiled pattern the
    installed entry carries (filterwarnings compiled it once, at
    install — never per dispatch) so repeated arming is an O(filters)
    string compare, not a filter-list mutation."""
    for entry in warnings.filters:
        if (entry[0] == "ignore"
                and getattr(entry[1], "pattern", None) == _DONATION_PATTERN
                and entry[2] is UserWarning):
            return
    warnings.filterwarnings("ignore", message=_DONATION_PATTERN,
                            category=UserWarning)


def best_effort_donation(fn):
    """Wrap a jitted decode executable whose cache arguments are
    donated: donation is an optimization, not a contract — under a
    mesh the caller's (e.g. replicated) cache layout may not alias the
    GSPMD-partitioned layout the executable compiled to, and JAX warns
    'Some donated buffers were not usable' on every call. The callers
    never reuse the passed-in cache either way, so suppress exactly
    that message (category + compiled-once regex match).

    The filter is installed AT MOST ONCE per process and only
    re-checked (not re-installed) per dispatch — the previous per-call
    `catch_warnings` save/restore mutated the thread-GLOBAL filter
    list on every decode step, which races with concurrent decode
    threads and thrashes the warning registry. The accepted trade:
    the ignore is process-wide, so a USER jit emitting the identical
    donation message is silenced too; that message is advisory (an
    optimization that didn't apply), never a correctness signal.
    """
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        _arm_donation_filter()
        return fn(*args, **kwargs)
    return wrapped


def bucket_length(n, cap=None):
    """The decode prefill bucket for a prompt of length `n`: the next
    power of two >= n, clipped to `cap` (the caller's token budget,
    typically `max_seq_len - max_new_tokens`).

    Under static shapes every distinct prompt length mints its own
    prefill executable; padding to power-of-two buckets bounds the
    executable census at ~log2(max_seq_len) per sampling config. The
    clip keeps the padded prompt inside the cache budget: lengths in
    (previous_power_of_two, cap] share the cap-width bucket. When `n`
    already exceeds `cap` the length is returned unchanged — bucketing
    pads, never truncates (overflow is the caller's validation error).
    """
    if n < 1:
        raise ValueError(
            "bucket_length needs a positive length; got {}.".format(n))
    bucket = 1
    while bucket < n:
        bucket *= 2
    if cap is not None:
        if cap < n:
            return n
        bucket = min(bucket, cap)
    return bucket


def validate_prompt_mask(prompt_mask, batch, prompt_len, reader):
    """The left-padded variable-length prompt contract, checked ONCE
    for every decode entry point (`generate`, `generate_beam`):
    prompt_mask is [batch, prompt_len] with every row's LAST column
    real — the position whose logits/log-probs `reader` consumes."""
    import numpy as np

    pm = np.asarray(prompt_mask)
    if pm.shape != (batch, prompt_len):
        raise ValueError(
            "prompt_mask must be [batch, prompt_len] = {}; got "
            "{}.".format((batch, prompt_len), pm.shape))
    if not pm[:, -1].all():
        raise ValueError(
            "prompt_mask must be LEFT-padded (last column all real): "
            "{} reads the final prompt position.".format(reader))


def warp_logits(logits, temperature, top_k=None, top_p=None):
    """HF-warper-order logits processing: top-k (on raw logits) →
    temperature → top-p nucleus. Shared by `generate()`'s sampler and
    stochastic speculative decoding, so the speculative accept/reject
    math targets EXACTLY the distribution `generate()` samples from.

    temperature must be > 0 (greedy argmax is a separate path).
    Nucleus membership is decided in sorted order and scattered back
    through the inverse permutation — exact logit ties at the cutoff
    are split by descending-sort position (jnp.argsort is stable, so
    equal logits keep vocab-index order), matching HF's sorted-index
    scatter rather than a value threshold that would keep every tied
    token (reference semantics: transformers TopPLogitsWarper).
    """
    logits = logits.astype(jnp.float32)
    if top_k is not None:
        # O(V log k), not a full vocab sort per decode step.
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    scaled = logits / temperature
    if top_p is not None and top_p < 1.0:
        # Keep the smallest top-probability set whose cumulative mass
        # reaches top_p: `cum - probs < top_p` keeps every token whose
        # EXCLUSIVE prefix mass is below the threshold — the set up to
        # and including the first token that crosses it, so at least
        # one always survives.
        # Descending order as HF's ascending stable sort, flipped:
        # among EXACT logit ties the higher vocab index outranks the
        # lower (TopPLogitsWarper removes the ascending prefix, so the
        # low-index tie is dropped first) — verified identical keep
        # sets against the torch warper incl. forced ties.
        sort_idx = jnp.flip(jnp.argsort(scaled, axis=-1), -1)
        sorted_scaled = jnp.take_along_axis(scaled, sort_idx, axis=-1)
        probs = jax.nn.softmax(sorted_scaled, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = (cum - probs) < top_p
        # sort_idx is a permutation per row, so its inverse is a
        # scatter of arange — O(V), where argsort would be a third
        # O(V log V) sort (XLA CPU sorts are the decode hot spot).
        vocab = sort_idx.shape[-1]
        flat = sort_idx.reshape(-1, vocab)
        inv = jnp.zeros_like(flat).at[
            jnp.arange(flat.shape[0])[:, None], flat].set(
                jnp.broadcast_to(jnp.arange(vocab), flat.shape))
        keep = jnp.take_along_axis(keep_sorted,
                                   inv.reshape(sort_idx.shape), axis=-1)
        scaled = jnp.where(keep, scaled, -1e30)
    return scaled


@functools.lru_cache(maxsize=256)
def _cache_shapes(decoder, batch):
    """Abstract decode-cache shapes for (decoder, batch), computed once
    per config: `jax.eval_shape` re-traces the whole model every call,
    which showed up as pure-python overhead on every generate()."""
    return jax.eval_shape(
        lambda: decoder.init(jax.random.PRNGKey(0),
                             jnp.zeros((batch, 1), jnp.int32)))["cache"]


def empty_cache(decoder, batch):
    """Zero-initialized decode-cache pytree for a decode-mode module
    (shared by `generate` and `generate_speculative`): built from the
    abstract init so no second params copy is ever materialized."""
    shapes = _cache_shapes(decoder, batch)
    return jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes)


# --------------------------------------------------------------------------
# Decode-cache reuse pool.
#
# `empty_cache` allocates a fresh HBM cache every call, so a serving loop
# of repeated generate() calls churns allocations the size of the whole
# KV cache at request rate. The pool below recycles them: release() parks
# a finished call's final cache, acquire() re-zeros a parked one IN PLACE
# (a donated jitted tree-zero, so XLA aliases the buffers instead of
# allocating) and hands it back. Keyed on (decoder, batch) — the pair
# that fixes every leaf shape. Bounded per key so a burst can't pin
# unbounded HBM; thread-safe for concurrent generate() callers.

_CACHE_POOL = {}
_CACHE_POOL_LOCK = None
_CACHE_POOL_DEPTH = 2  # parked caches per (decoder, batch) key


def _pool_lock():
    global _CACHE_POOL_LOCK
    if _CACHE_POOL_LOCK is None:
        import threading
        _CACHE_POOL_LOCK = threading.Lock()
    return _CACHE_POOL_LOCK


@functools.lru_cache(maxsize=None)
def _zero_in_place():
    from cloud_tpu.parallel import runtime

    @functools.partial(runtime.instrumented_jit, donate_argnums=0)
    def zero(cache):
        return jax.tree_util.tree_map(jnp.zeros_like, cache)
    return best_effort_donation(zero)


def acquire_cache(decoder, batch):
    """A zeroed decode cache for (decoder, batch): a recycled buffer
    when one is parked, a fresh `empty_cache` otherwise."""
    with _pool_lock():
        parked = _CACHE_POOL.get((decoder, batch))
        cache = parked.pop() if parked else None
    if cache is None:
        return empty_cache(decoder, batch)
    return _zero_in_place()(cache)


def release_cache(decoder, batch, cache):
    """Parks a finished decode's final cache for reuse. The caller must
    not touch `cache` afterwards (the next acquire donates it). Drops
    the cache on the floor (normal GC) when the pool is full."""
    if cache is None:
        return
    with _pool_lock():
        parked = _CACHE_POOL.setdefault((decoder, batch), [])
        if len(parked) < _CACHE_POOL_DEPTH:
            parked.append(cache)


def clear_cache_pool():
    """Empties the reuse pool (test isolation; frees the parked HBM)."""
    with _pool_lock():
        _CACHE_POOL.clear()


def decode_latency_start():
    """graftscope hook: monotonic-ns start handle for one generate()/
    beam/speculative call, or None when telemetry is off.

    Zero-cost discipline: `sys.modules.get` means the disabled path is
    one dict lookup — if the telemetry module was never imported, it is
    certainly not enabled, and no import happens here.
    """
    import sys

    telemetry = sys.modules.get("cloud_tpu.monitoring.telemetry")
    if telemetry is None or not telemetry.enabled():
        return None
    import time

    return time.monotonic_ns()


def decode_latency_finish(start, n_tokens, result=None):
    """Completes a `decode_latency_start` handle: blocks on `result`'s
    device leaves (the tokens are only 'generated' once the dispatch
    retires — measuring dispatch alone would report async-dispatch
    latency, not token latency), records one "decode" span and feeds
    the per-token decode-latency histogram. No-op for a None handle.
    The deliberate block only happens when telemetry is on: the
    measurement cost is the measurement.
    """
    if start is None:
        return
    import sys
    import time

    telemetry = sys.modules.get("cloud_tpu.monitoring.telemetry")
    if telemetry is None:
        return
    tele = telemetry.get()
    if tele is None or not tele.active:
        return
    if result is not None:
        for leaf in jax.tree_util.tree_leaves(result):
            if isinstance(leaf, jax.Array):
                leaf.block_until_ready()
    elapsed_ns = time.monotonic_ns() - start
    from cloud_tpu.monitoring import spans

    spans.complete("decode", start, elapsed_ns)
    tele.observe_decode(n_tokens, elapsed_ns / 1e9)


__all__ = ["acquire_cache", "best_effort_donation", "bucket_length",
           "clear_cache_pool", "decode_latency_finish",
           "decode_latency_start", "decode_slot_update", "empty_cache",
           "paged_slot_rewind", "paged_slot_update", "release_cache",
           "validate_prompt_mask", "warp_logits"]
