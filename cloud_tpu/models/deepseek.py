"""DeepSeek-V2/V3-style decoder LM: multi-head latent attention + MoE.

The third LM architecture family (after `TransformerLM` and `LlamaLM`),
for the DeepSeek checkpoint line. No reference equivalent — the
reference stops at Keras models (SURVEY §0) — but the two ideas this
family contributes are exactly the ones that matter at TPU scale:

- **MLA (multi-head latent attention)**: k/v are generated from a
  low-rank compressed latent (`kv_lora_rank` ~ 512 vs H*(nope+v) ~ 32k
  in DeepSeek-V3), so the decode cache stores the LATENT plus a small
  shared rope key — a ~50x KV-cache reduction, which is the decode
  memory bound. Queries optionally go through their own low-rank
  bottleneck (`q_lora_rank`). Attention runs at `qk_head_dim` =
  nope+rope width per head; only the rope slice is rotated, and the
  rope key is SHARED across heads (multi-query for the positional
  part). The value width (`v_head_dim`) can differ from the key width:
  v is zero-padded to the key width so the flash kernel's single-D
  layout serves MLA unchanged, and the pad is sliced off after (zero
  columns of V contribute zeros to the output — exact, not
  approximate; HF's flash path does the same).
- **DeepSeek MoE**: sigmoid router scores with a (non-learned) score
  correction bias used for SELECTION only, node-limited group routing
  (`n_group`/`topk_group`: only groups whose top-2 summed scores rank
  highest stay eligible), gates = the UNBIASED scores at the selected
  experts (normalized, then scaled by `routed_scaling_factor`), and a
  dense always-on shared expert alongside the routed ones. Expert
  compute reuses the same dense-dispatch einsums as `TopKMoEMLP`
  (`moe.routed_expert_ffn`) — static shapes, MXU-tiled, "ep"-shardable
  via `expert_parallel_rules`.

`DeepseekLM` keeps the `TransformerLM`/`LlamaLM` module contract
(decode=/cache collection/max_seq_len/vocab_size), so `generate()`
drives it unchanged — with the compressed-latent cache, not an
expanded one. Weights import from HF `DeepseekV3ForCausalLM` via
`models.hf_import.import_hf_deepseek` (rope_interleave -> the
"interleaved" rope style; rotate-half otherwise).
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from cloud_tpu.models.llama import (_GATE_ACTIVATIONS, RopeScaling,
                                    SwiGLU, apply_rope)


class MLAttention(nn.Module):
    """Multi-head latent attention (DeepSeek-V2/V3).

    Projections (all bias-free, matching `attention_bias=False`):
      q:  x -> [q_a -> RMSNorm -> q_b] (or direct `query` when
          q_lora_rank is None) -> [B, S, H, nope+rope]
      kv: x -> kv_a -> split(latent [kv_lora_rank], k_rot [rope]);
          latent -> RMSNorm -> kv_b -> [B, S, H, nope+v]
    The rope slices of q and the shared k_rot are rotated; attention
    runs over concat(nope, rope) keys with v zero-padded to the same
    width (sliced off after — exact).
    """

    num_heads: int
    kv_lora_rank: int
    qk_nope_head_dim: int
    qk_rope_head_dim: int
    v_head_dim: int
    q_lora_rank: Optional[int] = None  # None = direct q projection
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"  # auto | flash | reference
    rope_theta: float = 10000.0
    rope_style: str = "interleaved"  # HF rope_interleave=True
    rope_scaling: Optional[RopeScaling] = None  # yarn for long context
    attn_scale: Optional[float] = None  # None -> qk_head_dim**-0.5;
    # DeepSeek yarn checkpoints fold the mscale^2 factor in here.
    norm_eps: float = 1e-6
    decode: bool = False
    cache_len: int = 0

    def _rope(self, x, positions):
        return apply_rope(x, positions, self.rope_theta, self.rope_style,
                          self.rope_scaling)

    @nn.compact
    def __call__(self, x, mask=None):
        from cloud_tpu import ops
        from cloud_tpu.parallel import SEQUENCE_PARALLEL_IMPLS

        if self.attention_impl in SEQUENCE_PARALLEL_IMPLS:
            raise NotImplementedError(
                "MLA's shared rope key / mixed head widths are not "
                "wired into the sequence-parallel impls ({}); use "
                "flash/reference/auto.".format(self.attention_impl))
        d_model = x.shape[-1]
        qk_head_dim = self.qk_nope_head_dim + self.qk_rope_head_dim
        dense = lambda feats, name: nn.DenseGeneral(
            feats, axis=-1, use_bias=False, dtype=self.compute_dtype,
            name=name)

        if self.q_lora_rank is None:
            q = dense((self.num_heads, qk_head_dim), "query")(x)
        else:
            q = dense((self.q_lora_rank,), "q_a")(x)
            q = nn.RMSNorm(epsilon=self.norm_eps,
                           dtype=self.compute_dtype, name="q_a_norm")(q)
            q = dense((self.num_heads, qk_head_dim), "q_b")(q)
        q_nope = q[..., :self.qk_nope_head_dim]
        q_rot = q[..., self.qk_nope_head_dim:]

        ckv = dense((self.kv_lora_rank + self.qk_rope_head_dim,),
                    "kv_a")(x)
        latent = ckv[..., :self.kv_lora_rank]
        k_rot = ckv[..., None, self.kv_lora_rank:]  # [B, S, 1, rope]
        latent = nn.RMSNorm(epsilon=self.norm_eps,
                            dtype=self.compute_dtype,
                            name="kv_a_norm")(latent)

        kv_b = dense((self.num_heads,
                      self.qk_nope_head_dim + self.v_head_dim), "kv_b")

        if self.decode:
            # mask (optional [B, S]) marks REAL incoming tokens — the
            # left-padded-prompt contract (generate(prompt_mask=)).
            out = self._decode_attention(q_nope, q_rot, latent, k_rot,
                                         kv_b, mask)
        else:
            positions = jnp.arange(x.shape[1])
            q_rot = self._rope(q_rot, positions)
            k_rot = self._rope(k_rot, positions)
            kv = kv_b(latent)  # [B, S, H, nope+v]
            k_nope = kv[..., :self.qk_nope_head_dim]
            v = kv[..., self.qk_nope_head_dim:]
            q_full = jnp.concatenate([q_nope, q_rot], axis=-1)
            k_full = jnp.concatenate(
                [k_nope, jnp.broadcast_to(
                    k_rot, k_nope.shape[:-1] + (self.qk_rope_head_dim,))],
                axis=-1)
            # Zero-pad v to the key width so the single-D flash kernel
            # applies; zero columns contribute zeros — slice after.
            v_pad = jnp.pad(
                v, ((0, 0), (0, 0), (0, 0),
                    (0, qk_head_dim - self.v_head_dim)))
            out = ops.attention(
                q_full, k_full, v_pad, causal=True,
                sm_scale=self.attn_scale or qk_head_dim ** -0.5,
                mask=mask, impl=self.attention_impl)
            out = out[..., :self.v_head_dim]
        out = out.astype(self.compute_dtype)
        return nn.DenseGeneral(d_model, axis=(-2, -1), use_bias=False,
                               dtype=self.compute_dtype, name="out")(out)

    def _decode_attention(self, q_nope, q_rot, latent, k_rot, kv_b,
                          mask=None):
        """KV-cache attention over the COMPRESSED latent.

        The cache stores [B, L, kv_lora_rank] latents plus the shared
        [B, L, 1, rope] rotated key — the MLA memory win (~H*(nope+v)
        / (kv_lora_rank+rope) smaller than an expanded cache). Each
        step re-expands the cached latents through kv_b; that matmul
        is the same O(L) cost order as the attention itself.
        """
        import jax.lax as lax

        from cloud_tpu.models.decoding import decode_slot_update

        batch, seq = q_nope.shape[:2]
        if not self.cache_len:
            raise ValueError("decode=True needs cache_len > 0.")
        cached_latent = self.variable(
            "cache", "cached_latent", jnp.zeros,
            (batch, self.cache_len, self.kv_lora_rank),
            self.compute_dtype)
        cached_rope = self.variable(
            "cache", "cached_rope", jnp.zeros,
            (batch, self.cache_len, 1, self.qk_rope_head_dim),
            self.compute_dtype)

        idx, positions, allowed = decode_slot_update(
            self, mask, batch, seq, self.cache_len)
        q_rot = self._rope(q_rot, positions)
        k_rot = self._rope(k_rot, positions)

        cached_latent.value = lax.dynamic_update_slice(
            cached_latent.value, latent.astype(self.compute_dtype),
            (0, idx, 0))
        cached_rope.value = lax.dynamic_update_slice(
            cached_rope.value, k_rot.astype(self.compute_dtype),
            (0, idx, 0, 0))

        kv = kv_b(cached_latent.value)  # [B, L, H, nope+v]
        k_nope = kv[..., :self.qk_nope_head_dim]
        v = kv[..., self.qk_nope_head_dim:]
        scale = self.attn_scale or (
            self.qk_nope_head_dim + self.qk_rope_head_dim) ** -0.5
        # Two logit contributions, f32 on the MXU: per-head nope keys
        # and the head-shared rope key (multi-query on the rope part).
        logits = (
            jnp.einsum("bqhd,bkhd->bhqk", q_nope, k_nope,
                       preferred_element_type=jnp.float32)
            + jnp.einsum("bqhd,bkd->bhqk", q_rot, cached_rope.value[..., 0, :],
                         preferred_element_type=jnp.float32)) * scale
        logits = jnp.where(allowed[:, None], logits, -1e30)
        weights = nn.softmax(logits, axis=-1).astype(self.compute_dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


class DeepseekMoE(nn.Module):
    """DeepSeek-V3 MoE: sigmoid group-limited routing + shared expert.

    Routing (HF DeepseekV3TopkRouter semantics, re-expressed with
    static-shape jax ops):
      scores      = sigmoid(x @ router)                  (f32)
      choice      = scores + router_bias  (selection ONLY; the bias is
                    the aux-loss-free load-balancing control, a
                    non-learned buffer in checkpoints)
      group score = sum of each group's top-2 choice scores; only the
                    topk_group best groups stay eligible
      top_k selection over eligible choice scores; gates = UNBIASED
      scores at the winners, optionally sum-normalized, then scaled by
      routed_scaling_factor.
    Routed output + always-on shared SwiGLU expert (d_ff scaled by
    n_shared_experts). Returns (output, aux_loss): the aux loss is the
    Switch/Mixtral-style balance term over per-token-NORMALIZED scores
    (checkpoint forward outputs are unaffected — it is only sown by
    DeepseekBlock into "losses"). V3 checkpoints were TRAINED with
    aux-free bias updates instead, so when fine-tuning an imported
    model to match HF exactly set Trainer(aux_loss_weight=0); for
    from-scratch training the aux term is what counteracts router
    collapse (this implementation does not update the selection bias).
    """

    num_experts: int = 8
    top_k: int = 2
    d_ff: int = 256  # moe_intermediate_size (per routed expert)
    n_group: int = 1
    topk_group: int = 1
    norm_topk_prob: bool = True
    routed_scaling_factor: float = 1.0
    n_shared_experts: int = 1
    capacity_factor: Optional[float] = None  # None = drop-free
    compute_dtype: jnp.dtype = jnp.bfloat16
    activation: str = "silu"
    # Family switches: V3 = sigmoid scores + top-2-sum group scores +
    # the e_score_correction_bias buffer; V2 = softmax scores +
    # group-MAX scores (group_limited_greedy) + no bias.
    scoring: str = "sigmoid"  # "softmax" for DeepSeek-V2
    group_select: str = "top2sum"  # "max" for DeepSeek-V2
    route_bias: bool = True  # V3 e_score_correction_bias

    @nn.compact
    def __call__(self, x, deterministic=True):
        del deterministic
        from cloud_tpu.models.moe import routed_expert_ffn

        batch, seq, d_model = x.shape
        tokens = batch * seq
        if self.num_experts % self.n_group:
            raise ValueError(
                "num_experts={} must divide into n_group={} groups."
                .format(self.num_experts, self.n_group))
        group_size = self.num_experts // self.n_group
        act = _GATE_ACTIVATIONS[self.activation]

        router_kernel = self.param(
            "router", nn.initializers.lecun_normal(),
            (d_model, self.num_experts), jnp.float32)
        x2d = x.reshape(tokens, d_model)
        logits = jnp.asarray(x2d, jnp.float32) @ router_kernel
        if self.scoring == "sigmoid":
            scores = jax.nn.sigmoid(logits)               # [T, E]
        elif self.scoring == "softmax":
            scores = jax.nn.softmax(logits, axis=-1)
        else:
            raise ValueError(
                "Unknown scoring {!r}; expected 'sigmoid' or "
                "'softmax'.".format(self.scoring))
        if self.route_bias:
            # NOTE: a non-learned load-balancing buffer in V3
            # checkpoints. It only feeds the (non-differentiable)
            # selection, so it gets zero gradient — but a
            # weight-decaying optimizer (adamw) would still erode it;
            # exclude it when fine-tuning, e.g.
            # Trainer(trainable=lambda p: "router_bias" not in p).
            router_bias = self.param(
                "router_bias", nn.initializers.zeros,
                (self.num_experts,), jnp.float32)
            choice = scores + router_bias[None, :]
        else:
            choice = scores

        if self.n_group > 1:
            grouped = choice.reshape(tokens, self.n_group, group_size)
            if self.group_select == "top2sum":
                group_scores = jax.lax.top_k(
                    grouped, min(2, group_size))[0].sum(axis=-1)
            elif self.group_select == "max":
                group_scores = grouped.max(axis=-1)       # [T, G]
            else:
                raise ValueError(
                    "Unknown group_select {!r}; expected 'top2sum' or "
                    "'max'.".format(self.group_select))
            _, group_idx = jax.lax.top_k(group_scores, self.topk_group)
            group_mask = jax.nn.one_hot(
                group_idx, self.n_group, dtype=jnp.float32).sum(axis=1)
            eligible = jnp.repeat(group_mask, group_size, axis=-1)
            choice = jnp.where(eligible > 0, choice, 0.0)

        _, top_idx = jax.lax.top_k(choice, self.top_k)    # [T, k]
        gates = jnp.take_along_axis(scores, top_idx, axis=-1)
        if self.norm_topk_prob:
            gates = gates / (gates.sum(axis=-1, keepdims=True) + 1e-20)
        gates = gates * self.routed_scaling_factor

        # Balance term at the Mixtral scale (num_experts * sum f_e*P_e,
        # = top_k when uniform), over per-token-normalized scores so
        # sigmoid and softmax scoring share a scale.
        sel = jax.nn.one_hot(top_idx, self.num_experts,
                             dtype=jnp.float32)
        norm_scores = scores / (scores.sum(axis=-1, keepdims=True)
                                + 1e-20)
        aux_loss = self.num_experts * jnp.sum(
            sel.sum(axis=1).mean(axis=0) * norm_scores.mean(axis=0))

        if self.capacity_factor is None:
            capacity = tokens
        else:
            capacity = max(1, int(self.capacity_factor * tokens
                                  * self.top_k / self.num_experts))
        routed = routed_expert_ffn(self, x2d, top_idx, gates,
                                   self.num_experts, self.d_ff,
                                   capacity, act, self.compute_dtype)
        shared = SwiGLU(self.d_ff * self.n_shared_experts,
                        self.compute_dtype, activation=self.activation,
                        name="shared")(x)
        out = (routed.reshape(batch, seq, d_model) + shared).astype(
            x.dtype)
        return out, aux_loss


class DeepseekBlock(nn.Module):
    num_heads: int
    kv_lora_rank: int
    qk_nope_head_dim: int
    qk_rope_head_dim: int
    v_head_dim: int
    d_ff: int  # dense-MLP width (dense layers)
    q_lora_rank: Optional[int] = None
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"
    rope_theta: float = 10000.0
    rope_style: str = "interleaved"
    rope_scaling: Optional[RopeScaling] = None
    attn_scale: Optional[float] = None
    norm_eps: float = 1e-6
    decode: bool = False
    cache_len: int = 0
    mlp_activation: str = "silu"
    dropout_rate: float = 0.0
    # MoE (this block uses a dense SwiGLU when moe_experts == 0):
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_d_ff: int = 256
    n_group: int = 1
    topk_group: int = 1
    norm_topk_prob: bool = True
    routed_scaling_factor: float = 1.0
    n_shared_experts: int = 1
    moe_capacity_factor: Optional[float] = None
    moe_scoring: str = "sigmoid"
    moe_group_select: str = "top2sum"
    moe_route_bias: bool = True

    @nn.compact
    def __call__(self, x, mask=None, deterministic=True):
        norm = lambda name: nn.RMSNorm(
            epsilon=self.norm_eps, dtype=self.compute_dtype, name=name)
        y = norm("norm_attn")(x)
        y = MLAttention(self.num_heads, self.kv_lora_rank,
                        self.qk_nope_head_dim, self.qk_rope_head_dim,
                        self.v_head_dim, q_lora_rank=self.q_lora_rank,
                        compute_dtype=self.compute_dtype,
                        attention_impl=self.attention_impl,
                        rope_theta=self.rope_theta,
                        rope_style=self.rope_style,
                        rope_scaling=self.rope_scaling,
                        attn_scale=self.attn_scale,
                        norm_eps=self.norm_eps,
                        decode=self.decode, cache_len=self.cache_len,
                        name="attention")(y, mask)
        if self.dropout_rate:
            y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
        x = x + y
        y = norm("norm_mlp")(x)
        if self.moe_experts:
            y, aux_loss = DeepseekMoE(
                num_experts=self.moe_experts,
                top_k=self.moe_top_k, d_ff=self.moe_d_ff,
                n_group=self.n_group,
                topk_group=self.topk_group,
                norm_topk_prob=self.norm_topk_prob,
                routed_scaling_factor=self.routed_scaling_factor,
                n_shared_experts=self.n_shared_experts,
                capacity_factor=self.moe_capacity_factor,
                compute_dtype=self.compute_dtype,
                activation=self.mlp_activation,
                scoring=self.moe_scoring,
                group_select=self.moe_group_select,
                route_bias=self.moe_route_bias,
                name="moe")(y, deterministic)
            # Summed into the training loss by Trainer when "losses"
            # is mutable; set aux_loss_weight=0 to fine-tune imported
            # checkpoints exactly like HF (which emits no aux term).
            self.sow("losses", "moe_aux_loss", aux_loss,
                     reduce_fn=lambda a, b: a + b, init_fn=lambda: 0.0)
        else:
            y = SwiGLU(self.d_ff, self.compute_dtype,
                       activation=self.mlp_activation, name="mlp")(y)
        if self.dropout_rate:
            y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
        return x + y


class DeepseekLM(nn.Module):
    """DeepSeek-style decoder LM: MLA attention, dense-then-MoE stack.

    Layers below `first_k_dense` use a dense SwiGLU MLP; the rest use
    `DeepseekMoE` (set moe_experts=0 for an all-dense MLA model).
    Same Trainer/`generate()` contract as `TransformerLM`/`LlamaLM`.
    """

    vocab_size: int = 32000
    num_layers: int = 4
    num_heads: int = 8
    d_model: int = 512
    d_ff: int = 1408
    max_seq_len: int = 2048
    kv_lora_rank: int = 64
    qk_nope_head_dim: int = 32
    qk_rope_head_dim: int = 16
    v_head_dim: int = 32
    q_lora_rank: Optional[int] = None
    rope_theta: float = 10000.0
    rope_style: str = "interleaved"
    rope_scaling: Optional[RopeScaling] = None
    attn_scale: Optional[float] = None
    norm_eps: float = 1e-6
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"
    decode: bool = False
    mlp_activation: str = "silu"
    dropout_rate: float = 0.0
    # MoE stack shape:
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_d_ff: int = 256
    first_k_dense: int = 1
    n_group: int = 1
    topk_group: int = 1
    norm_topk_prob: bool = True
    routed_scaling_factor: float = 1.0
    n_shared_experts: int = 1
    moe_capacity_factor: Optional[float] = None
    moe_scoring: str = "sigmoid"  # "softmax" = DeepSeek-V2
    moe_group_select: str = "top2sum"  # "max" = DeepSeek-V2
    moe_route_bias: bool = True  # False = DeepSeek-V2

    @nn.compact
    def __call__(self, tokens, mask=None, deterministic=True):
        seq = tokens.shape[1]
        if seq > self.max_seq_len:
            raise ValueError(
                "Sequence length {} exceeds max_seq_len {}.".format(
                    seq, self.max_seq_len))
        x = nn.Embed(self.vocab_size, self.d_model,
                     dtype=self.compute_dtype, name="embed")(tokens)
        for i in range(self.num_layers):
            moe = (self.moe_experts
                   if i >= self.first_k_dense else 0)
            x = DeepseekBlock(
                self.num_heads, self.kv_lora_rank,
                self.qk_nope_head_dim, self.qk_rope_head_dim,
                self.v_head_dim, self.d_ff,
                q_lora_rank=self.q_lora_rank,
                compute_dtype=self.compute_dtype,
                attention_impl=self.attention_impl,
                rope_theta=self.rope_theta,
                rope_style=self.rope_style,
                rope_scaling=self.rope_scaling,
                attn_scale=self.attn_scale,
                norm_eps=self.norm_eps,
                decode=self.decode, cache_len=self.max_seq_len,
                mlp_activation=self.mlp_activation,
                dropout_rate=self.dropout_rate,
                moe_experts=moe, moe_top_k=self.moe_top_k,
                moe_d_ff=self.moe_d_ff, n_group=self.n_group,
                topk_group=self.topk_group,
                norm_topk_prob=self.norm_topk_prob,
                routed_scaling_factor=self.routed_scaling_factor,
                n_shared_experts=self.n_shared_experts,
                moe_capacity_factor=self.moe_capacity_factor,
                moe_scoring=self.moe_scoring,
                moe_group_select=self.moe_group_select,
                moe_route_bias=self.moe_route_bias,
                name="block_%d" % i)(x, mask, deterministic)
        x = nn.RMSNorm(epsilon=self.norm_eps, dtype=self.compute_dtype,
                       name="norm_final")(x)
        logits = nn.Dense(self.vocab_size, use_bias=False,
                          dtype=self.compute_dtype, name="lm_head")(x)
        return logits.astype(jnp.float32)


def deepseek_tensor_parallel_rules(tp_axis: str = "tp"):
    """Megatron-style layout for DeepseekLM, the MLA counterpart of
    `llama_tensor_parallel_rules` (for `Trainer(param_sharding_rules=)`,
    first-match-wins):

    - the low-rank bottlenecks (q_a, kv_a) stay REPLICATED: they are
      tiny, their RMSNorms need the full latent vector, and the shared
      rope key must exist on every shard;
    - the head-expanding projections (q_b / query / kv_b) are
      column-parallel over heads and `out` is row-parallel — the same
      two-collective block shape as the dense families (requires
      num_heads % tp == 0);
    - the always-on shared expert and the dense first-k MLPs split
      gate/up column- and down row-parallel; the router (and its bias)
      replicate, and the routed expert stacks are left for
      `expert_parallel_rules` ("ep") to shard — compose the two rule
      lists for tp x ep meshes.
    """
    from jax.sharding import PartitionSpec as P

    return [
        (r"attention/(q_b|query|kv_b)/kernel", P(None, tp_axis, None)),
        (r"attention/out/kernel", P(tp_axis, None, None)),
        (r"moe/shared/(gate|up)/kernel", P(None, tp_axis)),
        (r"moe/shared/down/kernel", P(tp_axis, None)),
        (r"mlp/(gate|up)/kernel", P(None, tp_axis)),
        (r"mlp/down/kernel", P(tp_axis, None)),
        (r"(^|/)embed/embedding", P(tp_axis, None)),
        (r"lm_head/kernel", P(None, tp_axis)),
    ]


__all__ = ["MLAttention", "DeepseekMoE", "DeepseekBlock", "DeepseekLM",
           "deepseek_tensor_parallel_rules"]
