"""Mixture-of-Experts MLP with expert parallelism.

Expert parallelism is another axis the reference never had (SURVEY §2.3
lists EP among the explicitly-absent strategies). The design is the
GShard/Switch dense-dispatch pattern, which is the XLA-friendly way to
do MoE on TPU: routing is expressed as dense one-hot dispatch/combine
einsums (static shapes, MXU-tiled), expert weights carry a leading
[num_experts] dim sharded over the mesh's "ep" axis, and XLA inserts the
all-to-alls when the dispatch einsum crosses the expert axis — no manual
collectives, the compiler schedules them on ICI.

Capacity-based top-1 (Switch) routing: each expert processes at most
`capacity = capacity_factor * tokens / num_experts` tokens; overflow
tokens are dropped (contribute zero, standard Switch behavior) and the
load-balancing auxiliary loss pushes the router toward uniform load.
"""

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class MoEMLP(nn.Module):
    """Switch-routing MoE feed-forward block, drop-in for a dense MLP.

    Call returns (output, aux_loss); add `aux_loss * aux_weight` to the
    training loss to balance expert load.
    """

    num_experts: int = 8
    d_ff: int = 2048
    capacity_factor: float = 1.25
    compute_dtype: jnp.dtype = jnp.bfloat16
    router_noise: float = 0.0  # jitter std during training (0 = off)

    @nn.compact
    def __call__(self, x, deterministic=True):
        """x: [batch, seq, d_model] -> ([batch, seq, d_model], scalar)."""
        batch, seq, d_model = x.shape
        tokens = batch * seq
        capacity = max(
            1, int(self.capacity_factor * tokens / self.num_experts))

        # --- Router (always f32: tiny matmul, precision matters) ---
        router_kernel = self.param(
            "router", nn.initializers.lecun_normal(),
            (d_model, self.num_experts), jnp.float32)
        logits = jnp.asarray(x, jnp.float32).reshape(
            tokens, d_model) @ router_kernel          # [T, E]
        if self.router_noise and not deterministic:
            rng = self.make_rng("router")
            logits = logits + self.router_noise * jax.random.normal(
                rng, logits.shape)
        probs = jax.nn.softmax(logits, axis=-1)
        expert_index = jnp.argmax(probs, axis=-1)     # [T]
        expert_gate = jnp.max(probs, axis=-1)         # [T]

        # --- Load-balancing aux loss (Switch eq. 4-6) ---
        one_hot = jax.nn.one_hot(expert_index, self.num_experts,
                                 dtype=jnp.float32)   # [T, E]
        fraction_routed = one_hot.mean(axis=0)
        fraction_prob = probs.mean(axis=0)
        aux_loss = self.num_experts * jnp.sum(
            fraction_routed * fraction_prob)

        # --- Capacity assignment: position of each token within its
        # expert's queue; tokens past capacity are dropped ---
        position_in_expert = (jnp.cumsum(one_hot, axis=0) - 1.0) * one_hot
        keep = (position_in_expert < capacity).astype(jnp.float32) * one_hot
        position = jnp.sum(position_in_expert * keep,
                           axis=-1).astype(jnp.int32)           # [T]
        position_oh = jax.nn.one_hot(position, capacity,
                                     dtype=jnp.float32)         # [T, C]

        # dispatch[t, e, c] = 1 iff token t sits in slot c of expert e
        dispatch = keep[:, :, None] * position_oh[:, None, :]   # [T,E,C]
        combine = dispatch * expert_gate[:, None, None]

        # --- Expert FFN: einsum over the (sharded) expert dim; XLA
        # inserts the token all-to-all when "ep" shards E ---
        xf = x.reshape(tokens, d_model).astype(self.compute_dtype)
        expert_in = jnp.einsum("tec,td->ecd",
                               dispatch.astype(self.compute_dtype), xf)
        w_in = self.param(
            "expert_in",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (self.num_experts, d_model, self.d_ff), jnp.float32)
        w_out = self.param(
            "expert_out",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (self.num_experts, self.d_ff, d_model), jnp.float32)
        h = jnp.einsum("ecd,edf->ecf", expert_in,
                       w_in.astype(self.compute_dtype))
        h = nn.gelu(h)
        expert_out = jnp.einsum("ecf,efd->ecd", h,
                                w_out.astype(self.compute_dtype))

        out = jnp.einsum("tec,ecd->td",
                         combine.astype(self.compute_dtype), expert_out)
        return (out.reshape(batch, seq, d_model).astype(x.dtype),
                aux_loss)


class TopKMoEMLP(nn.Module):
    """Mixtral-style top-k routed MoE with SwiGLU experts.

    The modern-LLM counterpart of `MoEMLP` (Switch top-1, GELU
    experts): each token is processed by its `top_k` highest-scoring
    experts, whose outputs are combined with the token's renormalized
    router probabilities — softmax over the selected logits, exactly
    HF Mixtral's softmax-then-topk-then-renormalize (the two are
    algebraically identical). Experts are the same gate/up/down SwiGLU
    as `models.llama.SwiGLU`, stacked on a leading [num_experts] dim
    that `expert_parallel_rules` shards over the "ep" mesh axis.

    Routing uses the same dense one-hot dispatch/combine einsums as
    `MoEMLP` (static shapes, MXU-tiled, XLA inserts the all-to-alls),
    processed slot-major so a token's top-1 choice wins capacity over
    any token's top-2 choice. `capacity_factor=None` disables dropping
    entirely (capacity = tokens): exact HF-Mixtral inference semantics,
    at O(T^2) dispatch-tensor cost — right for checkpoint-parity and
    small-batch decode, wrong for large-scale training (set a factor,
    conventionally 1.25-2.0, and let the aux loss balance load).

    Call returns (output, aux_loss); `LlamaBlock` sows the aux loss
    into the "losses" collection like `TransformerBlock` does.
    """

    num_experts: int = 8
    top_k: int = 2
    d_ff: int = 2048
    capacity_factor: Optional[float] = 2.0  # None = drop-free
    compute_dtype: jnp.dtype = jnp.bfloat16
    activation: str = "silu"
    norm_topk: bool = True  # Qwen3-MoE checkpoints may set False

    @nn.compact
    def __call__(self, x, deterministic=True):
        """x: [batch, seq, d_model] -> ([batch, seq, d_model], scalar)."""
        del deterministic  # no router noise in the Mixtral recipe
        from cloud_tpu.models.llama import _GATE_ACTIVATIONS

        batch, seq, d_model = x.shape
        tokens = batch * seq
        k = self.top_k
        if not 1 <= k <= self.num_experts:
            raise ValueError(
                "top_k={} must be in [1, num_experts={}].".format(
                    k, self.num_experts))
        if self.capacity_factor is None:
            capacity = tokens
        else:
            capacity = max(1, int(self.capacity_factor * tokens * k
                                  / self.num_experts))
        act = _GATE_ACTIVATIONS[self.activation]

        router_kernel = self.param(
            "router", nn.initializers.lecun_normal(),
            (d_model, self.num_experts), jnp.float32)
        logits = jnp.asarray(x, jnp.float32).reshape(
            tokens, d_model) @ router_kernel              # [T, E]
        probs = jax.nn.softmax(logits, axis=-1)
        top_probs, top_idx = jax.lax.top_k(probs, k)      # [T, k]
        if self.norm_topk:
            gates = top_probs / jnp.sum(top_probs, axis=-1,
                                        keepdims=True)
        else:  # Qwen3-MoE norm_topk_prob=False: raw softmax mass
            gates = top_probs

        # Load-balancing aux loss at HF Mixtral's scale
        # (load_balancing_loss_func): per-expert assignment counts are
        # SUMMED over the k routes (mean over tokens only), so a
        # uniform router scores top_k — coefficients calibrated
        # against HF (router_aux_loss_coef) transfer unchanged.
        sel = jax.nn.one_hot(top_idx, self.num_experts,
                             dtype=jnp.float32)           # [T, k, E]
        aux_loss = self.num_experts * jnp.sum(
            sel.sum(axis=1).mean(axis=0) * probs.mean(axis=0))

        out = routed_expert_ffn(self, x.reshape(tokens, d_model),
                                top_idx, gates, self.num_experts,
                                self.d_ff, capacity, act,
                                self.compute_dtype)
        return out.reshape(batch, seq, d_model).astype(x.dtype), aux_loss


def routed_expert_ffn(module, x2d, top_idx, gates, num_experts, d_ff,
                      capacity, act, compute_dtype):
    """Dense-dispatch top-k SwiGLU expert computation, shared by
    `TopKMoEMLP` (Mixtral) and `models.deepseek.DeepseekMoE`.

    x2d: [T, d] tokens; top_idx/gates: [T, k] selected experts and
    combine weights (any routing recipe). Creates the stacked
    expert_gate/up/down params on `module` (the caller's @nn.compact
    scope) so `expert_parallel_rules` shards them over "ep".

    Capacity assignment is slot-major: all slot-0 (highest-gate)
    assignments claim expert queue positions before any slot-1
    assignment, so when capacity binds the lowest-priority routes are
    shed first. dispatch[t, e, c] = 1 iff token t occupies slot c of
    expert e via ANY of its k routes (routes are distinct experts, so
    the sum over slots never overlaps); combine carries the gate.
    Returns [T, d] in compute_dtype.
    """
    tokens, d_model = x2d.shape
    k = top_idx.shape[1]
    sel = jax.nn.one_hot(top_idx, num_experts, dtype=jnp.float32)
    sel_sm = jnp.transpose(sel, (1, 0, 2)).reshape(
        k * tokens, num_experts)                      # [kT, E]
    position = (jnp.cumsum(sel_sm, axis=0) - 1.0) * sel_sm
    keep = (position < capacity).astype(jnp.float32) * sel_sm
    slot = jnp.sum(position * keep, axis=-1).astype(jnp.int32)
    slot_oh = jax.nn.one_hot(slot, capacity, dtype=jnp.float32)

    disp = (keep[:, :, None] * slot_oh[:, None, :]).reshape(
        k, tokens, num_experts, capacity)
    dispatch = disp.sum(axis=0)                       # [T, E, C]
    gates_sm = jnp.transpose(gates, (1, 0)).reshape(k, tokens)
    combine = (disp * gates_sm[:, :, None, None].astype(
        jnp.float32)).sum(axis=0)

    xf = x2d.astype(compute_dtype)
    expert_in = jnp.einsum("tec,td->ecd",
                           dispatch.astype(compute_dtype), xf)
    init = nn.initializers.lecun_normal(batch_axis=(0,))
    w_gate = module.param("expert_gate", init,
                          (num_experts, d_model, d_ff), jnp.float32)
    w_up = module.param("expert_up", init,
                        (num_experts, d_model, d_ff), jnp.float32)
    w_down = module.param("expert_down", init,
                          (num_experts, d_ff, d_model), jnp.float32)
    g = jnp.einsum("ecd,edf->ecf", expert_in,
                   w_gate.astype(compute_dtype))
    u = jnp.einsum("ecd,edf->ecf", expert_in,
                   w_up.astype(compute_dtype))
    expert_out = jnp.einsum("ecf,efd->ecd", act(g) * u,
                            w_down.astype(compute_dtype))
    return jnp.einsum("tec,ecd->td",
                      combine.astype(compute_dtype), expert_out)


def expert_parallel_rules(ep_axis: str = "ep"):
    """Sharding rules putting the expert dim on the "ep" mesh axis —
    compose with `tensor_parallel_rules` in
    `Trainer(param_sharding_rules=...)`."""
    return [
        (r"expert_(in|out|gate|up|down)$", P(ep_axis, None, None)),
        # Router stays replicated: every token scores every expert.
    ]
