"""Vision Transformer (ViT) family.

A second image-model family beyond ResNet (reference users bring
arbitrary Keras models to `run()`; ViT-B/16-style encoders are the
modern default). TPU-first choices: patchify as a single strided conv
(one big MXU matmul), bidirectional attention through the same
`cloud_tpu.ops.attention` dispatcher the LM uses (Pallas flash kernel on
TPU with causal=False), bfloat16 compute / float32 params, static
shapes throughout.
"""


import flax.linen as nn
import jax.numpy as jnp


class EncoderBlock(nn.Module):
    """Pre-norm transformer encoder block (bidirectional attention)."""

    num_heads: int
    d_ff: int
    dropout_rate: float = 0.0
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"

    @nn.compact
    def __call__(self, x, deterministic=True):
        from cloud_tpu import ops

        d_model = x.shape[-1]
        head_dim = d_model // self.num_heads

        y = nn.LayerNorm(dtype=self.compute_dtype, name="ln_attn")(x)
        dense = lambda feats, name: nn.DenseGeneral(
            feats, axis=-1, dtype=self.compute_dtype, name=name)
        q = dense((self.num_heads, head_dim), "query")(y)
        k = dense((self.num_heads, head_dim), "key")(y)
        v = dense((self.num_heads, head_dim), "value")(y)
        y = ops.attention(q, k, v, causal=False,
                          impl=self.attention_impl)
        y = nn.DenseGeneral(d_model, axis=(-2, -1),
                            dtype=self.compute_dtype, name="out")(
                                y.astype(self.compute_dtype))
        if self.dropout_rate:
            y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
        x = x + y

        y = nn.LayerNorm(dtype=self.compute_dtype, name="ln_mlp")(x)
        y = nn.Dense(self.d_ff, dtype=self.compute_dtype, name="mlp_in")(y)
        y = nn.gelu(y)
        y = nn.Dense(d_model, dtype=self.compute_dtype, name="mlp_out")(y)
        if self.dropout_rate:
            y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
        return x + y


class ViT(nn.Module):
    """Vision Transformer classifier.

    Input [B, H, W, C] images; H and W must divide by `patch_size`.
    """

    num_classes: int = 1000
    patch_size: int = 16
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    d_ff: int = 3072
    dropout_rate: float = 0.0
    pool: str = "cls"  # "cls" token or "mean" pooling
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"

    @nn.compact
    def __call__(self, x, train=False):
        batch, height, width, _ = x.shape
        if height % self.patch_size or width % self.patch_size:
            raise ValueError(
                "Image size {}x{} must divide by patch_size {}.".format(
                    height, width, self.patch_size))
        deterministic = not train

        # Patchify: one strided conv == per-patch linear projection.
        x = nn.Conv(self.d_model,
                    (self.patch_size, self.patch_size),
                    strides=(self.patch_size, self.patch_size),
                    dtype=self.compute_dtype, name="patch_embed")(
                        x.astype(self.compute_dtype))
        x = x.reshape(batch, -1, self.d_model)  # [B, N, D]

        if self.pool == "cls":
            cls = self.param("cls_token", nn.initializers.zeros,
                             (1, 1, self.d_model), jnp.float32)
            x = jnp.concatenate(
                [jnp.broadcast_to(cls, (batch, 1, self.d_model)
                                  ).astype(x.dtype), x], axis=1)

        num_tokens = x.shape[1]
        pos = self.param("pos_embed",
                         nn.initializers.normal(stddev=0.02),
                         (1, num_tokens, self.d_model), jnp.float32)
        x = x + pos.astype(x.dtype)
        if self.dropout_rate:
            x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)

        for i in range(self.num_layers):
            x = EncoderBlock(self.num_heads, self.d_ff,
                             self.dropout_rate, self.compute_dtype,
                             self.attention_impl,
                             name="block_%d" % i)(x, deterministic)
        x = nn.LayerNorm(dtype=self.compute_dtype, name="ln_final")(x)

        if self.pool == "cls":
            x = x[:, 0]
        elif self.pool == "mean":
            x = jnp.mean(x, axis=1)
        else:
            raise ValueError("pool must be 'cls' or 'mean', got {!r}"
                             .format(self.pool))
        logits = nn.Dense(self.num_classes, dtype=self.compute_dtype,
                          name="head")(x)
        return logits.astype(jnp.float32)


def ViT_S16(**kwargs):
    return ViT(num_layers=12, num_heads=6, d_model=384, d_ff=1536,
               **kwargs)


def ViT_B16(**kwargs):
    return ViT(num_layers=12, num_heads=12, d_model=768, d_ff=3072,
               **kwargs)


def ViT_L16(**kwargs):
    return ViT(num_layers=24, num_heads=16, d_model=1024, d_ff=4096,
               **kwargs)
