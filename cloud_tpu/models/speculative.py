"""Greedy speculative decoding: draft proposes, target verifies.

The latency optimization for single-stream decoding: a small DRAFT
model proposes `num_draft` tokens one at a time (cheap steps), and the
large TARGET model scores all of them in ONE forward pass (a single
large, MXU-friendly dispatch instead of `num_draft` small ones). Every
proposal matching the target's own greedy choice is accepted; the
first mismatch is replaced by the target's token — so the output is
TOKEN-IDENTICAL to plain greedy decoding with the target model
whenever the two paths' logits agree on every argmax, only faster
wall-clock when the draft's acceptance rate is decent. The parity
tests pin exact equality in f32; in bf16 on TPU, XLA may tile the
(k+1)-token verification forward differently from generate()'s
single-token steps, and a near-exact argmax tie could flip — rare in
practice, and benchmark config 10 reports the measured match fraction
rather than assuming it. Greedy only: the stochastic accept/reject
scheme (Leviathan et al., arXiv 2211.17192) changes the sampling math
and is not implemented.

Works with any pair of decode-capable models sharing a vocabulary
(`TransformerLM`, `LlamaLM`, `DeepseekLM` — e.g. a 2-layer draft for
a 16-layer target, or an imported small checkpoint drafting for a
large one). Batch size 1: acceptance counts differ per example, which
would force per-row cache rewinds; speculative decoding is a
latency (not throughput) tool, so the single-stream restriction is
the standard one.

Cache bookkeeping rides the slot-addressed decode caches
(models/decoding.py): rejected draft entries are rolled back by
rewinding the write pointer, slot validity, and token counts — the
stale k/v values beyond the pointer are overwritten by the next
write and never attended in between.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from cloud_tpu.models.decoding import empty_cache
from cloud_tpu.parallel import SEQUENCE_PARALLEL_IMPLS

_BOOKKEEPING = ("cache_index", "token_count", "pos_count")


def _rewind_cache(cache, n, new_idx):
    """Roll back the last n cache slots (bookkeeping only).

    new_idx: the write pointer AFTER the rewind — the caller tracks it
    host-side (it equals the number of committed cache entries), so no
    device fetch is needed on the latency-critical round loop.
    """
    if n == 0:
        return cache

    def fix(path, leaf):
        key = getattr(path[-1], "key", None)
        if key in _BOOKKEEPING:
            return leaf - n
        if key == "slot_valid":
            length = leaf.shape[-1]
            return leaf & (jnp.arange(length)[None, :] < new_idx)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


@functools.lru_cache(maxsize=128)
def _chunk_fn(decoder):
    """Jitted chunk feed: returns (new_cache, greedy tokens [B, S])."""

    @jax.jit
    def chunk(params, cache, tokens):
        logits, vars_ = decoder.apply(
            {"params": params, "cache": cache}, tokens,
            mutable=["cache"])
        return vars_["cache"], jnp.argmax(
            logits.astype(jnp.float32), axis=-1).astype(jnp.int32)

    return chunk


def generate_speculative(model, params, draft_model, draft_params,
                         prompt, max_new_tokens, num_draft=4,
                         eos_token=None):
    """Greedy decode with draft-model speculation.

    Args:
        model / params: the TARGET model (its greedy output is what
            this function reproduces, token for token).
        draft_model / draft_params: the cheap proposal model (same
            vocabulary; any decode-capable family).
        prompt: [1, S] int32 (batch 1 — see module docstring).
        max_new_tokens: tokens to generate beyond the prompt.
        num_draft: proposals per verification round. Each round costs
            num_draft draft steps + ONE target forward over
            num_draft+1 tokens, and commits between 1 and num_draft+1
            tokens.
        eos_token: optional stop token; the tail is filled with it.

    Returns:
        [1, S + max_new_tokens] int32 — identical to
        `generate(model, params, prompt, max_new_tokens,
        temperature=0.0)`.
    """
    batch, prompt_len = prompt.shape
    if batch != 1:
        raise ValueError(
            "generate_speculative is single-stream (batch 1); got "
            "batch={}. Use generate() for batched decoding.".format(
                batch))
    if num_draft < 1:
        raise ValueError("num_draft must be >= 1; got {}.".format(
            num_draft))
    if max_new_tokens < 0:
        raise ValueError("max_new_tokens must be >= 0; got {}.".format(
            max_new_tokens))
    if max_new_tokens == 0:
        return prompt
    for m, name in ((model, "model"), (draft_model, "draft_model")):
        if m.attention_impl in SEQUENCE_PARALLEL_IMPLS:
            raise NotImplementedError(
                "generate_speculative decodes on a single mesh shard; "
                "{} uses a sequence-parallel attention_impl.".format(
                    name))
    total = prompt_len + max_new_tokens
    for m, name in ((model, "model"), (draft_model, "draft_model")):
        # Final rounds clamp their draft count to the remaining token
        # budget, so the caches never need slack past `total` — the
        # same bound generate() has.
        if total > m.max_seq_len:
            raise ValueError(
                "prompt ({}) + max_new_tokens ({}) exceeds {}'s "
                "max_seq_len {}.".format(prompt_len, max_new_tokens,
                                         name, m.max_seq_len))

    target = model.clone(decode=True, dropout_rate=0.0)
    draft = draft_model.clone(decode=True, dropout_rate=0.0)
    target_chunk = _chunk_fn(target)
    draft_chunk = _chunk_fn(draft)
    t_cache = empty_cache(target, 1)
    d_cache = empty_cache(draft, 1)

    seq = [int(t) for t in np.asarray(prompt)[0]]
    # Invariant between rounds: both caches hold entries for seq[:-1].
    if prompt_len > 1:
        prefix = jnp.asarray([seq[:-1]], jnp.int32)
        t_cache, _ = target_chunk(params, t_cache, prefix)
        d_cache, _ = draft_chunk(draft_params, d_cache, prefix)

    while len(seq) < total:
        # Clamp the final rounds to the remaining budget: with
        # k = remaining, the verification writes len(seq)-1 + (k+1) =
        # `total` cache entries at peak — the same bound generate()
        # has — and a full-acceptance round overshoots the budget by
        # at most one committed token, trimmed by seq[:total] below.
        # At most num_draft distinct k values, so compilations stay
        # bounded.
        k = min(num_draft, total - len(seq))

        # --- Draft k proposals, one cheap step at a time ---
        drafts = []
        tok = seq[-1]
        for _ in range(k):
            d_cache, out = draft_chunk(
                draft_params, d_cache, jnp.asarray([[tok]], jnp.int32))
            tok = int(np.asarray(out)[0, -1])
            drafts.append(tok)

        # --- Verify all k in ONE target forward over k+1 tokens ---
        verify_in = jnp.asarray([[seq[-1]] + drafts], jnp.int32)
        t_cache, greedy = target_chunk(params, t_cache, verify_in)
        greedy = np.asarray(greedy)[0]  # g[i] = target token after d_i

        accepted = 0
        while accepted < k and drafts[accepted] == int(greedy[accepted]):
            accepted += 1
        committed = drafts[:accepted] + [int(greedy[accepted])]

        # --- Restore the invariant ---
        # Both caches must end holding entries for seq[:-1] after the
        # commit, i.e. len(seq) + accepted committed entries.
        kept = len(seq) + accepted
        # Target wrote k+1 entries (seq[-1], d1..dk); keep accepted+1.
        t_cache = _rewind_cache(t_cache, k - accepted, kept)
        # Draft wrote k entries (seq[-1], d1..d_{k-1}); its cache must
        # end holding (seq[-1], d1..d_accepted). Rejections rewind for
        # free; only full acceptance needs the one missing d_k entry.
        if accepted < k:
            d_cache = _rewind_cache(d_cache, k - accepted - 1, kept)
        else:
            d_cache, _ = draft_chunk(
                draft_params, d_cache,
                jnp.asarray([[drafts[-1]]], jnp.int32))

        seq.extend(committed)
        if eos_token is not None and eos_token in committed:
            seq = seq[:len(seq) - len(committed)
                      + committed.index(eos_token) + 1]
            break

    seq = seq[:total]
    if eos_token is not None and len(seq) < total:
        seq = seq + [eos_token] * (total - len(seq))
    return jnp.asarray([seq], jnp.int32)


__all__ = ["generate_speculative"]
