"""Speculative decoding: draft proposes, target verifies.

The latency optimization for single-stream decoding: a small DRAFT
model proposes `num_draft` tokens one at a time (cheap steps), and the
large TARGET model scores all of them in ONE forward pass (a single
large, MXU-friendly dispatch instead of `num_draft` small ones).

Two verification modes, selected by `temperature`:

- Greedy (temperature=0, the default): every proposal matching the
  target's own greedy choice is accepted; the first mismatch is
  replaced by the target's token — so the output is TOKEN-IDENTICAL
  to plain greedy decoding with the target model whenever the two
  paths' logits agree on every argmax, only faster wall-clock when
  the draft's acceptance rate is decent. The parity tests pin exact
  equality in f32; in bf16 on TPU, XLA may tile the (k+1)-token
  verification forward differently from generate()'s single-token
  steps, and a near-exact argmax tie could flip — rare in practice,
  and benchmark config 10 reports the measured match fraction rather
  than assuming it.

- Stochastic (temperature>0): the Leviathan et al. accept/reject
  scheme (arXiv 2211.17192). The draft SAMPLES each proposal from its
  warped distribution q; the target computes its warped distribution
  p at every position in the one verification forward; proposal i is
  accepted with probability min(1, p(x_i)/q(x_i)), and the first
  rejection is replaced by a sample from norm(max(p - q, 0)) — after
  full acceptance a bonus token is sampled from p. The committed
  stream is distributed EXACTLY as target-only sampling (the paper's
  Theorem 3.5), and because both sides share `generate()`'s warper
  (models/decoding.py warp_logits: top-k → temperature → top-p), the
  scheme composes with the whole sampling surface. The accept/reject
  math itself lives in `_accept_and_residual` (pure, unit-tested
  against a numpy oracle; the distribution-parity statistical test
  drives the same function through vmap).

Works with any pair of decode-capable models sharing a vocabulary
(`TransformerLM`, `LlamaLM`, `DeepseekLM` — e.g. a 2-layer draft for
a 16-layer target, or an imported small checkpoint drafting for a
large one). Batch size 1: acceptance counts differ per example, which
would force per-row cache rewinds; speculative decoding is a
latency (not throughput) tool, so the single-stream restriction is
the standard one.

Cache bookkeeping rides the slot-addressed decode caches
(models/decoding.py): rejected draft entries are rolled back by
rewinding the write pointer, slot validity, and token counts — the
stale k/v values beyond the pointer are overwritten by the next
write and never attended in between.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from cloud_tpu.models.decoding import (best_effort_donation,
                                       empty_cache, warp_logits)
from cloud_tpu.parallel import SEQUENCE_PARALLEL_IMPLS
from cloud_tpu.parallel import runtime

_BOOKKEEPING = ("cache_index", "token_count", "pos_count")


class SpeculativeBatchError(ValueError):
    """`generate_speculative` is single-stream: acceptance counts
    differ per example, which would force per-row cache rewinds the
    batch-synchronous fused round cannot express. (The serving tick's
    per-SLOT speculation is the batched form — serving/engine.py.)
    Subclasses ValueError for callers that caught the untyped error."""


class SpeculativeShardingError(NotImplementedError):
    """`generate_speculative` decodes on a single mesh shard; a
    sequence-parallel attention_impl on either model cannot run the
    fused round. Subclasses NotImplementedError for callers that
    caught the untyped error."""


def greedy_accept(drafts, greedy):
    """Leading-match acceptance count for greedy verification: the
    number of proposals matching the target's own greedy choices
    before the first mismatch, `sum(cumprod(drafts == greedy[:k]))`.

    Pure and shape-generic over leading batch dims (`drafts` [..., k],
    `greedy` [..., >=k]) — the single-stream fused round uses it at
    [k] and the serving tick's per-slot speculation at [S, k], so the
    two paths cannot drift (per-slot bit-identity rides on this being
    the same math).
    """
    k = drafts.shape[-1]
    accept = (drafts == greedy[..., :k]).astype(jnp.int32)
    return jnp.sum(jnp.cumprod(accept, axis=-1), axis=-1)


def observe_accept_rate(accepted, proposed):
    """Feeds the shared accepted-token-rate histogram (telemetry name
    SERVE_SPEC_ACCEPT_HISTOGRAM) — one observation per verification
    round, value accepted/proposed in [0, 1]. Zero-cost when telemetry
    is off: a sys.modules dict lookup, no import."""
    import sys

    telemetry = sys.modules.get("cloud_tpu.monitoring.telemetry")
    if telemetry is None or not telemetry.enabled():
        return
    tele = telemetry.get()
    if tele is None or not tele.active:
        return
    tele.registry.histogram(
        telemetry.SERVE_SPEC_ACCEPT_HISTOGRAM,
        start=1.0 / 64.0, factor=2.0 ** 0.5, buckets=16).observe(
            accepted / proposed if proposed else 0.0)


def _rewind_cache(cache, n, new_idx):
    """Roll back the last n cache slots (bookkeeping only).

    Runs INSIDE the fused round executable with a traced n (n == 0 is
    a no-op by construction: pointer -= 0, and the slot mask keeps
    exactly the already-valid entries when new_idx equals the current
    count). new_idx: the write pointer AFTER the rewind — the number
    of committed cache entries.
    """
    def fix(path, leaf):
        key = getattr(path[-1], "key", None)
        if key in _BOOKKEEPING:
            return leaf - n
        if key == "slot_valid":
            length = leaf.shape[-1]
            return leaf & (jnp.arange(length)[None, :] < new_idx)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


@functools.lru_cache(maxsize=128)
def _chunk_fn(decoder):
    """Jitted chunk feed: returns (new_cache, greedy tokens [B, S])."""

    # donate_argnums=1: callers always rebind the cache they pass in,
    # so the KV buffers update in place.
    @functools.partial(runtime.instrumented_jit, donate_argnums=1)
    def chunk(params, cache, tokens):
        logits, vars_ = decoder.apply(
            {"params": params, "cache": cache}, tokens,
            mutable=["cache"])
        return vars_["cache"], jnp.argmax(
            logits.astype(jnp.float32), axis=-1).astype(jnp.int32)

    return best_effort_donation(chunk)


def _fixup_caches(target_cache, draft, draft_params, d_cache, drafts,
                  n_acc, k, base_len):
    """Post-verification cache bookkeeping, on device (traced n_acc).

    Both caches must end holding entries for the new seq[:-1], i.e.
    base_len + n_acc committed entries. The target wrote k+1 entries
    (last_tok, d1..dk): keep n_acc+1. The draft wrote k entries
    (last_tok, d1..d_{k-1}): rejections rewind for free; only full
    acceptance needs the one missing d_k entry, written under the
    lax.cond so it costs a draft forward only when taken.
    """
    kept = base_len + n_acc
    target_cache = _rewind_cache(target_cache, k - n_acc, kept)

    def rewound(dc):
        return _rewind_cache(dc, k - n_acc - 1, kept)

    def caught_up(dc):
        _, vars_ = draft.apply(
            {"params": draft_params, "cache": dc},
            drafts[-1][None, None], mutable=["cache"])
        return vars_["cache"]

    d_cache = jax.lax.cond(n_acc < k, rewound, caught_up, d_cache)
    return target_cache, d_cache


@functools.lru_cache(maxsize=128)
def _greedy_round_fn(target, draft, k):
    """One FUSED greedy speculative round: the k-step draft scan, the
    target verification forward, argmax acceptance, and both cache
    fix-ups — a single dispatch, with one [k+1]-token fetch per round
    (the old loop paid k draft dispatches, each with a host sync for
    the argmax token, plus the verify — ~66ms of tunnel latency per
    dispatch, PERF.md)."""

    # Donate both caches: the round loop rebinds them every iteration.
    @functools.partial(runtime.instrumented_jit, donate_argnums=(2, 3))
    def round_step(params, draft_params, t_cache, d_cache, last_tok,
                   base_len):
        def draft_body(carry, _):
            d_cache, tok = carry
            logits, vars_ = draft.apply(
                {"params": draft_params, "cache": d_cache}, tok,
                mutable=["cache"])
            nxt = jnp.argmax(logits[:, -1].astype(jnp.float32),
                             axis=-1).astype(jnp.int32)[:, None]
            return (vars_["cache"], nxt), nxt[0, 0]

        (d_cache, _), drafts = jax.lax.scan(
            draft_body, (d_cache, last_tok), None, length=k)

        verify_in = jnp.concatenate([last_tok[0], drafts])[None, :]
        logits, vars_ = target.apply(
            {"params": params, "cache": t_cache}, verify_in,
            mutable=["cache"])
        greedy = jnp.argmax(logits[0].astype(jnp.float32),
                            axis=-1).astype(jnp.int32)  # [k+1]
        n_acc = greedy_accept(drafts, greedy)
        committed = jnp.concatenate(
            [drafts, jnp.zeros((1,), jnp.int32)])
        committed = committed.at[n_acc].set(greedy[n_acc])
        t_cache, d_cache = _fixup_caches(
            vars_["cache"], draft, draft_params, d_cache, drafts,
            n_acc, k, base_len)
        return t_cache, d_cache, committed, n_acc

    return best_effort_donation(round_step)


def _accept_and_residual(p, q, d_tokens, uniforms):
    """Leviathan et al. accept/reject math (pure; oracle-tested).

    Args:
        p: [k+1, V] target probabilities (post-warp softmax) at the
            k+1 verification positions.
        q: [k, V] draft probabilities the k proposals were drawn from.
        d_tokens: [k] int32 proposals.
        uniforms: [k] U[0,1) draws, one per proposal.

    Returns (n_acc, resid):
        n_acc: number of LEADING proposals accepted — proposal i is
            accepted iff uniforms[i] < min(1, p_i(x_i)/q_i(x_i)), and
            acceptance stops at the first failure.
        resid: [V] the distribution for the extra committed token —
            norm(max(p - q, 0)) at the first rejected position, or
            p[k] (the bonus position) when all k were accepted. The
            committed stream (accepted proposals + this sample) is
            then distributed exactly as target-only sampling.
    """
    k = q.shape[0]
    idx = jnp.arange(k)
    p_tok = p[idx, d_tokens]
    q_tok = q[idx, d_tokens]
    # q(x_i) > 0 by construction (x_i was sampled from q); the
    # denominator guard is numerical only.
    accept = uniforms < jnp.minimum(
        1.0, p_tok / jnp.maximum(q_tok, 1e-38))
    n_acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32)))
    p_row = p[n_acc]
    q_row = jnp.where(n_acc < k, q[jnp.minimum(n_acc, k - 1)],
                      jnp.zeros_like(p_row))
    resid = jnp.maximum(p_row - q_row, 0.0)
    total = jnp.sum(resid)
    # total == 0 would need a rejection at a position where p == q,
    # which has probability 0 in exact arithmetic; the fallback to
    # p_row guards float underflow only.
    resid = jnp.where(total > 0.0, resid / total, p_row)
    return n_acc, resid


@functools.lru_cache(maxsize=128)
def _stochastic_round_fn(target, draft, k, temperature, top_k, top_p):
    """One FUSED stochastic speculative round: the k-step sampling
    draft scan (each step's warped logits captured as the
    q-distribution its token was drawn from), the target verification
    forward, the Leviathan accept/reject + replacement/bonus sample,
    and both cache fix-ups — a single dispatch, one [k+1]-token fetch
    per round."""

    # Donate both caches: the round loop rebinds them every iteration.
    @functools.partial(runtime.instrumented_jit, donate_argnums=(2, 3))
    def round_step(params, draft_params, t_cache, d_cache, last_tok,
                   base_len, rng):
        rngs = jax.random.split(rng, k + 2)
        step_rngs, uni_rng, extra_rng = rngs[:k], rngs[k], rngs[k + 1]

        def draft_body(carry, step_rng):
            d_cache, tok = carry
            logits, vars_ = draft.apply(
                {"params": draft_params, "cache": d_cache}, tok,
                mutable=["cache"])
            warped = warp_logits(logits[:, -1], temperature, top_k,
                                 top_p)
            nxt = jax.random.categorical(
                step_rng, warped, axis=-1).astype(jnp.int32)[:, None]
            return (vars_["cache"], nxt), (nxt[0, 0], warped[0])

        (d_cache, _), (drafts, q_warped) = jax.lax.scan(
            draft_body, (d_cache, last_tok), step_rngs)

        verify_in = jnp.concatenate([last_tok[0], drafts])[None, :]
        logits, vars_ = target.apply(
            {"params": params, "cache": t_cache}, verify_in,
            mutable=["cache"])
        p_warped = warp_logits(logits[0], temperature, top_k, top_p)
        n_acc, resid = _accept_and_residual(
            jax.nn.softmax(p_warped, axis=-1),
            jax.nn.softmax(q_warped, axis=-1), drafts,
            jax.random.uniform(uni_rng, (k,)))
        extra = jax.random.categorical(
            extra_rng, jnp.log(resid)).astype(jnp.int32)
        committed = jnp.concatenate(
            [drafts, jnp.zeros((1,), jnp.int32)])
        committed = committed.at[n_acc].set(extra)
        t_cache, d_cache = _fixup_caches(
            vars_["cache"], draft, draft_params, d_cache, drafts,
            n_acc, k, base_len)
        return t_cache, d_cache, committed, n_acc

    return best_effort_donation(round_step)


def generate_speculative(model, params, draft_model, draft_params,
                         prompt, max_new_tokens, num_draft=4,
                         eos_token=None, rng=None, temperature=0.0,
                         top_k=None, top_p=None, return_stats=False):
    """Decode with draft-model speculation (greedy or stochastic).

    Args:
        model / params: the TARGET model. With temperature=0 its
            greedy output is what this function reproduces, token for
            token; with temperature>0 the committed stream is
            distributed exactly as sampling from the target alone.
        draft_model / draft_params: the cheap proposal model (same
            vocabulary; any decode-capable family).
        prompt: [1, S] int32 (batch 1 — see module docstring).
        max_new_tokens: tokens to generate beyond the prompt.
        num_draft: proposals per verification round. Each round is ONE
            fused dispatch (a num_draft-step draft scan + one target
            forward over num_draft+1 tokens + accept math + cache
            fix-ups) and commits between 1 and num_draft+1 tokens.
        eos_token: optional stop token; the tail is filled with it.
        rng: PRNGKey; required when temperature > 0.
        temperature: 0 = greedy verification (the default, original
            behavior); > 0 = stochastic accept/reject targeting the
            temperature-scaled distribution.
        top_k / top_p: sampling warpers, exactly `generate()`'s
            semantics; applied to BOTH the draft's proposal
            distribution and the target's verification distribution
            (temperature > 0 only — greedy ignores them, as argmax is
            warp-invariant).
        return_stats: when True, returns (tokens, stats) where stats
            has `rounds`, `proposed`, `accepted_drafts`, and
            `acceptance_rate` (accepted_drafts / proposed) — the
            number benchmark config 10 reports.

    Returns:
        [1, S + max_new_tokens] int32 — with temperature=0, identical
        to `generate(model, params, prompt, max_new_tokens,
        temperature=0.0)`. With return_stats, a (tokens, dict) pair.
    """
    batch, prompt_len = prompt.shape
    if batch != 1:
        raise SpeculativeBatchError(
            "generate_speculative is single-stream (batch 1); got "
            "batch={}. Use generate() for batched decoding, or the "
            "serving engine's per-slot speculation for concurrent "
            "streams.".format(batch))
    if num_draft < 1:
        raise ValueError("num_draft must be >= 1; got {}.".format(
            num_draft))
    if max_new_tokens < 0:
        raise ValueError("max_new_tokens must be >= 0; got {}.".format(
            max_new_tokens))
    stochastic = bool(temperature)
    if stochastic and rng is None:
        raise ValueError("Sampling (temperature > 0) needs `rng`.")
    if top_k is not None and not 1 <= top_k <= model.vocab_size:
        raise ValueError(
            "top_k must be in [1, vocab_size={}]; got {}.".format(
                model.vocab_size, top_k))
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(
            "top_p must be in (0, 1]; got {}.".format(top_p))
    stats = {"rounds": 0, "proposed": 0, "accepted_drafts": 0,
             "acceptance_rate": 0.0}

    def finish(tokens):
        if stats["proposed"]:
            stats["acceptance_rate"] = (
                stats["accepted_drafts"] / stats["proposed"])
        return (tokens, stats) if return_stats else tokens

    if max_new_tokens == 0:
        return finish(prompt)
    for m, name in ((model, "model"), (draft_model, "draft_model")):
        if m.attention_impl in SEQUENCE_PARALLEL_IMPLS:
            raise SpeculativeShardingError(
                "generate_speculative decodes on a single mesh shard; "
                "{} uses a sequence-parallel attention_impl.".format(
                    name))
    total = prompt_len + max_new_tokens
    for m, name in ((model, "model"), (draft_model, "draft_model")):
        # Final rounds clamp their draft count to the remaining token
        # budget, so the caches never need slack past `total` — the
        # same bound generate() has.
        if total > m.max_seq_len:
            raise ValueError(
                "prompt ({}) + max_new_tokens ({}) exceeds {}'s "
                "max_seq_len {}.".format(prompt_len, max_new_tokens,
                                         name, m.max_seq_len))

    target = model.clone(decode=True, dropout_rate=0.0)
    draft = draft_model.clone(decode=True, dropout_rate=0.0)
    target_chunk = _chunk_fn(target)
    draft_chunk = _chunk_fn(draft)
    if stochastic:
        warp_key = (float(temperature),
                    None if top_k is None else int(top_k),
                    None if top_p is None else float(top_p))
    t_cache = empty_cache(target, 1)
    d_cache = empty_cache(draft, 1)

    from cloud_tpu.models.decoding import (decode_latency_finish,
                                           decode_latency_start)

    latency = decode_latency_start()
    seq = [int(t) for t in np.asarray(prompt)[0]]
    # Invariant between rounds: both caches hold entries for seq[:-1].
    if prompt_len > 1:
        prefix = jnp.asarray([seq[:-1]], jnp.int32)
        t_cache, _ = target_chunk(params, t_cache, prefix)
        d_cache, _ = draft_chunk(draft_params, d_cache, prefix)

    while len(seq) < total:
        # Clamp the final rounds to the remaining budget: with
        # k = remaining, the verification writes len(seq)-1 + (k+1) =
        # `total` cache entries at peak — the same bound generate()
        # has — and a full-acceptance round overshoots the budget by
        # at most one committed token, trimmed by seq[:total] below.
        # At most num_draft distinct k values, so compilations stay
        # bounded (each k compiles its own fused round executable).
        k = min(num_draft, total - len(seq))

        # One FUSED dispatch per round (draft scan + verify + accept
        # + cache fix-ups), one [k+1]-token fetch. base_len rides as a
        # device scalar so round executables are shared across rounds.
        last = jnp.asarray([[seq[-1]]], jnp.int32)
        base = jnp.asarray(len(seq), jnp.int32)
        if stochastic:
            rng, round_rng = jax.random.split(rng)
            round_step = _stochastic_round_fn(target, draft, k,
                                              *warp_key)
            t_cache, d_cache, committed_dev, n_acc = round_step(
                params, draft_params, t_cache, d_cache, last, base,
                round_rng)
        else:
            round_step = _greedy_round_fn(target, draft, k)
            t_cache, d_cache, committed_dev, n_acc = round_step(
                params, draft_params, t_cache, d_cache, last, base)
        committed_h, accepted = jax.device_get((committed_dev, n_acc))
        accepted = int(accepted)
        committed = [int(t) for t in committed_h[:accepted + 1]]

        stats["rounds"] += 1
        stats["proposed"] += k
        stats["accepted_drafts"] += accepted
        observe_accept_rate(accepted, k)

        seq.extend(committed)
        if eos_token is not None and eos_token in committed:
            seq = seq[:len(seq) - len(committed)
                      + committed.index(eos_token) + 1]
            break

    seq = seq[:total]
    # The per-round device_get above already retired every dispatch;
    # n_tokens is what was actually generated (EOS may cut the budget).
    decode_latency_finish(latency, len(seq) - prompt_len)
    if eos_token is not None and len(seq) < total:
        seq = seq + [eos_token] * (total - len(seq))
    return finish(jnp.asarray([seq], jnp.int32))


__all__ = ["SpeculativeBatchError", "SpeculativeShardingError",
           "generate_speculative", "greedy_accept",
           "observe_accept_rate"]
