"""MNIST models: the framework's hello-world family.

Parity target: the reference README's `mnist_example.py` (a Keras
Sequential dense net trained via `tfc.run()`, reference README.md "High
level overview" and core/tests/testdata/mnist_example_using_fit.py).
Implemented in flax for the MXU: dense layers in bfloat16 compute with
float32 params.
"""

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """Keras-README-equivalent dense net: Flatten -> 512 relu -> 10."""

    hidden: int = 512
    num_classes: int = 10
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.compute_dtype)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.hidden, dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype)(x)
        return x.astype(jnp.float32)


class ConvNet(nn.Module):
    """Small conv net for MNIST-scale images."""

    num_classes: int = 10
    compute_dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        if x.ndim == 3:  # add channel dim
            x = x[..., None]
        x = x.astype(self.compute_dtype)
        x = nn.Conv(32, (3, 3), dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.compute_dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype)(x)
        return x.astype(jnp.float32)
