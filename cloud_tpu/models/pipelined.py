"""PipelinedLM: a decoder LM whose blocks run as GPipe pipeline stages.

The Trainer integration for pipeline parallelism (round-2 verdict gap:
`pipeline_apply` existed but no model could train through it). No
reference equivalent — the reference's parallelism ceiling is data
parallelism via `tf.distribute` (SURVEY §2.3); pp is TPU-first
extension surface.

Design (the shard_map pipelining pattern, scaling-playbook shape):

- The transformer blocks — where the parameters and FLOPs are — are the
  pipeline: `pp_stages` stages of `layers_per_stage` blocks each, block
  params stacked [pp_stages, layers_per_stage, ...] and sharded over
  the "pp" mesh axis (each device holds ONE stage's slice). Activations
  hop stage-to-stage via `ppermute` inside `pipeline_apply`'s
  `lax.scan` schedule.
- Embedding, final norm and LM head run OUTSIDE the schedule,
  replicated over pp. They are a few % of FLOPs; placing them on
  stages 0/n-1 is a layout optimization the same-shape stage contract
  doesn't need.
- Composes with dp in one mesh: `pipeline_apply(batch_axis="auto")`
  shards microbatches over "dp" while stage params replicate across it;
  shard_map's transpose inserts the dp gradient psum, the Trainer's
  standard state machinery shards the optimizer moments pp-wise via
  `pipelined_lm_rules`.
- Schedule: GPipe with a `jax.checkpoint`ed tick (M + n - 1 ticks,
  bubble (n-1)/(M+n-1)). 1F1B is deliberately NOT implemented: its
  advantage over GPipe is peak-activation memory, not bubble, and the
  checkpointed scan already caps live activations at one tick's worth —
  while a true 1F1B interleave would require scheduling the backward by
  hand (custom_vjp over the whole schedule) instead of letting XLA
  transpose the scan. Raise `num_microbatches` to shrink the bubble.
  MEASURED (round 4, benchmarks/pipeline_schedule_bench.py, XLA
  compiled-buffer analysis at pp=4, batch 16): peak temp memory FALLS
  as M rises — 146.7 MB (M=4) -> 89.4 (M=8) -> 61.0 (M=16) — because
  live activations scale with the microbatch SIZE (batch/M), the same
  direction 1F1B optimizes; step time also falls (smaller bubble).
  1F1B would add schedule complexity for memory behavior the remat'd
  scan already has. Numbers in PERF.md §pipeline.

This is an `(init_fn, apply_fn)`-pair model (the Trainer's second model
contract, trainer.py): `init` builds the param pytree directly — no
tracing, so building with a batch-of-1 sample never hits the
microbatch divisibility rule — and `apply` runs embed -> pipeline ->
head.

Usage:
    model = PipelinedLM(vocab_size=32000, d_model=512, num_heads=8,
                        pp_stages=4, layers_per_stage=2,
                        num_microbatches=8)
    trainer = Trainer((model.init, model.apply),
                      optimizer=optax.adamw(3e-4),
                      param_sharding_rules=pipelined_lm_rules())
"""

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from cloud_tpu.parallel.pipeline import pipeline_apply


def pipelined_lm_rules(axis="pp"):
    """Trainer `param_sharding_rules` for PipelinedLM: the stacked
    stage params shard their leading [pp_stages] dim over `axis`;
    embed/head/final-norm replicate."""
    return [(r"stages/", P(axis))]


def _layer_norm(x, scale, bias, eps=1e-5):
    x = x.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + eps) * scale + bias


class PipelinedLM:
    """GPT-style decoder LM over GPipe stages; see module docstring."""

    def __init__(self, vocab_size=32000, d_model=512, num_heads=8,
                 d_ff=None, pp_stages=2, layers_per_stage=2,
                 max_seq_len=2048, num_microbatches=4,
                 compute_dtype=jnp.bfloat16, pp_axis="pp"):
        if d_model % num_heads:
            raise ValueError(
                "d_model {} must be divisible by num_heads {}."
                .format(d_model, num_heads))
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.num_heads = num_heads
        self.d_ff = d_ff or 4 * d_model
        self.pp_stages = pp_stages
        self.layers_per_stage = layers_per_stage
        self.max_seq_len = max_seq_len
        self.num_microbatches = num_microbatches
        self.compute_dtype = compute_dtype
        self.pp_axis = pp_axis

    # -- params ---------------------------------------------------------

    def _init_block(self, key):
        d, f = self.d_model, self.d_ff
        ks = jax.random.split(key, 4)
        w = lambda k, shape: (jax.random.normal(k, shape, jnp.float32)
                              * 0.02)
        return {
            "ln1_scale": jnp.ones((d,), jnp.float32),
            "ln1_bias": jnp.zeros((d,), jnp.float32),
            "wqkv": w(ks[0], (d, 3 * d)),
            "wo": w(ks[1], (d, d)) / math.sqrt(
                2 * self.pp_stages * self.layers_per_stage),
            "ln2_scale": jnp.ones((d,), jnp.float32),
            "ln2_bias": jnp.zeros((d,), jnp.float32),
            "w1": w(ks[2], (d, f)),
            "w2": w(ks[3], (f, d)) / math.sqrt(
                2 * self.pp_stages * self.layers_per_stage),
        }

    def init(self, rng, tokens, **_):
        """Builds the param pytree (no forward trace). `tokens` fixes
        nothing but the contract shape; any [B, S] int array works."""
        del tokens
        k_embed, k_pos, k_head, k_blocks = jax.random.split(rng, 4)
        n = self.pp_stages * self.layers_per_stage
        block_keys = jax.random.split(k_blocks, n)
        stacked = jax.vmap(self._init_block)(block_keys)
        # [n, ...] -> [pp_stages, layers_per_stage, ...]
        stacked = jax.tree_util.tree_map(
            lambda l: l.reshape((self.pp_stages, self.layers_per_stage)
                                + l.shape[1:]),
            stacked)
        d = self.d_model
        return {
            "embed": jax.random.normal(
                k_embed, (self.vocab_size, d), jnp.float32) * 0.02,
            "pos": jax.random.normal(
                k_pos, (self.max_seq_len, d), jnp.float32) * 0.02,
            "stages": stacked,
            "final_scale": jnp.ones((d,), jnp.float32),
            "final_bias": jnp.zeros((d,), jnp.float32),
            "head": jax.random.normal(
                k_head, (d, self.vocab_size), jnp.float32) * 0.02,
        }

    # -- forward --------------------------------------------------------

    def _block(self, p, x):
        """Pre-LN GPT block on [mb, S, d] activations (compute dtype)."""
        from cloud_tpu import ops

        mb, seq, d = x.shape
        h = _layer_norm(x, p["ln1_scale"], p["ln1_bias"]).astype(
            self.compute_dtype)
        qkv = h @ p["wqkv"].astype(self.compute_dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        hd = d // self.num_heads
        shape = (mb, seq, self.num_heads, hd)
        out = ops.attention(q.reshape(shape), k.reshape(shape),
                            v.reshape(shape), causal=True)
        out = out.reshape(mb, seq, d) @ p["wo"].astype(self.compute_dtype)
        x = x + out
        h = _layer_norm(x, p["ln2_scale"], p["ln2_bias"]).astype(
            self.compute_dtype)
        h = jax.nn.gelu(h @ p["w1"].astype(self.compute_dtype))
        return x + h @ p["w2"].astype(self.compute_dtype)

    def _stage_fn(self, stage_params, x):
        """One pipeline stage: scan this stage's layers_per_stage
        blocks ([L, ...] param leaves) over the activations."""
        def body(x, layer_params):
            return self._block(layer_params, x), None

        x, _ = jax.lax.scan(body, x, stage_params)
        return x

    def apply(self, params, tokens, train=False, **_):
        """tokens [B, S] -> logits [B, S, vocab] (f32)."""
        del train
        seq = tokens.shape[1]
        if seq > self.max_seq_len:
            raise ValueError(
                "Sequence length {} exceeds max_seq_len {}.".format(
                    seq, self.max_seq_len))
        x = params["embed"][tokens] + params["pos"][None, :seq]
        x = x.astype(self.compute_dtype)
        x = pipeline_apply(self._stage_fn, params["stages"], x,
                           self.num_microbatches, axis=self.pp_axis,
                           batch_axis="auto")
        x = _layer_norm(x, params["final_scale"], params["final_bias"])
        return x @ params["head"]


__all__ = ["PipelinedLM", "pipelined_lm_rules"]
