"""ResNet v1.5 family (ResNet50 flagship).

Parity target: BASELINE.md config 2 — "ResNet50 tf.keras.applications,
single-host TPUStrategy (v5e-8)". Built TPU-first: NHWC layout, bfloat16
compute with float32 params/batch-stats (the MXU-native mixed-precision
recipe), strided 3x3 in the bottleneck (v1.5), and no data-dependent
control flow so XLA tiles every conv onto the systolic array.
"""

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3(stride) -> 1x1 bottleneck with projection shortcut."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), use_bias=False)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        # Stride on the 3x3 (ResNet v1.5; v1 strides the 1x1).
        y = self.conv(self.filters, (3, 3), strides=(self.strides,
                                                     self.strides),
                      use_bias=False)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1), use_bias=False)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)

        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 strides=(self.strides, self.strides),
                                 use_bias=False, name="shortcut")(residual)
            residual = self.norm(name="shortcut_bn")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    """3x3(stride) -> 3x3 basic block (ResNet-18/34)."""

    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides,
                                                     self.strides),
                      use_bias=False)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), use_bias=False)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)

        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1),
                                 strides=(self.strides, self.strides),
                                 use_bias=False, name="shortcut")(residual)
            residual = self.norm(name="shortcut_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """ResNet v1.5 with bottleneck (50+) or basic (18/34) blocks.

    conv0_space_to_depth: fold 2x2 input blocks into channels
    ([H, W, C] -> [H/2, W/2, 4C]) and run the stem as a 4x4/s1 conv —
    the MLPerf TPU trick that turns the memory-bound 7x7/s2 stem into an
    MXU-friendly matmul over 12 input channels. Same receptive-field
    class, not weight-compatible with the standard stem.
    """

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    compute_dtype: jnp.dtype = jnp.bfloat16
    conv0_space_to_depth: bool = False
    block: ModuleDef = BottleneckBlock

    @nn.compact
    def __call__(self, x, train=True):
        conv = partial(nn.Conv, dtype=self.compute_dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5,
                       dtype=self.compute_dtype)

        x = x.astype(self.compute_dtype)
        if self.conv0_space_to_depth:
            b, h, w, c = x.shape
            if h % 2 or w % 2:
                raise ValueError(
                    "space-to-depth needs even spatial dims; got "
                    "{}x{}.".format(h, w))
            x = x.reshape(b, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(
                b, h // 2, w // 2, 4 * c)
            x = conv(self.num_filters, (4, 4), use_bias=False,
                     name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), strides=(2, 2),
                     use_bias=False, name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block(self.num_filters * 2 ** i,
                               strides=strides, conv=conv,
                               norm=norm)(x)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype)(x)
        return x.astype(jnp.float32)


def ResNet18(**kwargs):
    return ResNet(stage_sizes=(2, 2, 2, 2), block=BasicBlock, **kwargs)


def ResNet34(**kwargs):
    return ResNet(stage_sizes=(3, 4, 6, 3), block=BasicBlock, **kwargs)


def ResNet50(**kwargs):
    return ResNet(stage_sizes=(3, 4, 6, 3), **kwargs)


def ResNet101(**kwargs):
    return ResNet(stage_sizes=(3, 4, 23, 3), **kwargs)


def ResNet152(**kwargs):
    return ResNet(stage_sizes=(3, 8, 36, 3), **kwargs)
