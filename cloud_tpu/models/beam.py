"""Beam search decoding over the slot-addressed KV caches.

Batched beam search (`B` prompts × `beam_width` hypotheses) for any
decode-capable model (`TransformerLM`, `LlamaLM`, `DeepseekLM`): the
B×W hypothesis grid rides the BATCH dimension of one decode cache
(row-major: prompt b, beam w → row b*W + w), so each step is a single
[B*W, 1] forward, and beam reordering is a gather on the leading axis
of every cache leaf (the caches are batch-first throughout —
models/decoding.py). Variable-length prompt batches use the same
left-padded `prompt_mask` contract as `generate()`: each row's beams
expand exactly as that prompt's solo beam search would.

Ranking runs ON DEVICE: per prompt, `jax.lax.top_k` over the [W*V]
candidate scores — only the [B, W] winners (score, source row, token)
travel to host per step, not the whole [B*W, V] log-prob matrix (a
128k-vocab imported checkpoint would otherwise pay an O(W·V log W·V)
host sort plus the transfer every token).

Scoring is accumulated log-probability with optional length
normalization (score / length**length_penalty, the standard GNMT-style
alpha). Scores accumulate in float32 ON DEVICE (TPUs have no f64;
keeping the ranking on device is the point) — two hypotheses whose
true summed log-probs differ by less than f32 resolution at the
accumulated magnitude can rank either way, the same tolerance every
TPU decode stack accepts. Finished hypotheses (eos) are frozen: their row keeps
re-feeding eos with score held fixed, so shapes never change.

`beam_width=1` reduces exactly to greedy decoding (tested), a padded
batch row matches its solo beam search (tested), and with a beam wide
enough to cover every alive prefix the search is exhaustive (tested
against brute force on a tiny vocabulary).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from cloud_tpu.models.decoding import empty_cache, validate_prompt_mask
from cloud_tpu.parallel import SEQUENCE_PARALLEL_IMPLS


@functools.lru_cache(maxsize=64)
def _logprob_fn(decoder):
    """Jitted chunk feed returning (new_cache, log-probs [rows, V])."""

    @jax.jit
    def step(params, cache, tokens, mask=None):
        logits, vars_ = decoder.apply(
            {"params": params, "cache": cache}, tokens, mask,
            mutable=["cache"])
        logp = jax.nn.log_softmax(
            logits[:, -1].astype(jnp.float32), axis=-1)
        return vars_["cache"], logp

    return step


@functools.lru_cache(maxsize=64)
def _rank_fn(width, eos_token):
    """Jitted per-prompt beam ranking: candidate scores, frozen-row
    handling, and lax.top_k — all on device."""

    @jax.jit
    def rank(scores, logp, finished):
        # scores/finished [B, W]; logp [B*W, V].
        b = scores.shape[0]
        vocab = logp.shape[-1]
        cand = scores[:, :, None] + logp.reshape(b, width, vocab)
        if eos_token is not None:
            # A frozen row contributes exactly one continuation (eos,
            # score unchanged) so it survives ranking without forking.
            frozen = jnp.full((vocab,), -jnp.inf,
                              jnp.float32).at[eos_token].set(0.0)
            cand = jnp.where(finished[:, :, None],
                             scores[:, :, None] + frozen[None, None, :],
                             cand)
        top_scores, flat = jax.lax.top_k(cand.reshape(b, width * vocab),
                                         width)
        rows, toks = flat // vocab, flat % vocab
        new_finished = jnp.take_along_axis(finished, rows, axis=1)
        if eos_token is not None:
            new_finished = new_finished | (toks == eos_token)
        return top_scores, rows, toks.astype(jnp.int32), new_finished

    return rank


def _reorder(cache, order):
    """Gather hypothesis rows: every batch-first cache leaf follows the
    surviving hypotheses; scalars (the shared write pointer) pass
    through."""
    rows = order.shape[0]

    def pick(leaf):
        if leaf.ndim and leaf.shape[0] == rows:
            return leaf[order]
        return leaf

    return jax.tree_util.tree_map(pick, cache)


def generate_beam(model, params, prompt, max_new_tokens, beam_width=4,
                  length_penalty=0.0, eos_token=None, prompt_mask=None):
    """Beam-search decode; returns the best hypothesis per prompt.

    Args:
        model / params: a decode-capable model (same contract as
            `generate`).
        prompt: [B, S] int32 — every row runs its own `beam_width`-wide
            search in one shared forward/ranking pipeline.
        max_new_tokens: tokens to generate beyond the prompt.
        beam_width: hypotheses kept per prompt per step.
        length_penalty: 0.0 = raw summed log-prob; alpha > 0 divides
            each hypothesis' score by (generated_length ** alpha) when
            ranking FINAL hypotheses. In-loop pruning compares RAW
            scores, so a frozen (shorter) eos hypothesis competes at
            its raw score against longer alive ones — the standard
            beam bias: a hypothesis that would win only after length
            normalization can be pruned mid-loop.
        eos_token: optional stop token; a hypothesis sampling it is
            frozen and its tail is filled with eos_token.
        prompt_mask: optional [B, S] bool marking REAL prompt tokens,
            LEFT-padded (`generate()`'s variable-length contract):
            each row's search behaves exactly as its unpadded solo
            search would.

    Returns:
        ([B, S + max_new_tokens] int32 best sequences,
         score) — `score` is a float for B == 1 (back-compat) and a
         [B] float numpy array otherwise.
    """
    batch, prompt_len = prompt.shape
    if beam_width < 1:
        raise ValueError("beam_width must be >= 1; got {}.".format(
            beam_width))
    if max_new_tokens < 0:
        raise ValueError("max_new_tokens must be >= 0; got {}.".format(
            max_new_tokens))
    if max_new_tokens == 0:
        return prompt, (0.0 if batch == 1 else np.zeros(batch))
    if model.attention_impl in SEQUENCE_PARALLEL_IMPLS:
        raise NotImplementedError(
            "generate_beam decodes on a single mesh shard; use a "
            "non-sequence-parallel attention_impl for inference.")
    total = prompt_len + max_new_tokens
    if total > model.max_seq_len:
        raise ValueError(
            "prompt ({}) + max_new_tokens ({}) exceeds max_seq_len {}."
            .format(prompt_len, max_new_tokens, model.max_seq_len))
    if prompt_mask is not None:
        validate_prompt_mask(prompt_mask, batch, prompt_len,
                             "beam ranking")

    width = int(beam_width)
    decoder = model.clone(decode=True, dropout_rate=0.0)
    step = _logprob_fn(decoder)
    rank = _rank_fn(width, None if eos_token is None else int(eos_token))

    # Prefill ONCE at batch B, then tile each prompt's cache rows to
    # the beam width (jnp.repeat keeps the b*W + w row-major layout):
    # the W copies would be byte-identical, so B*W prompt forwards
    # would buy nothing. Per-example bookkeeping (slot_valid,
    # token_count) repeats with its prompt; the scalar write pointer
    # passes through exactly as it passes through _reorder's gather.
    mask_arg = (None if prompt_mask is None
                else jnp.asarray(prompt_mask, bool))
    cache_b, logp = step(params, empty_cache(decoder, batch), prompt,
                         mask_arg)
    cache = jax.tree_util.tree_map(
        lambda leaf: (jnp.repeat(leaf, width, axis=0)
                      if leaf.ndim and leaf.shape[0] == batch else leaf),
        cache_b)

    vocab = logp.shape[-1]
    # First expansion: top width tokens per prompt. width > vocab (the
    # exhaustive-search configuration): only vocab distinct first
    # expansions exist; surplus rows duplicate the best one at -inf so
    # they can never win a ranking.
    s0, t0 = jax.lax.top_k(logp, min(width, vocab))
    s0 = np.asarray(s0, np.float32)
    t0 = np.asarray(t0)
    if width > vocab:
        pad = width - vocab
        t0 = np.concatenate([t0, np.repeat(t0[:, :1], pad, axis=1)], 1)
        s0 = np.concatenate(
            [s0, np.full((batch, pad), -np.inf, np.float32)], 1)
    scores = jnp.asarray(s0)                                 # [B, W]
    seqs = [[[int(t)] for t in t0[b]] for b in range(batch)]
    fin_host = np.array([[eos_token is not None and t == eos_token
                          for t in t0[b]] for b in range(batch)])
    finished = jnp.asarray(fin_host)
    feed = jnp.asarray(t0.reshape(-1, 1), jnp.int32)         # [B*W, 1]

    for _ in range(max_new_tokens - 1):
        if fin_host.all():
            break
        cache, logp = step(params, cache, feed, None)
        scores, rows, toks, finished = rank(scores, logp, finished)
        # The only per-step device→host traffic: [B, W] winners.
        rows_h, toks_h, fin_host = jax.device_get(
            (rows, toks, finished))
        seqs = [[seqs[b][r] + [int(t)]
                 for r, t in zip(rows_h[b], toks_h[b])]
                for b in range(batch)]
        order = (np.arange(batch)[:, None] * width + rows_h).reshape(-1)
        cache = _reorder(cache, jnp.asarray(order, jnp.int32))
        feed = toks.reshape(-1, 1)

    scores_h = np.asarray(jax.device_get(scores), np.float64)  # [B, W]

    def final_score(b, w):
        if length_penalty:
            n = len(seqs[b][w])
            if eos_token is not None and eos_token in seqs[b][w]:
                n = seqs[b][w].index(eos_token) + 1
            return scores_h[b, w] / (n ** length_penalty)
        return scores_h[b, w]

    prompt_h = np.asarray(prompt)
    full_rows, best_scores = [], []
    for b in range(batch):
        best = max(range(width), key=lambda w: final_score(b, w))
        out = seqs[b][best]
        if eos_token is not None and eos_token in out:
            cut = out.index(eos_token) + 1
            out = out[:cut] + [eos_token] * (len(out) - cut)
        row = [int(t) for t in prompt_h[b]] + out
        if len(row) < total:  # early all-finished exit
            row = row + [eos_token] * (total - len(row))
        full_rows.append(row)
        best_scores.append(float(final_score(b, best)))
    tokens = jnp.asarray(full_rows, jnp.int32)
    if batch == 1:
        return tokens, best_scores[0]
    return tokens, np.asarray(best_scores)


__all__ = ["generate_beam"]
