"""Beam search decoding over the slot-addressed KV caches.

Single-stream beam search (`beam_width` hypotheses) for any
decode-capable model (`TransformerLM`, `LlamaLM`, `DeepseekLM`): the
beam rides the BATCH dimension of one decode cache, so each step is a
single [W, 1] forward, and beam reordering is a gather on the leading
axis of every cache leaf (the caches are batch-first throughout —
models/decoding.py). Scoring is accumulated log-probability with
optional length normalization (score / length**length_penalty, the
standard GNMT-style alpha). Finished hypotheses (eos) are frozen: their
row keeps re-feeding eos with score held fixed, so the [W] scan shape
never changes.

`beam_width=1` reduces exactly to greedy decoding (tested), and with a
beam wide enough to cover every alive prefix the search is exhaustive
(tested against brute force on a tiny vocabulary).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from cloud_tpu.models.decoding import empty_cache
from cloud_tpu.parallel import SEQUENCE_PARALLEL_IMPLS


@functools.lru_cache(maxsize=64)
def _logprob_fn(decoder):
    """Jitted chunk feed returning (new_cache, log-probs [W, V])."""

    @jax.jit
    def step(params, cache, tokens):
        logits, vars_ = decoder.apply(
            {"params": params, "cache": cache}, tokens,
            mutable=["cache"])
        logp = jax.nn.log_softmax(
            logits[:, -1].astype(jnp.float32), axis=-1)
        return vars_["cache"], logp

    return step


def _reorder(cache, order):
    """Gather beam rows: every batch-first cache leaf follows the
    surviving hypotheses; scalars (the shared write pointer) pass
    through."""
    width = order.shape[0]

    def pick(leaf):
        if leaf.ndim and leaf.shape[0] == width:
            return leaf[order]
        return leaf

    return jax.tree_util.tree_map(pick, cache)


def generate_beam(model, params, prompt, max_new_tokens, beam_width=4,
                  length_penalty=0.0, eos_token=None):
    """Beam-search decode; returns the best hypothesis.

    Args:
        model / params: a decode-capable model (same contract as
            `generate`).
        prompt: [1, S] int32 (single stream; the beam occupies the
            batch dimension internally).
        max_new_tokens: tokens to generate beyond the prompt.
        beam_width: hypotheses kept per step.
        length_penalty: 0.0 = raw summed log-prob; alpha > 0 divides
            each hypothesis' score by (generated_length ** alpha) when
            ranking FINAL hypotheses. In-loop pruning compares RAW
            scores, so a frozen (shorter) eos hypothesis competes at
            its raw score against longer alive ones — the standard
            beam bias: a hypothesis that would win only after length
            normalization can be pruned mid-loop.
        eos_token: optional stop token; a hypothesis sampling it is
            frozen and its tail is filled with eos_token.

    Returns:
        ([1, S + max_new_tokens] int32 best sequence,
         float final score of that sequence).
    """
    batch, prompt_len = prompt.shape
    if batch != 1:
        raise ValueError(
            "generate_beam is single-stream (batch 1); the beam rides "
            "the batch dimension. Got batch={}.".format(batch))
    if beam_width < 1:
        raise ValueError("beam_width must be >= 1; got {}.".format(
            beam_width))
    if max_new_tokens < 0:
        raise ValueError("max_new_tokens must be >= 0; got {}.".format(
            max_new_tokens))
    if max_new_tokens == 0:
        return prompt, 0.0
    if model.attention_impl in SEQUENCE_PARALLEL_IMPLS:
        raise NotImplementedError(
            "generate_beam decodes on a single mesh shard; use a "
            "non-sequence-parallel attention_impl for inference.")
    total = prompt_len + max_new_tokens
    if total > model.max_seq_len:
        raise ValueError(
            "prompt ({}) + max_new_tokens ({}) exceeds max_seq_len {}."
            .format(prompt_len, max_new_tokens, model.max_seq_len))

    width = int(beam_width)
    decoder = model.clone(decode=True, dropout_rate=0.0)
    step = _logprob_fn(decoder)

    # Prefill ONCE at batch 1, then tile the cache to the beam width:
    # the W rows would be byte-identical, so W prompt forwards would
    # buy nothing (the scalar write pointer passes through the tile
    # exactly as it passes through _reorder's gather).
    cache1, logp = step(params, empty_cache(decoder, 1), prompt)
    cache = jax.tree_util.tree_map(
        lambda leaf: (jnp.broadcast_to(
            leaf, (width,) + leaf.shape[1:])
            if leaf.ndim and leaf.shape[0] == 1 else leaf),
        cache1)
    logp0 = np.asarray(logp)[0]
    vocab = logp0.shape[-1]
    # width > vocab (the exhaustive-search configuration): only vocab
    # distinct first expansions exist; surplus rows duplicate the best
    # one at -inf so they can never win a ranking.
    first = np.argsort(-logp0)[:min(width, vocab)]
    scores = logp0[first].astype(np.float64)
    if width > vocab:
        pad = width - vocab
        first = np.concatenate([first, np.repeat(first[:1], pad)])
        scores = np.concatenate([scores, np.full(pad, -np.inf)])
    seqs = [[int(t)] for t in first]
    finished = np.array(
        [eos_token is not None and t == eos_token for t in first])

    for _ in range(max_new_tokens - 1):
        if finished.all():
            break
        feed = jnp.asarray([[s[-1]] for s in seqs], jnp.int32)
        cache, logp = step(params, cache, feed)
        logp = np.asarray(logp).astype(np.float64)  # [W, V]
        # Frozen rows contribute exactly one continuation (eos, no
        # score change) so they survive ranking without forking.
        cand = scores[:, None] + logp
        for w in range(width):
            if finished[w]:
                cand[w, :] = -np.inf
                cand[w, eos_token] = scores[w]
        flat = np.argsort(-cand.reshape(-1))[:width]
        rows, toks = flat // vocab, flat % vocab
        scores = cand.reshape(-1)[flat]
        seqs = [seqs[r] + [int(t)] for r, t in zip(rows, toks)]
        finished = np.array(
            [finished[r]
             or (eos_token is not None and t == eos_token)
             for r, t in zip(rows, toks)])
        cache = _reorder(cache, jnp.asarray(rows, jnp.int32))

    def final_score(w):
        if length_penalty:
            n = len(seqs[w])
            if eos_token is not None and eos_token in seqs[w]:
                n = seqs[w].index(eos_token) + 1
            return scores[w] / (n ** length_penalty)
        return scores[w]

    best = max(range(width), key=final_score)
    out = seqs[best]
    if eos_token is not None and eos_token in out:
        cut = out.index(eos_token) + 1
        out = out[:cut] + [eos_token] * (len(out) - cut)
    full = [int(t) for t in np.asarray(prompt)[0]] + out
    if len(full) < total:  # early all-finished exit
        full = full + [eos_token] * (total - len(full))
    return jnp.asarray([full], jnp.int32), float(final_score(best))


__all__ = ["generate_beam"]
