"""Beam search decoding over the slot-addressed KV caches.

Batched beam search (`B` prompts × `beam_width` hypotheses) for any
decode-capable model (`TransformerLM`, `LlamaLM`, `DeepseekLM`): the
B×W hypothesis grid rides the BATCH dimension of one decode cache
(row-major: prompt b, beam w → row b*W + w), so each step is a single
[B*W, 1] forward, and beam reordering is a gather on the leading axis
of every cache leaf (the caches are batch-first throughout —
models/decoding.py). Variable-length prompt batches use the same
left-padded `prompt_mask` contract as `generate()`: each row's beams
expand exactly as that prompt's solo beam search would.

The WHOLE generation loop is device-resident: one `lax.scan` carries
(cache, scores, finished, token buffer) through forward → per-prompt
`jax.lax.top_k` ranking → cache reorder → token bookkeeping, so
decoding costs one dispatch and ONE device→host fetch total — no
per-token host sync (each costs ~66ms through the TPU tunnel,
PERF.md) and no [B*W, V] log-prob transfer (a 128k-vocab imported
checkpoint would otherwise pay an O(W·V log W·V) host sort every
token).

Scoring is accumulated log-probability with optional length
normalization (score / length**length_penalty, the standard GNMT-style
alpha). Scores accumulate in float32 ON DEVICE (TPUs have no f64;
keeping the ranking on device is the point) — two hypotheses whose
true summed log-probs differ by less than f32 resolution at the
accumulated magnitude can rank either way, the same tolerance every
TPU decode stack accepts. Finished hypotheses (eos) are frozen: their row keeps
re-feeding eos with score held fixed, so shapes never change.

`beam_width=1` reduces exactly to greedy decoding (tested), a padded
batch row matches its solo beam search (tested), and with a beam wide
enough to cover every alive prefix the search is exhaustive (tested
against brute force on a tiny vocabulary).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from cloud_tpu.models.decoding import (best_effort_donation,
                                       empty_cache,
                                       validate_prompt_mask)
from cloud_tpu.parallel import SEQUENCE_PARALLEL_IMPLS
from cloud_tpu.parallel import runtime


def _step_logp(decoder, params, cache, tokens, mask=None):
    """One decode forward → (new_cache, last-position log-probs
    [rows, V]) — the single recipe shared by the prefill executable
    and the scan body, so the two cannot drift."""
    logits, vars_ = decoder.apply(
        {"params": params, "cache": cache}, tokens, mask,
        mutable=["cache"])
    logp = jax.nn.log_softmax(
        logits[:, -1].astype(jnp.float32), axis=-1)
    return vars_["cache"], logp


@functools.lru_cache(maxsize=64)
def _logprob_fn(decoder):
    """Jitted chunk feed returning (new_cache, log-probs [rows, V])."""

    # donate_argnums=1: prefill consumes the fresh empty cache; no
    # caller reuses it, so the KV buffers update in place.
    @functools.partial(runtime.instrumented_jit, donate_argnums=1)
    def step(params, cache, tokens, mask=None):
        return _step_logp(decoder, params, cache, tokens, mask)

    return best_effort_donation(step)


@functools.lru_cache(maxsize=64)
def _beam_scan_fn(decoder, width, eos_token):
    """Jitted device-resident beam loop: one `lax.scan` carrying
    (cache, scores, finished, token buffer, feed) — forward, ranking
    (`lax.top_k`), cache reorder, and token bookkeeping all stay on
    device, so the whole generation costs ONE dispatch and ONE
    device→host fetch regardless of length (a per-token host sync
    costs ~66ms through the TPU tunnel — PERF.md). With eos set, an
    all-frozen step short-circuits through `lax.cond` (the
    device-resident analogue of a host-loop early exit). Like
    generate()'s decode_steps, the scan length is baked into the
    executable: distinct max_new_tokens values compile their own
    specializations, as they must under static shapes."""

    # Donate the cache and token buffer: generate_beam passes both in
    # exactly once, so the scan's carries reuse their storage.
    @functools.partial(runtime.instrumented_jit, donate_argnums=(1, 4))
    def run(params, cache, scores, finished, buf, feed, step_ids):
        batch = scores.shape[0]

        def expand(carry, t):
            cache, scores, finished, buf, feed = carry
            cache, logp = _step_logp(decoder, params, cache, feed)
            vocab = logp.shape[-1]
            cand = scores[:, :, None] + logp.reshape(batch, width,
                                                     vocab)
            if eos_token is not None:
                # A frozen row contributes exactly one continuation
                # (eos, score unchanged) so it survives ranking
                # without forking. Invariant exception: with
                # width > vocab the pool of finite candidates
                # (≤ width·vocab minus the frozen rows' -inf entries)
                # can run short of width, so top_k backfills with -inf
                # candidates and a frozen row may re-enter the beam as
                # -inf duplicates — degenerate hypotheses a caller
                # ranking by score discards anyway, so no behavioral
                # guard; beams wider than the vocabulary are already
                # meaningless.
                frozen = jnp.full((vocab,), -jnp.inf,
                                  jnp.float32).at[eos_token].set(0.0)
                cand = jnp.where(
                    finished[:, :, None],
                    scores[:, :, None] + frozen[None, None, :], cand)
            scores, flat = jax.lax.top_k(
                cand.reshape(batch, width * vocab), width)
            rows = flat // vocab
            toks = (flat % vocab).astype(jnp.int32)
            finished = jnp.take_along_axis(finished, rows, axis=1)
            if eos_token is not None:
                finished = finished | (toks == eos_token)
            order = (jnp.arange(batch)[:, None] * width
                     + rows).reshape(-1)
            cache = _reorder(cache, order)
            buf = jnp.take_along_axis(buf, rows[:, :, None], axis=1)
            buf = buf.at[:, :, t].set(toks)
            return (cache, scores, finished, buf,
                    toks.reshape(-1, 1))

        def body(carry, t):
            if eos_token is None:
                return expand(carry, t), None
            # Every hypothesis of every prompt frozen: keep the frozen
            # state (buf column t must still be eos for the tail fill)
            # instead of running the forward — the device-resident
            # analogue of the old host loop's early exit.
            def frozen_step(carry, t=t):
                cache, scores, finished, buf, feed = carry
                buf = buf.at[:, :, t].set(eos_token)
                return (cache, scores, finished, buf, feed)

            carry = jax.lax.cond(
                jnp.all(carry[2]),
                frozen_step,
                lambda c, t=t: expand(c, t),
                carry)
            return carry, None

        (cache, scores, finished, buf, feed), _ = jax.lax.scan(
            body, (cache, scores, finished, buf, feed), step_ids)
        return scores, finished, buf

    return best_effort_donation(run)


def _reorder(cache, order):
    """Gather hypothesis rows: every batch-first cache leaf follows the
    surviving hypotheses; scalars (the shared write pointer) pass
    through."""
    rows = order.shape[0]

    def pick(leaf):
        if leaf.ndim and leaf.shape[0] == rows:
            return leaf[order]
        return leaf

    return jax.tree_util.tree_map(pick, cache)


def generate_beam(model, params, prompt, max_new_tokens, beam_width=4,
                  length_penalty=0.0, eos_token=None, prompt_mask=None):
    """Beam-search decode; returns the best hypothesis per prompt.

    Args:
        model / params: a decode-capable model (same contract as
            `generate`).
        prompt: [B, S] int32 — every row runs its own `beam_width`-wide
            search in one shared forward/ranking pipeline.
        max_new_tokens: tokens to generate beyond the prompt.
        beam_width: hypotheses kept per prompt per step.
        length_penalty: 0.0 = raw summed log-prob; alpha > 0 divides
            each hypothesis' score by (generated_length ** alpha) when
            ranking FINAL hypotheses. In-loop pruning compares RAW
            scores, so a frozen (shorter) eos hypothesis competes at
            its raw score against longer alive ones — the standard
            beam bias: a hypothesis that would win only after length
            normalization can be pruned mid-loop.
        eos_token: optional stop token; a hypothesis sampling it is
            frozen and its tail is filled with eos_token.
        prompt_mask: optional [B, S] bool marking REAL prompt tokens,
            LEFT-padded (`generate()`'s variable-length contract):
            each row's search behaves exactly as its unpadded solo
            search would.

    Returns:
        ([B, S + max_new_tokens] int32 best sequences,
         score) — `score` is a float for B == 1 (back-compat) and a
         [B] float numpy array otherwise.
    """
    batch, prompt_len = prompt.shape
    if beam_width < 1:
        raise ValueError("beam_width must be >= 1; got {}.".format(
            beam_width))
    if max_new_tokens < 0:
        raise ValueError("max_new_tokens must be >= 0; got {}.".format(
            max_new_tokens))
    if max_new_tokens == 0:
        return prompt, (0.0 if batch == 1 else np.zeros(batch))
    if model.attention_impl in SEQUENCE_PARALLEL_IMPLS:
        raise NotImplementedError(
            "generate_beam decodes on a single mesh shard; use a "
            "non-sequence-parallel attention_impl for inference.")
    total = prompt_len + max_new_tokens
    if total > model.max_seq_len:
        raise ValueError(
            "prompt ({}) + max_new_tokens ({}) exceeds max_seq_len {}."
            .format(prompt_len, max_new_tokens, model.max_seq_len))
    if prompt_mask is not None:
        validate_prompt_mask(prompt_mask, batch, prompt_len,
                             "beam ranking")

    width = int(beam_width)
    decoder = model.clone(decode=True, dropout_rate=0.0)
    step = _logprob_fn(decoder)

    # Prefill ONCE at batch B, then tile each prompt's cache rows to
    # the beam width (jnp.repeat keeps the b*W + w row-major layout):
    # the W copies would be byte-identical, so B*W prompt forwards
    # would buy nothing. Per-example bookkeeping (slot_valid,
    # token_count) repeats with its prompt; the scalar write pointer
    # passes through exactly as it passes through _reorder's gather.
    from cloud_tpu.models.decoding import (decode_latency_finish,
                                           decode_latency_start)

    latency = decode_latency_start()
    mask_arg = (None if prompt_mask is None
                else jnp.asarray(prompt_mask, bool))
    cache_b, logp = step(params, empty_cache(decoder, batch), prompt,
                         mask_arg)
    cache = jax.tree_util.tree_map(
        lambda leaf: (jnp.repeat(leaf, width, axis=0)
                      if leaf.ndim and leaf.shape[0] == batch else leaf),
        cache_b)

    vocab = logp.shape[-1]
    # First expansion: top width tokens per prompt, all in eager
    # device ops (no host fetch — the shapes are static, so the
    # width > vocab branch is plain Python). width > vocab (the
    # exhaustive-search configuration): only vocab distinct first
    # expansions exist; surplus rows duplicate the best one at -inf so
    # they can never win a ranking.
    s0, t0 = jax.lax.top_k(logp, min(width, vocab))
    if width > vocab:
        pad = width - vocab
        t0 = jnp.concatenate(
            [t0, jnp.repeat(t0[:, :1], pad, axis=1)], axis=1)
        s0 = jnp.concatenate(
            [s0, jnp.full((batch, pad), -jnp.inf, s0.dtype)], axis=1)
    t0 = t0.astype(jnp.int32)
    scores = s0.astype(jnp.float32)                          # [B, W]
    finished = (jnp.zeros(t0.shape, bool) if eos_token is None
                else t0 == eos_token)
    feed = t0.reshape(-1, 1)                                 # [B*W, 1]
    buf = jnp.zeros((batch, width, max_new_tokens), jnp.int32)
    buf = buf.at[:, :, 0].set(t0)

    if max_new_tokens > 1:
        run = _beam_scan_fn(decoder, width, None if eos_token is None
                            else int(eos_token))
        scores, finished, buf = run(params, cache, scores, finished,
                                    buf, feed,
                                    jnp.arange(1, max_new_tokens))
    # The ONLY device→host fetch of the whole generation. The fetch
    # retires every decode dispatch, so the latency handle closes here
    # (result=None: this device_get IS the block).
    scores_h, buf_h = jax.device_get((scores, buf))
    decode_latency_finish(latency, max_new_tokens)
    scores_h = np.asarray(scores_h, np.float64)                # [B, W]
    seqs = [[buf_h[b, w].tolist() for w in range(width)]
            for b in range(batch)]

    def final_score(b, w):
        if length_penalty:
            n = len(seqs[b][w])
            if eos_token is not None and eos_token in seqs[b][w]:
                n = seqs[b][w].index(eos_token) + 1
            return scores_h[b, w] / (n ** length_penalty)
        return scores_h[b, w]

    prompt_h = np.asarray(prompt)
    full_rows, best_scores = [], []
    for b in range(batch):
        best = max(range(width), key=lambda w: final_score(b, w))
        out = seqs[b][best]
        if eos_token is not None and eos_token in out:
            cut = out.index(eos_token) + 1
            out = out[:cut] + [eos_token] * (len(out) - cut)
        # buf always holds max_new_tokens entries (a frozen hypothesis
        # keeps re-feeding eos), so rows are full-length by
        # construction.
        row = [int(t) for t in prompt_h[b]] + out
        full_rows.append(row)
        best_scores.append(float(final_score(b, best)))
    tokens = jnp.asarray(full_rows, jnp.int32)
    if batch == 1:
        return tokens, best_scores[0]
    return tokens, np.asarray(best_scores)


__all__ = ["generate_beam"]
