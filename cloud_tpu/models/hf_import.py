"""Import HuggingFace Llama-family checkpoints into `LlamaLM`.

The bridge from the open-checkpoint ecosystem to this framework's
TPU-native Llama implementation (no reference equivalent — the
reference loads Keras SavedModels only, SURVEY §2.1 #18-19). Converts a
`transformers` `LlamaForCausalLM`/`MistralForCausalLM` (or its raw
state_dict + config) into the flax param pytree
`cloud_tpu.models.LlamaLM` expects, building the model with
`rope_style="rotate_half"` — the pairing the checkpoint's q/k
projections were trained against (llama.py:apply_rope). Config
features carried through: GQA, rms_norm_eps, rope_theta, Llama-3.1 /
linear `rope_scaling`, Mistral `sliding_window` (banded flash kernel +
decode band mask), Mistral-Nemo decoupled `head_dim`, Qwen2-style
q/k/v biases (detected from the state_dict), Gemma2 sandwich norms /
tanh soft-capping / query_pre_attn_scalar / alternating local-global
attention, and Gemma3 q/k RMSNorm + dual-theta 5:1 local-global
layers.

Layout mapping (HF torch [out, in] row-major vs flax [in, out(+split)]):

    model.embed_tokens.weight [V, d]      -> embed/embedding [V, d]
    layers.i.input_layernorm.weight       -> block_i/norm_attn/scale
    layers.i.self_attn.q_proj.weight      -> block_i/attention/query/
        [H*hd, d]                            kernel [d, H, hd] (T+reshape)
    layers.i.self_attn.{k,v}_proj.weight  -> key/value kernels
        [Hkv*hd, d]                          [d, Hkv, hd]
    layers.i.self_attn.o_proj.weight      -> block_i/attention/out/
        [d, H*hd]                            kernel [H, hd, d]
    layers.i.post_attention_layernorm     -> block_i/norm_mlp/scale
    layers.i.mlp.{gate,up}_proj.weight    -> block_i/mlp/{gate,up}/
        [f, d]                               kernel [d, f]
    layers.i.mlp.down_proj.weight [d, f]  -> block_i/mlp/down/kernel [f, d]
    model.norm.weight                     -> norm_final/scale
    lm_head.weight [V, d]                 -> lm_head/kernel [d, V]
        (falls back to tied embed_tokens when absent)

Works offline: only numpy/jax are required for the conversion itself;
`transformers`/`torch` are touched only to read the input model.
"""

import numpy as np

import jax.numpy as jnp

from cloud_tpu.models.llama import LlamaLM, RopeScaling


def _translate_rope_scaling(hf_scaling, default_original_max=None):
    """HF `rope_scaling` config dict -> RopeScaling (or None).

    Supports the "llama3" banded scheme (Llama-3.1 family), "yarn"
    NTK-by-parts (DeepSeek/Qwen long-context, incl. the DeepSeek
    mscale pair), and plain "linear" position compression; "default"
    means no transform. Other schemes (dynamic, longrope) change the
    rotation math in ways apply_rope does not implement — rejected
    loudly rather than silently mis-rotating.
    """
    if not hf_scaling:
        return None
    if not isinstance(hf_scaling, dict):
        hf_scaling = dict(hf_scaling)
    kind = hf_scaling.get("rope_type", hf_scaling.get("type", ""))
    if kind == "default":
        return None
    if kind == "linear":
        return RopeScaling(kind="linear",
                           factor=float(hf_scaling["factor"]))
    if kind == "llama3":
        return RopeScaling(
            kind="llama3",
            factor=float(hf_scaling["factor"]),
            low_freq_factor=float(hf_scaling["low_freq_factor"]),
            high_freq_factor=float(hf_scaling["high_freq_factor"]),
            original_max_len=int(
                hf_scaling["original_max_position_embeddings"]))
    if kind == "yarn":
        original = (hf_scaling.get("original_max_position_embeddings")
                    or default_original_max)
        if not original:
            raise ValueError(
                "yarn rope_scaling needs original_max_position_"
                "embeddings (or the config's max_position_embeddings).")
        af = hf_scaling.get("attention_factor")
        mscale = hf_scaling.get("mscale")
        mscale_all = hf_scaling.get("mscale_all_dim")
        return RopeScaling(
            kind="yarn",
            factor=float(hf_scaling["factor"]),
            original_max_len=int(original),
            beta_fast=float(hf_scaling.get("beta_fast") or 32.0),
            beta_slow=float(hf_scaling.get("beta_slow") or 1.0),
            attention_factor=(None if af is None else float(af)),
            mscale=(None if mscale is None else float(mscale)),
            mscale_all_dim=(None if mscale_all is None
                            else float(mscale_all)),
            truncate=bool(hf_scaling.get("truncate", True)))
    raise NotImplementedError(
        "This checkpoint uses rope_scaling={!r}; only 'llama3', "
        "'yarn', 'linear', and 'default' import.".format(hf_scaling))


def _to_numpy(tensor):
    """torch tensor (any dtype/device) -> float32 numpy array."""
    if hasattr(tensor, "detach"):
        tensor = tensor.detach()
        if hasattr(tensor, "float"):
            tensor = tensor.float()
        if hasattr(tensor, "cpu"):
            tensor = tensor.cpu()
        return np.asarray(tensor)
    return np.asarray(tensor, dtype=np.float32)


def _unpack(model, state_dict, config):
    """(model | state_dict+config) -> (state_dict, config)."""
    if model is not None:
        return {k: v for k, v in model.state_dict().items()}, model.config
    if state_dict is None or config is None:
        raise ValueError("Pass either `model` or both `state_dict` "
                         "and `config`.")
    return state_dict, config


def _cfg_reader(config):
    """Uniform reader over HF config objects and plain dicts."""
    def cfg(name, default=None):
        if isinstance(config, dict):
            value = config.get(name, default)
        else:
            value = getattr(config, name, default)
        if value is None and default is None:
            raise ValueError("HF config is missing {!r}.".format(name))
        return value
    return cfg


def _taker(state_dict, prefix=""):
    """(take, consumed): take() fetches a tensor loudly and records it
    so _check_all_consumed can prove nothing was silently dropped."""
    consumed = set()

    def take(name):
        name = prefix + name
        if name not in state_dict:
            raise KeyError(
                "HF state_dict is missing {!r} (have e.g. {}).".format(
                    name, sorted(state_dict)[:5]))
        consumed.add(name)
        return _to_numpy(state_dict[name])

    return take, consumed


def _check_all_consumed(state_dict, consumed, skip_pattern):
    """Every parameter in the checkpoint must have landed somewhere:
    silently dropping an unmapped tensor (an o_proj/MLP bias, a novel
    adapter) would produce a model whose logits are wrong with nothing
    flagging it. skip_pattern: regex of derivable non-parameter buffers
    (rotary tables, causal-mask buffers)."""
    import re

    leftover = sorted(
        name for name in state_dict
        if name not in consumed and not re.search(skip_pattern, name))
    if leftover:
        raise ValueError(
            "HF state_dict has parameters this importer does not map "
            "(the imported model would silently diverge): {}".format(
                leftover[:8]))


def _mlp_activation(act):
    """HF hidden_act name -> SwiGLU activation name (shared by every
    importer so new activations land everywhere at once)."""
    try:
        return {"silu": "silu",
                "gelu_pytorch_tanh": "gelu_tanh",
                "gelu": "gelu"}[act]
    except KeyError:
        raise NotImplementedError(
            "hidden activation {!r} is not supported (silu / "
            "gelu_pytorch_tanh / gelu import).".format(act))


def import_hf_llama(model=None, state_dict=None, config=None,
                    compute_dtype=jnp.bfloat16, attention_impl="auto",
                    max_seq_len=None):
    """Converts an HF Llama-family model to (LlamaLM, variables).

    Args:
        model: A `transformers.LlamaForCausalLM`-like module (anything
            with `.config` and `.state_dict()`); OR pass
            `state_dict` + `config` explicitly.
        state_dict: Mapping of HF parameter names to tensors/arrays.
        config: HF config object or dict with hidden_size,
            num_attention_heads, num_key_value_heads,
            intermediate_size, num_hidden_layers, vocab_size,
            rope_theta, rms_norm_eps, max_position_embeddings.
        compute_dtype: LlamaLM compute dtype (params stay f32; bf16
            compute is the TPU default).
        attention_impl: Forwarded to LlamaLM.
        max_seq_len: Override the checkpoint's max_position_embeddings
            (e.g. to cap decode-cache memory).

    Returns:
        (model, variables): an un-initialized `LlamaLM` configured to
        match the checkpoint (rotate-half RoPE, checkpoint theta) and
        the `{"params": ...}` variables dict for `model.apply`.
    """
    state_dict, config = _unpack(model, state_dict, config)
    cfg = _cfg_reader(config)

    d_model = cfg("hidden_size")
    heads = cfg("num_attention_heads")
    kv_heads = cfg("num_key_value_heads", heads)
    layers = cfg("num_hidden_layers")
    head_dim = d_model // heads
    explicit_head_dim = cfg("head_dim", False)
    if explicit_head_dim:
        # Mistral-Nemo-style decoupled head_dim: the attention width is
        # independent of hidden_size//num_heads; GQAttention takes it
        # as an explicit field and the out projection maps back.
        head_dim = int(explicit_head_dim)

    # Mistral-style sliding-window attention: mapped onto the flash
    # kernel's banded causal path (ops.attention window=; the decode
    # cache masks the same band), so the imported model attends exactly
    # the keys the checkpoint was trained on at any sequence length.
    window = cfg("sliding_window", False)
    if window:
        # Qwen2/Qwen3-family gate: HF applies the band only when
        # use_sliding_window is true, default FALSE
        # (configuration_qwen2.py: `self.sliding_window =
        # sliding_window if self.use_sliding_window else None`) — real
        # config objects null the window themselves, so this fires
        # only for raw dict configs. Families without the gate
        # (mistral, ...) default to applying the window.
        gated_family = str(cfg("model_type", "llama")).startswith("qwen")
        if not cfg("use_sliding_window", not gated_family):
            window = False
    horizon = max_seq_len or cfg("max_position_embeddings", 2048)

    rope_scaling = _translate_rope_scaling(
        cfg("rope_scaling", False),
        default_original_max=cfg("max_position_embeddings", 2048))

    # Qwen2-style biased q/k/v projections (o_proj and the MLP stay
    # bias-free in that family). Detected from the weights themselves —
    # config attribute names differ across families (attention_bias vs
    # implicit) but the state_dict does not lie.
    qkv_bias = "model.layers.0.self_attn.q_proj.bias" in state_dict

    # Phi-3 fuses the projections: qkv_proj = cat(q, k, v) rows and
    # gate_up_proj = cat(gate, up) rows. Detected from the state_dict
    # (same reason as qkv_bias); split during mapping below.
    fused_qkv = "model.layers.0.self_attn.qkv_proj.weight" in state_dict
    fused_gate_up = ("model.layers.0.mlp.gate_up_proj.weight"
                     in state_dict)
    partial_rotary = cfg("partial_rotary_factor", 1.0) or 1.0
    if float(partial_rotary) != 1.0:
        raise NotImplementedError(
            "partial_rotary_factor={} is not supported; apply_rope "
            "rotates the full head_dim.".format(partial_rotary))

    # Gemma family: GeGLU gate activation, sqrt(d_model)-scaled
    # embeddings, and the (1 + weight) RMSNorm convention — the last is
    # a pure reparameterization, folded into the imported scales below.
    # Gemma2 adds sandwich norms (post-attn/post-MLP), tanh logit
    # soft-capping (attention + final), a query_pre_attn_scalar softmax
    # scale, and alternating local/global attention; Gemma3 swaps the
    # softcaps for per-head q/k RMSNorm, runs 5:1 local:global with a
    # separate local RoPE theta, and applies rope_scaling to global
    # layers only (HF gemma3 modeling builds its local rotary from an
    # unscaled rope_local_base_freq config copy).
    model_type = cfg("model_type", "llama")
    if model_type == "gemma3":
        raise NotImplementedError(
            "model_type='gemma3' is the multimodal wrapper; import the "
            "text tower (model_type='gemma3_text', e.g. "
            "model.language_model with config.text_config).")
    is_gemma2 = model_type == "gemma2"
    is_gemma3 = model_type == "gemma3_text"
    if is_gemma3 and cfg("use_bidirectional_attention", False):
        raise NotImplementedError(
            "use_bidirectional_attention=True (embedding-Gemma) is not "
            "supported; causal gemma3_text imports.")
    is_gemma = model_type == "gemma"
    gemma_family = is_gemma or is_gemma2 or is_gemma3
    mlp_activation = _mlp_activation(
        cfg("hidden_activation", False) or cfg("hidden_act", False)
        or ("gelu_pytorch_tanh" if gemma_family else "silu"))

    def norm_scale(w):
        # HF Gemma RMSNorm computes x * (1 + weight); flax RMSNorm
        # computes x * scale. Folding the +1 into the imported scale is
        # numerically identical.
        return w + 1.0 if gemma_family else w

    # Gemma2/3 per-layer attention pattern: HF layer_types (list of
    # "sliding_attention"/"full_attention") when present, else each
    # family's documented default (gemma2: alternating starting local;
    # gemma3: 5 local then 1 global).
    attn_kinds = None
    layer_types = cfg("layer_types", False)
    if layer_types:
        kinds = {"sliding_attention": "local", "full_attention": "global"}
        try:
            attn_kinds = tuple(kinds[t] for t in layer_types)
        except KeyError:
            raise NotImplementedError(
                "Unknown layer_types entries {!r}.".format(
                    sorted(set(layer_types) - set(kinds))))
    elif is_gemma2:
        attn_kinds = tuple(
            "local" if (i + 1) % 2 else "global" for i in range(layers))
    elif is_gemma3:
        pattern = int(cfg("sliding_window_pattern", 6))
        attn_kinds = tuple(
            "local" if (i + 1) % pattern else "global"
            for i in range(layers))
    elif window and str(cfg("model_type", "llama")).startswith("qwen"):
        # Qwen2 without explicit layer_types: HF bands only layers
        # i >= max_window_layers (configuration_qwen2.py layer_types
        # derivation); the early layers stay full attention. The
        # fallback is HF's own default (configuration_qwen2.py:
        # max_window_layers=28), NOT num layers — a deep raw-dict
        # config omitting the key must band layers 28+ exactly as the
        # HF config object would.
        mwl = int(cfg("max_window_layers", 28))
        if mwl > 0:
            attn_kinds = tuple("global" if i < mwl else "local"
                               for i in range(layers))

    attn_scale = None
    if is_gemma2 or is_gemma3:
        attn_scale = float(cfg("query_pre_attn_scalar")) ** -0.5

    # Qwen3-style per-head q/k RMSNorm (standard scale, unlike Gemma3's
    # (1+w) fold which norm_scale handles) — detected from the weights;
    # the module-side mechanism is shared with Gemma3.
    has_qk_norm = ("model.layers.0.self_attn.q_norm.weight"
                   in state_dict)

    # Mixtral / Qwen3-MoE: top-k routed MoE FFN in every block.
    # Imported drop-free (capacity_factor=None) so inference matches HF
    # exactly — HF never drops tokens; set a capacity factor for
    # large-scale fine-tuning and let the aux loss balance load.
    is_mixtral = model_type == "mixtral"
    is_qwen3_moe = model_type == "qwen3_moe"
    if is_qwen3_moe:
        if cfg("mlp_only_layers", False) or \
                int(cfg("decoder_sparse_step", 1) or 1) != 1:
            raise NotImplementedError(
                "qwen3_moe with dense layers interleaved "
                "(mlp_only_layers / decoder_sparse_step != 1) is not "
                "supported; LlamaLM's MoE applies to every block.")
        moe_experts = int(cfg("num_experts"))
        d_ff = int(cfg("moe_intermediate_size"))
    else:
        moe_experts = (int(cfg("num_local_experts", 8))
                       if is_mixtral else 0)
        d_ff = cfg("intermediate_size")
    # Fallbacks follow each family's HF config default (Mixtral 2,
    # Qwen3-MoE 8) so a raw config dict missing the key imports with
    # HF's routing, not ours.
    moe_top_k = (int(cfg("num_experts_per_tok",
                         8 if is_qwen3_moe else 2))
                 if (is_mixtral or is_qwen3_moe) else 2)
    # Qwen3MoeConfig defaults norm_topk_prob to FALSE — a raw config
    # dict missing the key must import with HF's default, not ours.
    moe_norm_topk = (bool(cfg("norm_topk_prob", False))
                     if is_qwen3_moe else True)

    take, consumed = _taker(state_dict)

    params = {
        "embed": {"embedding": take("model.embed_tokens.weight")},
        "norm_final": {"scale": norm_scale(take("model.norm.weight"))},
    }
    if "lm_head.weight" in state_dict:
        head_w = take("lm_head.weight").T  # [V, d] -> [d, V]
    else:
        # Tied embeddings (e.g. Gemma-style / tie_word_embeddings).
        head_w = params["embed"]["embedding"].T.copy()
    params["lm_head"] = {"kernel": head_w}

    for i in range(layers):
        hf = "model.layers.{}.".format(i)

        def hfmt(w, n_heads):
            # [n*hd, d] row-major -> [d, n, hd] flax DenseGeneral.
            return w.reshape(n_heads, head_dim, d_model).transpose(
                2, 0, 1)

        def proj(name, n_heads):
            w = take(hf + "self_attn.{}_proj.weight".format(name))
            entry = {"kernel": hfmt(w, n_heads)}
            if qkv_bias:
                # [n*hd] -> [n, hd] (DenseGeneral bias matches features)
                entry["bias"] = take(
                    hf + "self_attn.{}_proj.bias".format(name)
                ).reshape(n_heads, head_dim)
            return entry

        if fused_qkv:
            # Phi-3: qkv_proj rows are cat(q [H*hd], k [Hkv*hd],
            # v [Hkv*hd]); split, then reshape like the unfused path.
            w = take(hf + "self_attn.qkv_proj.weight")
            q_rows = heads * head_dim
            kv_rows = kv_heads * head_dim
            qkv = {
                "query": {"kernel": hfmt(w[:q_rows], heads)},
                "key": {"kernel": hfmt(
                    w[q_rows:q_rows + kv_rows], kv_heads)},
                "value": {"kernel": hfmt(
                    w[q_rows + kv_rows:], kv_heads)},
            }
        else:
            qkv = {
                "query": proj("q", heads),
                "key": proj("k", kv_heads),
                "value": proj("v", kv_heads),
            }
        o = take(hf + "self_attn.o_proj.weight")  # [d, H*hd]
        attention = dict(
            qkv, out={"kernel": o.T.reshape(heads, head_dim, d_model)})
        if has_qk_norm:
            # Per-head q/k RMSNorm, scale shared across heads ([hd]);
            # Gemma3 and Qwen3 (norm_scale folds Gemma's +1 only).
            attention["q_norm"] = {"scale": norm_scale(
                take(hf + "self_attn.q_norm.weight"))}
            attention["k_norm"] = {"scale": norm_scale(
                take(hf + "self_attn.k_norm.weight"))}
        block = {
            "norm_attn": {"scale": norm_scale(
                take(hf + "input_layernorm.weight"))},
            "attention": attention,
        }
        if is_mixtral or is_qwen3_moe:
            # Mixtral block_sparse_moe.{gate, experts.e.w1/w3/w2} or
            # Qwen3-MoE mlp.{gate, experts.e.gate/up/down_proj}:
            # gate.weight [E, d] is the router; experts stack on a
            # leading expert dim for TopKMoEMLP.
            moe = hf + ("block_sparse_moe." if is_mixtral else "mlp.")
            g, u, dn = (("w1", "w3", "w2") if is_mixtral
                        else ("gate_proj", "up_proj", "down_proj"))
            block["moe"] = {
                "router": take(moe + "gate.weight").T,  # [d, E]
                "expert_gate": np.stack([
                    take(moe + "experts.{}.{}.weight".format(e, g)).T
                    for e in range(moe_experts)]),      # [E, d, f]
                "expert_up": np.stack([
                    take(moe + "experts.{}.{}.weight".format(e, u)).T
                    for e in range(moe_experts)]),
                "expert_down": np.stack([
                    take(moe + "experts.{}.{}.weight".format(e, dn)).T
                    for e in range(moe_experts)]),      # [E, f, d]
            }
        elif fused_gate_up:
            # Phi-3: gate_up_proj rows are cat(gate [f], up [f]).
            gu = take(hf + "mlp.gate_up_proj.weight")  # [2f, d]
            d_ff = gu.shape[0] // 2
            block["mlp"] = {
                "gate": {"kernel": gu[:d_ff].T},
                "up": {"kernel": gu[d_ff:].T},
                "down": {"kernel": take(hf + "mlp.down_proj.weight").T},
            }
        else:
            block["mlp"] = {
                "gate": {"kernel": take(hf + "mlp.gate_proj.weight").T},
                "up": {"kernel": take(hf + "mlp.up_proj.weight").T},
                "down": {"kernel": take(hf + "mlp.down_proj.weight").T},
            }
        if is_gemma2 or is_gemma3:
            # Sandwich norms: HF's post_attention_layernorm normalizes
            # the ATTENTION OUTPUT here (in llama/gemma1 the same name
            # is the pre-MLP norm), and the pre/post_feedforward pair
            # brackets the MLP.
            block["norm_attn_post"] = {"scale": norm_scale(
                take(hf + "post_attention_layernorm.weight"))}
            block["norm_mlp"] = {"scale": norm_scale(
                take(hf + "pre_feedforward_layernorm.weight"))}
            block["norm_mlp_post"] = {"scale": norm_scale(
                take(hf + "post_feedforward_layernorm.weight"))}
        else:
            block["norm_mlp"] = {"scale": norm_scale(
                take(hf + "post_attention_layernorm.weight"))}
        params["block_%d" % i] = block

    # Rotary inv_freq tables are derivable non-parameter buffers.
    _check_all_consumed(state_dict, consumed, r"rotary_emb")

    lm = LlamaLM(
        vocab_size=cfg("vocab_size"),
        num_layers=layers,
        num_heads=heads,
        num_kv_heads=kv_heads,
        d_model=d_model,
        d_ff=d_ff,
        max_seq_len=horizon,
        rope_theta=float(cfg("rope_theta", 10000.0)),
        rope_style="rotate_half",
        norm_eps=float(cfg("rms_norm_eps", 1e-6)),
        compute_dtype=compute_dtype,
        attention_impl=attention_impl,
        head_dim=(head_dim if head_dim != d_model // heads else None),
        rope_scaling=rope_scaling,
        sliding_window=(int(window) if window else None),
        qkv_bias=qkv_bias,
        mlp_activation=mlp_activation,
        scale_embed=gemma_family,
        post_block_norms=is_gemma2 or is_gemma3,
        attn_scale=attn_scale,
        attn_logit_softcap=(
            float(cfg("attn_logit_softcapping", 0) or 0) or None
            if is_gemma2 else None),
        final_logit_softcap=(
            float(cfg("final_logit_softcapping", 0) or 0) or None
            if is_gemma2 else None),
        qk_norm=has_qk_norm,
        attn_kinds=attn_kinds,
        rope_theta_local=(float(cfg("rope_local_base_freq", 10000.0))
                          if is_gemma3 else None),
        # Gemma3 is the only family whose local layers run an UNSCALED
        # separate rotary (HF builds rotary_emb_local from an unscaled
        # rope_local_base_freq config copy); every other family with
        # layer_types (e.g. Qwen2 use_sliding_window) applies the same
        # scaled rotary to sliding and full layers alike.
        rope_scaling_local=(None if is_gemma3 else rope_scaling),
        moe_experts=moe_experts,
        moe_top_k=moe_top_k,
        moe_capacity_factor=None,  # drop-free: exact HF semantics
        moe_norm_topk=moe_norm_topk,
    )
    return lm, {"params": params}


def import_hf_gpt2(model=None, state_dict=None, config=None,
                   compute_dtype=jnp.bfloat16, attention_impl="auto",
                   max_seq_len=None):
    """Converts an HF GPT-2 model to (TransformerLM, variables).

    `TransformerLM` is already GPT-2-shaped (pre-LN blocks, learned
    positions, tanh-approximate GELU — flax's `nn.gelu` default matches
    HF's "gelu_new"), so the conversion is pure layout: GPT-2's Conv1D
    weights are stored [in, out] (no transpose, unlike Linear), the
    fused c_attn [d, 3d] splits into per-head q/k/v, and the LM head is
    tied to wte. Layer-norm epsilon (1e-5 in GPT-2 checkpoints) is
    carried onto the module's norm_eps.

        wte [V, d]            -> embed/embedding      (+ tied lm_head)
        wpe [P, d]            -> pos_embed/embedding
        h.i.ln_1.{weight,bias}   -> block_i/ln_attn/{scale,bias}
        h.i.attn.c_attn [d, 3d]  -> query/key/value kernels [d, H, hd]
        h.i.attn.c_proj [d, d]   -> out kernel [H, hd, d]
        h.i.ln_2                 -> block_i/ln_mlp
        h.i.mlp.c_fc [d, f]      -> mlp_in kernel
        h.i.mlp.c_proj [f, d]    -> mlp_out kernel
        ln_f                     -> ln_final

    Args/returns mirror `import_hf_llama`. Non-parameter attention
    buffers (h.i.attn.bias causal masks in older checkpoints) are
    skipped; any other unmapped tensor fails loudly.
    """
    from cloud_tpu.models.transformer import TransformerLM

    state_dict, config = _unpack(model, state_dict, config)
    cfg = _cfg_reader(config)

    act = cfg("activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh"):
        raise NotImplementedError(
            "GPT-2 activation_function={!r} is not supported; "
            "TransformerLM uses tanh-approximate GELU "
            "(gelu_new).".format(act))
    # Attention variants with NO extra parameters would pass the
    # leftover check and import with silently wrong logits — reject.
    for flag in ("scale_attn_by_inverse_layer_idx",
                 "reorder_and_upcast_attn"):
        if cfg(flag, False):
            raise NotImplementedError(
                "GPT-2 {}=True is not supported; TransformerLM always "
                "scales attention by 1/sqrt(head_dim).".format(flag))
    if not cfg("scale_attn_weights", True):
        raise NotImplementedError(
            "GPT-2 scale_attn_weights=False is not supported; "
            "TransformerLM always scales attention by "
            "1/sqrt(head_dim).")

    d_model = cfg("n_embd")
    heads = cfg("n_head")
    layers = cfg("n_layer")
    head_dim = d_model // heads
    d_ff = cfg("n_inner", False) or 4 * d_model
    n_positions = cfg("n_positions", 1024)
    horizon = max_seq_len or n_positions
    if horizon > n_positions:
        # Learned positions cannot be extended (unlike RoPE in
        # import_hf_llama, where any horizon is valid): a larger
        # horizon would declare an Embed the checkpoint cannot fill
        # and fail with an opaque shape error at apply time.
        raise ValueError(
            "max_seq_len={} exceeds the checkpoint's n_positions={}; "
            "GPT-2's learned position table cannot be extended.".format(
                horizon, n_positions))

    prefix = ("transformer."
              if any(k.startswith("transformer.") for k in state_dict)
              else "")
    take, consumed = _taker(state_dict, prefix=prefix)

    def ln(name):
        return {"scale": take(name + ".weight"),
                "bias": take(name + ".bias")}

    wte = take("wte.weight")
    # GPT-2 proper ties the head to wte, but tie_word_embeddings=False
    # re-trainings carry an independent lm_head.weight — use the
    # checkpoint's head tensor whenever it is present (identical to
    # wte in the tied case) instead of assuming the tie.
    if "lm_head.weight" in state_dict:
        consumed.add("lm_head.weight")
        head_w = _to_numpy(state_dict["lm_head.weight"]).T
    else:
        head_w = wte.T.copy()
    params = {
        "embed": {"embedding": wte},
        "pos_embed": {"embedding": take("wpe.weight")[:horizon]},
        "ln_final": ln("ln_f"),
        "lm_head": {"kernel": head_w},
    }

    for i in range(layers):
        hf = "h.{}.".format(i)
        # Conv1D stores [in, out]: split the fused [d, 3d] c_attn into
        # q/k/v [d, d] then reshape to [d, H, hd]; biases [3d] -> [H, hd].
        ca = take(hf + "attn.c_attn.weight")
        cb = take(hf + "attn.c_attn.bias")
        qkv_w = [w.reshape(d_model, heads, head_dim)
                 for w in np.split(ca, 3, axis=1)]
        qkv_b = [b.reshape(heads, head_dim) for b in np.split(cb, 3)]
        params["block_%d" % i] = {
            "ln_attn": ln(hf + "ln_1"),
            "ln_mlp": ln(hf + "ln_2"),
            "attention": {
                "query": {"kernel": qkv_w[0], "bias": qkv_b[0]},
                "key": {"kernel": qkv_w[1], "bias": qkv_b[1]},
                "value": {"kernel": qkv_w[2], "bias": qkv_b[2]},
                "out": {
                    # [d(in = H*hd), d] -> [H, hd, d] DenseGeneral.
                    "kernel": take(hf + "attn.c_proj.weight").reshape(
                        heads, head_dim, d_model),
                    "bias": take(hf + "attn.c_proj.bias"),
                },
            },
            "mlp_in": {"kernel": take(hf + "mlp.c_fc.weight"),
                       "bias": take(hf + "mlp.c_fc.bias")},
            "mlp_out": {"kernel": take(hf + "mlp.c_proj.weight"),
                        "bias": take(hf + "mlp.c_proj.bias")},
        }

    # Older checkpoints carry non-parameter causal-mask buffers.
    _check_all_consumed(state_dict, consumed,
                        r"\.attn\.(bias|masked_bias)$")

    lm = TransformerLM(
        vocab_size=cfg("vocab_size"),
        num_layers=layers,
        num_heads=heads,
        d_model=d_model,
        d_ff=d_ff,
        max_seq_len=horizon,
        norm_eps=float(cfg("layer_norm_epsilon", 1e-5)),
        compute_dtype=compute_dtype,
        attention_impl=attention_impl,
    )
    return lm, {"params": params}


def import_hf_deepseek(model=None, state_dict=None, config=None,
                       compute_dtype=jnp.bfloat16, attention_impl="auto",
                       max_seq_len=None, moe_capacity_factor=None):
    """Converts an HF DeepSeek-V2/V3 model to (DeepseekLM, variables).

    Maps multi-head latent attention (q_a/q_b low-rank query path when
    `q_lora_rank` is set, kv_a_proj_with_mqa -> kv_a + the shared rope
    key, kv_b expansion) and the dense-then-MoE stack. Both router
    generations import: V3's sigmoid scores + top-2-sum group limit +
    e_score_correction_bias (a NON-LEARNED balancing buffer — exclude
    it from weight-decay fine-tuning, e.g. Trainer(trainable=lambda p:
    "router_bias" not in p)), and V2's softmax scores + group-MAX
    limit (topk_method "greedy"/"group_limited_greedy") without bias
    or top-k normalization. `rope_interleave` selects the
    "interleaved" rope style (V2's complex-pair rotation is the same
    convention). Imported drop-free by default
    (moe_capacity_factor=None) for exact HF routing semantics.

    Layout highlights (HF torch [out, in] -> flax [in, out(+split)]):

        self_attn.q_a_proj [r_q, d]       -> attention/q_a [d, r_q]
        self_attn.q_b_proj [H*qk, r_q]    -> attention/q_b [r_q, H, qk]
        self_attn.kv_a_proj_with_mqa      -> attention/kv_a
            [rank+rope, d]                   [d, rank+rope]
        self_attn.kv_b_proj               -> attention/kv_b
            [H*(nope+v), rank]               [rank, H, nope+v]
        mlp.gate (router) [E, d]          -> moe/router [d, E]
        mlp.e_score_correction_bias [E]   -> moe/router_bias
        mlp.experts.{e}.{gate,up,down}    -> moe/expert_{gate,up,down}
            _proj                            stacked [E, ...]
        mlp.shared_experts.*_proj         -> moe/shared/{gate,up,down}

    Yarn rope_scaling (DeepSeek's 128k long-context recipe) carries
    through: the NTK-by-parts frequency blend and cos/sin attention
    factor ride on RopeScaling(kind="yarn"), and the
    mscale(factor, mscale_all_dim)^2 softmax adjustment lands in
    `attn_scale` (HF DeepseekV3Attention.scaling).
    """
    from cloud_tpu.models.deepseek import DeepseekLM

    state_dict, config = _unpack(model, state_dict, config)
    cfg = _cfg_reader(config)

    rope_scaling = _translate_rope_scaling(
        cfg("rope_scaling", False),
        default_original_max=cfg("max_position_embeddings", 2048))

    d_model = cfg("hidden_size")
    heads = cfg("num_attention_heads")
    layers = cfg("num_hidden_layers")
    q_rank = cfg("q_lora_rank", False) or None
    kv_rank = cfg("kv_lora_rank")
    nope = cfg("qk_nope_head_dim")
    rope = cfg("qk_rope_head_dim")
    v_dim = cfg("v_head_dim")
    qk_dim = nope + rope
    n_routed = int(cfg("n_routed_experts", 0) or 0)
    first_dense = int(cfg("first_k_dense_replace", 0))
    if not n_routed:
        first_dense = layers  # all-dense variant
    horizon = max_seq_len or cfg("max_position_embeddings", 2048)

    # V2 vs V3 routing recipes (HF DeepseekV2MoEGate vs
    # DeepseekV3TopkRouter): V2 scores with softmax, selects groups by
    # their MAX score (topk_method="group_limited_greedy"; "greedy" =
    # no group limit), has no correction bias, and never normalizes
    # the top-k weights (its modeling ignores norm_topk_prob); V3
    # scores with sigmoid, selects groups by top-2 sums over
    # bias-corrected scores, and normalizes.
    is_v2 = cfg("model_type", "deepseek_v3") == "deepseek_v2"
    n_group = int(cfg("n_group", 1) or 1)
    topk_group = int(cfg("topk_group", 1) or 1)
    if is_v2:
        moe_scoring, moe_route_bias = "softmax", False
        moe_group_select = "max"
        # norm_topk_prob=true is contested for V2: the HF port ignores
        # it (DeepseekV2MoEGate.forward scales by
        # routed_scaling_factor only) while DeepSeek's remote-code
        # modeling honors it when top_k > 1. No shipped V2/V2-Lite
        # checkpoint sets it, so refuse loudly instead of silently
        # picking a side.
        if cfg("norm_topk_prob", False):
            raise NotImplementedError(
                "DeepSeek-V2 config sets norm_topk_prob=true: the HF "
                "port ignores it while DeepSeek's own modeling "
                "normalizes the top-k gates — no shipped checkpoint "
                "sets it, and importing one would silently pick a "
                "side. Set it false (the shipped default) to import.")
        norm_topk = False
        topk_method = cfg("topk_method", "greedy")
        if topk_method == "greedy":
            n_group = topk_group = 1  # no group limiting
        elif topk_method != "group_limited_greedy":
            raise NotImplementedError(
                "DeepSeek-V2 topk_method={!r} is not supported."
                .format(topk_method))
    else:
        moe_scoring, moe_route_bias = "sigmoid", True
        moe_group_select = "top2sum"
        norm_topk = bool(cfg("norm_topk_prob", True))

    mlp_activation = _mlp_activation(cfg("hidden_act", "silu"))

    take, consumed = _taker(state_dict)

    params = {
        "embed": {"embedding": take("model.embed_tokens.weight")},
        "norm_final": {"scale": take("model.norm.weight")},
    }
    if "lm_head.weight" in state_dict:
        params["lm_head"] = {"kernel": take("lm_head.weight").T}
    else:
        params["lm_head"] = {
            "kernel": params["embed"]["embedding"].T.copy()}

    for i in range(layers):
        hf = "model.layers.{}.".format(i)
        sa = hf + "self_attn."
        attention = {
            "kv_a": {"kernel": take(sa + "kv_a_proj_with_mqa.weight").T},
            "kv_a_norm": {"scale": take(sa + "kv_a_layernorm.weight")},
            "kv_b": {"kernel": take(sa + "kv_b_proj.weight").reshape(
                heads, nope + v_dim, kv_rank).transpose(2, 0, 1)},
            "out": {"kernel": take(sa + "o_proj.weight").T.reshape(
                heads, v_dim, d_model)},
        }
        if q_rank:
            attention["q_a"] = {"kernel": take(sa + "q_a_proj.weight").T}
            attention["q_a_norm"] = {
                "scale": take(sa + "q_a_layernorm.weight")}
            attention["q_b"] = {"kernel": take(
                sa + "q_b_proj.weight").reshape(
                    heads, qk_dim, q_rank).transpose(2, 0, 1)}
        else:
            attention["query"] = {"kernel": take(
                sa + "q_proj.weight").reshape(
                    heads, qk_dim, d_model).transpose(2, 0, 1)}
        block = {
            "norm_attn": {"scale": take(hf + "input_layernorm.weight")},
            "norm_mlp": {"scale": take(
                hf + "post_attention_layernorm.weight")},
            "attention": attention,
        }
        if i >= first_dense:
            moe = hf + "mlp."
            block["moe"] = {
                "router": take(moe + "gate.weight").T,
                "expert_gate": np.stack([
                    take(moe + "experts.{}.gate_proj.weight".format(e)).T
                    for e in range(n_routed)]),
                "expert_up": np.stack([
                    take(moe + "experts.{}.up_proj.weight".format(e)).T
                    for e in range(n_routed)]),
                "expert_down": np.stack([
                    take(moe + "experts.{}.down_proj.weight".format(e)).T
                    for e in range(n_routed)]),
                "shared": {
                    "gate": {"kernel": take(
                        moe + "shared_experts.gate_proj.weight").T},
                    "up": {"kernel": take(
                        moe + "shared_experts.up_proj.weight").T},
                    "down": {"kernel": take(
                        moe + "shared_experts.down_proj.weight").T},
                },
            }
            if moe_route_bias:
                block["moe"]["router_bias"] = take(
                    moe + "gate.e_score_correction_bias")
        else:
            block["mlp"] = {
                "gate": {"kernel": take(hf + "mlp.gate_proj.weight").T},
                "up": {"kernel": take(hf + "mlp.up_proj.weight").T},
                "down": {"kernel": take(hf + "mlp.down_proj.weight").T},
            }
        params["block_%d" % i] = block

    _check_all_consumed(state_dict, consumed, r"rotary_emb")

    # DeepSeek yarn checkpoints additionally scale the softmax by
    # mscale(factor, mscale_all_dim)^2 (HF DeepseekV3Attention.scaling).
    attn_scale = None
    if rope_scaling is not None and rope_scaling.kind == "yarn" \
            and rope_scaling.mscale_all_dim:
        from cloud_tpu.models.llama import _yarn_mscale
        mscale = _yarn_mscale(rope_scaling.factor,
                              rope_scaling.mscale_all_dim)
        attn_scale = qk_dim ** -0.5 * mscale * mscale

    lm = DeepseekLM(
        vocab_size=cfg("vocab_size"),
        num_layers=layers,
        num_heads=heads,
        d_model=d_model,
        d_ff=cfg("intermediate_size"),
        max_seq_len=horizon,
        kv_lora_rank=kv_rank,
        qk_nope_head_dim=nope,
        qk_rope_head_dim=rope,
        v_head_dim=v_dim,
        q_lora_rank=q_rank,
        rope_theta=float(cfg("rope_theta", 10000.0)),
        rope_style=("interleaved" if cfg("rope_interleave", True)
                    else "rotate_half"),
        rope_scaling=rope_scaling,
        attn_scale=attn_scale,
        norm_eps=float(cfg("rms_norm_eps", 1e-6)),
        compute_dtype=compute_dtype,
        attention_impl=attention_impl,
        mlp_activation=mlp_activation,
        moe_experts=n_routed,
        moe_top_k=int(cfg("num_experts_per_tok", 2) or 2),
        moe_d_ff=int(cfg("moe_intermediate_size", 0)
                     or cfg("intermediate_size")),
        first_k_dense=first_dense,
        n_group=n_group,
        topk_group=topk_group,
        norm_topk_prob=norm_topk,
        routed_scaling_factor=float(cfg("routed_scaling_factor", 1.0)),
        n_shared_experts=int(cfg("n_shared_experts", 1) or 1),
        moe_capacity_factor=moe_capacity_factor,
        moe_scoring=moe_scoring,
        moe_group_select=moe_group_select,
        moe_route_bias=moe_route_bias,
    )
    return lm, {"params": params}


__all__ = ["import_hf_llama", "import_hf_gpt2", "import_hf_deepseek"]
