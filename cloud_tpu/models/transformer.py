"""Decoder-only Transformer LM with tensor/sequence-parallel layouts.

The long-context/model-parallel flagship (absent from the reference, which
stops at data parallelism — SURVEY §2.3; built here because a pjit mesh
makes TP/SP natural extension points). Design is MXU/ICI-first:

- All matmuls batched and bfloat16; params float32.
- Megatron-style tensor parallelism expressed as sharding *rules* over
  the ambient mesh (qkv/mlp-in kernels split on "tp" columns, proj/mlp-out
  on "tp" rows), so XLA inserts exactly the two all-reduces per block.
- Causal attention with static shapes; `cloud_tpu.ops` provides the
  flash/pallas path and `cloud_tpu.parallel.ring_attention` the
  sequence-parallel path for long context.
"""

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P


class CausalSelfAttention(nn.Module):
    num_heads: int
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"  # auto | flash | reference | ring

    @nn.compact
    def __call__(self, x, mask=None):
        from cloud_tpu import ops

        d_model = x.shape[-1]
        head_dim = d_model // self.num_heads
        dense = lambda feats, name: nn.DenseGeneral(
            feats, axis=-1, dtype=self.compute_dtype, name=name)

        # [B, S, H, D] per-head projections.
        q = dense((self.num_heads, head_dim), "query")(x)
        k = dense((self.num_heads, head_dim), "key")(x)
        v = dense((self.num_heads, head_dim), "value")(x)

        if self.attention_impl == "ring":
            # Sequence-parallel long-context path: the sequence dim is
            # sharded over the ambient mesh's "sp" axis and K/V rotate
            # around the ring (cloud_tpu/parallel/ring_attention.py).
            from cloud_tpu.parallel import sequence_parallel_attention
            if mask is not None:
                raise NotImplementedError(
                    "ring attention does not take a padding mask.")
            out = sequence_parallel_attention(q, k, v, causal=True)
        else:
            # "auto" uses the Pallas flash kernel on TPU (mask-free
            # shapes), the jnp reference elsewhere; both are causal
            # with 1/sqrt(D).
            out = ops.attention(q, k, v, causal=True, mask=mask,
                                impl=self.attention_impl)
        out = out.astype(self.compute_dtype)
        return nn.DenseGeneral(d_model, axis=(-2, -1),
                               dtype=self.compute_dtype, name="out")(out)


class TransformerBlock(nn.Module):
    num_heads: int
    d_ff: int
    dropout_rate: float = 0.0
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"
    moe_experts: int = 0  # > 0 swaps the dense MLP for a Switch MoE

    @nn.compact
    def __call__(self, x, mask=None, deterministic=True):
        y = nn.LayerNorm(dtype=self.compute_dtype, name="ln_attn")(x)
        y = CausalSelfAttention(self.num_heads, self.compute_dtype,
                                self.attention_impl,
                                name="attention")(y, mask)
        if self.dropout_rate:
            y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
        x = x + y

        y = nn.LayerNorm(dtype=self.compute_dtype, name="ln_mlp")(x)
        if self.moe_experts:
            from cloud_tpu.models.moe import MoEMLP
            y, aux_loss = MoEMLP(num_experts=self.moe_experts,
                                 d_ff=self.d_ff,
                                 compute_dtype=self.compute_dtype,
                                 name="moe")(y, deterministic)
            # Surfaced via mutable=["losses"]; summed into the training
            # loss by Trainer when present.
            self.sow("losses", "moe_aux_loss", aux_loss,
                     reduce_fn=lambda a, b: a + b, init_fn=lambda: 0.0)
        else:
            y = nn.Dense(self.d_ff, dtype=self.compute_dtype,
                         name="mlp_in")(y)
            y = nn.gelu(y)
            y = nn.Dense(x.shape[-1], dtype=self.compute_dtype,
                         name="mlp_out")(y)
        if self.dropout_rate:
            y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
        return x + y


class TransformerLM(nn.Module):
    """GPT-style decoder-only language model."""

    vocab_size: int = 32000
    num_layers: int = 4
    num_heads: int = 8
    d_model: int = 512
    d_ff: int = 2048
    max_seq_len: int = 2048
    dropout_rate: float = 0.0
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"
    moe_experts: int = 0

    @nn.compact
    def __call__(self, tokens, mask=None, deterministic=True):
        seq = tokens.shape[1]
        if seq > self.max_seq_len:
            raise ValueError(
                "Sequence length {} exceeds max_seq_len {}.".format(
                    seq, self.max_seq_len))
        x = nn.Embed(self.vocab_size, self.d_model,
                     dtype=self.compute_dtype, name="embed")(tokens)
        pos = nn.Embed(self.max_seq_len, self.d_model,
                       dtype=self.compute_dtype,
                       name="pos_embed")(jnp.arange(seq)[None, :])
        x = x + pos
        for i in range(self.num_layers):
            x = TransformerBlock(self.num_heads, self.d_ff,
                                 self.dropout_rate, self.compute_dtype,
                                 self.attention_impl, self.moe_experts,
                                 name="block_%d" % i)(
                                     x, mask, deterministic)
        x = nn.LayerNorm(dtype=self.compute_dtype, name="ln_final")(x)
        # Tied-free output head; vocab dim sharded on tp by the rules.
        logits = nn.Dense(self.vocab_size, use_bias=False,
                          dtype=self.compute_dtype, name="lm_head")(x)
        return logits.astype(jnp.float32)


def tensor_parallel_rules(tp_axis: str = "tp"):
    """Megatron-style sharding rules for Trainer(param_sharding_rules=...).

    Column-parallel qkv/mlp-in, row-parallel out-proj/mlp-out: exactly one
    all-reduce after attention and one after the MLP per block, riding ICI.
    """
    return [
        # Attention projections: split heads across tp.
        (r"attention/(query|key|value)/kernel", P(None, tp_axis, None)),
        (r"attention/out/kernel", P(tp_axis, None, None)),
        # MLP: column-parallel in, row-parallel out.
        (r"mlp_in/kernel", P(None, tp_axis)),
        (r"mlp_out/kernel", P(tp_axis, None)),
        # Embeddings / head: vocab-sharded.
        (r"(^|/)embed/embedding", P(tp_axis, None)),
        (r"lm_head/kernel", P(None, tp_axis)),
    ]
