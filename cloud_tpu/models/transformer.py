"""Decoder-only Transformer LM with tensor/sequence-parallel layouts.

The long-context/model-parallel flagship (absent from the reference, which
stops at data parallelism — SURVEY §2.3; built here because a pjit mesh
makes TP/SP natural extension points). Design is MXU/ICI-first:

- All matmuls batched and bfloat16; params float32.
- Megatron-style tensor parallelism expressed as sharding *rules* over
  the ambient mesh (qkv/mlp-in kernels split on "tp" columns, proj/mlp-out
  on "tp" rows), so XLA inserts exactly the two all-reduces per block.
- Causal attention with static shapes; `cloud_tpu.ops` provides the
  flash/pallas path and `cloud_tpu.parallel.ring_attention` the
  sequence-parallel path for long context.
"""

import functools
from typing import Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from cloud_tpu.parallel import SEQUENCE_PARALLEL_IMPLS
from cloud_tpu.parallel import runtime


class CausalSelfAttention(nn.Module):
    num_heads: int
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"  # auto | flash | reference | ring | ulysses
    decode: bool = False  # autoregressive KV-cache mode
    cache_len: int = 0  # cache size (tokens); set by TransformerLM
    causal: bool = True  # False = bidirectional (encoder) attention
    # Paged-pool decode (serving): > 0 swaps the per-example dense cache
    # for a shared physical page pool with per-slot page tables and
    # per-slot write pointers (continuous batching; serving/kvpool.py).
    page_size: int = 0
    num_pages: int = 0
    # "" = pages in compute_dtype; "int8" = quantized pages with
    # per-page per-head f32 scales (key_scales/value_scales cache
    # variables), dequantized inside ops.paged_attention's block loads.
    page_dtype: str = ""

    @nn.compact
    def __call__(self, x, mask=None):
        from cloud_tpu import ops

        d_model = x.shape[-1]
        head_dim = d_model // self.num_heads
        dense = lambda feats, name: nn.DenseGeneral(
            feats, axis=-1, dtype=self.compute_dtype, name=name)

        # [B, S, H, D] per-head projections.
        q = dense((self.num_heads, head_dim), "query")(x)
        k = dense((self.num_heads, head_dim), "key")(x)
        v = dense((self.num_heads, head_dim), "value")(x)

        if self.decode:
            # mask (optional [B, S]) marks REAL incoming tokens — the
            # left-padded-prompt contract (generate(prompt_mask=)).
            if self.page_size:
                out = self._paged_decode_attention(q, k, v, mask)
            else:
                out = self._decode_attention(q, k, v, mask)
        elif self.attention_impl in SEQUENCE_PARALLEL_IMPLS:
            # Sequence-parallel long-context paths over the mesh's "sp"
            # axis: "ring" rotates K/V around a ppermute ring
            # (parallel/ring_attention.py); "ulysses" all-to-alls into
            # head-sharded full-sequence layout and runs the flash
            # kernel (parallel/ulysses.py).
            from cloud_tpu.parallel import sp_attention
            out = sp_attention(self.attention_impl, q, k, v,
                               causal=self.causal, mask=mask)
        else:
            # "auto" uses the Pallas flash kernel on TPU, the jnp
            # reference elsewhere; direction follows self.causal
            # (False = bidirectional encoder attention), scale
            # 1/sqrt(D).
            out = ops.attention(q, k, v, causal=self.causal, mask=mask,
                                impl=self.attention_impl)
        out = out.astype(self.compute_dtype)
        return nn.DenseGeneral(d_model, axis=(-2, -1),
                               dtype=self.compute_dtype, name="out")(out)

    def _decode_attention(self, q, k, v, mask=None):
        """KV-cache attention: append this call's K/V to the cache, then
        attend q against everything cached so far.

        One code path serves both phases of generation: prefill (the
        whole prompt in one call, cache index 0) and single-token decode
        steps (S=1). Causality is slot order (append-only writes);
        `slot_valid` excludes left-padded prompt slots (mask=0) and the
        never-written tail. O(cache_len) work per step — the standard
        autoregressive trade.
        """
        import jax.lax as lax

        from cloud_tpu.models.decoding import decode_slot_update

        batch, seq, heads, head_dim = q.shape
        if not self.cache_len:
            raise ValueError("decode=True needs cache_len > 0.")
        cached_k = self.variable(
            "cache", "cached_key", jnp.zeros,
            (batch, self.cache_len, heads, head_dim), self.compute_dtype)
        cached_v = self.variable(
            "cache", "cached_value", jnp.zeros,
            (batch, self.cache_len, heads, head_dim), self.compute_dtype)

        idx, _, allowed = decode_slot_update(
            self, mask, batch, seq, self.cache_len)
        cached_k.value = lax.dynamic_update_slice(
            cached_k.value, k.astype(self.compute_dtype), (0, idx, 0, 0))
        cached_v.value = lax.dynamic_update_slice(
            cached_v.value, v.astype(self.compute_dtype), (0, idx, 0, 0))
        scale = 1.0 / np.sqrt(head_dim)
        # f32 MXU accumulation, like every training attention path —
        # bf16 logits would round before the argmax/softmax.
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, cached_k.value,
                            preferred_element_type=jnp.float32) * scale
        logits = jnp.where(allowed[:, None], logits, -1e30)
        weights = nn.softmax(logits, axis=-1).astype(self.compute_dtype)
        return jnp.einsum("bhqk,bkhd->bqhd", weights, cached_v.value)

    def _paged_decode_attention(self, q, k, v, mask=None):
        """Decode over the paged KV pool (continuous batching). The
        batch dimension is SLOTS, each at its own depth: physical K/V
        live in a shared page pool `[num_pages, page_size, H, D]`, each
        slot's logical `[cache_len]` view is its page table's gather
        over the pool. Writes are per-slot scatters at `slot_steps[s]`;
        insertion/eviction are index updates on the page table and
        validity rows (serving/engine.py), so the tick executable never
        retraces.

        `seq` is 1 for the plain tick and k+1 for the speculative
        verify window — each slot's tokens land at consecutive logical
        positions from its own pointer and every query attends exactly
        the keys a solo decode at its depth would (per-query causality
        from `paged_slot_update`).

        Per-slot math is EXACTLY `_decode_attention`'s per-row math
        over the gathered logical view (same masking, same f32 einsum),
        which is what makes engine tokens bit-identical to solo
        `generate()` — see tests/unit/test_serving.py.

        Pages may be SHARED between slots (radix prefix cache,
        serving/prefixcache.py): shared pages sit strictly below every
        holder's write pointer, so they are only ever gathered, never
        scattered to — copy-on-write happens at insert time by routing
        divergent content into fresh pages.

        The scratch page (physical page 0) is never handed out by the
        pool allocator: freed/empty page-table rows are all 0, so an
        inactive slot's write lands in scratch and its garbage is
        masked to exact-zero weight, never attended by anyone.
        """
        from cloud_tpu.models.decoding import paged_slot_update

        slots, seq, heads, head_dim = q.shape
        if not self.cache_len or self.cache_len % self.page_size:
            raise ValueError(
                "cache_len ({}) must be a positive multiple of "
                "page_size ({}).".format(self.cache_len, self.page_size))
        if self.num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is the "
                             "scratch page).")
        if self.page_dtype not in ("", "int8"):
            raise ValueError(
                "page_dtype must be '' or 'int8'; got {!r}.".format(
                    self.page_dtype))
        quantized = self.page_dtype == "int8"
        pages_per_slot = self.cache_len // self.page_size
        page_store = jnp.int8 if quantized else self.compute_dtype
        key_pages = self.variable(
            "cache", "key_pages", jnp.zeros,
            (self.num_pages, self.page_size, heads, head_dim),
            page_store)
        value_pages = self.variable(
            "cache", "value_pages", jnp.zeros,
            (self.num_pages, self.page_size, heads, head_dim),
            page_store)
        page_table = self.variable(
            "cache", "page_table", jnp.zeros, (slots, pages_per_slot),
            jnp.int32)
        if quantized:
            # Per-page per-head symmetric scales; 0 = never-written
            # page (dequantizes to exact zeros). They live in the same
            # attention cache subtree as the pages, so the engine's
            # _map_attention / paged_slot_rewind carry them for free.
            key_scales = self.variable(
                "cache", "key_scales", jnp.zeros,
                (self.num_pages, heads), jnp.float32)
            value_scales = self.variable(
                "cache", "value_scales", jnp.zeros,
                (self.num_pages, heads), jnp.float32)

        pos, allowed = paged_slot_update(self, mask, slots, seq,
                                         self.cache_len)
        # Physical write targets: slot s's page for each token's
        # logical position pos[s, j]. Inactive/evicted slots resolve
        # to page 0 (scratch) via their zeroed page-table row.
        phys = jnp.take_along_axis(page_table.value,
                                   pos // self.page_size, 1)
        off = pos % self.page_size
        if quantized:
            if mask is not None:
                # Zero invalid tokens pre-quantize so pad garbage never
                # inflates a real page's amax scale (their positions are
                # masked from attention either way).
                m = mask.reshape(slots, seq).astype(k.dtype)
                k = k * m[:, :, None, None]
                v = v * m[:, :, None, None]
            key_pages.value, key_scales.value = _quantized_page_write(
                key_pages.value, key_scales.value, k, phys, off)
            value_pages.value, value_scales.value = (
                _quantized_page_write(value_pages.value,
                                      value_scales.value, v, phys,
                                      off))
            scales_kw = dict(key_scales=key_scales.value,
                             value_scales=value_scales.value)
        else:
            key_pages.value = key_pages.value.at[phys, off].set(
                k.astype(self.compute_dtype))
            value_pages.value = value_pages.value.at[phys, off].set(
                v.astype(self.compute_dtype))
            scales_kw = {}

        # Impl selection (ops/paged_attention.py): "auto" runs the
        # Pallas paged kernel on TPU — the page table rides as a
        # scalar-prefetch operand, so the pool is block-indexed page by
        # page with online softmax in VMEM, never materialized as a
        # dense [slots, cache_len, H, D] gather — and the gathered-lax
        # reference elsewhere, which is bitwise the dense path's math
        # (engine-vs-solo bit-identity). CLOUD_TPU_PAGED_KERNEL=1/0
        # force-overrides (kernel runs in interpret mode off-TPU).
        # Every paged decode — engine tick, speculative verify window,
        # solo paged decode — routes through this one call.
        from cloud_tpu.ops import paged_attention
        return paged_attention(
            q, key_pages.value, value_pages.value, page_table.value,
            allowed, sm_scale=1.0 / np.sqrt(head_dim),
            impl=self.attention_impl, **scales_kw)


def _quantized_page_write(pages, scales, x, phys, off):
    """Write [slots, seq, H, D] decode K/V into int8 pages with
    per-page per-head amax rescale.

    pages: [N, P, H, D] int8; scales: [N, H] f32; phys/off: [slots,
    seq] physical page / in-page offset per token. Returns the updated
    (pages, scales).

    Per position j (static python loop — seq is 1 for the plain tick,
    spec_k + 1 for the verify window): the page's scale grows
    monotonically to cover the new token's amax
    (`new = max(old, amax / 127)`), the page's existing block is
    rescaled by `old / new` and the token quantized at `new`. When the
    scale doesn't grow the rescale factor is exactly 1.0 and
    `round(x * 1.0) == x` for int8-range values in f32, so the rewrite
    is an exact no-op — steady-state decode never degrades earlier
    tokens. Duplicate physical targets across slots only happen at the
    scratch page (inactive slots' zeroed table rows); its undefined
    winner is never attended. Scales only *reset* at page-granular
    rewrites (the engine insert scatter / host-tier promote), which
    cover every recycled page before a decode write can touch it.
    """
    slots = x.shape[0]
    seq = x.shape[1]
    xf = x.astype(jnp.float32)
    rows = jnp.arange(slots)
    for j in range(seq):
        p = phys[:, j]                       # [slots]
        o = off[:, j]
        xj = xf[:, j]                        # [slots, H, D]
        amax = jnp.max(jnp.abs(xj), axis=-1)  # [slots, H]
        old = scales[p]
        new = jnp.maximum(old, amax / 127.0)
        safe = jnp.where(new > 0, new, 1.0)
        factor = (old / safe)[:, None, :, None]
        block = jnp.clip(jnp.round(pages[p].astype(jnp.float32)
                                   * factor), -127, 127)
        qx = jnp.clip(jnp.round(xj / safe[:, :, None]), -127, 127)
        block = block.at[rows, o].set(qx)
        pages = pages.at[p].set(block.astype(jnp.int8))
        scales = scales.at[p].set(new)
    return pages, scales


class TransformerBlock(nn.Module):
    num_heads: int
    d_ff: int
    dropout_rate: float = 0.0
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"
    moe_experts: int = 0  # > 0 swaps the dense MLP for a Switch MoE
    decode: bool = False
    cache_len: int = 0
    causal: bool = True
    norm_eps: float = 1e-6  # GPT-2 checkpoints use 1e-5
    page_size: int = 0  # paged-pool decode (serving); see attention
    num_pages: int = 0
    page_dtype: str = ""

    @nn.compact
    def __call__(self, x, mask=None, deterministic=True):
        y = nn.LayerNorm(epsilon=self.norm_eps,
                         dtype=self.compute_dtype, name="ln_attn")(x)
        y = CausalSelfAttention(self.num_heads, self.compute_dtype,
                                self.attention_impl,
                                decode=self.decode,
                                cache_len=self.cache_len,
                                causal=self.causal,
                                page_size=self.page_size,
                                num_pages=self.num_pages,
                                page_dtype=self.page_dtype,
                                name="attention")(y, mask)
        if self.dropout_rate:
            y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
        x = x + y

        y = nn.LayerNorm(epsilon=self.norm_eps,
                         dtype=self.compute_dtype, name="ln_mlp")(x)
        if self.moe_experts:
            from cloud_tpu.models.moe import MoEMLP
            y, aux_loss = MoEMLP(num_experts=self.moe_experts,
                                 d_ff=self.d_ff,
                                 compute_dtype=self.compute_dtype,
                                 name="moe")(y, deterministic)
            # Surfaced via mutable=["losses"]; summed into the training
            # loss by Trainer when present.
            self.sow("losses", "moe_aux_loss", aux_loss,
                     reduce_fn=lambda a, b: a + b, init_fn=lambda: 0.0)
        else:
            y = nn.Dense(self.d_ff, dtype=self.compute_dtype,
                         name="mlp_in")(y)
            y = nn.gelu(y)
            y = nn.Dense(x.shape[-1], dtype=self.compute_dtype,
                         name="mlp_out")(y)
        if self.dropout_rate:
            y = nn.Dropout(self.dropout_rate)(y, deterministic=deterministic)
        return x + y


class TransformerLM(nn.Module):
    """GPT-style decoder-only language model."""

    vocab_size: int = 32000
    num_layers: int = 4
    num_heads: int = 8
    d_model: int = 512
    d_ff: int = 2048
    max_seq_len: int = 2048
    dropout_rate: float = 0.0
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"
    moe_experts: int = 0
    decode: bool = False  # autoregressive KV-cache mode (see generate())
    norm_eps: float = 1e-6  # GPT-2 checkpoints use 1e-5
    # Paged-pool decode (serving/engine.py): kv_page_size > 0 swaps the
    # dense per-example cache for the shared page pool with per-slot
    # page tables (requires decode=True; batch dim becomes slots).
    kv_page_size: int = 0
    kv_num_pages: int = 0
    kv_page_dtype: str = ""  # "int8" = quantized pages (graftpack)

    @nn.compact
    def __call__(self, tokens, mask=None, deterministic=True):
        seq = tokens.shape[1]
        if seq > self.max_seq_len:
            raise ValueError(
                "Sequence length {} exceeds max_seq_len {}.".format(
                    seq, self.max_seq_len))
        x = nn.Embed(self.vocab_size, self.d_model,
                     dtype=self.compute_dtype, name="embed")(tokens)
        if self.decode:
            # Per-example LOGICAL positions (only real tokens count),
            # so left-padded prompts look up the same position rows as
            # their unpadded equivalents; padded entries reuse row 0
            # harmlessly (their slots are never attended).
            batch = tokens.shape[0]
            pos_count = self.variable("cache", "pos_count",
                                      jnp.zeros, (batch,), jnp.int32)
            m = (jnp.ones((batch, seq), jnp.int32) if mask is None
                 else mask.astype(jnp.int32))
            positions = pos_count.value[:, None] + jnp.cumsum(m, 1) - m
            pos_count.value = pos_count.value + m.sum(axis=1)
        else:
            positions = jnp.arange(seq)[None, :]
        pos = nn.Embed(self.max_seq_len, self.d_model,
                       dtype=self.compute_dtype,
                       name="pos_embed")(positions)
        x = x + pos
        for i in range(self.num_layers):
            x = TransformerBlock(self.num_heads, self.d_ff,
                                 self.dropout_rate, self.compute_dtype,
                                 self.attention_impl, self.moe_experts,
                                 decode=self.decode,
                                 cache_len=self.max_seq_len,
                                 norm_eps=self.norm_eps,
                                 page_size=self.kv_page_size,
                                 num_pages=self.kv_num_pages,
                                 page_dtype=self.kv_page_dtype,
                                 name="block_%d" % i)(
                                     x, mask, deterministic)
        x = nn.LayerNorm(epsilon=self.norm_eps, dtype=self.compute_dtype,
                         name="ln_final")(x)
        # Tied-free output head; vocab dim sharded on tp by the rules.
        logits = nn.Dense(self.vocab_size, use_bias=False,
                          dtype=self.compute_dtype, name="lm_head")(x)
        return logits.astype(jnp.float32)


class TransformerEncoder(nn.Module):
    """BERT-style bidirectional encoder.

    The encoder counterpart of TransformerLM (same blocks, same tp
    sharding rules, bidirectional attention): per-token hidden states,
    or a pooled classification / masked-LM head.

    head: None -> [B, S, d_model] hidden states;
          "classify" -> [B, num_classes] (masked-mean pooled);
          "mlm" -> [B, S, vocab_size] token logits.
    mask: optional [B, S] validity mask (1 = real token). Padding is
        excluded from attention keys AND from the classify pooling.
    """

    vocab_size: int = 32000
    num_layers: int = 4
    num_heads: int = 8
    d_model: int = 512
    d_ff: int = 2048
    max_seq_len: int = 512
    num_classes: int = 2
    head: Optional[str] = "classify"
    dropout_rate: float = 0.0
    compute_dtype: jnp.dtype = jnp.bfloat16
    attention_impl: str = "auto"

    @nn.compact
    def __call__(self, tokens, mask=None, deterministic=True):
        seq = tokens.shape[1]
        if seq > self.max_seq_len:
            raise ValueError(
                "Sequence length {} exceeds max_seq_len {}.".format(
                    seq, self.max_seq_len))
        if self.head not in (None, "classify", "mlm"):
            raise ValueError("Unknown head: {!r}".format(self.head))
        x = nn.Embed(self.vocab_size, self.d_model,
                     dtype=self.compute_dtype, name="embed")(tokens)
        pos = nn.Embed(self.max_seq_len, self.d_model,
                       dtype=self.compute_dtype,
                       name="pos_embed")(jnp.arange(seq)[None, :])
        x = x + pos
        for i in range(self.num_layers):
            x = TransformerBlock(self.num_heads, self.d_ff,
                                 self.dropout_rate, self.compute_dtype,
                                 self.attention_impl, causal=False,
                                 name="block_%d" % i)(
                                     x, mask, deterministic)
        x = nn.LayerNorm(dtype=self.compute_dtype, name="ln_final")(x)
        if self.head is None:
            return x.astype(jnp.float32)
        if self.head == "mlm":
            logits = nn.Dense(self.vocab_size, use_bias=False,
                              dtype=self.compute_dtype,
                              name="lm_head")(x)
            return logits.astype(jnp.float32)
        # Pool in f32: bf16 can't count >256 valid tokens exactly, and
        # summing hundreds of tokens in bf16 rounds the features.
        xf = x.astype(jnp.float32)
        if mask is not None:
            m = mask.astype(jnp.float32)[:, :, None]
            pooled = jnp.sum(xf * m, axis=1) / jnp.maximum(
                jnp.sum(m, axis=1), 1.0)
        else:
            pooled = jnp.mean(xf, axis=1)
        logits = nn.Dense(self.num_classes, dtype=self.compute_dtype,
                          name="classifier")(pooled.astype(
                              self.compute_dtype))
        return logits.astype(jnp.float32)


def generate(model,
             params,
             prompt,
             max_new_tokens,
             rng=None,
             temperature=1.0,
             top_k=None,
             top_p=None,
             eos_token=None,
             prompt_mask=None,
             bucket_prompts=True):
    """Autoregressive sampling with a KV cache.

    The inference counterpart of Trainer.fit for `TransformerLM` (no
    reference equivalent — the reference delegates inference to Keras).
    XLA-friendly by construction: one jitted prefill call over the
    whole prompt, then a `lax.scan` of single-token steps over a
    static-size cache, so the whole generation compiles to two
    executables regardless of length.

    Args:
        model: A `TransformerLM` (decode=False training instance; a
            decode clone is derived internally).
        params: The trained "params" pytree.
        prompt: [B, S] int32 prompt tokens (S >= 1).
        max_new_tokens: How many tokens to sample beyond the prompt.
        rng: PRNGKey for sampling; required unless temperature == 0.
        temperature: 0 = greedy argmax; otherwise softmax temperature.
        top_k: Optional truncation to the k highest-probability tokens.
        top_p: Optional nucleus sampling: keep the smallest
            highest-probability set whose cumulative probability
            reaches top_p (computed after temperature and any top_k
            truncation, the HF warper order). (0, 1]; 1.0 = no-op.
        eos_token: Optional stop token: positions after a sampled eos
            are filled with eos_token.
        prompt_mask: Optional [B, S] bool marking REAL prompt tokens —
            the variable-length-batch contract. Prompts must be
            LEFT-padded (every example's last column real): padded
            slots are never attended, and positions (learned table or
            RoPE) count only real tokens, so each row generates
            exactly as its unpadded equivalent would.
        bucket_prompts: Pad the prompt LEFT to the next power-of-two
            bucket (capped at `max_seq_len - max_new_tokens`) before
            prefill, so varied prompt lengths share executables
            instead of minting one per length. The left-padded-mask
            contract makes the padding output-invisible; the returned
            array keeps the ORIGINAL prompt width. False = compile at
            the exact prompt length.

    Returns:
        [B, S + max_new_tokens] int32: prompt + generated continuation
        (left-padded rows keep their padding in the prompt columns).
    """
    import jax

    if model.attention_impl in SEQUENCE_PARALLEL_IMPLS:
        raise NotImplementedError(
            "generate() decodes on a single mesh shard; use a "
            "non-sequence-parallel attention_impl for inference.")
    batch, prompt_len = prompt.shape
    if max_new_tokens < 0:
        raise ValueError("max_new_tokens must be >= 0; got {}.".format(
            max_new_tokens))
    if max_new_tokens == 0:
        return prompt
    total = prompt_len + max_new_tokens
    if total > model.max_seq_len:
        raise ValueError(
            "prompt ({}) + max_new_tokens ({}) exceeds max_seq_len {}."
            .format(prompt_len, max_new_tokens, model.max_seq_len))
    if temperature and rng is None:
        raise ValueError("Sampling (temperature > 0) needs `rng`.")
    if top_k is not None and not 1 <= top_k <= model.vocab_size:
        raise ValueError(
            "top_k must be in [1, vocab_size={}]; got {}.".format(
                model.vocab_size, top_k))
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(
            "top_p must be in (0, 1]; got {}.".format(top_p))
    if prompt_mask is not None:
        from cloud_tpu.models.decoding import validate_prompt_mask
        validate_prompt_mask(prompt_mask, batch, prompt_len, "sampling")
    if rng is None:
        rng = jax.random.PRNGKey(0)

    from cloud_tpu.models.decoding import (acquire_cache, bucket_length,
                                           release_cache)

    decoder = model.clone(decode=True, dropout_rate=0.0)
    # Reuse pool, not a fresh HBM allocation per call: a parked cache
    # from a previous generate() is re-zeroed in place when available.
    cache = acquire_cache(decoder, batch)

    prefill, decode_steps = _decode_fns(
        decoder, float(temperature),
        None if top_k is None else int(top_k),
        None if top_p is None else float(top_p),
        None if eos_token is None else int(eos_token))

    rng, prefill_rng = jax.random.split(rng)
    mask_arg = (None if prompt_mask is None
                else jnp.asarray(prompt_mask, bool))
    prefill_tokens = prompt
    if bucket_prompts:
        # Left-pad to the bucket; the mask keeps padded slots out of
        # attention and position counting, so outputs match the
        # unbucketed call exactly. The final concatenate below uses the
        # ORIGINAL prompt, so the extra columns never escape.
        bucket = bucket_length(prompt_len,
                               model.max_seq_len - max_new_tokens)
        if bucket > prompt_len:
            pad = bucket - prompt_len
            prefill_tokens = jnp.pad(prompt, ((0, 0), (pad, 0)))
            real = (jnp.ones((batch, prompt_len), bool)
                    if mask_arg is None else mask_arg)
            mask_arg = jnp.pad(real, ((0, 0), (pad, 0)))
    from cloud_tpu.models.decoding import (decode_latency_finish,
                                           decode_latency_start)

    latency = decode_latency_start()
    cache, first = prefill(params, cache, prefill_tokens, prefill_rng,
                           mask_arg)
    out = [first[:, None]]
    if max_new_tokens > 1:
        cache, toks = decode_steps(
            params, cache, first,
            jax.random.split(rng, max_new_tokens - 1))
        out.append(jnp.transpose(toks, (1, 0)))
    result = jnp.concatenate([prompt] + out, axis=1)
    # Park the final cache for the next call's acquire (its contents
    # are dead weight; the acquire re-zeros it in place).
    release_cache(decoder, batch, cache)
    decode_latency_finish(latency, max_new_tokens, result)
    return result


@functools.lru_cache(maxsize=64)
def _decode_fns(decoder, temperature, top_k, top_p, eos_token):
    """Jitted (prefill, decode_steps) for one decoder/sampling config.

    Cached so repeated generate() calls reuse the compiled executables
    (jit keys on function identity; a fresh closure per call would
    re-trace every time). params/cache/tokens are arguments, not
    captured constants, so one compilation serves any weights of the
    same shapes; distinct prompt lengths or scan lengths still compile
    their own specializations, as they must under static shapes.
    """
    import jax

    def sample(logits, rng):
        logits = logits.astype(jnp.float32)
        if not temperature:
            # top-k/top-p never change the argmax; greedy skips them.
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # Shared warper (models/decoding.py): top-k → temperature →
        # top-p with sorted-order nucleus membership, the exact
        # distribution the speculative accept/reject math targets.
        from cloud_tpu.models.decoding import warp_logits
        warped = warp_logits(logits, temperature, top_k, top_p)
        return jax.random.categorical(rng, warped,
                                      axis=-1).astype(jnp.int32)

    # donate_argnums=1: the caller never reuses the passed-in cache
    # (prefill gets the fresh empty cache; decode_steps consumes
    # prefill's), so XLA can update the KV buffers in place instead of
    # holding two cache-sized allocations across the call.
    @functools.partial(runtime.instrumented_jit, donate_argnums=1)
    def prefill(params, cache, prompt, rng, prompt_mask=None):
        logits, vars_ = decoder.apply({"params": params, "cache": cache},
                                      prompt, prompt_mask,
                                      mutable=["cache"])
        return vars_["cache"], sample(logits[:, -1], rng)

    @functools.partial(runtime.instrumented_jit, donate_argnums=1)
    def decode_steps(params, cache, first_token, step_rngs):
        def step(carry, step_rng):
            cache, tok, done = carry
            logits, vars_ = decoder.apply(
                {"params": params, "cache": cache}, tok[:, None],
                mutable=["cache"])
            nxt = sample(logits[:, 0], step_rng)
            if eos_token is not None:
                nxt = jnp.where(done, eos_token, nxt)
                done = done | (nxt == eos_token)
            return (vars_["cache"], nxt, done), nxt

        done = (first_token == eos_token) if eos_token is not None \
            else jnp.zeros(first_token.shape, bool)
        (cache, _, _), toks = jax.lax.scan(
            step, (cache, first_token, done), step_rngs)
        # The final cache rides back out so generate() can park it in
        # the reuse pool (donation aliases it over the input buffers).
        return cache, toks  # toks: [T-1, B]

    from cloud_tpu.models.decoding import best_effort_donation
    return best_effort_donation(prefill), best_effort_donation(
        decode_steps)


def tensor_parallel_rules(tp_axis: str = "tp"):
    """Megatron-style sharding rules for Trainer(param_sharding_rules=...).

    Column-parallel qkv/mlp-in, row-parallel out-proj/mlp-out: exactly one
    all-reduce after attention and one after the MLP per block, riding ICI.
    """
    return [
        # Attention projections: split heads across tp.
        (r"attention/(query|key|value)/kernel", P(None, tp_axis, None)),
        (r"attention/out/kernel", P(tp_axis, None, None)),
        # MLP: column-parallel in, row-parallel out.
        (r"mlp_in/kernel", P(None, tp_axis)),
        (r"mlp_out/kernel", P(tp_axis, None)),
        # Embeddings / head: vocab-sharded.
        (r"(^|/)embed/embedding", P(tp_axis, None)),
        (r"lm_head/kernel", P(None, tp_axis)),
    ]
