"""Ambient distribution runtime for TPU-native execution.

This is the TPU-native replacement for the reference's ambient strategy
mechanism (`tf.distribute.experimental_set_strategy(strategy)`, reference
core/preprocess.py:148-149) and its TPU bootstrap dance (the 40x10s
`TPU_CONFIG`-polling `TPUClusterResolver`, reference
core/preprocess.py:215-262). On TPU-VMs the chips are local devices, so
bootstrap collapses to a bounded wait on `jax.devices()`; multi-host pods
bootstrap through `jax.distributed.initialize` driven by an env-var
contract (the analogue of the reference's `TF_CONFIG`/`TPU_CONFIG`
injection, reference core/deploy.py:159-161).

The initialized context — a `jax.sharding.Mesh` plus the strategy name —
is ambient: `cloud_tpu.training.Trainer` and the `run()`-generated runner
scripts pick it up via `global_mesh()` without user code changes.

Env contract (set by the deployer on every remote process):
    CLOUD_TPU_COORDINATOR_ADDRESS  host:port of process 0
    CLOUD_TPU_NUM_PROCESSES        total process count
    CLOUD_TPU_PROCESS_ID           this process's index
    CLOUD_TPU_RUNNING_REMOTELY     guard consumed by `run.remote()`
    CLOUD_TPU_MESH                 optional mesh layout, e.g.
                                   "dp:-1,tp:2" (-1 = infer from device
                                   count); lets a launched job request
                                   tensor/sequence/expert axes without
                                   code changes
"""

import logging
import os
import threading
import time

logger = logging.getLogger("cloud_tpu")

# Known strategy names, selected by the strategy compiler
# (cloud_tpu/core/preprocess.py) from the cluster shape.
STRATEGIES = ("one_device", "mirrored", "multi_worker", "tpu_slice",
              "tpu_pod", "multi_slice")

_context = None


class DistributionContext:
    """The ambient distribution state: strategy name + device mesh."""

    def __init__(self, strategy, mesh):
        self.strategy = strategy
        self.mesh = mesh

    @property
    def num_devices(self):
        return self.mesh.devices.size

    def __repr__(self):
        return "DistributionContext(strategy={!r}, mesh_shape={})".format(
            self.strategy, dict(self.mesh.shape))


def _wait_for_devices(min_devices=1, retries=40, retry_interval_secs=10.0):
    """Bounded wait for accelerator availability.

    Parity with the reference's TPU-provisioning wait
    (core/preprocess.py:238-261: 40 retries x 10s), collapsed to a local
    device query because TPU-VM chips are local.
    """
    import jax

    last_err = None
    for attempt in range(retries):
        try:
            devices = jax.devices()
            if len(devices) >= min_devices:
                return devices
        except RuntimeError as e:  # backend not ready yet
            last_err = e
        if attempt < retries - 1:
            time.sleep(retry_interval_secs)
    raise RuntimeError(
        "Accelerator devices did not become available after {} attempts "
        "({}s apart). Last error: {}".format(
            retries, retry_interval_secs, last_err))


def initialize(strategy="tpu_slice",
               axis_names=None,
               mesh_shape=None,
               dcn_mesh_shape=None,
               coordinator_address=None,
               num_processes=None,
               process_id=None,
               devices=None,
               retries=40,
               retry_interval_secs=10.0):
    """Initializes the ambient distribution context.

    Args:
        strategy: One of `STRATEGIES`. Multi-process strategies
            ("multi_worker", "tpu_pod") run `jax.distributed.initialize`
            first, using the env contract when args are not given.
        axis_names: Mesh axis names. Default (None) is the CLOUD_TPU_MESH
            env layout when set, else a pure data-parallel 1D mesh
            ("dp",); pass e.g. ("dp", "tp") with `mesh_shape` for hybrid
            layouts (explicit args always beat the env).
        mesh_shape: Optional tuple of ints matching `axis_names`. Default:
            all devices on the first axis. For "multi_slice" this is the
            PER-SLICE (ICI) shape; the full mesh axis sizes are
            elementwise `dcn_mesh_shape * mesh_shape`.
        dcn_mesh_shape: ("multi_slice" only) how each axis spans slices
            over DCN; same length as axis_names. Default: all slices on
            the first (data) axis — dp gradient reductions cross DCN,
            tp/sp/pp collectives stay on intra-slice ICI, the standard
            multi-slice layout. Slices are identified by the devices'
            `slice_index` (fallback for simulation: contiguous groups of
            CLOUD_TPU_NUM_SLICES equal chunks).
        coordinator_address / num_processes / process_id: Multi-process
            bootstrap parameters; default to the CLOUD_TPU_* env contract.
        devices: Explicit device list (tests); default `jax.devices()`
            after a bounded availability wait.
        retries / retry_interval_secs: Device-wait bounds (reference
            parity: 40 x 10s).

    Returns:
        The installed `DistributionContext`.
    """
    global _context
    if strategy not in STRATEGIES:
        raise ValueError(
            "Unknown strategy {!r}. Expected one of {}.".format(
                strategy, STRATEGIES))

    # Launch-time mesh layout via env contract: only when the caller did
    # not pass an explicit layout (generated runners pass neither).
    env_mesh = os.environ.get("CLOUD_TPU_MESH")
    if axis_names is None and mesh_shape is None and env_mesh:
        axis_names, mesh_shape = _parse_mesh_env(env_mesh)
    elif axis_names is None:
        axis_names = ("dp",)

    if strategy in ("multi_worker", "tpu_pod", "multi_slice"):
        _maybe_init_distributed(coordinator_address, num_processes,
                                process_id)

    import jax
    from jax.sharding import Mesh
    import numpy as np

    if devices is None:
        if strategy == "one_device":
            devices = _wait_for_devices(1, retries, retry_interval_secs)[:1]
        else:
            devices = _wait_for_devices(1, retries, retry_interval_secs)

    if strategy == "multi_slice":
        device_array = _hybrid_device_array(devices, axis_names,
                                            mesh_shape, dcn_mesh_shape)
    else:
        device_array = np.asarray(devices)
        mesh_shape = _infer_mesh_shape(mesh_shape, device_array.size)
        if mesh_shape is not None:
            if len(mesh_shape) != len(axis_names):
                raise ValueError(
                    "mesh_shape {} does not match axis_names {}.".format(
                        mesh_shape, axis_names))
            device_array = device_array.reshape(mesh_shape)
        else:
            device_array = device_array.reshape(
                (device_array.size,) + (1,) * (len(axis_names) - 1))

    mesh = Mesh(device_array, axis_names)
    _context = DistributionContext(strategy, mesh)
    logger.info("cloud_tpu runtime initialized: %r", _context)
    return _context


def _infer_mesh_shape(mesh_shape, total):
    """Resolves one -1 entry against `total` devices (env-contract
    layouts like "dp:-1,tp:2" leave the data axis inferred)."""
    if mesh_shape is None or -1 not in mesh_shape:
        return mesh_shape
    known = 1
    for dim in mesh_shape:
        if dim != -1:
            known *= dim
    if known <= 0 or mesh_shape.count(-1) != 1 or total % known:
        raise ValueError(
            "Cannot infer mesh_shape {} for {} devices.".format(
                mesh_shape, total))
    return tuple(total // known if d == -1 else d for d in mesh_shape)


def _group_by_slice(devices):
    """Devices grouped by TPU slice.

    Real multi-slice platforms expose `slice_index` per device; when
    absent (CPU simulation, single slice), CLOUD_TPU_NUM_SLICES splits
    the flat list into contiguous equal chunks so the layout logic can
    be exercised anywhere.
    """
    groups = {}
    for d in devices:
        idx = getattr(d, "slice_index", None)
        if idx is None:
            break
        groups.setdefault(idx, []).append(d)
    else:
        if len(groups) > 1:
            return [groups[k] for k in sorted(groups)]
    n = int(os.environ.get("CLOUD_TPU_NUM_SLICES", "1"))
    if n <= 1:
        return [list(devices)]
    if len(devices) % n:
        raise ValueError(
            "CLOUD_TPU_NUM_SLICES={} does not divide {} devices.".format(
                n, len(devices)))
    per = len(devices) // n
    return [list(devices[i * per:(i + 1) * per]) for i in range(n)]


def _hybrid_device_array(devices, axis_names, ici_shape, dcn_shape):
    """DCN x ICI hybrid mesh layout (the multi-slice analogue of
    jax.experimental.mesh_utils.create_hybrid_device_mesh, built
    directly from the slice grouping so it also works on simulated
    slices).

    Each mesh axis k has size dcn[k] * ici[k]; devices are arranged so
    that moving along an axis inside one ICI block stays within a
    slice (fast ICI hops) and the dcn factor strides across slices
    (DCN hops). With the default dcn = (num_slices, 1, ...), dp spans
    slices and every other axis is slice-local.
    """
    import numpy as np

    groups = _group_by_slice(devices)
    num_slices = len(groups)
    per_slice = len(groups[0])
    if any(len(g) != per_slice for g in groups):
        raise ValueError("Slices are unequal: {}.".format(
            [len(g) for g in groups]))
    rank = len(axis_names)
    if dcn_shape is None:
        dcn_shape = (num_slices,) + (1,) * (rank - 1)
    if len(dcn_shape) != rank:
        raise ValueError(
            "dcn_mesh_shape {} does not match axis_names {}.".format(
                dcn_shape, axis_names))
    dcn_total = int(np.prod(dcn_shape))
    if dcn_total != num_slices:
        raise ValueError(
            "dcn_mesh_shape {} needs {} slices; found {}.".format(
                dcn_shape, dcn_total, num_slices))
    if ici_shape is None:
        ici_shape = (per_slice,) + (1,) * (rank - 1)
    if len(ici_shape) != rank:
        raise ValueError(
            "mesh_shape {} does not match axis_names {}.".format(
                ici_shape, axis_names))
    # Env-contract layouts leave one dim inferred ("dp:-1,tp:2"); for
    # multi_slice the per-slice device count is the inference base.
    ici_shape = _infer_mesh_shape(tuple(ici_shape), per_slice)
    if int(np.prod(ici_shape)) != per_slice:
        raise ValueError(
            "Per-slice mesh_shape {} needs {} devices; each slice has "
            "{}.".format(ici_shape, int(np.prod(ici_shape)), per_slice))

    # [dcn0, dcn1, ..., ici0, ici1, ...] -> interleave -> combined.
    arr = np.array([np.array(g).reshape(ici_shape) for g in groups])
    arr = arr.reshape(tuple(dcn_shape) + tuple(ici_shape))
    order = []
    for k in range(rank):
        order.extend([k, rank + k])
    arr = np.transpose(arr, order)
    return arr.reshape(tuple(d * i for d, i in zip(dcn_shape, ici_shape)))


def _maybe_init_distributed(coordinator_address, num_processes, process_id):
    """Runs `jax.distributed.initialize` from args or the env contract."""
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "CLOUD_TPU_COORDINATOR_ADDRESS")
    if num_processes is None:
        num_processes = _env_int("CLOUD_TPU_NUM_PROCESSES")
    if process_id is None:
        process_id = _env_int("CLOUD_TPU_PROCESS_ID")

    if coordinator_address is None and num_processes in (None, 1):
        # Single-process "pod": legitimate in tests and on a single
        # TPU-VM; nothing to bootstrap.
        logger.info("No multi-process env contract found; running "
                    "single-process.")
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)


def _parse_mesh_env(value):
    """"dp:-1,tp:2" -> (("dp", "tp"), (-1, 2)). Shapeless entries
    ("dp,tp:2") default to -1 (inferred)."""
    names, shape = [], []
    for part in value.split(","):
        part = part.strip()
        if not part:
            continue
        name, _, dim = part.partition(":")
        names.append(name.strip())
        shape.append(int(dim) if dim else -1)
    if not names:
        raise ValueError("Empty CLOUD_TPU_MESH value: {!r}".format(value))
    return tuple(names), tuple(shape)


def _env_int(name):
    value = os.environ.get(name)
    return int(value) if value is not None else None


class BackendUnavailable(RuntimeError):
    """The accelerator backend stopped answering.

    The typed failure ROADMAP item 4 asks for: a dispatch hang, a dead
    tunnel, or a failed device probe becomes THIS within a bounded
    deadline — not a 30-minute outer timeout with no artifact. Raised
    by `probe-driven` callers (graftwatch's stall handler, bench.py's
    probe loop consumers); carries the probe diagnosis, the deadline
    that was exceeded, and the flight-recorder path when one was
    written.

    `fault_kind` places it in graftguard's typed-fault taxonomy
    (training/resilience.py) — the retry loop classifies every caught
    fault by this attribute.
    """

    fault_kind = "backend_unavailable"

    def __init__(self, message="accelerator backend unavailable",
                 diagnosis=None, deadline=None, blackbox=None):
        super().__init__(message)
        self.diagnosis = diagnosis
        self.deadline = deadline
        self.blackbox = blackbox


#: Default probe bound, seconds: a healthy backend answers a 1-op jit
#: in a few seconds (cold import included); a stalled tunnel eats the
#: whole bound without returning.
PROBE_DEADLINE_S = 60.0


def probe_backend(deadline=None, force_cpu=False, register=None):
    """Compile-and-run a trivial jit in a fresh deadline-bounded process.

    Hoisted out of bench.py (round-5 lesson: the harness's private
    probe was the only deadline-bounded device check in the tree) so
    the Trainer's watchdog, bench.py, and future elastic-training retry
    policies share ONE probe. Returns (ok, diagnosis) — it never
    raises and never hangs past `deadline`: a backend whose init or
    dispatch stalls takes the CHILD process down, not the caller.

    Args:
        deadline: Seconds before the child is killed (default: the
            CLOUD_TPU_PROBE_DEADLINE env var, then PROBE_DEADLINE_S).
        force_cpu: Probe the CPU backend via an explicit in-child
            config update (a site hook can pin JAX_PLATFORMS to the
            tunnel, so the override must not be an env var the hook
            would fight).
        register: Optional callable receiving the spawned Popen (then
            None once reaped) — bench.py's SIGTERM handler uses it so
            an orphaned probe dies with the harness.
    """
    import subprocess
    import sys as _sys

    if deadline is None:
        try:
            deadline = float(os.environ.get("CLOUD_TPU_PROBE_DEADLINE",
                                            PROBE_DEADLINE_S))
        except ValueError:
            deadline = PROBE_DEADLINE_S
    env = dict(os.environ)
    if force_cpu:
        env["CLOUD_TPU_PROBE_CPU"] = "1"
    code = ("import os, jax; "
            "os.environ.get('CLOUD_TPU_PROBE_CPU') == '1' and "
            "jax.config.update('jax_platforms', 'cpu'); "
            "x = jax.jit(lambda v: v + 1)(1.0); x.block_until_ready(); "
            "print('PROBE_OK', jax.default_backend(), len(jax.devices()))")
    try:
        proc = subprocess.Popen([_sys.executable, "-c", code],
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True,
                                env=env)
    except OSError as e:
        return False, "backend probe failed to launch: {}".format(e)
    if register is not None:
        register(proc)
    try:
        stdout, stderr = proc.communicate(timeout=deadline)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        return False, "backend probe hung past {:g}s".format(deadline)
    finally:
        if register is not None:
            register(None)
    for line in stdout.splitlines():
        if line.startswith("PROBE_OK"):
            return True, line.strip()
    tail = (stderr or stdout or "").strip().splitlines()
    return False, "backend init failed: {}".format(
        tail[-1] if tail else "rc={}".format(proc.returncode))


def is_initialized():
    return _context is not None


def context():
    if _context is None:
        raise RuntimeError(
            "cloud_tpu runtime is not initialized. Call "
            "cloud_tpu.parallel.runtime.initialize() first (the run() "
            "generated runner does this automatically).")
    return _context


def global_mesh():
    """The ambient mesh, or None when uninitialized (single-device ok)."""
    return _context.mesh if _context is not None else None


def reset():
    """Clears the ambient context (test isolation)."""
    global _context
    _context = None


# --------------------------------------------------------------------------
# Host<->device transfer observability.
#
# H2D: every feed-path entry point (sharding.shard_batch /
# make_global_batch, Trainer's no-mesh device_put branches,
# prefetch_to_device's default feed, and the DeviceResidentDataset
# one-time upload) records what it is about to move.
#
# D2H: every device->host readback goes through `device_fetch` (or calls
# `record_d2h` right before its own jax.device_get), so "the async host
# loop issues at most ONE fetch per logging interval" is a counted
# invariant, not a wall-clock inference. One `device_fetch` CALL counts
# as one fetch no matter how many leaves the tree has — coalescing N
# metric reads into one call is exactly the round-trip win the counter
# exists to pin (~66ms per round trip on the tunneled chip, PERF.md).
#
# Tests and bench.py assert transfer behavior from these counters
# instead of inferring it from wall clock — in particular that the
# device-resident pipeline does ZERO per-step H2D data transfers after
# its one-time upload, and that input_cast="bfloat16" halves the bytes
# on the wire.

_transfer_stats = {"h2d_transfers": 0, "h2d_bytes": 0,
                   "d2h_fetches": 0, "d2h_bytes": 0}

# --------------------------------------------------------------------------
# graftsan observer seam (cloud_tpu.analysis.sanitizer).
#
# The counters above say THAT a transfer/compile happened; the sanitizer
# wants to know WHERE. Rather than having the sanitizer monkeypatch the
# record_* functions (fragile against `from runtime import record_d2h`
# binding), each record site notifies a single module-level observer.
# When no observer is installed — the default, and the production state
# — the cost is one global load + None check per record call; nothing
# is wrapped, patched, or allocated.
#
# Phases are thread-local labels the Trainer (and its worker threads)
# publish so an observer can tell a step-loop fetch from a sanctioned
# boundary fetch: "step" inside the epoch step loop, "boundary" between
# epochs, "async_reader" / "checkpoint" on the worker threads. The
# label is advisory context for attribution, never control flow.

_observer = None
_observers = ()
_phase = threading.local()


class _FanoutObserver:
    """Dispatch target when more than one observer is installed
    (graftsan + graftscope stacking). Forwards each event to every
    target that implements it; a missing method on one target never
    hides the event from the others. Hot-path cost with a single
    observer is unchanged: the fanout only exists with >= 2."""

    __slots__ = ("targets",)

    def __init__(self, targets):
        self.targets = tuple(targets)

    def _fan(self, method, *args):
        for target in self.targets:
            fn = getattr(target, method, None)
            if fn is not None:
                fn(*args)

    def on_h2d(self, transfers, nbytes):
        self._fan("on_h2d", transfers, nbytes)

    def on_d2h(self, nbytes, tree):
        self._fan("on_d2h", nbytes, tree)

    def on_compile(self, n_traces, n_compiles, cache_hits):
        self._fan("on_compile", n_traces, n_compiles, cache_hits)

    def on_cache_miss(self):
        self._fan("on_cache_miss")

    def on_epoch(self, epoch):
        self._fan("on_epoch", epoch)

    def on_donation(self, args):
        self._fan("on_donation", args)

    def on_warm_mark(self):
        self._fan("on_warm_mark")

    def on_retrace(self, label, diffs):
        self._fan("on_retrace", label, diffs)

    def on_mesh_drift(self, label, drifts):
        self._fan("on_mesh_drift", label, drifts)


def _rebuild_dispatch():
    """Recomputes the fast dispatch target `_observer` from the
    installed set: None (record sites stay one None-check), the sole
    observer (direct calls, no indirection), or a fanout."""
    global _observer
    if not _observers:
        _observer = None
    elif len(_observers) == 1:
        _observer = _observers[0]
    else:
        _observer = _FanoutObserver(_observers)


def add_observer(observer):
    """Adds `observer` to the installed set (idempotent). Observers
    see `on_h2d(transfers, nbytes)`, `on_d2h(nbytes, tree)`,
    `on_compile(n_traces, n_compiles, cache_hits)`, `on_cache_miss()`,
    `on_epoch(epoch)`, `on_donation(args)`, `on_warm_mark()`,
    `on_retrace(label, diffs)`, `on_mesh_drift(label, drifts)` — all
    best-effort, called inline at record time on whatever thread
    recorded; any subset of those methods may be implemented when
    stacked. Returns `observer`."""
    global _observers
    if observer is not None and observer not in _observers:
        _observers = _observers + (observer,)
        _rebuild_dispatch()
    return observer


def remove_observer(observer):
    """Removes `observer` from the installed set (no-op if absent)."""
    global _observers
    if observer in _observers:
        _observers = tuple(o for o in _observers if o is not observer)
        _rebuild_dispatch()


def observers():
    """Snapshot of the installed observer set (install order)."""
    return _observers


def set_observer(observer):
    """Legacy single-observer API: replaces the WHOLE installed set
    with `observer` (or clears it for None). Returns the previous
    dispatch target so scoped installers can restore it. New code —
    anything that must coexist with another observer — uses
    `add_observer`/`remove_observer` instead."""
    global _observers
    previous = _observer
    _observers = (observer,) if observer is not None else ()
    _rebuild_dispatch()
    return previous


def get_observer():
    """The current dispatch target: None, the sole observer, or the
    internal fanout when several are stacked."""
    return _observer


def set_phase(name):
    """Sets this thread's phase label; returns the previous label."""
    previous = getattr(_phase, "name", None)
    _phase.name = name
    return previous


def current_phase():
    """This thread's phase label, or None when never set."""
    return getattr(_phase, "name", None)


def notify_epoch(epoch):
    """Tells the observer (if any) that epoch `epoch` just finished."""
    if _observer is not None:
        _observer.on_epoch(epoch)


def notify_warm_mark():
    """Tells the observer (if any) that warmup just finished — every
    executable the workload needs is compiled, so from here on a trace
    is a bug and `on_retrace` events carry blame (GS005). getattr-
    guarded: observers that predate the event simply never see it."""
    if _observer is not None:
        fn = getattr(_observer, "on_warm_mark", None)
        if fn is not None:
            fn()


def _notify_retrace(label, diffs):
    """Forwards one attributed retrace to the observer (if any)."""
    if _observer is not None:
        fn = getattr(_observer, "on_retrace", None)
        if fn is not None:
            fn(label, diffs)


def _notify_mesh_drift(label, drifts):
    """Forwards one attributed jit-boundary resharding to the observer
    (if any): `drifts` is a tuple of (leaf path, sharding at first
    dispatch, sharding now) — the GS006 mesh-drift event. getattr-
    guarded like on_warm_mark: observers that predate the event never
    see it."""
    if _observer is not None:
        fn = getattr(_observer, "on_mesh_drift", None)
        if fn is not None:
            fn(label, drifts)


def record_h2d(batch):
    """Counts the host->device bytes about to be transferred for `batch`.

    Only host-resident leaves count: a leaf that is already a `jax.Array`
    costs nothing to "transfer" again (device_put is a no-op or a
    device-to-device move), so it is skipped. Python scalars and lists are
    measured through `np.asarray`. Returns the byte count recorded, so the
    one-time resident upload can report its own size.
    """
    import jax
    import numpy as np

    transfers = 0
    total = 0
    for leaf in jax.tree_util.tree_leaves(batch):
        if isinstance(leaf, jax.Array):
            continue
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is None:
            nbytes = np.asarray(leaf).nbytes
        transfers += 1
        total += int(nbytes)
    if transfers:
        _transfer_stats["h2d_transfers"] += transfers
        _transfer_stats["h2d_bytes"] += total
        if _observer is not None:
            _observer.on_h2d(transfers, total)
    return total


def record_d2h(tree):
    """Counts one device->host fetch about to be issued for `tree`.

    The unit is the ROUND TRIP, not the leaf: a coalesced
    `jax.device_get` of a whole metric pytree is one tunnel round trip
    regardless of leaf count, so one call here increments
    `d2h_fetches` by exactly one. Bytes sum over the `jax.Array`
    leaves (host-resident leaves ride along for free — they are not
    fetched). A tree with no device leaves records nothing: there is
    no round trip to count. Returns the byte count recorded.
    """
    import jax

    total = 0
    device_leaves = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            device_leaves += 1
            total += int(leaf.nbytes)
    if device_leaves:
        _transfer_stats["d2h_fetches"] += 1
        _transfer_stats["d2h_bytes"] += total
        if _observer is not None:
            _observer.on_d2h(total, tree)
    return total


def device_fetch(tree):
    """The sanctioned instrumented readback: record, then device_get.

    All Trainer/bench device->host reads route through here so the
    d2h counters stay an exhaustive census of fetch sites — and so one
    graftscope span ("d2h_fetch") times every round trip. Returns
    `jax.device_get(tree)` (host numpy leaves, same structure).
    """
    import jax

    record_d2h(tree)
    from cloud_tpu.monitoring import spans

    with spans.span("d2h_fetch"):
        return jax.device_get(tree)


def transfer_stats():
    """A snapshot of the process-wide transfer counters (H2D + D2H)."""
    return dict(_transfer_stats)


def reset_transfer_stats():
    """Zeroes all transfer counters (test isolation / bench warmup
    barrier)."""
    for key in _transfer_stats:
        _transfer_stats[key] = 0


# --------------------------------------------------------------------------
# Compilation observability.
#
# The same doctrine as the transfer counters above, applied to the other
# uncounted wall-clock sink: trace + XLA compile. Every framework
# `jax.jit` site (Trainer steps, decode prefill/step, speculative round
# functions) goes through `instrumented_jit`, so "a steady-state epoch
# performs ZERO new traces/compiles" is a counted invariant a test can
# pin, not a wall-clock inference.
#
# n_traces  — times a wrapped function body was re-traced (bumped from
#             inside the traced body, so it fires exactly when jax
#             actually retraces: dispatch-cache misses and .lower()).
# n_compiles — executables built (dispatch-path misses + explicit AOT
#             `.compile()` calls).
# compile_seconds — wall seconds spent in calls that traced. On the
#             dispatch path this includes the first execution (jax
#             offers no clean split there); AOT `.compile()` timings are
#             pure compile.
# cache_hits — persistent-compile-cache hits (fed by the
#             `compile_cache` module's jax.monitoring listener).

_compile_stats = {"n_traces": 0, "n_compiles": 0,
                  "compile_seconds": 0.0, "cache_hits": 0}


class RetraceWarning(UserWarning):
    """A steady-state epoch compiled something new.

    Raised as a warning (opt-in: an exception) by the Trainer's retrace
    sentinel when `compile_stats()` moved during an epoch that should
    have been fully warm — the usual culprits are a ragged tail batch,
    a dtype drift in the input pipeline, or a new decode prompt length.
    """


def record_compile(n_traces=0, n_compiles=0, compile_seconds=0.0,
                   cache_hits=0):
    """Adds to the process-wide compile counters."""
    _compile_stats["n_traces"] += n_traces
    _compile_stats["n_compiles"] += n_compiles
    _compile_stats["compile_seconds"] += compile_seconds
    _compile_stats["cache_hits"] += cache_hits
    if _observer is not None and (n_traces or n_compiles or cache_hits):
        _observer.on_compile(n_traces, n_compiles, cache_hits)


def compile_stats():
    """A snapshot of the process-wide compile counters."""
    return dict(_compile_stats)


def reset_compile_stats():
    """Zeroes all compile counters (test isolation / bench warmup
    barrier). Does NOT clear jax's own caches — an executable compiled
    before the reset stays warm, which is exactly what a steady-state
    invariant wants."""
    _compile_stats["n_traces"] = 0
    _compile_stats["n_compiles"] = 0
    _compile_stats["compile_seconds"] = 0.0
    _compile_stats["cache_hits"] = 0


def _aval_signature(args):
    """A hashable (treedef, leaf-aval) key for the warm-executable table.

    Returns None when any leaf lacks shape/dtype (python scalars,
    strings) — those calls fall back to the ordinary jit dispatch path
    rather than risking a wrong executable match.
    """
    import jax
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            return None
        sig.append((tuple(shape),
                    jax.dtypes.canonicalize_dtype(np.dtype(dtype))))
    return (treedef, tuple(sig))


class _InstrumentedLowered:
    """Proxy over `jax.stages.Lowered` that counts `.compile()`."""

    def __init__(self, lowered):
        self._lowered = lowered

    def compile(self, *args, **kwargs):
        t0 = time.perf_counter()
        compiled = self._lowered.compile(*args, **kwargs)
        record_compile(n_compiles=1,
                       compile_seconds=time.perf_counter() - t0)
        return compiled

    def __getattr__(self, name):
        return getattr(self._lowered, name)


class InstrumentedJit:
    """`jax.jit` with compile counting and an AOT warm-start table.

    Drop-in at call sites: `__call__` and `.lower()` mirror the jitted
    function. Tracing is detected from inside the traced body (a
    counter bump that only runs when jax actually retraces), so cached
    dispatches cost one integer compare and no counter traffic.

    `.warm(*specs)` AOT-compiles for the given `ShapeDtypeStruct`s (or
    example arrays) and installs the executable in a signature-keyed
    table that `__call__` consults first — a warmed call never enters
    jit dispatch at all, so step 1 after `Trainer.warmup()` runs
    trace-free. Signature mismatches (and executables whose sharding
    check rejects the actual args) fall back to the jit path; the warm
    table is an accelerator, never a correctness gate.
    """

    def __init__(self, fun, **jit_kwargs):
        import functools
        import jax

        self._fun = fun
        self._label = getattr(fun, "__name__", None) or repr(fun)
        self._trace_count = 0
        self._warm = {}
        # treedef -> leaf-aval tuple of the LAST traced call with that
        # structure. Written only when a trace actually fired (rare by
        # construction), read only to attribute the NEXT trace: the
        # diff against it names the exact leaf whose avals moved.
        self._sig_history = {}
        # aval signature -> per-leaf (path, sharding str) tuple of the
        # FIRST observed dispatch with that signature. Only populated
        # while an observer is installed (graftsan): a later dispatch
        # whose shardings differ is an implicit reshard at the jit
        # boundary, forwarded as the GS006 mesh-drift event.
        self._shard_baseline = {}
        # Donated positions, kept for the graftsan observer: donation
        # invalidates the caller's buffer, so the sanitizer tracks the
        # donated arrays (by weakref) to catch later reads of them.
        donate = jit_kwargs.get("donate_argnums")
        if donate is None:
            donate = ()
        elif isinstance(donate, int):
            donate = (donate,)
        self._donate_argnums = tuple(donate)
        # The warm table matches on positional avals only; static or
        # keyword-routed arguments would make the signature ambiguous.
        self._warmable = not any(
            jit_kwargs.get(k) for k in ("static_argnums", "static_argnames"))

        def _shim(*args, **kwargs):
            # Runs at TRACE time only: jax executes the python body
            # exactly when (re)tracing, which is the event we count.
            self._trace_count += 1
            record_compile(n_traces=1)
            return fun(*args, **kwargs)

        try:
            functools.update_wrapper(_shim, fun)
        except AttributeError:  # functools.partial etc.
            pass
        self._jitted = jax.jit(_shim, **jit_kwargs)

    @property
    def n_traces(self):
        """Times THIS wrapper's body was traced (per-site counter)."""
        return self._trace_count

    def __call__(self, *args, **kwargs):
        if _observer is not None and self._donate_argnums:
            _observer.on_donation(
                [args[i] for i in self._donate_argnums
                 if 0 <= i < len(args)])
        sig = None
        if (self._warm or _observer is not None) and not kwargs:
            sig = _aval_signature(args)
        if _observer is not None and sig is not None:
            self._check_mesh_drift(sig, args)
        if self._warm and sig is not None:
            compiled = self._warm.get(sig)
            if compiled is not None:
                try:
                    return compiled(*args)
                except Exception:
                    # Aval match but sharding/layout rejection: evict
                    # and let jit dispatch handle it from now on.
                    self._warm.pop(sig, None)
        before = self._trace_count
        t0 = time.perf_counter()
        out = self._jitted(*args, **kwargs)
        if self._trace_count != before:
            record_compile(n_compiles=1,
                           compile_seconds=time.perf_counter() - t0)
            if not kwargs:
                self._attribute_trace(args)
        return out

    def _check_mesh_drift(self, sig, args):
        """GS006: the first observed dispatch per aval signature
        records every jax.Array input leaf's concrete sharding (the
        mesh AND the spec, via its str form); a later dispatch with
        the same signature but different leaf shardings means the jit
        boundary is silently resharding — a device transfer per call —
        and the observer gets the exact leaves with both layouts.
        Runs only while an observer is installed, so the unobserved
        hot path never flattens shardings."""
        import jax

        try:
            flat, _ = jax.tree_util.tree_flatten_with_path(args)
            current = tuple(
                ("args" + jax.tree_util.keystr(path), str(leaf.sharding))
                for path, leaf in flat
                if isinstance(leaf, jax.Array))
        except Exception:
            return  # exotic leaves: attribution is best-effort
        baseline = self._shard_baseline.get(sig)
        if baseline is None:
            self._shard_baseline[sig] = current
            return
        if baseline == current:
            return
        base = dict(baseline)
        drifts = tuple(
            (path, base[path], sharding)
            for path, sharding in current
            if path in base and base[path] != sharding)
        if drifts:
            _notify_mesh_drift(self._label, drifts)

    def _attribute_trace(self, args):
        """Names the leaves that forced the trace that just fired.

        Diffs the call's aval signature against the closest previously
        seen signature of the same tree structure (warm table first,
        then the per-structure trace history) and forwards the diff to
        the observer as an `on_retrace` event — the GS005 runtime dual
        of graftlint GL010. Runs only on traced calls, so steady-state
        dispatch cost is untouched."""
        sig = _aval_signature(args)
        if sig is None:
            _notify_retrace(self._label, None)
            return
        treedef, leaves = sig
        diffs = None
        if _observer is not None:
            candidates = [s[1] for s in self._warm if s[0] == treedef]
            prior = self._sig_history.get(treedef)
            if prior is not None:
                candidates.append(prior)
            best = None
            for old in candidates:
                if len(old) != len(leaves):
                    continue
                changed = [i for i, (a, b) in enumerate(zip(old, leaves))
                           if a != b]
                if changed and (best is None or len(changed) < len(best[0])):
                    best = (changed, old)
            if best is not None:
                diffs = self._leaf_diffs(args, best[1], leaves, best[0])
            _notify_retrace(self._label, diffs)
        self._sig_history[treedef] = leaves

    @staticmethod
    def _leaf_diffs(args, old, new, changed):
        """[(leaf path, old aval, new aval), ...] with human names:
        `args[1]['page_table']` widened `int32[4,16]` -> `int32[8,16]`."""
        import jax

        flat, _ = jax.tree_util.tree_flatten_with_path(args)

        def aval(entry):
            shape, dtype = entry
            return "{}[{}]".format(dtype, ",".join(map(str, shape)))

        out = []
        for i in changed:
            path = ("args" + jax.tree_util.keystr(flat[i][0])
                    if i < len(flat) else "leaf {}".format(i))
            out.append((path, aval(old[i]), aval(new[i])))
        return tuple(out)

    def lower(self, *args, **kwargs):
        return _InstrumentedLowered(self._jitted.lower(*args, **kwargs))

    def warm(self, *specs):
        """AOT-compiles for `specs` (ShapeDtypeStructs or example
        arrays) and installs the executable in the warm table. Returns
        the `jax.stages.Compiled`. Idempotent per signature: a spec
        already warm returns its executable without re-lowering, so
        `warmup()` followed by `fit(warm_start=True)` compiles once."""
        sig = _aval_signature(specs) if self._warmable else None
        if sig is not None and sig in self._warm:
            return self._warm[sig]
        compiled = self.lower(*specs).compile()
        if sig is not None:
            self._warm[sig] = compiled
        return compiled

    def warm_signatures(self):
        """The aval signatures currently warm (introspection/tests)."""
        return tuple(self._warm)

    def clear_warm(self):
        self._warm.clear()

    def __getattr__(self, name):
        return getattr(self._jitted, name)


def instrumented_jit(fun, **jit_kwargs):
    """`jax.jit` replacement that feeds `compile_stats()`.

    Usage matches jit: `instrumented_jit(f, donate_argnums=0)` or
    `@functools.partial(instrumented_jit, donate_argnums=1)`.
    """
    return InstrumentedJit(fun, **jit_kwargs)
