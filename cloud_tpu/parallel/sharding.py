"""Sharding helpers: how arrays lay out over the ambient device mesh.

The TPU-native replacement for the implicit placement decisions inside
`tf.distribute` strategies (reference core/preprocess.py:124-149 selects a
strategy; the strategy owns variable/batch placement). Here placement is
explicit and compiler-visible: `jax.sharding.NamedSharding` specs over the
ambient `Mesh`, with XLA inserting the collectives (psum for gradient
reduction rides ICI automatically when the batch is sharded on the "dp"
axis and parameters are replicated).
"""

import re

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from cloud_tpu.parallel import runtime

DATA_AXIS = "dp"
MODEL_AXIS = "tp"
SEQUENCE_AXIS = "sp"


def _active_context_mesh():
    """The mesh of an enclosing `with Mesh(...)` block, if any.

    The legacy-but-idiomatic `with Mesh(devices, axes):` context sets a
    thread-local physical mesh that `jax.sharding` doesn't expose
    publicly. Two lookup paths, most-stable first: the internal module
    (fast, no deprecation machinery), then the public-but-deprecated
    `jax.interpreters.pxla` re-export — so a jax upgrade that moves the
    internal doesn't silently disable `with Mesh(...)` resolution
    (tests/unit/test_runtime.py pins this behavior)."""
    m = None
    try:
        from jax._src import mesh as _mesh_lib
        m = _mesh_lib.thread_resources.env.physical_mesh
    except (ImportError, AttributeError):
        import warnings
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore", DeprecationWarning)
                from jax.interpreters import pxla
                m = pxla.thread_resources.env.physical_mesh
        except (ImportError, AttributeError):
            # Both paths gone: don't silently ignore the user's
            # `with Mesh(...)` block — say why it can't be seen.
            warnings.warn(
                "cloud_tpu: this jax version does not expose the "
                "active Mesh context (jax._src.mesh.thread_resources "
                "or jax.interpreters.pxla); pass `mesh=` explicitly "
                "or use runtime.initialize().",
                RuntimeWarning, stacklevel=3)
            return None
    if m is not None and not m.empty:
        return m
    return None


def _resolve_mesh(mesh=None):
    """Explicit arg > enclosing `with Mesh(...)` context > ambient
    runtime mesh — most-local wins, like variable scoping."""
    if mesh is None:
        mesh = _active_context_mesh()
    if mesh is None:
        mesh = runtime.global_mesh()
    if mesh is None:
        raise RuntimeError(
            "No mesh: pass `mesh=`, enter a `with Mesh(...)` block, or "
            "initialize the ambient runtime "
            "(cloud_tpu.parallel.runtime.initialize).")
    return mesh


def batch_sharding(mesh=None, axis=DATA_AXIS):
    """Sharding for a batch: leading dim split over the data axis."""
    mesh = _resolve_mesh(mesh)
    if axis not in mesh.axis_names:
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, P(axis))


def replicated(mesh=None):
    """Fully-replicated sharding (default for parameters under pure DP)."""
    return NamedSharding(_resolve_mesh(mesh), P())


def shard_batch(batch, mesh=None, axis=DATA_AXIS):
    """Device-puts a (possibly nested) batch with the leading dim sharded
    over the data axis. Works for single-process use; multi-host feeding
    goes through `make_global_batch`."""
    sharding = batch_sharding(mesh, axis)
    runtime.record_h2d(batch)
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), batch)


def make_global_batch(local_batch, mesh=None, axis=DATA_AXIS,
                      sharding=None):
    """Assembles a global array from per-process local batches.

    On multi-host pods each process holds 1/num_processes of the global
    batch (the analogue of `tf.distribute` per-worker dataset sharding,
    reference cloud_fit/remote.py:84-88 delegates this to the strategy).
    `sharding` overrides the default batch layout (e.g. the
    steps_per_execution path assembles [spe, B, ...] stacks under
    P(None, dp)).
    """
    if sharding is None:
        mesh = _resolve_mesh(mesh)
        sharding = batch_sharding(mesh, axis)
    runtime.record_h2d(local_batch)
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(sharding, x),
        local_batch)


def param_sharding(params, rules=None, mesh=None):
    """Returns a sharding pytree for `params`.

    Args:
        params: Parameter pytree (or its shape-struct).
        rules: Optional list of (path_regex, PartitionSpec) pairs, first
            match wins — e.g. [(r".*attention.*kernel", P(None, "tp"))].
            Unmatched params are replicated. None means replicate all
            (pure data parallelism).
        mesh: Mesh override; default ambient.

    Returns:
        Pytree of `NamedSharding` congruent with `params`.
    """
    mesh = _resolve_mesh(mesh)
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    shardings = []
    for path, _ in flat:
        spec = P()
        if rules:
            path_str = path_string(path)
            for pattern, rule_spec in rules:
                if re.search(pattern, path_str):
                    spec = rule_spec
                    break
        shardings.append(NamedSharding(mesh, spec))
    return jax.tree_util.tree_unflatten(treedef, shardings)


def add_axis_sharding(params, shardings, mesh=None, axis=DATA_AXIS):
    """Adds `axis` to each leaf's spec on the first eligible dimension.

    Eligible = not already sharded and divisible by the axis size;
    leaves already sharded on `axis` (anywhere) or with no eligible
    dimension keep their layout. The generic building block for
    weight/moment sharding over the data axis (ZeRO / FSDP layouts).
    """
    mesh = _resolve_mesh(mesh)
    if axis not in mesh.axis_names:
        return shardings
    n = mesh.shape[axis]
    if n <= 1:
        return shardings

    def _mentions(spec_entry, name):
        if spec_entry is None:
            return False
        if isinstance(spec_entry, (tuple, list)):
            return name in spec_entry
        return spec_entry == name

    def leaf(p, s):
        spec = list(s.spec) + [None] * (p.ndim - len(s.spec))
        if any(_mentions(e, axis) for e in spec):
            return s  # already sharded on the data axis somewhere
        for i, dim in enumerate(p.shape):
            if spec[i] is None and dim % n == 0 and dim >= n:
                spec[i] = axis
                return NamedSharding(mesh, P(*spec))
        return s

    return jax.tree_util.tree_map(leaf, params, shardings)


def zero1_opt_sharding(params, param_shardings, mesh=None, axis=DATA_AXIS):
    """ZeRO-1 layout for params-shaped optimizer subtrees (moments).

    Each leaf's spec is its parameter's spec with the data axis added
    (see `add_axis_sharding`). Under pjit this makes XLA compute the
    optimizer update on 1/|dp| shards and all-gather the updates —
    optimizer memory drops to O(1/|dp|) per device (the ZeRO-1 trade:
    one all-gather per step for an |dp|-fold moment-memory saving)
    while parameters themselves stay in their data-parallel (replicated
    or tp-sharded) layout.
    """
    return add_axis_sharding(params, param_shardings, mesh, axis)


def fsdp_sharding(params, mesh=None, axis=DATA_AXIS, rules=None):
    """Fully-sharded (ZeRO-3 style) parameter layout.

    Every parameter is sharded over the data axis on its first eligible
    dimension, on top of any model-parallel `rules` (tp rules apply
    first; dp lands on a free dimension). XLA's SPMD partitioner then
    all-gathers weights where layers consume them and reduce-scatters
    gradients — per-device weight+grad+moment memory drops to
    O(1/|dp|), the pjit form of FSDP (How-to-Scale-Your-Model recipe:
    annotate shardings, let XLA insert the collectives).
    """
    base = param_sharding(params, rules=rules, mesh=mesh)
    return add_axis_sharding(params, base, mesh, axis)


def path_string(path):
    """Key path -> slash-separated string, e.g. "block_0/mlp_in/kernel"."""
    parts = []
    for entry in path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
        elif hasattr(entry, "name"):
            parts.append(str(entry.name))
        else:
            parts.append(str(entry))
    return "/".join(parts)


def local_batch_size(global_batch_size, mesh=None, axis=DATA_AXIS):
    """Per-process batch size for a global batch sharded on `axis`."""
    mesh = _resolve_mesh(mesh)
    num_processes = jax.process_count()
    if global_batch_size % num_processes:
        raise ValueError(
            "global_batch_size={} is not divisible by the process count "
            "{}.".format(global_batch_size, num_processes))
    return global_batch_size // num_processes
