"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The second long-context strategy next to `ring_attention` (SURVEY §5
"Long-context / sequence parallelism: Absent" — the reference scales by
DP replica count only; both strategies here are new surface). The
DeepSpeed-Ulysses recipe, re-expressed as XLA collectives:

    [B, S/n, H, D]  --all_to_all-->  [B, S, H/n, D]
         attention over the FULL sequence, local head subset
    [B, S, H/n, D]  --all_to_all-->  [B, S/n, H, D]

versus ring attention's n-step `ppermute` rotation. The trade:

- **Ulysses** does O(1) collective rounds (three tiled all-to-alls in,
  one out) and then runs the *unmodified* flash kernel over the full
  sequence — the attention inner loop is the single-device fast path,
  no per-chunk online-softmax merge. Per-device attention memory is
  O(S · H/n), i.e. it scales sequence length at fixed memory only while
  heads outnumber devices: n is capped at the head count.
- **Ring** needs only neighbor exchanges (perfect for the ICI torus),
  caps at much larger n (any divisor of S), and keeps K/V memory at
  O(S/n) — but pays n-1 rotation steps and does its softmax merge in
  HLO rather than inside the Pallas kernel.

Rule of thumb on a TPU slice: Ulysses for moderate sp degrees
(sp <= heads, e.g. one v5e-8 slice), ring for pod-scale context where
sp must exceed the head count or memory must stay strictly O(S/n).

All-to-all volume rides ICI: with the sequence sharded on "sp" and
batch on "dp", each exchange moves (n-1)/n of the local Q/K/V block
between the sp peers, the same links ring's ppermute uses.

Like `ring_attention`, everything is differentiable lax code —
`all_to_all`'s transpose is the inverse all-to-all, so `jax.grad`
flows through with the identical communication pattern reversed.
"""

import functools
import math

import jax
from jax.sharding import PartitionSpec as P


def ulysses_local(q, k, v, axis_name, causal=True, sm_scale=None,
                  impl="auto", mask=None):
    """Ulysses attention on per-device shards inside `shard_map`.

    Args:
        q, k, v: Local chunks [B, S_local, H, D]; the sequence dim is
            sharded over `axis_name`, heads are full.
        axis_name: Mesh axis of the sequence sharding. H must divide by
            the axis size (heads are scattered across it).
        causal / sm_scale: As in `cloud_tpu.ops.attention`.
        impl: Attention implementation for the full-sequence local
            compute ("auto" = flash kernel on TPU).
        mask: Optional [B, S_local] boolean key mask for this device's
            sequence chunk (True = attend). The local attention after
            the head/sequence exchange covers the FULL sequence, so the
            mask chunks are all-gathered along `axis_name` — [B, S]
            bools, a negligible fraction of the q/k/v all-to-all bytes
            — and handed to the kernel's native masked path.

    Returns:
        Local output chunk [B, S_local, H, D], same dtype as q.
    """
    from cloud_tpu import ops
    from cloud_tpu.ops.attention import repeat_kv

    n = jax.lax.psum(1, axis_name)
    heads = q.shape[2]
    h_kv = k.shape[2]
    if heads % n:
        raise ValueError(
            "Ulysses needs head count {} divisible by the {!r} axis "
            "size {} (use ring attention beyond that).".format(
                heads, axis_name, n))
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])

    # GQA: keep K/V at H_kv width through the exchange when the kv
    # heads split over the axis too — the all-to-all then moves
    # H_kv/H as many K/V bytes and the local flash kernel takes the
    # grouped layout natively. Otherwise (h_kv < n) expand first.
    if h_kv != heads and h_kv % n:
        k = repeat_kv(k, heads)
        v = repeat_kv(v, heads)

    def scatter_heads(x):  # [B, S/n, H', D] -> [B, S, H'/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

    def scatter_seq(x):  # [B, S, H/n, D] -> [B, S/n, H, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

    full_mask = None
    if mask is not None:
        full_mask = jax.lax.all_gather(mask.astype(bool), axis_name,
                                       axis=1, tiled=True)
    out = ops.attention(scatter_heads(q), scatter_heads(k),
                        scatter_heads(v), causal=causal,
                        sm_scale=sm_scale, impl=impl, mask=full_mask)
    return scatter_seq(out)


def ulysses_attention(q, k, v, mesh=None, axis="sp", causal=True,
                      sm_scale=None, batch_axis="auto", impl="auto",
                      mask=None):
    """Ulysses sequence-parallel attention over global [B, S, H, D].

    The standalone entry point, API-compatible with
    `sequence_parallel_attention` (ring): shards the sequence dim over
    `axis` with `shard_map`, all-to-alls into head-sharded
    full-sequence layout, runs the flash/reference kernel, and
    all-to-alls back. S and H must both divide by the axis size.
    `mask` is the global [B, S] boolean key mask (True = attend); it is
    sharded over `axis` and re-gathered inside the shard for the
    full-sequence local kernel.

    batch_axis: Mesh axis the batch dim is sharded over — "auto" picks
    the ambient data axis ("dp") when present, so Ulysses (sp) and data
    (dp) parallelism compose without replicated compute. (No head_axis
    knob: the sp all-to-all owns the head dim; combine tp with ring
    instead when heads must stay tp-sharded.)
    """
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    from cloud_tpu.parallel import sharding as _sharding

    mesh = _sharding._resolve_mesh(mesh)
    if axis not in mesh.axis_names:
        raise ValueError(
            "Mesh axes {} have no {!r} axis for sequence parallelism; "
            "initialize the runtime with e.g. axis_names=('dp', 'sp')."
            .format(tuple(mesh.axis_names), axis))
    axis_size = mesh.shape[axis]
    batch, seq, heads = q.shape[:3]
    if seq % axis_size:
        raise ValueError(
            "Sequence length {} must divide the {!r} axis size {}."
            .format(seq, axis, axis_size))
    if heads % axis_size:
        raise ValueError(
            "Ulysses needs head count {} divisible by the {!r} axis "
            "size {} (use ring attention beyond that).".format(
                heads, axis, axis_size))

    if batch_axis == "auto":
        batch_axis = (_sharding.DATA_AXIS
                      if _sharding.DATA_AXIS in mesh.axis_names else None)
        if batch_axis is not None and batch % mesh.shape[batch_axis]:
            batch_axis = None
    elif batch_axis is not None:
        if batch_axis not in mesh.axis_names:
            raise ValueError(
                "Mesh axes {} have no {!r} batch axis.".format(
                    tuple(mesh.axis_names), batch_axis))
        if batch % mesh.shape[batch_axis]:
            raise ValueError(
                "Batch size {} is not divisible by the {!r} axis size "
                "{}.".format(batch, batch_axis, mesh.shape[batch_axis]))

    from cloud_tpu.parallel.ring_attention import sharded_sp_call

    spec = P(batch_axis, axis, None, None)
    fn = functools.partial(ulysses_local, axis_name=axis, causal=causal,
                           sm_scale=sm_scale, impl=impl)
    return sharded_sp_call(shard_map, fn, mesh, spec, axis, q, k, v,
                           mask)
