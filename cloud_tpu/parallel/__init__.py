"""Parallelism: ambient mesh runtime, sharding specs, sequence parallel.

The TPU-native substrate replacing the reference's delegation to
`tf.distribute` strategies (SURVEY §2.3/§2.4): explicit
`jax.sharding.Mesh` + NamedSharding layouts with XLA collectives over
ICI/DCN, plus ring attention for long-context sequence parallelism
(absent from the reference; first-class here).
"""

from cloud_tpu.parallel import compile_cache
from cloud_tpu.parallel import runtime
from cloud_tpu.parallel import sharding
# NOTE: the schedule-level `pipeline` function stays in its submodule
# (`parallel.pipeline.pipeline`) — importing it here would shadow the
# submodule attribute. The global-array entry point is exported.
from cloud_tpu.parallel.pipeline import pipeline_apply
from cloud_tpu.parallel.ring_attention import ring_attention
from cloud_tpu.parallel.ring_attention import sequence_parallel_attention
from cloud_tpu.parallel.ulysses import ulysses_attention, ulysses_local

# The names model code dispatches on (transformer/llama attention_impl).
SEQUENCE_PARALLEL_IMPLS = ("ring", "ulysses")


def sp_attention(impl, q, k, v, causal=True, mask=None):
    """Sequence-parallel attention dispatch, shared by every model.

    One place owns the impl-name set and the padding-mask contract so
    the model families can't drift apart. Both impls accept GQA
    (k/v with H_kv < H heads): ulysses exchanges at H_kv width when it
    divides the sp axis; ring expands to H before rotating. Both accept
    a [B, S] boolean key mask (True = attend, the `flash_attention`
    padded-batch contract): ring rotates the mask chunks with k/v,
    ulysses re-gathers them for the full-sequence local kernel — so
    Keras-parity padded batches stay on the sequence-parallel path.
    """
    if impl == "ring":
        return sequence_parallel_attention(q, k, v, causal=causal,
                                           mask=mask)
    if impl == "ulysses":
        return ulysses_attention(q, k, v, causal=causal, mask=mask)
    raise ValueError(
        "Unknown sequence-parallel impl {!r}; expected one of {}.".format(
            impl, SEQUENCE_PARALLEL_IMPLS))


__all__ = ["compile_cache", "runtime", "sharding", "pipeline_apply",
           "ring_attention", "sequence_parallel_attention",
           "ulysses_attention", "ulysses_local",
           "SEQUENCE_PARALLEL_IMPLS", "sp_attention"]
