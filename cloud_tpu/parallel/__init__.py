"""Parallelism: ambient mesh runtime, sharding specs, sequence parallel.

The TPU-native substrate replacing the reference's delegation to
`tf.distribute` strategies (SURVEY §2.3/§2.4): explicit
`jax.sharding.Mesh` + NamedSharding layouts with XLA collectives over
ICI/DCN, plus ring attention for long-context sequence parallelism
(absent from the reference; first-class here).
"""

from cloud_tpu.parallel import runtime
from cloud_tpu.parallel import sharding
# NOTE: the schedule-level `pipeline` function stays in its submodule
# (`parallel.pipeline.pipeline`) — importing it here would shadow the
# submodule attribute. The global-array entry point is exported.
from cloud_tpu.parallel.pipeline import pipeline_apply
from cloud_tpu.parallel.ring_attention import ring_attention
from cloud_tpu.parallel.ring_attention import sequence_parallel_attention

__all__ = ["runtime", "sharding", "pipeline_apply",
           "ring_attention", "sequence_parallel_attention"]
