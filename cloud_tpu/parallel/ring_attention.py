"""Ring attention: sequence/context parallelism over the device mesh.

Long-context support the reference never had (SURVEY §5 "Long-context /
sequence parallelism: Absent"): sequence length there is never a concept
and scaling is DP-only. Here long context is first-class — the sequence
axis of Q/K/V is sharded over a mesh axis ("sp"), each device keeps its
local Q chunk resident, and K/V chunks rotate around the ring via
`jax.lax.ppermute` (neighbor exchange rides the ICI torus links; no
all-gather, so per-device memory is O(S/n) instead of O(S)).

Per ring step each device computes blockwise attention of its Q chunk
against the visiting K/V chunk and folds the result into a running
(output, logsumexp) pair with the numerically-stable online-softmax
merge — the same recurrence the Pallas flash kernel uses across k-blocks
(cloud_tpu/ops/attention.py), lifted one level up to mesh shards. The
per-chunk einsums are plain XLA matmuls (MXU-tiled by the compiler);
chunks strictly above the causal diagonal skip the compute via
`lax.cond`.

Everything is pure lax (scan + ppermute), so `jax.grad` differentiates
straight through it — ppermute's transpose is the reverse permute, which
XLA again schedules on ICI. The scan body is `jax.checkpoint`ed: the
backward pass recomputes per-chunk attention instead of keeping
O(steps) residuals, the standard flash/ring memory trade.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

_NEG_INF = -1e30


def _chunk_attention(q, k, v, row_offset, col_offset, kv_len, causal,
                     sm_scale, key_mask=None):
    """Attention of a Q chunk against one K/V chunk, with logsumexp.

    Args:
        q: [B, Sq, H, D] local query chunk.
        k, v: [B, Sk, H, D] visiting key/value chunk.
        row_offset / col_offset: Global positions of element 0 of the
            chunks (traced values; the ring rotates col_offset).
        kv_len: True global K/V length (masks ring padding).
        causal / sm_scale: As in `ring_attention`.
        key_mask: Optional [B, Sk] per-example key validity for THIS
            visiting chunk (True = attend); rotates with k/v.

    Returns:
        (out, lse): normalized chunk output [B, Sq, H, D] and its
        logsumexp [B, Sq, H] (−inf rows ⇒ fully-masked chunk).
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * sm_scale
    rows = row_offset + jnp.arange(q.shape[1])
    cols = col_offset + jnp.arange(k.shape[1])
    mask = (cols < kv_len)[None, :]
    if causal:
        mask = mask & (cols[None, :] <= rows[:, None])
    mask = mask[None, None]                 # [1, 1, {1|Sq}, Sk]
    if key_mask is not None:
        mask = mask & key_mask[:, None, None, :]  # [B, 1, {1|Sq}, Sk]
    logits = jnp.where(mask, logits, _NEG_INF)

    m = jnp.max(logits, axis=-1)                      # [B, H, Sq]
    p = jnp.exp(logits - m[..., None])
    l = jnp.sum(p, axis=-1)                           # [B, H, Sq]
    masked = m <= _NEG_INF / 2
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    out = out / safe_l.transpose(0, 2, 1)[..., None]
    lse = jnp.where(masked, -jnp.inf, m + jnp.log(safe_l))
    return out, lse.transpose(0, 2, 1)                # [B, Sq, H]


def _merge(o1, lse1, o2, lse2):
    """Online-softmax merge of two normalized partial attentions."""
    m = jnp.maximum(lse1, lse2)
    m = jnp.where(jnp.isneginf(m), 0.0, m)            # both empty: avoid nan
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    total = w1 + w2
    safe = jnp.where(total == 0.0, 1.0, total)
    out = (o1 * w1[..., None] + o2 * w2[..., None]) / safe[..., None]
    lse = m + jnp.log(safe)
    lse = jnp.where(total == 0.0, -jnp.inf, lse)
    return out, lse


def ring_attention(q, k, v, axis_name, causal=True, sm_scale=None,
                   kv_len=None, mask=None):
    """Sequence-parallel attention inside `shard_map`.

    Call this from a `shard_map`-ed function whose inputs shard the
    sequence dim of q/k/v over `axis_name`. Each device holds
    [B, S/n, H, D] of each operand; K/V rotate n steps around the ring.

    Args:
        q, k, v: Local chunks, [B, S_local, H, D].
        axis_name: Mesh axis the sequence is sharded over.
        causal: Autoregressive masking in *global* positions.
        sm_scale: Softmax scale; default 1/sqrt(D).
        kv_len: True global sequence length when the padded global length
            (S_local * axis_size) exceeds it; default no padding.
        mask: Optional [B, S_local] boolean key mask for THIS device's
            local sequence chunk (True = attend) — the per-example
            padding contract of `flash_attention`, sharded with the
            sequence. The mask chunk rotates around the ring alongside
            its k/v chunk. Rows whose keys end up all masked output
            zeros (flash convention): although the finite _NEG_INF
            makes a fully-masked chunk's softmax a uniform average
            locally, `_chunk_attention` flags such rows with an lse of
            −inf, and `_merge` weighs an −inf-lse contribution to
            exactly zero — so the uniform average never reaches the
            output (pinned by
            tests/unit/test_ring_attention.py::test_fully_masked_rows).
            Any pattern is supported, not just contiguous prefixes.

    Returns:
        Local output chunk [B, S_local, H, D], same dtype as q.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    axis_size = jax.lax.psum(1, axis_name)
    my_index = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    if kv_len is None:
        kv_len = s_local * axis_size
    if mask is not None:
        mask = mask.astype(bool)

    row_offset = my_index * s_local
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def compute_chunk(out, lse, ck, cv, cm, chunk_index):
        """Folds one visiting chunk into (out, lse), skipping the
        attention compute entirely for chunks strictly above the causal
        diagonal (their mask is all-False; `lax.cond` makes that a real
        skip, not a masked full-price einsum). `cm` is the visiting
        chunk's key mask (None when no padding mask is in play — a
        static choice, so the no-mask path compiles identically to
        before)."""
        def visit(out, lse, ck, cv, cm):
            chunk_out, chunk_lse = _chunk_attention(
                q, ck, cv, row_offset, chunk_index * s_local, kv_len,
                causal, sm_scale, key_mask=cm)
            return _merge(out, lse, chunk_out, chunk_lse)

        if not causal:
            return visit(out, lse, ck, cv, cm)
        fully_masked = chunk_index * s_local > row_offset + s_local - 1
        return jax.lax.cond(fully_masked,
                            lambda out, lse, ck, cv, cm: (out, lse),
                            visit, out, lse, ck, cv, cm)

    # Derived from q (not fresh literals) so the carry is marked varying
    # over `axis_name` under shard_map's per-axis type system.
    out0 = (q * 0).astype(jnp.float32)
    lse0 = jnp.sum(out0, axis=-1) - jnp.inf           # [B, Sq, H]

    # Step 0: the locally-resident chunk, no rotation needed.
    out, lse = compute_chunk(out0, lse0, k, v, mask, my_index)

    @jax.checkpoint
    def body(carry, step):
        # `mask is None` is static: the carry simply has no mask leaf
        # on the unmasked path (None is an empty pytree).
        out, lse, ck, cv, cm = carry
        ck = jax.lax.ppermute(ck, axis_name, perm)
        cv = jax.lax.ppermute(cv, axis_name, perm)
        if cm is not None:
            cm = jax.lax.ppermute(cm, axis_name, perm)
        # After `step` forward rotations, this device holds the chunk
        # originally resident on (my_index - step) mod n.
        chunk_index = jax.lax.rem(my_index - step + axis_size, axis_size)
        out, lse = compute_chunk(out, lse, ck, cv, cm, chunk_index)
        return (out, lse, ck, cv, cm), None

    (out, _, _, _, _), _ = jax.lax.scan(
        body, (out, lse, k, v, mask), jnp.arange(1, axis_size))
    return out.astype(q.dtype)


def sharded_sp_call(shard_map_fn, fn, mesh, spec, seq_axis, q, k, v,
                     mask):
    """Shared masked/unmasked shard_map entry for the sp strategies.

    One place owns the mask leg of the entry contract (shape check,
    [B, S] spec over (batch, sequence) axes, bool cast) so ring and
    ulysses can't drift apart.
    """
    if mask is None:
        return shard_map_fn(fn, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec)(q, k, v)
    expect = (q.shape[0], q.shape[1])
    if mask.shape != expect:
        raise ValueError(
            "mask must be [batch, seq] = {}; got {}.".format(
                expect, mask.shape))
    mask_spec = P(spec[0], seq_axis)
    masked = lambda q, k, v, m: fn(q, k, v, mask=m)
    return shard_map_fn(masked, mesh=mesh,
                        in_specs=(spec, spec, spec, mask_spec),
                        out_specs=spec)(q, k, v, mask.astype(bool))


def sequence_parallel_attention(q, k, v, mesh=None, axis="sp", causal=True,
                                sm_scale=None, batch_axis="auto",
                                head_axis="auto", mask=None):
    """Ring attention over global [B, S, H, D] arrays on a mesh.

    The standalone entry point: shards the sequence dim over `axis` with
    `shard_map` and runs `ring_attention` per shard. S must divide by the
    axis size (pad upstream; causal masking makes right-padding safe for
    all non-pad rows). `mask` is the global [B, S] boolean key mask
    (True = attend, the `flash_attention` padded-batch contract); it is
    sharded over `axis` with the sequence and rotates with k/v.

    batch_axis: Mesh axis the batch dim is sharded over — "auto" picks
    the ambient data axis ("dp") when the mesh has one, so ring (sp) and
    data (dp) parallelism compose without replicated compute.

    head_axis: Mesh axis the head dim is sharded over — "auto" picks the
    ambient model axis ("tp") when the mesh has one and the head count
    divides it. Heads are independent in attention, so this composes
    ring (sp) with Megatron-style tensor parallelism (tp-sharded qkv
    heads stay resident; no cross-tp gather).
    """
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    from cloud_tpu.parallel import sharding as _sharding_resolve

    if k.shape[2] != q.shape[2]:
        # GQA: the ring rotates K/V at full q-head width (no native
        # grouped path yet — the per-chunk einsums assume matching
        # heads), so expand before sharding. Ulysses keeps H_kv width;
        # prefer it when kv heads divide the sp axis.
        from cloud_tpu.ops.attention import repeat_kv
        k = repeat_kv(k, q.shape[2])
        v = repeat_kv(v, q.shape[2])

    mesh = _sharding_resolve._resolve_mesh(mesh)
    if axis not in mesh.axis_names:
        raise ValueError(
            "Mesh axes {} have no {!r} axis for sequence parallelism; "
            "initialize the runtime with e.g. axis_names=('dp', 'sp').".format(
                tuple(mesh.axis_names), axis))
    axis_size = mesh.shape[axis]
    seq = q.shape[1]
    if seq % axis_size:
        raise ValueError(
            "Sequence length {} must divide the {!r} axis size {}.".format(
                seq, axis, axis_size))

    from cloud_tpu.parallel import sharding as _sharding

    def _resolve_axis(value, default_axis, dim, what):
        """auto -> default axis when present+divisible; explicit axes
        are validated, only the implicit path gets silent fallback."""
        if value == "auto":
            resolved = (default_axis
                        if default_axis in mesh.axis_names else None)
            if resolved is not None and dim % mesh.shape[resolved]:
                resolved = None
            return resolved
        if value is None:
            return None
        if value not in mesh.axis_names:
            raise ValueError(
                "Mesh axes {} have no {!r} {} axis.".format(
                    tuple(mesh.axis_names), value, what))
        if dim % mesh.shape[value]:
            raise ValueError(
                "{} size {} is not divisible by the {!r} axis size "
                "{}.".format(what.capitalize(), dim, value,
                             mesh.shape[value]))
        return value

    batch_axis = _resolve_axis(batch_axis, _sharding.DATA_AXIS,
                               q.shape[0], "batch")
    head_axis = _resolve_axis(head_axis, _sharding.MODEL_AXIS,
                              q.shape[2], "head")
    spec = P(batch_axis, axis, head_axis, None)
    fn = functools.partial(ring_attention, axis_name=axis, causal=causal,
                           sm_scale=sm_scale, kv_len=seq)
    return sharded_sp_call(shard_map, fn, mesh, spec, axis, q, k, v,
                           mask)
