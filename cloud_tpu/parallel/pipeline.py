"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

Another capability absent from the reference (SURVEY §2.3: "tensor
parallelism, pipeline parallelism ... Nothing in the tree implements or
references them") built here as a first-class mesh axis. The design is
the shard_map pipelining pattern from the public scaling playbook: each
device along the "pp" axis holds ONE stage's parameters, activations hop
stage-to-stage with `jax.lax.ppermute` (one neighbor transfer per tick,
riding ICI), and a `lax.scan` over ticks runs the M-microbatch / n-stage
schedule in M + n - 1 ticks — device utilization M / (M + n - 1), the
standard GPipe bubble.

Everything is lax-traceable, so `jax.grad` differentiates through the
whole schedule (ppermute transposes to the reverse hop; the scan body is
`jax.checkpoint`ed so backward recomputes a tick instead of storing
every intermediate).

Usage inside shard_map (see `pipeline_apply` for the global-array entry
point):

    def stage_fn(stage_params, x):          # one pipeline stage
        ...
    y = pipeline(stage_fn, stage_params, x_microbatches, axis_name="pp")
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def pipeline(stage_fn, stage_params, microbatches, axis_name):
    """Runs the GPipe schedule inside `shard_map`.

    Args:
        stage_fn: `(stage_params, x) -> y` applying one stage; input and
            output must have the same shape/dtype (the classic pipeline
            contract — embed/head belong to stages themselves).
        stage_params: This device's stage parameters (pytree; under
            shard_map, shard the stacked [n_stages, ...] params on
            `axis_name` so each device sees its own stage's slice with
            the leading stage axis collapsed... see `pipeline_apply`).
        microbatches: [M, mb, ...] microbatched input, resident on every
            device (replicated over `axis_name`).
        axis_name: The pipeline mesh axis.

    Returns:
        [M, mb, ...] outputs of the final stage, replicated over
        `axis_name`.
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage_index = jax.lax.axis_index(axis_name)
    num_micro = microbatches.shape[0]
    total_ticks = num_micro + n_stages - 1

    # i -> i+1 activation hop; the wrap-around edge (last -> 0) carries
    # garbage that stage 0 always overwrites with a fresh microbatch.
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    # The scan carry must be typed device-varying over the pp axis from
    # tick 0 (stage outputs are varying), hence the pvary casts.
    def _pvary(v):
        try:
            return jax.lax.pcast(v, (axis_name,), to="varying")
        except AttributeError:
            try:  # jax < 0.8
                return jax.lax.pvary(v, (axis_name,))
            except AttributeError:  # older jax: vma typing absent anyway
                return v

    carry0 = _pvary(jnp.zeros_like(microbatches[0]))
    outputs0 = _pvary(jnp.zeros_like(microbatches))

    @jax.checkpoint
    def tick(state, t):
        carry, outputs = state
        # Stage 0 ingests microbatch t (clamped; ticks >= M feed dummy
        # work that never reaches the output buffer).
        feed = microbatches[jnp.minimum(t, num_micro - 1)]
        x = jnp.where(stage_index == 0, feed, carry)
        y = stage_fn(stage_params, x)
        # The last stage finished microbatch t - (n-1) at tick t.
        mb_done = t - (n_stages - 1)
        is_last = stage_index == n_stages - 1
        outputs = jax.lax.cond(
            jnp.logical_and(is_last, mb_done >= 0),
            lambda o: o.at[jnp.maximum(mb_done, 0)].set(y),
            lambda o: o,
            outputs)
        carry = jax.lax.ppermute(y, axis_name, perm)
        return (carry, outputs), None

    (carry, outputs), _ = jax.lax.scan(
        tick, (carry0, outputs0), jnp.arange(total_ticks))
    # Only the last stage holds real outputs; broadcast them to every
    # stage so the result is replicated over the pp axis (psum of
    # one-hot contributions — a single all-reduce at the end).
    is_last = (stage_index == n_stages - 1).astype(outputs.dtype)
    return jax.lax.psum(outputs * is_last, axis_name)


def pipeline_apply(stage_fn, stacked_params, x, num_microbatches,
                   mesh=None, axis="pp", batch_axis="auto"):
    """Pipeline-parallel apply over global arrays.

    Args:
        stage_fn: `(stage_params, x) -> y`, one stage (same-shape in/out).
        stacked_params: Pytree whose leaves are stacked along a leading
            [n_stages] axis — stage i's params at index i. Sharded over
            `axis` so each device keeps only its stage.
        x: [B, ...] global input batch.
        num_microbatches: M; B must divide by it.
        mesh: Mesh override; default ambient.
        axis: Pipeline mesh axis name.
        batch_axis: Mesh axis the microbatch dim is sharded over —
            "auto" picks the ambient data axis ("dp") when the mesh has
            one and the per-microbatch size divides it, so pp composes
            with dp in ONE mesh: each dp group runs the full schedule
            on its batch shard, stage params replicated across dp (the
            dp gradient psum over stage grads is inserted by shard_map's
            transpose). None forces replication (pure pp).

    Returns:
        [B, ...] output of the last stage.
    """
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    from cloud_tpu.parallel import sharding as sharding_lib

    mesh = sharding_lib._resolve_mesh(mesh)
    if axis not in mesh.axis_names:
        raise ValueError(
            "Mesh axes {} have no {!r} axis for pipeline parallelism."
            .format(tuple(mesh.axis_names), axis))
    n_stages = mesh.shape[axis]
    batch = x.shape[0]
    if num_microbatches < 1 or batch % num_microbatches:
        raise ValueError(
            "Batch size {} is not divisible by num_microbatches {}."
            .format(batch, num_microbatches))
    micro_b = batch // num_microbatches

    if batch_axis == "auto":
        batch_axis = (sharding_lib.DATA_AXIS
                      if sharding_lib.DATA_AXIS in mesh.axis_names
                      else None)
        if batch_axis is not None and micro_b % mesh.shape[batch_axis]:
            # Falling back to replication is correct but duplicates the
            # whole schedule on every dp group — say so instead of
            # silently burning dp-fold compute.
            import logging
            logging.getLogger("cloud_tpu").warning(
                "pipeline_apply: microbatch size %d does not divide the "
                "'%s' axis (size %d); running the pipeline REPLICATED "
                "across it. Raise the batch or lower num_microbatches "
                "to restore data parallelism.",
                micro_b, batch_axis, mesh.shape[batch_axis])
            batch_axis = None
    elif batch_axis is not None:
        if batch_axis not in mesh.axis_names:
            raise ValueError(
                "Mesh axes {} have no {!r} batch axis.".format(
                    tuple(mesh.axis_names), batch_axis))
        if micro_b % mesh.shape[batch_axis]:
            raise ValueError(
                "Microbatch size {} is not divisible by the {!r} axis "
                "size {}.".format(micro_b, batch_axis,
                                  mesh.shape[batch_axis]))

    def check_leading(leaf):
        if leaf.shape[0] != n_stages:
            raise ValueError(
                "stacked_params leaves must have leading dim n_stages={}"
                "; got shape {}.".format(n_stages, leaf.shape))
        return leaf

    jax.tree_util.tree_map(check_leading, stacked_params)

    micro = x.reshape((num_microbatches, micro_b) + x.shape[1:])

    def local_fn(stage_params, microbatches):
        # shard_map keeps the sharded leading stage axis as size 1;
        # collapse it so stage_fn sees this stage's params directly.
        own = jax.tree_util.tree_map(lambda l: l[0], stage_params)
        return pipeline(stage_fn, own, microbatches, axis_name=axis)

    params_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    micro_spec = P(None, batch_axis)
    out = shard_map(
        local_fn, mesh=mesh,
        in_specs=(params_spec, micro_spec),
        out_specs=micro_spec)(stacked_params, micro)
    return out.reshape((batch,) + out.shape[2:])
