"""Persistent XLA compilation cache + AOT executable helpers.

The reference delegates compilation entirely to TF; a TPU-native stack
pays trace + XLA compile on every cold start. This module makes that
cost a managed resource in three pieces:

1. `enable()` — framework-level persistent-compile-cache enablement
   (previously hardcoded inside bench.py's worker). Entries are scoped
   to a `jax-<ver>-jaxlib-<ver>` subdirectory, so upgrading either
   package starts a fresh namespace instead of deserializing stale
   executables — version invalidation by construction.
2. Cache hit/miss stats — a `jax.monitoring` listener feeds persistent
   cache hits into `runtime.compile_stats()["cache_hits"]` so tests and
   bench can assert "the second process compiled nothing" as a counted
   invariant (the same doctrine as `runtime.transfer_stats()`).
3. `serialize_executable` / `deserialize_executable` — thin wrappers
   over the JAX AOT serialization API for shipping a compiled step to
   another same-topology process (deploy-time warm start).

Env contract:
    CLOUD_TPU_COMPILE_CACHE   cache directory override. Beats the
                              directory passed to `enable()`. The
                              values "" / "0" / "off" / "none" /
                              "false" disable the cache entirely.
"""

import logging
import os

logger = logging.getLogger("cloud_tpu")

ENV_VAR = "CLOUD_TPU_COMPILE_CACHE"
_DISABLE_VALUES = ("", "0", "off", "none", "false", "disabled")

_enabled_dir = None          # resolved, version-scoped directory
_listener_installed = False
_counting = False            # listener no-ops unless enable() succeeded
_event_stats = {"persistent_hits": 0, "persistent_misses": 0}


def version_scope():
    """The cache-invalidation namespace: jax + jaxlib versions."""
    import jax
    try:
        import jaxlib
        jaxlib_version = jaxlib.__version__
    except Exception:  # pragma: no cover - jaxlib always ships with jax
        jaxlib_version = "unknown"
    return "jax-{}-jaxlib-{}".format(jax.__version__, jaxlib_version)


def resolve_dir(cache_dir=None):
    """Resolves the cache root: env override beats the argument.

    Returns None when disabled (no directory anywhere, or an explicit
    disable value in the env). The returned path includes the version
    scope subdirectory.
    """
    env = os.environ.get(ENV_VAR)
    if env is not None:
        if env.strip().lower() in _DISABLE_VALUES:
            return None
        cache_dir = env
    if not cache_dir:
        return None
    return os.path.join(os.path.expanduser(cache_dir), version_scope())


def enable(cache_dir=None, min_compile_time_secs=0.0):
    """Turns on the persistent compilation cache for this process.

    Args:
        cache_dir: Cache root. `CLOUD_TPU_COMPILE_CACHE` (when set)
            overrides it; disable values there win over everything.
        min_compile_time_secs: Persist executables whose compile took at
            least this long. The default 0.0 persists everything — on
            the tunneled-CPU bench even sub-second compiles are worth
            skipping, and the entry-size floor is lifted for the same
            reason.

    Returns:
        The resolved version-scoped directory, or None when disabled.
    """
    global _enabled_dir, _counting
    resolved = resolve_dir(cache_dir)
    if resolved is None:
        _counting = False
        _enabled_dir = None
        return None

    import jax

    os.makedirs(resolved, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", resolved)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_time_secs))
    try:
        # Without this, small (CPU/test) executables fall under the
        # default size floor and never persist, which would make the
        # hit-after-restart invariant silently untestable.
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # pragma: no cover - option absent on old jax
        pass
    try:
        # jax memoizes the is-the-cache-used decision per process at
        # the FIRST compile — enabling after anything has compiled
        # would otherwise be a silent no-op (no writes, no events).
        # Drop the memo so the new directory takes effect now.
        from jax._src import compilation_cache as _jax_cc
        _jax_cc.reset_cache()
    except Exception:  # pragma: no cover - private API moved
        logger.warning("could not reset jax's compilation-cache memo; "
                       "cache may stay off if jit ran before enable().")
    _enabled_dir = resolved
    _counting = True
    _install_listener()
    logger.info("Persistent compile cache enabled at %s", resolved)
    return resolved


def disable():
    """Stops persisting and counting (test isolation)."""
    global _enabled_dir, _counting
    _counting = False
    if _enabled_dir is None:
        return
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", None)
        from jax._src import compilation_cache as _jax_cc
        _jax_cc.reset_cache()
    except Exception:  # pragma: no cover
        pass
    _enabled_dir = None


def is_enabled():
    return _enabled_dir is not None


def cache_dir():
    """The active version-scoped cache directory, or None."""
    return _enabled_dir


def _install_listener():
    """Registers the (idempotent, irrevocable) jax.monitoring hook.

    jax has no unregister API, so the listener is installed once and
    gated on `_counting`; `disable()` just flips the gate. The private
    `jax._src.monitoring` import is deliberately failure-tolerant — on
    a jax that moved it, cache_hits stays 0 instead of crashing.
    """
    global _listener_installed
    if _listener_installed:
        return
    try:
        from jax._src import monitoring
    except Exception:  # pragma: no cover - private API moved
        logger.warning("jax monitoring API unavailable; persistent "
                       "cache hit counting disabled.")
        return

    def _on_event(event, **kwargs):
        if not _counting:
            return
        if event == "/jax/compilation_cache/cache_hits":
            _event_stats["persistent_hits"] += 1
            from cloud_tpu.parallel import runtime
            runtime.record_compile(cache_hits=1)
        elif event == "/jax/compilation_cache/cache_misses":
            _event_stats["persistent_misses"] += 1
            # A miss is a compile-from-scratch the persistent cache
            # could not absorb; the graftsan observer attributes it to
            # the dispatch site (the hit path notifies through
            # record_compile above).
            from cloud_tpu.parallel import runtime
            observer = runtime.get_observer()
            if observer is not None:
                observer.on_cache_miss()

    monitoring.register_event_listener(_on_event)
    _listener_installed = True


def stats():
    """Persistent-cache event counts (process-wide, since enable())."""
    return dict(_event_stats)


def reset_stats():
    for key in _event_stats:
        _event_stats[key] = 0


# --------------------------------------------------------------------------
# AOT executable serialization (deploy-time warm start).

def serialize_executable(compiled):
    """Serializes a `jax.stages.Compiled` to a portable triple.

    Returns `(payload_bytes, in_tree, out_tree)` — exactly what
    `deserialize_executable` needs. Raises whatever the JAX AOT API
    raises when the executable is not serializable on this backend.
    """
    from jax.experimental import serialize_executable as se
    return se.serialize(compiled)


def deserialize_executable(triple):
    """Loads a `(payload, in_tree, out_tree)` triple back into a
    callable Compiled. Only valid on a same-topology process with the
    same jax/jaxlib versions (the same constraint the version-scoped
    cache directory encodes)."""
    from jax.experimental import serialize_executable as se
    payload, in_tree, out_tree = triple
    return se.deserialize_and_load(payload, in_tree, out_tree)


def save_executable(path, compiled):
    """Serializes `compiled` to `path` (pickle of the AOT triple)."""
    import pickle
    triple = serialize_executable(compiled)
    with open(path, "wb") as f:
        pickle.dump(triple, f)
    return path


def load_executable(path):
    """Loads an executable previously written by `save_executable`."""
    import pickle
    with open(path, "rb") as f:
        triple = pickle.load(f)
    return deserialize_executable(triple)
