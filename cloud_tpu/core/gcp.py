"""Google Cloud platform knowledge tables, TPU-first.

Redesign of the reference's hard-coded GCP tables
(reference: src/python/tensorflow_cloud/core/gcp.py). Differences:

- TPU is the primary target: accelerator-type mapping covers v2-v5p and
  emits Cloud TPU API slice strings (``v5litepod-8`` etc.) instead of the
  reference's two CAIP-era enum values (reference gcp.py:88-89).
- The ~170-tuple GPU whitelist (reference gcp.py:123-406) is expressed as
  the generative rule it encodes: a machine-type family table plus a
  per-(gpu, count) max-CPU-cores limit.
- TPU runtime versions replace the TF-2.1-only gate
  (reference gcp.py:119-120).
"""

import os
import re


def get_project_name():
    """Returns the current GCP project name.

    Resolution order: explicit env (``GOOGLE_CLOUD_PROJECT`` /
    ``GCP_PROJECT``), then application-default credentials — mirroring
    reference gcp.py:25-32 (which uses ``google.auth.default()`` only) but
    usable on machines without the google-auth package installed.
    """
    for var in ("GOOGLE_CLOUD_PROJECT", "GCP_PROJECT", "PROJECT_ID"):
        project = os.environ.get(var)
        if project:
            return project
    try:
        import google.auth  # pylint: disable=g-import-not-at-top
        _, project = google.auth.default()
    except Exception as e:  # ImportError or DefaultCredentialsError
        raise RuntimeError(
            "Could not determine the GCP project id: application default "
            "credentials are unavailable and none of GOOGLE_CLOUD_PROJECT / "
            "GCP_PROJECT / PROJECT_ID are set.") from e
    if not project:
        raise RuntimeError(
            "Could not determine the GCP project id from application "
            "default credentials. Set GOOGLE_CLOUD_PROJECT.")
    return project


def get_region():
    """Returns the default compute region for job submission.

    Env-overridable (``CLOUD_TPU_REGION``); defaults to ``us-central1``
    like reference gcp.py:73-75.
    """
    return os.environ.get("CLOUD_TPU_REGION", "us-central1")


def get_zone():
    """Returns the default zone for TPU-VM provisioning."""
    return os.environ.get("CLOUD_TPU_ZONE", get_region() + "-a")


# Cloud TPU API accelerator-type prefixes per generation.
_TPU_SLICE_PREFIX = {
    "TPU_V2": "v2",
    "TPU_V3": "v3",
    "TPU_V4": "v4",
    "TPU_V5E": "v5litepod",
    "TPU_V5P": "v5p",
}

_GPU_API_NAMES = {
    "K80": "NVIDIA_TESLA_K80",
    "P100": "NVIDIA_TESLA_P100",
    "V100": "NVIDIA_TESLA_V100",
    "P4": "NVIDIA_TESLA_P4",
    "T4": "NVIDIA_TESLA_T4",
}


def get_accelerator_type(accl_type):
    """Returns the platform API accelerator-type string.

    Reference parity: gcp.py:78-91, extended with the v4/v5e/v5p
    generations. TPU values here are generation tags; slice strings come
    from `get_tpu_slice_type`.
    """
    accl_type_map = dict(
        {"CPU": "ACCELERATOR_TYPE_UNSPECIFIED"},
        **_GPU_API_NAMES,
        **{k: k for k in _TPU_SLICE_PREFIX},
    )
    return accl_type_map[accl_type]


def get_tpu_slice_type(accelerator_type, accelerator_count):
    """Returns the Cloud TPU API slice string, e.g. ``v5litepod-8``.

    The reference never needed this because CAIP modelled TPUs as a machine
    type ``cloud_tpu`` plus an accelerator config (reference
    deploy.py:137-154); the TPU-native path provisions slices directly.
    """
    value = getattr(accelerator_type, "value", accelerator_type)
    if value not in _TPU_SLICE_PREFIX:
        raise ValueError("Not a TPU accelerator type: %r" % (value,))
    return "%s-%d" % (_TPU_SLICE_PREFIX[value], accelerator_count)


# Valid slice sizes per generation, in Cloud TPU accelerator-type naming
# units (TensorCores for v2/v3/v4/v5p, chips for v5e — i.e. the N in
# "v4-N" / "v5litepod-N"). The TPU analogue of the reference's
# (cpu, memory, accelerator, count) whitelist (reference gcp.py:123-406).
TPU_VALID_SLICE_SIZES = {
    "TPU_V2": (8, 32, 128, 256, 512),
    "TPU_V3": (8, 32, 128, 256, 512, 1024),
    "TPU_V4": (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096),
    "TPU_V5E": (1, 4, 8, 16, 32, 64, 128, 256),
    "TPU_V5P": (8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096, 8192, 12288),
}


# Machine-type families: (cpu_cores, memory_gb) -> machine type name
# (reference gcp.py:97-117).
_MACHINE_TYPE_MAP = {
    (4, 15): "n1-standard-4",
    (8, 30): "n1-standard-8",
    (16, 60): "n1-standard-16",
    (32, 120): "n1-standard-32",
    (64, 240): "n1-standard-64",
    (96, 360): "n1-standard-96",
    (2, 13): "n1-highmem-2",
    (4, 26): "n1-highmem-4",
    (8, 52): "n1-highmem-8",
    (16, 104): "n1-highmem-16",
    (32, 208): "n1-highmem-32",
    (64, 416): "n1-highmem-64",
    (96, 624): "n1-highmem-96",
    (16, 14.4): "n1-highcpu-16",
    (32, 28.8): "n1-highcpu-32",
    (64, 57.6): "n1-highcpu-64",
    (96, 86.4): "n1-highcpu-96",
}


def get_machine_type(cpu_cores, memory, accelerator_type):
    """Returns the platform machine type.

    TPU configs map to the TPU-VM host type for their generation (the
    reference collapses all TPUs to CAIP's ``cloud_tpu``, gcp.py:93-96 —
    kept as the returned value for v2/v3 legacy configs).
    """
    value = getattr(accelerator_type, "value", accelerator_type)
    if value in ("TPU_V2", "TPU_V3"):
        return "cloud_tpu"
    if value in _TPU_SLICE_PREFIX:
        # TPU-VM: the host is part of the slice; no separate machine type.
        return "tpu-vm"
    return _MACHINE_TYPE_MAP[(cpu_cores, memory)]


def get_tpu_runtime_versions():
    """Supported TPU-VM runtime (software) versions, newest first.

    Replaces the reference's TF-version gate (gcp.py:119-120 → ["2.1"]).
    """
    return ["tpu-ubuntu2204-base", "v2-alpha-tpuv5-lite", "tpu-vm-v4-base"]


def get_cloud_tpu_supported_tf_versions():
    """Reference-parity shim (gcp.py:119-120) for the legacy CAIP path."""
    return ["2.1"]


# Max host CPU cores allowed for each (gpu_type, gpu_count) — the rule
# underlying the reference's exhaustive whitelist (gcp.py:148-406).
_GPU_MAX_CPU_CORES = {
    ("K80", 1): 8, ("K80", 2): 16, ("K80", 4): 32, ("K80", 8): 32,
    ("P100", 1): 16, ("P100", 2): 32, ("P100", 4): 32,
    ("P4", 1): 16, ("P4", 2): 32, ("P4", 4): 96,
    ("T4", 1): 16, ("T4", 2): 32, ("T4", 4): 96,
    ("V100", 1): 8, ("V100", 2): 16, ("V100", 4): 32, ("V100", 8): 96,
}

# Machine families GPUs can attach to (highcpu excluded, matching the
# reference whitelist which never pairs GPUs with n1-highcpu).
_GPU_MACHINE_FAMILIES = ("n1-standard", "n1-highmem")


def validate_machine_configuration(cpu_cores, memory, accelerator_type,
                                   accelerator_count):
    """Errors out if the given machine configuration is not valid on GCP.

    Reference parity: gcp.py's whitelist check, generalised to TPU slices
    of every generation.
    """
    value = getattr(accelerator_type, "value", accelerator_type)

    if value in _TPU_SLICE_PREFIX:
        if cpu_cores is not None or memory is not None:
            raise ValueError(
                "Invalid machine configuration: TPU configs take the host "
                "shape from the slice; pass cpu_cores=None, memory=None. "
                "Received cpu_cores={}, memory={}.".format(cpu_cores, memory))
        valid = TPU_VALID_SLICE_SIZES[value]
        if accelerator_count not in valid:
            raise ValueError(
                "Invalid machine configuration: accelerator_count={} is not "
                "a valid {} slice size. Valid sizes: {}.".format(
                    accelerator_count, value, list(valid)))
        return

    if (cpu_cores, memory) not in _MACHINE_TYPE_MAP:
        raise ValueError(
            "Invalid machine configuration: (cpu_cores={}, memory={}) does "
            "not match a GCP machine type. Valid combinations: {}.".format(
                cpu_cores, memory, sorted(
                    _MACHINE_TYPE_MAP, key=lambda k: (str(k[0]), str(k[1])))))

    if value == "CPU":
        if accelerator_count != 0:
            raise ValueError(
                "Invalid machine configuration: accelerator_count must be 0 "
                "for CPU configs. Received {}.".format(accelerator_count))
        return

    machine_type = _MACHINE_TYPE_MAP[(cpu_cores, memory)]
    family = machine_type.rsplit("-", 1)[0]
    max_cores = _GPU_MAX_CPU_CORES.get((value, accelerator_count))
    if max_cores is None or family not in _GPU_MACHINE_FAMILIES:
        raise ValueError(
            "Invalid machine configuration: {} x{} on {} is not supported "
            "on GCP.".format(value, accelerator_count, machine_type))
    if cpu_cores > max_cores:
        raise ValueError(
            "Invalid machine configuration: {} x{} supports at most {} CPU "
            "cores; received {} ({}).".format(
                value, accelerator_count, max_cores, cpu_cores, machine_type))


def validate_job_labels(job_labels):
    """Validates job labels conform to GCP resource-label guidelines.

    Same rules as reference gcp.py:409-481: at most 64 labels; keys and
    values at most 63 chars, starting with a lowercase letter, containing
    only lowercase letters, digits, underscores and dashes.
    """
    if not job_labels:
        return

    if len(job_labels) > 64:
        raise ValueError(
            "Invalid job labels: too many labels, expecting at most 64. "
            "Received {}.".format(len(job_labels)))

    for k, v in job_labels.items():
        for kind, s in (("key", k), ("value", v)):
            if not s or not s[0].islower():
                raise ValueError(
                    "Invalid job labels: label {} must start with a "
                    "lowercase letter. Received {!r}.".format(kind, s))
            if len(s) > 63:
                raise ValueError(
                    "Invalid job labels: label {} is too long, expecting at "
                    "most 63 characters. Received {!r}.".format(kind, s))
            if not re.match(r"^[a-z0-9_-]+$", s):
                raise ValueError(
                    "Invalid job labels: label {} can only contain lowercase "
                    "letters, digits, underscores and dashes. "
                    "Received {!r}.".format(kind, s))
