"""Machine configuration annotations used by the `run` API.

TPU-first redesign of the reference's machine catalog
(reference: src/python/tensorflow_cloud/core/machine_config.py:25-185).
Where the reference treats TPUs as a 2-entry afterthought (TPU_V2/TPU_V3,
one 8-core slice), this catalog makes Cloud TPU generations (v2-v5p) the
primary axis, models slice topology explicitly (chips per host, valid slice
sizes), and keeps the reference's GPU presets as the secondary path.
"""

import enum

from cloud_tpu.core import gcp


class AcceleratorType(enum.Enum):
    """Types of accelerators.

    TPU generations are first-class (vs reference machine_config.py:34-35
    which stops at TPU_V3); GPU types are retained for the secondary path.
    """

    NO_ACCELERATOR = "CPU"
    # --- TPU generations (primary target) ---
    TPU_V2 = "TPU_V2"
    TPU_V3 = "TPU_V3"
    TPU_V4 = "TPU_V4"
    TPU_V5E = "TPU_V5E"
    TPU_V5P = "TPU_V5P"
    # --- GPU types (secondary path, reference parity) ---
    NVIDIA_TESLA_K80 = "K80"
    NVIDIA_TESLA_P100 = "P100"
    NVIDIA_TESLA_V100 = "V100"
    NVIDIA_TESLA_P4 = "P4"
    NVIDIA_TESLA_T4 = "T4"

    @classmethod
    def all(cls):
        return tuple(cls)

    @classmethod
    def tpu_types(cls):
        return (cls.TPU_V2, cls.TPU_V3, cls.TPU_V4, cls.TPU_V5E, cls.TPU_V5P)

    @classmethod
    def gpu_types(cls):
        return (
            cls.NVIDIA_TESLA_K80,
            cls.NVIDIA_TESLA_P100,
            cls.NVIDIA_TESLA_V100,
            cls.NVIDIA_TESLA_P4,
            cls.NVIDIA_TESLA_T4,
        )

    @classmethod
    def validate(cls, key):
        if key not in cls.all():
            raise ValueError("Invalid accelerator key provided: %s." % key)


# Physical slice topology per TPU generation. `accelerator_count` follows
# Cloud TPU accelerator-type naming units (the N in "v4-N"/"v5litepod-N"):
# TensorCores for v2/v3/v4/v5p, chips for v5e. Every generation packs 8
# naming units per host (4 chips x 2 cores, or 8 single-core chips).
# `cores_per_device` converts naming units to JAX devices: v2/v3 expose one
# device per core, v4/v5p run megacore (one device per 2-core chip), v5e is
# one device per chip. The reference never models topology because it only
# ever submits one 8-core slice (reference validate.py:160-166).
TPU_UNITS_PER_HOST = {
    AcceleratorType.TPU_V2: 8,
    AcceleratorType.TPU_V3: 8,
    AcceleratorType.TPU_V4: 8,
    AcceleratorType.TPU_V5E: 8,
    AcceleratorType.TPU_V5P: 8,
}

TPU_UNITS_PER_DEVICE = {
    AcceleratorType.TPU_V2: 1,   # device per core
    AcceleratorType.TPU_V3: 1,   # device per core
    AcceleratorType.TPU_V4: 2,   # megacore: device per chip
    AcceleratorType.TPU_V5E: 1,  # device per (single-core) chip
    AcceleratorType.TPU_V5P: 2,  # megacore: device per chip
}


class MachineConfig(object):
    """Represents the configuration or type of machine to be used.

    Reference parity: same four constructor fields as
    reference machine_config.py:58-90, but `accelerator_type='auto'`
    resolves TPU-first (v5e) instead of to a GPU (reference
    machine_config.py:82-83 resolves to P100).
    """

    def __init__(self,
                 cpu_cores="auto",
                 memory="auto",
                 accelerator_type="auto",
                 accelerator_count=8):
        """Constructor.

        Args:
          cpu_cores: Number of virtual CPU cores on the host, or `None` for
            TPU configs ("whatever the TPU-VM host has"). Defaults to
            'auto': `None` for TPU accelerators, 8 otherwise.
          memory: Amount of memory in GB, or `None` for TPU configs.
            Defaults to 'auto': `None` for TPU accelerators, 30 otherwise.
          accelerator_type: An `AcceleratorType` ('TPU_V5E', ..., 'K80', or
            'CPU' for no accelerator). Defaults to 'auto', which maps to the
            current-generation TPU (TPU_V5E).
          accelerator_count: Accelerator count in Cloud TPU naming units for
            TPUs (the N in "v5litepod-N" — may span hosts), or the GPU
            count otherwise. Defaults to 8 (one v5e host).
        """
        if accelerator_type == "auto":
            accelerator_type = AcceleratorType.TPU_V5E
        is_tpu = accelerator_type in AcceleratorType.tpu_types()
        if cpu_cores == "auto":
            cpu_cores = None if is_tpu else 8
        if memory == "auto":
            memory = None if is_tpu else 30

        self.cpu_cores = cpu_cores
        self.memory = memory
        self.accelerator_type = accelerator_type
        self.accelerator_count = accelerator_count

        self.validate()

    def validate(self):
        """Checks that the machine configuration created is valid for GCP."""
        AcceleratorType.validate(self.accelerator_type)
        gcp.validate_machine_configuration(self.cpu_cores,
                                           self.memory,
                                           self.accelerator_type,
                                           self.accelerator_count)

    @property
    def is_tpu(self):
        return self.accelerator_type in AcceleratorType.tpu_types()

    @property
    def num_hosts(self):
        """Number of TPU-VM hosts backing this config (1 for non-TPU)."""
        if not self.is_tpu:
            return 1
        units_per_host = TPU_UNITS_PER_HOST[self.accelerator_type]
        return max(1, -(-self.accelerator_count // units_per_host))

    @property
    def num_devices(self):
        """Number of JAX devices this config exposes (len(jax.devices()))."""
        if not self.is_tpu:
            return max(1, self.accelerator_count)
        return max(
            1,
            self.accelerator_count
            // TPU_UNITS_PER_DEVICE[self.accelerator_type])

    def __repr__(self):
        accel = self.accelerator_type
        name = accel.value if isinstance(accel, AcceleratorType) else accel
        return ("MachineConfig(cpu_cores={}, memory={}, "
                "accelerator_type={!r}, accelerator_count={})").format(
                    self.cpu_cores, self.memory, name, self.accelerator_count)


def _tpu(accel_type, count):
    return MachineConfig(
        cpu_cores=None,
        memory=None,
        accelerator_type=accel_type,
        accelerator_count=count,
    )


def _gpu(accel_type, count, cpu_cores, memory):
    return MachineConfig(
        cpu_cores=cpu_cores,
        memory=memory,
        accelerator_type=accel_type,
        accelerator_count=count,
    )


# Dictionary with common machine configurations. TPU slice presets are the
# primary entries (vs the single "TPU" entry at reference
# machine_config.py:170-175); GPU presets retained for the secondary path
# (reference machine_config.py:97-169).
COMMON_MACHINE_CONFIGS = {
    "CPU": MachineConfig(
        cpu_cores=4,
        memory=15,
        accelerator_type=AcceleratorType.NO_ACCELERATOR,
        accelerator_count=0,
    ),
    # --- TPU slice presets ---
    "TPU_V2_8": _tpu(AcceleratorType.TPU_V2, 8),
    "TPU_V3_8": _tpu(AcceleratorType.TPU_V3, 8),
    "TPU_V4_8": _tpu(AcceleratorType.TPU_V4, 8),
    "TPU_V4_32": _tpu(AcceleratorType.TPU_V4, 32),
    "TPU_V5E_1": _tpu(AcceleratorType.TPU_V5E, 1),
    "TPU_V5E_4": _tpu(AcceleratorType.TPU_V5E, 4),
    "TPU_V5E_8": _tpu(AcceleratorType.TPU_V5E, 8),
    "TPU_V5E_16": _tpu(AcceleratorType.TPU_V5E, 16),
    "TPU_V5E_32": _tpu(AcceleratorType.TPU_V5E, 32),
    "TPU_V5E_64": _tpu(AcceleratorType.TPU_V5E, 64),
    "TPU_V5E_128": _tpu(AcceleratorType.TPU_V5E, 128),
    "TPU_V5E_256": _tpu(AcceleratorType.TPU_V5E, 256),
    "TPU_V5P_8": _tpu(AcceleratorType.TPU_V5P, 8),
    "TPU_V5P_32": _tpu(AcceleratorType.TPU_V5P, 32),
    # Legacy alias matching the reference's single TPU preset
    # (reference machine_config.py:170-175: TPU_V3 x 8).
    "TPU": _tpu(AcceleratorType.TPU_V3, 8),
    # --- GPU presets (secondary path) ---
    "K80_1X": _gpu(AcceleratorType.NVIDIA_TESLA_K80, 1, 8, 30),
    "K80_4X": _gpu(AcceleratorType.NVIDIA_TESLA_K80, 4, 16, 60),
    "K80_8X": _gpu(AcceleratorType.NVIDIA_TESLA_K80, 8, 32, 120),
    "P100_1X": _gpu(AcceleratorType.NVIDIA_TESLA_P100, 1, 8, 30),
    "P100_4X": _gpu(AcceleratorType.NVIDIA_TESLA_P100, 4, 16, 60),
    "P4_1X": _gpu(AcceleratorType.NVIDIA_TESLA_P4, 1, 8, 30),
    "P4_4X": _gpu(AcceleratorType.NVIDIA_TESLA_P4, 4, 16, 60),
    "V100_1X": _gpu(AcceleratorType.NVIDIA_TESLA_V100, 1, 8, 30),
    "V100_4X": _gpu(AcceleratorType.NVIDIA_TESLA_V100, 4, 16, 60),
    "T4_1X": _gpu(AcceleratorType.NVIDIA_TESLA_T4, 1, 8, 30),
    "T4_4X": _gpu(AcceleratorType.NVIDIA_TESLA_T4, 4, 16, 60),
}


def is_tpu_config(config):
    """True if `config` requests any TPU generation.

    Reference parity: machine_config.py:179-185, extended to v4/v5e/v5p.
    """
    if config:
        return config.accelerator_type in AcceleratorType.tpu_types()
    return False
