"""Containerization: Dockerfile synthesis, tar packaging, image build+push.

Reference parity: core/containerize.py:44-498, redesigned TPU-first:

- Base images are Python-slim + a version-matched `jax[tpu]` wheel install
  (vs the reference's TF-version-matched `tensorflow/tensorflow:*-gpu`
  images, reference containerize.py:136-178). GPU configs get `jax[cuda]`;
  the TPU libtpu wheel rides the official jax release index.
- The docker-hub existence probe + latest-fallback behavior is kept
  (reference containerize.py:228-240).
- The Cloud Build request is corrected: the reference nests `images` in a
  double list and passes `steps` as a dict (reference
  containerize.py:472-498), and drops submission errors on the floor
  (`RuntimeError` constructed but never raised, containerize.py:454-456);
  this implementation emits the documented Build schema and raises.

External boundaries (docker daemon, GCS, Cloud Build REST) are imported
lazily and injectable so golden tests pin artifacts without cloud access.
"""

import logging
import os
import sys
import tarfile
import tempfile
import time
import uuid
import warnings

try:
    import requests
except ImportError:  # probed lazily; tests inject a fake
    requests = None

try:
    import docker
except ImportError:
    docker = None

try:
    from google.cloud import storage
    from google.cloud.exceptions import NotFound
except ImportError:
    storage = None
    NotFound = Exception

try:
    from googleapiclient import discovery
    from googleapiclient import errors as googleapiclient_errors
except ImportError:
    discovery = None
    googleapiclient_errors = None

from cloud_tpu.core import machine_config

logger = logging.getLogger("cloud_tpu")

_IMAGE_NAME = "cloud_tpu_train"

# The jax release index that carries libtpu wheels.
_JAX_RELEASE_INDEX = (
    "https://storage.googleapis.com/jax-releases/libtpu_releases.html")


def _local_python_tag():
    return "%d.%d" % (sys.version_info.major, sys.version_info.minor)


def _local_jax_version():
    try:
        import jax
        return jax.__version__
    except ImportError:
        return None


class ContainerBuilder(object):
    """Container builder for building and pushing a docker image.

    Constructor signature mirrors reference containerize.py:47-60.
    """

    def __init__(
        self,
        entry_point,
        preprocessed_entry_point,
        chief_config,
        worker_config,
        docker_registry,
        project_id,
        requirements_txt=None,
        destination_dir="/app/",
        docker_base_image=None,
        docker_image_bucket_name=None,
        called_from_notebook=False,
    ):
        self.entry_point = entry_point
        self.preprocessed_entry_point = preprocessed_entry_point
        self.chief_config = chief_config
        self.worker_config = worker_config
        self.docker_registry = docker_registry
        self.project_id = project_id
        self.requirements_txt = requirements_txt
        self.destination_dir = destination_dir
        self.docker_base_image = docker_base_image
        self.docker_image_bucket_name = docker_image_bucket_name
        self.called_from_notebook = called_from_notebook

        # Populated lazily.
        self.tar_file_path = None
        self.docker_file_path = None
        self.docker_client = None

    def get_docker_image(self, max_status_check_attempts=None,
                         delay_between_status_checks=None):
        """Builds, publishes and returns a docker image URI."""
        raise NotImplementedError

    def get_generated_files(self):
        return [self.docker_file_path, self.tar_file_path]

    # -- Dockerfile synthesis -------------------------------------------

    def _is_tpu_job(self):
        return (machine_config.is_tpu_config(self.chief_config) or
                machine_config.is_tpu_config(self.worker_config))

    def _uses_accelerator(self):
        configs = (self.chief_config, self.worker_config)
        return self._is_tpu_job() or any(
            c is not None and c.accelerator_type !=
            machine_config.AcceleratorType.NO_ACCELERATOR
            for c in configs)

    def _default_base_image(self):
        """Python-slim base matched to the local interpreter version.

        The TPU-native analogue of the reference's TF-version-matched base
        image (containerize.py:136-158): the ML stack (jax) is installed
        as an explicit pip step, so the base only has to match Python.
        """
        tag = "{}-slim".format(_local_python_tag())
        image = "python:{}".format(tag)
        if not self._base_image_exists(image):
            warnings.warn(
                "The `run` API uses a python docker base image matching "
                "your local python version. No image exists for python {}; "
                "falling back to `python:3.12-slim`. If you see "
                "compatibility issues, pass a custom "
                "`docker_base_image`.".format(_local_python_tag()))
            image = "python:3.12-slim"
        return image

    def _jax_install_lines(self):
        """pip-install lines for the accelerator-matched jax stack."""
        version = _local_jax_version()
        spec = "jax=={}".format(version) if version else "jax"
        if self._is_tpu_job():
            tpu_spec = ("jax[tpu]=={}".format(version)
                        if version else "jax[tpu]")
            return ["RUN pip install --no-cache '{}' -f {}".format(
                tpu_spec, _JAX_RELEASE_INDEX)]
        if self._uses_accelerator():
            cuda_spec = ("jax[cuda12]=={}".format(version)
                         if version else "jax[cuda12]")
            return ["RUN pip install --no-cache '{}'".format(cuda_spec)]
        return ["RUN pip install --no-cache '{}'".format(spec)]

    def _create_docker_file(self):
        """Creates the Dockerfile (reference containerize.py:134-226)."""
        if self.docker_base_image is None:
            self.docker_base_image = self._default_base_image()

        lines = [
            "FROM {}".format(self.docker_base_image),
            "WORKDIR {}".format(self.destination_dir),
        ]
        lines.extend(self._jax_install_lines())

        if self.requirements_txt is not None:
            _, requirements_txt_name = os.path.split(self.requirements_txt)
            requirements_txt_path = os.path.join(
                self.destination_dir, requirements_txt_name)
            lines.append("COPY {requirements_txt} {requirements_txt}".format(
                requirements_txt=requirements_txt_path))
            lines.append(
                "RUN if [ -e {requirements_txt} ]; "
                "then pip install --no-cache -r {requirements_txt}; "
                "fi".format(requirements_txt=requirements_txt_name))

        if self.entry_point is None:
            # The generated runner imports the framework remotely
            # (reference containerize.py:201-202 installs tensorflow-cloud).
            lines.append("RUN pip install cloud-tpu-framework")

        # Copy the packaged working tree into the container filesystem.
        lines.append("COPY {} {}".format(self.destination_dir,
                                         self.destination_dir))

        docker_entry_point = self.preprocessed_entry_point or self.entry_point
        _, docker_entry_point_file_name = os.path.split(docker_entry_point)
        # ENTRYPOINT (vs CMD) so user code flags pass through
        # (reference containerize.py:217-221).
        lines.append('ENTRYPOINT ["python", "{}"]'.format(
            docker_entry_point_file_name))

        content = "\n".join(lines)
        _, self.docker_file_path = tempfile.mkstemp()
        with open(self.docker_file_path, "w") as f:
            f.write(content)

    def _base_image_exists(self, image):
        """Dockerhub existence probe (reference containerize.py:228-240);
        degrades to True when the network/requests is unavailable."""
        if requests is None:
            return True
        repo_name, tag_name = image.split(":")
        if "/" not in repo_name:
            repo_name = "library/" + repo_name
        try:
            r = requests.get(
                "https://hub.docker.com/v2/repositories/{}/tags/{}".format(
                    repo_name, tag_name), timeout=10)
            # Only a definitive 404 means the tag is missing; rate limits
            # (429) or hub outages must not silently downgrade the image.
            return r.status_code != 404
        except Exception:  # no egress: assume the default tag is fine
            return True

    # -- Packaging ------------------------------------------------------

    def _get_tar_file_path(self):
        """Packages the Dockerfile + working tree into a tarball
        (reference containerize.py:124-132)."""
        self._create_docker_file()
        file_path_map = self._get_file_path_map()

        _, self.tar_file_path = tempfile.mkstemp()
        with tarfile.open(self.tar_file_path, "w:gz", dereference=True) as tar:
            for source, destination in file_path_map.items():
                tar.add(source, arcname=destination)

    def _get_file_path_map(self):
        """Maps local paths to docker build context paths
        (reference containerize.py:242-284)."""
        location_map = {}
        if self.entry_point is None and sys.argv[0].endswith(".py"):
            self.entry_point = sys.argv[0]
        if self.entry_point is None and not self.called_from_notebook:
            raise ValueError(
                "Could not determine the entry point: `entry_point` was not "
                "given and the current process ({!r}) is not a python "
                "script. Pass `entry_point` explicitly.".format(sys.argv[0]))

        if not self.called_from_notebook:
            entry_point_dir, _ = os.path.split(self.entry_point)
            if not entry_point_dir:
                entry_point_dir = "."
            location_map[entry_point_dir] = self.destination_dir

        if self.preprocessed_entry_point is not None:
            _, preprocessed_name = os.path.split(
                self.preprocessed_entry_point)
            location_map[self.preprocessed_entry_point] = os.path.join(
                self.destination_dir, preprocessed_name)

        if self.requirements_txt is not None:
            _, requirements_txt_name = os.path.split(self.requirements_txt)
            location_map[self.requirements_txt] = os.path.join(
                self.destination_dir, requirements_txt_name)

        location_map[self.docker_file_path] = "Dockerfile"
        return location_map

    def _generate_name(self):
        """Unique image name+tag, uniform with the job id format
        (reference containerize.py:286-292)."""
        unique_tag = str(uuid.uuid4()).replace("-", "_")
        return "{}/{}:{}".format(self.docker_registry, _IMAGE_NAME,
                                 unique_tag)


class LocalContainerBuilder(ContainerBuilder):
    """Builds via the local docker daemon (reference
    containerize.py:295-374)."""

    def get_docker_image(self, max_status_check_attempts=None,
                         delay_between_status_checks=None):
        if docker is None:
            raise RuntimeError(
                "The `docker` python package is required for local builds. "
                "Install it, or pass `docker_image_bucket_name` to use "
                "Cloud Build instead.")
        self.docker_client = docker.APIClient(version="auto")
        self._get_tar_file_path()

        image_uri = self._build_docker_image()
        self._publish_docker_image(image_uri)
        return image_uri

    def _build_docker_image(self):
        image_uri = self._generate_name()
        logger.info("Building docker image: %s", image_uri)
        # The tarball is the build context (contains the Dockerfile), so
        # custom_context is set (reference containerize.py:325-338).
        with open(self.tar_file_path, "rb") as fileobj:
            bld_logs_generator = self.docker_client.build(
                path=".",
                custom_context=True,
                fileobj=fileobj,
                tag=image_uri,
                encoding="utf-8",
                decode=True,
            )
        self._get_logs(bld_logs_generator, "build", image_uri)
        return image_uri

    def _publish_docker_image(self, image_uri):
        logger.info("Publishing docker image: %s", image_uri)
        pb_logs_generator = self.docker_client.push(
            image_uri, stream=True, decode=True)
        self._get_logs(pb_logs_generator, "publish", image_uri)

    def _get_logs(self, logs_generator, name, image_uri):
        """Decodes daemon logs; raises on error chunks
        (reference containerize.py:351-374)."""
        for chunk in logs_generator:
            if "stream" in chunk:
                for line in chunk["stream"].splitlines():
                    logger.info(line)
            if "error" in chunk:
                raise RuntimeError(
                    "Docker image {} failed: {}\nImage URI: {}".format(
                        name, str(chunk["error"]), image_uri))


class CloudContainerBuilder(ContainerBuilder):
    """Builds via Google Cloud Build (reference containerize.py:377-498)."""

    def get_docker_image(self, max_status_check_attempts=20,
                         delay_between_status_checks=30):
        if discovery is None or storage is None:
            raise RuntimeError(
                "google-api-python-client and google-cloud-storage are "
                "required for Cloud Build containerization.")
        from cloud_tpu.utils import google_api_client

        self._get_tar_file_path()
        storage_object_name = self._upload_tar_to_gcs()
        image_uri = self._generate_name()

        logger.info(
            "Building and publishing docker image via Cloud Build: %s",
            image_uri)
        build_service = discovery.build(
            "cloudbuild",
            "v1",
            cache_discovery=False,
            requestBuilder=google_api_client.CloudTpuHttpRequest,
        )
        request_dict = self._create_cloud_build_request_dict(
            image_uri, storage_object_name)

        try:
            create_response = (
                build_service.projects()
                .builds()
                .create(projectId=self.project_id, body=request_dict)
                .execute())

            # `create` returns a long-running Operation carrying the build
            # id; poll it (reference containerize.py:423-449: 20 x 30s).
            attempts = 1
            status = None
            while attempts <= max_status_check_attempts:
                get_response = (
                    build_service.projects()
                    .builds()
                    .get(projectId=self.project_id,
                         id=create_response["metadata"]["build"]["id"])
                    .execute())
                status = get_response["status"]
                # PENDING/STATUS_UNKNOWN are pre-queue states (e.g. at the
                # project's Cloud Build concurrency limit) — keep polling.
                if status not in ("WORKING", "QUEUED", "PENDING",
                                  "STATUS_UNKNOWN"):
                    break
                attempts += 1
                time.sleep(delay_between_status_checks)
            if status != "SUCCESS":
                raise RuntimeError(
                    "There was an error executing the cloud build job. "
                    "Job status: " + str(status))
        except Exception as err:
            if (googleapiclient_errors is not None and
                    isinstance(err, googleapiclient_errors.HttpError)):
                # The reference constructs-but-forgets this error
                # (containerize.py:454-456); raise it.
                raise RuntimeError(
                    "There was an error submitting the cloud build job: "
                    "{}".format(err)) from err
            raise
        return image_uri

    def _upload_tar_to_gcs(self):
        """Uploads the build context to GCS (reference
        containerize.py:456-470)."""
        logger.info("Uploading files to GCS.")
        storage_client = storage.Client()
        try:
            bucket = storage_client.get_bucket(self.docker_image_bucket_name)
        except NotFound:
            bucket = storage_client.create_bucket(
                self.docker_image_bucket_name)

        unique_tag = str(uuid.uuid4()).replace("-", "_")
        storage_object_name = "{}_tar_{}".format(_IMAGE_NAME, unique_tag)
        blob = bucket.blob(storage_object_name)
        blob.upload_from_filename(self.tar_file_path)
        return storage_object_name

    def _create_cloud_build_request_dict(self, image_uri,
                                         storage_object_name):
        """Build-request body per the documented Build schema.

        Fixes two reference payload bugs (containerize.py:479-490):
        `images` was a nested list and `steps` a bare dict.
        """
        return {
            "projectId": self.project_id,
            "images": [image_uri],
            "steps": [{
                "name": "gcr.io/cloud-builders/docker",
                "args": ["build", "-t", image_uri, "."],
            }],
            "source": {
                "storageSource": {
                    "bucket": self.docker_image_bucket_name,
                    "object": storage_object_name,
                }
            },
        }
