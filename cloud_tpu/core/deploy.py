"""Job deployment: request synthesis and submission for TPU training jobs.

Reference parity: core/deploy.py:28-220, redesigned TPU-first:

- Modern TPU configs (v4/v5e/v5p) submit TPU-VM worker pools: machine
  type ``tpu-vm``, a Cloud TPU slice string (``v5litepod-8``) in the
  accelerator config, and a TPU runtime version — replacing the CAIP-era
  ``cloud_tpu`` + ``tpuTfVersion: "2.1"`` encoding, which is retained for
  legacy v2/v3 configs (reference deploy.py:149-154).
- The deployer injects the multi-process bootstrap env contract
  (CLOUD_TPU_NUM_PROCESSES; coordinator/process-id resolve remotely from
  the platform-injected TF_CONFIG, see cloud_tpu/parallel/runtime.py) —
  the analogue of `use_chief_in_tf_config` (reference deploy.py:159-161,
  also kept).
"""

import logging
import subprocess
import uuid

try:
    from googleapiclient import discovery
    from googleapiclient import errors as googleapiclient_errors
except ImportError:
    discovery = None
    googleapiclient_errors = None

from cloud_tpu.core import gcp
from cloud_tpu.utils import google_api_client

logger = logging.getLogger("cloud_tpu")

_JOB_PREFIX = "cloud_tpu_train"


def deploy_job(
    region,
    image_uri,
    chief_config,
    worker_count,
    worker_config,
    entry_point_args,
    enable_stream_logs,
    job_labels=None,
    api_client=None,
):
    """Deploys the job and returns its id (reference deploy.py:28-95).

    Args:
        region: GCP region name.
        image_uri: The docker image uri.
        chief_config: `MachineConfig` for the chief.
        worker_count: Number of additional workers.
        worker_config: `MachineConfig` for the workers.
        entry_point_args: Command line args for the entry point program.
        enable_stream_logs: Stream remote logs to stdout when True.
        job_labels: Optional dict of str: str job labels.
        api_client: Injectable platform API client (tests).

    Returns:
        ID of the submitted training job.

    Raises:
        RuntimeError: if job submission failed.
    """
    job_id = _generate_job_id()
    project_id = gcp.get_project_name()
    if api_client is None:
        if discovery is None:
            raise RuntimeError(
                "google-api-python-client is required to submit training "
                "jobs.")
        api_client = discovery.build(
            "ml", "v1", cache_discovery=False,
            requestBuilder=google_api_client.CloudTpuHttpRequest)

    request_dict = _create_request_dict(
        job_id, region, image_uri, chief_config, worker_count,
        worker_config, entry_point_args, job_labels=job_labels or {})
    try:
        (api_client.projects()
         .jobs()
         .create(parent="projects/{}".format(project_id), body=request_dict)
         .execute())
    except Exception as err:
        if (googleapiclient_errors is not None and
                isinstance(err, googleapiclient_errors.HttpError)):
            print("There was an error submitting the job.")
            raise err
        raise
    _print_logs_info(job_id, project_id)
    if enable_stream_logs:
        _stream_logs(job_id)
    return job_id


def _machine_config_dict(config, image_uri):
    """Per-pool machine config for the request body."""
    machine = {"imageUri": image_uri}
    if config.is_tpu:
        value = config.accelerator_type.value
        if value in ("TPU_V2", "TPU_V3"):
            # Legacy CAIP TPU encoding (reference deploy.py:137-154).
            machine["acceleratorConfig"] = {
                "count": str(config.accelerator_count),
                "type": gcp.get_accelerator_type(value),
            }
            machine["tpuTfVersion"] = (
                gcp.get_cloud_tpu_supported_tf_versions()[0])
        else:
            machine["acceleratorConfig"] = {
                "count": str(config.accelerator_count),
                "type": gcp.get_tpu_slice_type(config.accelerator_type,
                                               config.accelerator_count),
            }
            machine["tpuRuntimeVersion"] = gcp.get_tpu_runtime_versions()[0]
    else:
        machine["acceleratorConfig"] = {
            "count": str(config.accelerator_count),
            "type": gcp.get_accelerator_type(config.accelerator_type.value),
        }
    return machine


def _create_request_dict(
    job_id,
    region,
    image_uri,
    chief_config,
    worker_count,
    worker_config,
    entry_point_args,
    job_labels,
):
    """Creates the training-service request body (reference
    deploy.py:98-167)."""
    training_input = {
        "region": region,
        "scaleTier": "custom",
        "masterType": gcp.get_machine_type(chief_config.cpu_cores,
                                           chief_config.memory,
                                           chief_config.accelerator_type),
    }

    chief = _machine_config_dict(chief_config, image_uri)
    training_input["masterConfig"] = chief
    training_input["workerCount"] = str(worker_count)

    num_processes = chief_config.num_hosts
    if worker_count > 0:
        training_input["workerType"] = gcp.get_machine_type(
            worker_config.cpu_cores,
            worker_config.memory,
            worker_config.accelerator_type)
        training_input["workerConfig"] = _machine_config_dict(
            worker_config, image_uri)
        num_processes += worker_count * worker_config.num_hosts

    # Multi-process bootstrap env contract: every pool learns the total
    # process count; coordinator address + process id come from the
    # platform cluster spec (TF_CONFIG) at runtime.
    if num_processes > 1:
        env = [{"name": "CLOUD_TPU_NUM_PROCESSES",
                "value": str(num_processes)}]
        training_input["masterConfig"]["env"] = env
        if "workerConfig" in training_input:
            training_input["workerConfig"]["env"] = list(env)

    if entry_point_args is not None:
        training_input["args"] = entry_point_args

    # Keep chief-style naming in the injected cluster spec
    # (reference deploy.py:159-161).
    training_input["use_chief_in_tf_config"] = True

    request_dict = {"jobId": job_id, "trainingInput": training_input}
    if job_labels:
        request_dict["labels"] = job_labels
    return request_dict


def _print_logs_info(job_id, project_id):
    """Prints job id and console/log URLs (reference deploy.py:170-186)."""
    print("\nJob submitted successfully.")
    print("Your job ID is: ", job_id)
    print("\nPlease access your training job information here:")
    print("https://console.cloud.google.com/mlengine/jobs/{}?project={}"
          .format(job_id, project_id))
    print("\nPlease access your training job logs here: "
          "https://console.cloud.google.com/logs/viewer?resource=ml_job%2F"
          "job_id%2F{}&interval=NO_LIMIT&project={}\n".format(
              job_id, project_id))


def _stream_logs(job_id):
    """Streams job logs to stdout via the gcloud CLI (reference
    deploy.py:189-213)."""
    try:
        print("Streaming job logs: ")
        process = subprocess.Popen(
            ["gcloud", "ai-platform", "jobs", "stream-logs", job_id],
            stdout=subprocess.PIPE)
        while True:
            output = process.stdout.readline()
            if process.poll() is not None:
                break
            if output:
                print(output.decode().replace("\x08", ""))
    except (ValueError, OSError) as err:
        print("There was an error streaming the job logs.")
        raise err


def _generate_job_id():
    """Unique job id (numbers, letters, underscores only — reference
    deploy.py:216-220)."""
    unique_tag = str(uuid.uuid4()).replace("-", "_")
    return "{}_{}".format(_JOB_PREFIX, unique_tag)
