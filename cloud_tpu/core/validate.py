"""Pre-flight validation for every `run()` argument.

Reference parity: src/python/tensorflow_cloud/core/validate.py:23-218,
with the TPU restrictions inverted for the TPU-native path:

- A TPU chief IS allowed (reference validate.py:153-158 forbids it):
  on TPU-VMs the chief process runs on the slice's host 0.
- Multi-host TPU slices are allowed — `worker_count` counts additional
  TPU-VM host groups; the reference forces worker_count==1
  (validate.py:160-166) because CAIP modelled a TPU as one 8-core node.
- The TF<=2.1 gate (validate.py:167-176) is replaced by a TPU runtime
  version check on the docker base image.
"""

import os

from cloud_tpu.core import gcp
from cloud_tpu.core import machine_config


def validate(
    entry_point,
    requirements_txt,
    distribution_strategy,
    chief_config,
    worker_config,
    worker_count,
    region,
    entry_point_args,
    stream_logs,
    docker_image_bucket_name,
    called_from_notebook,
    job_labels=None,
    docker_base_image=None,
    lint="warn",
    sanitize="off",
):
    """Validates the inputs to `run()`.

    Args:
        entry_point: Optional string. File path to the python file or
            notebook that contains the training code.
        requirements_txt: Optional string. File path to requirements.txt.
        distribution_strategy: 'auto' or None. 'auto' means the framework
            builds the JAX device mesh + data-parallel step wrapper from
            the cluster shape; None runs user code unwrapped.
        chief_config: `MachineConfig` for the chief (host 0 of the slice
            for TPU jobs).
        worker_config: `MachineConfig` for the workers.
        worker_count: Optional integer, number of workers (not counting
            the chief). For TPU configs, additional slice host-groups.
        region: String. Cloud region in which to submit the job.
        entry_point_args: Optional list of strings passed as command line
            arguments to the entry point program.
        stream_logs: Boolean; stream remote logs back when True.
        docker_image_bucket_name: Optional string, GCS bucket for Cloud
            Build containerization.
        called_from_notebook: Boolean, True when invoked from a notebook.
        job_labels: Dict of str: str labels to organize jobs.
        docker_base_image: Optional base docker image name.
        lint: "warn", "strict" or "off" — the graftlint preflight mode
            (`cloud_tpu.analysis`); the lint itself runs in `run()`
            after validation, this only rejects unknown modes.
        sanitize: "off", "warn" or "strict" — the graftsan runtime
            sanitizer mode baked into the generated runner (the remote
            job sees it as CLOUD_TPU_SANITIZE); this only rejects
            unknown modes.

    Raises:
        ValueError: if any of the inputs is invalid.
    """
    _validate_files(entry_point, requirements_txt)
    _validate_distribution_strategy(distribution_strategy)
    _validate_cluster_config(
        chief_config, worker_count, worker_config, docker_base_image)
    gcp.validate_job_labels(job_labels or {})
    _validate_lint_mode(lint)
    _validate_sanitize_mode(sanitize)
    _validate_other_args(
        region,
        entry_point_args,
        stream_logs,
        docker_image_bucket_name,
        called_from_notebook,
    )


def _validate_files(entry_point, requirements_txt):
    """Validates all the file path params (reference validate.py:87-114)."""
    cwd = os.getcwd()
    if entry_point is not None and (
            not os.path.isfile(os.path.join(cwd, entry_point))):
        raise ValueError(
            "Invalid `entry_point`. "
            "Expected a relative path in the current directory tree. "
            "Received: {}".format(entry_point))

    if requirements_txt is not None and (
            not os.path.isfile(os.path.join(cwd, requirements_txt))):
        raise ValueError(
            "Invalid `requirements_txt`. "
            "Expected a relative path in the current directory tree. "
            "Received: {}".format(requirements_txt))

    if entry_point is not None and (
            not entry_point.endswith((".py", ".ipynb"))):
        raise ValueError(
            "Invalid `entry_point`. "
            "Expected a python file or an iPython notebook. "
            "Received: {}".format(entry_point))


def _validate_distribution_strategy(distribution_strategy):
    """Reference validate.py:117-124."""
    if distribution_strategy not in ["auto", None]:
        raise ValueError(
            "Invalid `distribution_strategy` input. "
            'Expected "auto" or None. '
            "Received {}.".format(distribution_strategy))


def _validate_cluster_config(chief_config, worker_count, worker_config,
                             docker_base_image):
    """Validates cluster shape; TPU rules are TPU-native (see module doc)."""
    if not isinstance(chief_config, machine_config.MachineConfig):
        raise ValueError(
            "Invalid `chief_config` input. "
            'Expected "auto" or `MachineConfig` instance. '
            "Received {}.".format(chief_config))

    if not isinstance(worker_count, int) or worker_count < 0:
        raise ValueError(
            "Invalid `worker_count` input. "
            "Expected a non-negative integer value. "
            "Received {}.".format(worker_count))

    if (worker_count > 0 and
            not isinstance(worker_config, machine_config.MachineConfig)):
        raise ValueError(
            "Invalid `worker_config` input. "
            'Expected "auto" or `MachineConfig` instance. '
            "Received {}.".format(worker_config))

    if machine_config.is_tpu_config(chief_config) and worker_count > 0:
        if (not machine_config.is_tpu_config(worker_config) or
                worker_config.accelerator_type !=
                chief_config.accelerator_type):
            raise ValueError(
                "Invalid cluster configuration. "
                "A TPU chief requires workers of the same TPU generation "
                "(the slice is homogeneous). "
                "Received chief {} with worker {}.".format(
                    chief_config, worker_config))

    if machine_config.is_tpu_config(chief_config) or (
            worker_count > 0 and
            machine_config.is_tpu_config(worker_config)):
        _validate_tpu_base_image(docker_base_image)

    if (worker_count > 0 and machine_config.is_tpu_config(worker_config)
            and not machine_config.is_tpu_config(chief_config)):
        # Legacy CAIP-style topology: CPU chief + one TPU worker node.
        # Multi-host scale-out in that topology goes through slice size,
        # not worker_count (reference validate.py:160-166 kept as-is).
        if worker_count != 1:
            raise ValueError(
                "Invalid `worker_count` input. "
                "With a non-TPU chief, expected worker_count=1 for a TPU "
                "`worker_config` (scale via the slice size instead). "
                "Received {}.".format(worker_count))


def _validate_tpu_base_image(docker_base_image):
    """Pre-flight check on custom base images for TPU jobs.

    Replaces the reference's TF<=2.1 gate (reference validate.py:167-176):
    when `docker_base_image` is None the containerizer picks a matching
    TPU-VM base image itself, so there is nothing to check; a custom image
    that is visibly built for GPUs is rejected before any cloud spend.
    """
    if docker_base_image is None:
        return
    name = docker_base_image.lower()
    if "-gpu" in name or "cuda" in name or "nvidia" in name:
        raise ValueError(
            "Invalid `docker_base_image` for a TPU job: {!r} looks like a "
            "GPU/CUDA image. Use a TPU-VM base image (see "
            "gcp.get_tpu_runtime_versions()) or leave docker_base_image "
            "unset to get one automatically.".format(docker_base_image))


def _validate_lint_mode(lint):
    """The graftlint preflight knob takes exactly three modes."""
    if lint not in ("warn", "strict", "off"):
        raise ValueError(
            "Invalid `lint` input. "
            'Expected "warn", "strict" or "off". '
            "Received {}.".format(str(lint)))


def _validate_sanitize_mode(sanitize):
    """The graftsan runtime-sanitizer knob takes exactly three modes."""
    if sanitize not in ("off", "warn", "strict"):
        raise ValueError(
            "Invalid `sanitize` input. "
            'Expected "off", "warn" or "strict". '
            "Received {}.".format(str(sanitize)))


def _validate_other_args(region, args, stream_logs, docker_image_bucket_name,
                         called_from_notebook):
    """Reference validate.py:184-218."""
    if not isinstance(region, str):
        raise ValueError(
            "Invalid `region` input. "
            "Expected None or a string value. "
            "Received {}.".format(str(region)))

    if args is not None and not isinstance(args, list):
        raise ValueError(
            "Invalid `entry_point_args` input. "
            "Expected None or a list. "
            "Received {}.".format(str(args)))

    if args is not None and any(not isinstance(a, str) for a in args):
        # argv elements must already be strings: subprocess/AI-Platform
        # would coerce (or crash on) non-strings at deploy time, after
        # the container build was already paid.
        raise ValueError(
            "Invalid `entry_point_args` input. "
            "Expected every element to be a string. "
            "Received {}.".format(str(args)))

    if not isinstance(stream_logs, bool):
        raise ValueError(
            "Invalid `stream_logs` input. "
            "Expected a boolean. "
            "Received {}.".format(str(stream_logs)))

    if called_from_notebook and docker_image_bucket_name is None:
        raise ValueError(
            "Invalid `docker_image_bucket_name` input. "
            "When the `run` API is used within a python notebook, "
            "`docker_image_bucket_name` must be specified; it is used for "
            "Google Cloud Storage/Build docker containerization. "
            "Received {}.".format(str(docker_image_bucket_name)))
