"""The `run()` orchestrator: validate -> preprocess -> containerize -> deploy.

Reference parity: core/run.py:31-265, TPU-first:

- 'auto' machine configs resolve to a v5e-8 TPU slice (the reference
  resolves to one T4 GPU, reference run.py:154-157).
- `run()` returns the submitted job id (the reference returns nothing).
- `sys.exit(0)` fires only in the self-launch case (`entry_point=None`
  from a plain script), where continuing would train locally; launcher
  scripts that pass an explicit `entry_point` keep running (the
  reference exits unconditionally outside notebooks, run.py:245-248).
"""

import os
import sys

from cloud_tpu.analysis import preflight
from cloud_tpu.core import containerize
from cloud_tpu.core import deploy
from cloud_tpu.core import gcp
from cloud_tpu.core import machine_config
from cloud_tpu.core import preprocess
from cloud_tpu.core import validate


def remote():
    """True when running in a cloud environment launched by this framework
    (reference run.py:31-33; the TF_KERAS_* alias is honoured too)."""
    return bool(os.environ.get("CLOUD_TPU_RUNNING_REMOTELY") or
                os.environ.get("TF_KERAS_RUNNING_REMOTELY"))


def run(
    entry_point=None,
    requirements_txt=None,
    distribution_strategy="auto",
    docker_base_image=None,
    chief_config="auto",
    worker_config="auto",
    worker_count=0,
    entry_point_args=None,
    stream_logs=False,
    docker_image_bucket_name=None,
    job_labels=None,
    container_builder_cls=None,
    api_client=None,
    lint="warn",
    sanitize="off",
    **kwargs
):
    """Runs your training code on Cloud TPUs (or GPUs) in GCP.

    Args:
        entry_point: Optional path (in the working tree) to the python
            file or notebook with the training code. When None, the
            calling script (or notebook) itself is the entry point.
        requirements_txt: Optional path to additional pip requirements.
        distribution_strategy: 'auto' (default) wraps the entry point in
            a runner that initializes the ambient JAX mesh runtime from
            the cluster shape; None runs user code unwrapped.
        docker_base_image: Optional custom docker base image.
        chief_config: `MachineConfig` or 'auto' (a v5e-8 TPU slice).
        worker_config: `MachineConfig` or 'auto' (a v5e-8 TPU slice).
        worker_count: Number of additional workers. Defaults to 0.
        entry_point_args: Optional list of command line args for the
            entry point program.
        stream_logs: Stream remote job logs back when True.
        docker_image_bucket_name: When set, containerize via GCS + Cloud
            Build instead of the local docker daemon.
        job_labels: Optional dict of up-to-64 str: str job labels.
        container_builder_cls: Optional `ContainerBuilder` subclass
            overriding the Local/Cloud choice — the injection seam for
            offline use and tests.
        api_client: Optional AI-Platform jobs API client forwarded to
            `deploy.deploy_job` (same seam).
        lint: graftlint preflight mode for the entry point's code
            (`cloud_tpu.analysis`): "warn" (default) reports findings
            and proceeds, "strict" raises before containerize, "off"
            skips. Notebook entry points are never linted.
        sanitize: graftsan runtime-sanitizer mode for the REMOTE job
            ("off" default): "warn"/"strict" bake CLOUD_TPU_SANITIZE
            into the generated runner, so every Trainer.fit/evaluate on
            the slice runs under a `sanitize()` scope — step-loop
            fetches, steady-state retraces and RNG key reuse are
            attributed to their source lines in the job's event log
            ("strict" makes any finding fatal at scope exit). The
            dynamic complement of `lint`. Requires
            distribution_strategy='auto' (the runner is where the env
            var lives); ignored with a warning otherwise.
        **kwargs: Swallowed-then-rejected for forward compatibility with
            newer clients in older cloud environments (reference
            run.py:137-145).

    Returns:
        The submitted job id (None when running remotely).
    """
    # If code is triggered in a cloud environment, do nothing
    # (reference run.py:133-135).
    if remote():
        return None

    if kwargs:
        raise TypeError("Unknown keyword arguments: %s" % (kwargs.keys(),))

    # Defaults (TPU-first; reference run.py:154-165).
    if chief_config == "auto":
        chief_config = machine_config.COMMON_MACHINE_CONFIGS["TPU_V5E_8"]
    if not isinstance(worker_count, int):
        worker_count = int(worker_count)
    if worker_config == "auto":
        # No phantom worker config when there are no workers: downstream
        # stages (validate, containerize) key TPU/GPU behavior off it.
        worker_config = (
            machine_config.COMMON_MACHINE_CONFIGS["TPU_V5E_8"]
            if worker_count > 0 else None)
    region = gcp.get_region()
    destination_dir = "/app/"
    project_id = gcp.get_project_name()
    docker_registry = "gcr.io/{}".format(project_id)
    called_from_notebook = _called_from_notebook()

    validate.validate(
        entry_point,
        requirements_txt,
        distribution_strategy,
        chief_config,
        worker_config,
        worker_count,
        region,
        entry_point_args,
        stream_logs,
        docker_image_bucket_name,
        called_from_notebook,
        job_labels=job_labels or {},
        docker_base_image=docker_base_image,
        lint=lint,
        sanitize=sanitize,
    )

    # Static analysis of the code being shipped, after argument
    # validation and before any containerize/deploy spend: a GL001
    # host sync or GL002 retrace hazard is exactly the class of bug
    # that otherwise only surfaces as wall-clock pathology on the
    # slice (runtime.transfer_stats/compile_stats counters at epoch 2).
    preflight.preflight_lint(entry_point, mode=lint)

    # Make the entry point cloud- and distribution-ready (reference
    # run.py:184-200; the None-entry_point crash when strategy is None is
    # guarded here).
    preprocessed_entry_point = None
    if (distribution_strategy == "auto" or entry_point is None
            or entry_point.endswith(".ipynb")):
        preprocessed_entry_point = preprocess.get_preprocessed_entry_point(
            entry_point,
            chief_config,
            worker_config,
            worker_count,
            distribution_strategy,
            called_from_notebook=called_from_notebook,
            sanitize=sanitize,
        )
    elif sanitize != "off":
        # No generated runner means nowhere to bake the env var; warn
        # instead of silently shipping an unsanitized job.
        import warnings
        warnings.warn(
            "sanitize={!r} requires the generated runner "
            "(distribution_strategy='auto' or a notebook entry point); "
            "the job will run without graftsan.".format(sanitize))

    cb_args = (
        entry_point,
        preprocessed_entry_point,
        chief_config,
        worker_config,
        docker_registry,
        project_id,
    )
    cb_kwargs = {
        "requirements_txt": requirements_txt,
        "destination_dir": destination_dir,
        "docker_base_image": docker_base_image,
        "docker_image_bucket_name": docker_image_bucket_name,
        "called_from_notebook": called_from_notebook,
    }
    if container_builder_cls is not None:
        container_builder = container_builder_cls(*cb_args, **cb_kwargs)
    elif docker_image_bucket_name is None:
        container_builder = containerize.LocalContainerBuilder(
            *cb_args, **cb_kwargs)
    else:
        container_builder = containerize.CloudContainerBuilder(
            *cb_args, **cb_kwargs)
    docker_img_uri = container_builder.get_docker_image()

    # Delete the temporary artifacts (reference run.py:227-231).
    if preprocessed_entry_point is not None:
        os.remove(preprocessed_entry_point)
    for f in container_builder.get_generated_files():
        if f is not None and os.path.exists(f):
            os.remove(f)

    job_id = deploy.deploy_job(
        region,
        docker_img_uri,
        chief_config,
        worker_count,
        worker_config,
        entry_point_args,
        stream_logs,
        job_labels=job_labels,
        api_client=api_client,
    )

    # In the self-launch case the rest of this script is the training
    # code: exit so it does not also train locally (reference
    # run.py:245-248).
    if entry_point is None and not called_from_notebook:
        sys.exit(0)
    return job_id


def _called_from_notebook():
    """Detects a notebook environment (reference run.py:251-265)."""
    try:
        import IPython  # pylint: disable=g-import-not-at-top
    except ImportError:
        return False
    try:
        shell = IPython.get_ipython().__class__.__name__
        return "Shell" in shell
    except NameError:
        return False
