"""Preflight lint hook for `run()`.

`validate.py` checks the *arguments* of a launch; this checks the
*training code* being shipped — before the containerize/deploy spend,
which is the whole point: a GL001 host sync or GL002 retrace hazard
costs minutes of idle TPU slice once it is only discoverable from the
job's wall-clock metrics.

Modes (the `lint=` knob on `run()`):

    "warn"    (default) findings go to stderr + the job event log;
              the launch proceeds.
    "strict"  findings raise GraftlintError before containerize.
    "off"     skip entirely.

Findings are also surfaced through `utils.events.log_job_event` (kind
"graftlint"), so a launcher wrapper pointing CLOUD_TPU_EVENT_LOG at a
file — local or gs:// — gets a structured JSONL record of what the
preflight saw, alongside whatever else the job logs.
"""

import os
import sys

from cloud_tpu.analysis import engine
from cloud_tpu.utils import events

LINT_MODES = ("warn", "strict", "off")


class GraftlintError(ValueError):
    """Raised by strict-mode preflight; carries the findings."""

    def __init__(self, message, findings):
        super().__init__(message)
        self.findings = findings


def resolve_target(entry_point):
    """The .py file preflight should lint, or None.

    `entry_point=None` is the self-launch case: the calling script
    itself ships, so lint `sys.argv[0]`. Notebooks are skipped — their
    code only becomes a .py after preprocess, and linting generated
    wrapper code would attribute findings to lines the user never
    wrote.
    """
    target = entry_point if entry_point is not None else sys.argv[0]
    if not isinstance(target, str) or not target.endswith(".py"):
        return None
    if not os.path.isfile(target):
        return None
    return target


def preflight_lint(entry_point, mode="warn"):
    """Lints the launch's entry point; returns the findings list.

    Raises GraftlintError in strict mode when anything fires, and
    ValueError on an unknown mode (validate.py rejects that earlier on
    the `run()` path; this guard covers direct callers).
    """
    if mode not in LINT_MODES:
        raise ValueError(
            "Invalid `lint` input. Expected one of {}. "
            "Received {}.".format(LINT_MODES, mode))
    if mode == "off":
        return []
    target = resolve_target(entry_point)
    if target is None:
        return []

    findings, _ = engine.check_paths([target])
    if not findings:
        return []

    events.log_job_event("graftlint", {
        "mode": mode,
        "entry_point": target,
        "findings": [f.to_dict() for f in findings],
    })
    text = "\n".join("  " + f.format() for f in findings)
    if mode == "strict":
        raise GraftlintError(
            "graftlint strict preflight: {} finding(s) in {} — fix or "
            "suppress (# graftlint: disable=RULE), or pass "
            "lint=\"warn\":\n{}".format(len(findings), target, text),
            findings)
    sys.stderr.write(
        "graftlint preflight: {} finding(s) in {} (launch proceeds; "
        "pass lint=\"strict\" to gate, lint=\"off\" to "
        "silence):\n{}\n".format(len(findings), target, text))
    return findings
