"""Preflight lint hook for `run()`.

`validate.py` checks the *arguments* of a launch; this checks the
*training code* being shipped — before the containerize/deploy spend,
which is the whole point: a GL001 host sync or GL002 retrace hazard
costs minutes of idle TPU slice once it is only discoverable from the
job's wall-clock metrics.

The lint covers the entry point AND its first-level local imports
(`local_imports`): one level deep, bounded at MAX_IMPORT_FOLLOW files,
cycle-safe — enough for the interprocedural rules (GL006-GL010,
GL014-GL018) to see
the helper modules a real training script factors its step functions
into, without turning a launch into a whole-tree crawl.

Modes (the `lint=` knob on `run()`):

    "warn"    (default) findings go to stderr + the job event log;
              the launch proceeds.
    "strict"  findings raise GraftlintError before containerize.
    "off"     skip entirely.

Findings are also surfaced through `utils.events.log_job_event` (kind
"graftlint"), so a launcher wrapper pointing CLOUD_TPU_EVENT_LOG at a
file — local or gs:// — gets a structured JSONL record of what the
preflight saw, alongside whatever else the job logs.
"""

import ast
import os
import sys

from cloud_tpu.analysis import engine
from cloud_tpu.utils import events

LINT_MODES = ("warn", "strict", "off")

#: Import-following is first-level only, and even that is bounded: an
#: entry point with a pathological import list can't turn preflight
#: into a whole-tree lint (the CI self-run owns that job).
MAX_IMPORT_FOLLOW = 16


class GraftlintError(ValueError):
    """Raised by strict-mode preflight; carries the findings."""

    def __init__(self, message, findings):
        super().__init__(message)
        self.findings = findings


def resolve_target(entry_point):
    """The .py file preflight should lint, or None.

    `entry_point=None` is the self-launch case: the calling script
    itself ships, so lint `sys.argv[0]`. Notebooks are skipped — their
    code only becomes a .py after preprocess, and linting generated
    wrapper code would attribute findings to lines the user never
    wrote.
    """
    target = entry_point if entry_point is not None else sys.argv[0]
    if not isinstance(target, str) or not target.endswith(".py"):
        return None
    if not os.path.isfile(target):
        return None
    return target


def local_imports(target):
    """First-level local imports of `target` that exist as .py files.

    "Local" means resolvable RELATIVE TO THE ENTRY POINT's directory —
    the files that ship in the same container context and that the
    user actually wrote; site-packages and stdlib imports resolve to
    nothing here and are skipped. Both `import helpers` and
    `from helpers import step` map to `<dir>/helpers.py`; dotted and
    relative forms map through the package path (`from pkg.sub import
    f` -> `<dir>/pkg/sub.py` or `<dir>/pkg/sub/__init__.py`). One
    level only (imports of imports are NOT followed), capped at
    MAX_IMPORT_FOLLOW, cycle-safe by construction (the entry point
    itself is excluded, and each path appears once).

    A `target` that is missing or unreadable yields [] — the caller
    already linted (or failed to read) it; this helper never raises.
    """
    try:
        with open(target, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=target)
    except (OSError, SyntaxError, ValueError):
        return []
    base = os.path.dirname(os.path.abspath(target))

    modules = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            modules.extend(alias.name for alias in node.names)
        elif isinstance(node, ast.ImportFrom):
            if node.module:
                # Relative imports (level>0) resolve against the entry
                # point's own directory too — for a shipped flat
                # context that IS the package root.
                modules.append(node.module)
            elif node.level:
                # `from . import helpers`: the imported NAMES are the
                # modules.
                modules.extend(alias.name for alias in node.names)

    found = []
    seen = {os.path.abspath(target)}
    for module in modules:
        parts = module.split(".")
        candidates = (
            os.path.join(base, *parts) + ".py",
            os.path.join(base, *(parts + ["__init__.py"])),
        )
        for candidate in candidates:
            resolved = os.path.abspath(candidate)
            if resolved in seen or not os.path.isfile(resolved):
                continue
            seen.add(resolved)
            found.append(resolved)
            break
        if len(found) >= MAX_IMPORT_FOLLOW:
            break
    return found


def preflight_lint(entry_point, mode="warn"):
    """Lints the launch's entry point AND its first-level local
    imports; returns the findings list.

    The imports ride along because they ship in the same container: a
    GL001 host sync in `helpers.py` costs the same idle slice minutes
    as one in `train.py`, and the interprocedural rules (GL006-GL010,
    GL014-GL018) only see cross-module facts when the modules are
    linted together.

    Raises GraftlintError in strict mode when anything fires, and
    ValueError on an unknown mode (validate.py rejects that earlier on
    the `run()` path; this guard covers direct callers).
    """
    if mode not in LINT_MODES:
        raise ValueError(
            "Invalid `lint` input. Expected one of {}. "
            "Received {}.".format(LINT_MODES, mode))
    if mode == "off":
        return []
    target = resolve_target(entry_point)
    if target is None:
        return []

    findings, _ = engine.check_paths([target] + local_imports(target))
    if not findings:
        return []

    events.log_job_event("graftlint", {
        "mode": mode,
        "entry_point": target,
        "findings": [f.to_dict() for f in findings],
    })
    text = "\n".join("  " + f.format() for f in findings)
    if mode == "strict":
        raise GraftlintError(
            "graftlint strict preflight: {} finding(s) in {} — fix or "
            "suppress (# graftlint: disable=RULE), or pass "
            "lint=\"warn\":\n{}".format(len(findings), target, text),
            findings)
    sys.stderr.write(
        "graftlint preflight: {} finding(s) in {} (launch proceeds; "
        "pass lint=\"strict\" to gate, lint=\"off\" to "
        "silence):\n{}\n".format(len(findings), target, text))
    return findings
