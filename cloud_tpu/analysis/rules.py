"""graftlint rules GL001-GL009.

Every rule is keyed to the runtime counter it predicts (PERF.md has the
table): the linter is the static half of the transfer/compile
accounting that `runtime.transfer_stats()` / `runtime.compile_stats()`
do at runtime. Rules are deliberately heuristic — they run on an AST,
with no types and no tracing — so each one is tuned to fire on the
unambiguous shape of its pitfall and stay silent otherwise; the escape
hatch for a deliberate pattern is a `# graftlint: disable=RULE` comment
on the flagged line.

Shared infrastructure: `FileContext` runs ONE pre-pass over the tree
collecting everything more than one rule needs — which functions are
jit-compiled (decorator forms, `functools.partial` forms, and
`g = jax.jit(f, ...)` assignment forms), their static/donated argument
positions, module-level mutable literals, mesh axis-name literals, and
the import aliases under which `PartitionSpec` and `jax.random` travel.

GL001-GL005 are intraprocedural and need only the FileContext. GL006
(cross-module mesh axes) and GL007-GL009 additionally read
`ctx.project` — a `callgraph.ProjectContext` over every file in the
lint invocation, attached by the engine before rules run — so facts
propagate through calls: a host sync two helpers below a jit body
(GL007), a key consumed inside a callee then reused at the call site
(GL008), a donated buffer retained by an earlier callee (GL009).
"""

import ast

from cloud_tpu.analysis.engine import Finding

# Callables that make the wrapped function traced/compiled. `pjit` and
# `instrumented_jit` (cloud_tpu.parallel.runtime) behave like jax.jit
# for every rule here.
_JIT_NAMES = {"jit", "instrumented_jit", "pjit"}

# numpy's conventional import aliases: `np.asarray(x)` on a tracer
# inside jit is a concretization (host sync) hazard.
_NUMPY_ALIASES = {"np", "numpy", "onp"}

# Test-expression calls whose result is static even on traced args.
_STATIC_CALLS = {"len", "isinstance", "hasattr", "getattr", "callable",
                 "issubclass"}


def _terminal_name(node):
    """`jax.jit` -> 'jit', `runtime.instrumented_jit` ->
    'instrumented_jit', `jit` -> 'jit'; None for anything else."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _base_name(node):
    """`np.asarray` -> 'np' (the root Name of an attribute chain)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _literal(node):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError, TypeError):
        return None


class JitInfo:
    """What we know about one jit-compiled callable."""

    __slots__ = ("static_argnums", "static_argnames", "donate_argnums",
                 "node")

    def __init__(self):
        self.static_argnums = set()
        self.static_argnames = set()
        self.donate_argnums = set()
        self.node = None  # the FunctionDef, when known

    @property
    def has_statics(self):
        return bool(self.static_argnums or self.static_argnames)

    def absorb_kwargs(self, call):
        """Reads static_argnums/static_argnames/donate_argnums literal
        keywords off a jit(...) / partial(jit, ...) call node."""
        for kw in call.keywords:
            value = _literal(kw.value)
            if value is None:
                continue
            if not isinstance(value, (tuple, list)):
                value = (value,)
            if kw.arg == "static_argnums":
                self.static_argnums |= {v for v in value
                                        if isinstance(v, int)}
            elif kw.arg == "static_argnames":
                self.static_argnames |= {v for v in value
                                         if isinstance(v, str)}
            elif kw.arg == "donate_argnums":
                self.donate_argnums |= {v for v in value
                                        if isinstance(v, int)}


def _jit_call_info(node):
    """If `node` is a Call that jit-compiles something, return
    (JitInfo, wrapped) where wrapped is the first positional argument
    (the function being compiled) or None. Handles `jax.jit(f, ...)`,
    `instrumented_jit(f, ...)` and `functools.partial(jax.jit, ...)`.
    """
    if not isinstance(node, ast.Call):
        return None, None
    name = _terminal_name(node.func)
    if name in _JIT_NAMES:
        info = JitInfo()
        info.absorb_kwargs(node)
        wrapped = node.args[0] if node.args else None
        return info, wrapped
    if name == "partial" and node.args:
        inner = _terminal_name(node.args[0])
        if inner in _JIT_NAMES:
            info = JitInfo()
            info.absorb_kwargs(node)
            return info, None  # partial(jit, ...) decorates the def below
    return None, None


class FileContext:
    """One shared pre-pass over the tree; rules read, never re-walk."""

    def __init__(self, tree, source, path):
        self.tree = tree
        self.source = source
        self.path = path
        self.parents = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

        #: FunctionDef/Lambda node -> JitInfo for jit-compiled defs.
        self.jit_defs = {}
        #: local callable name -> JitInfo (call sites: `g = jax.jit(f)`
        #: assignments AND decorated defs, callable by their own name).
        self.jit_names = {}
        #: module-level names bound to mutable literals ({} [] set()).
        self.mutable_globals = set()
        #: axis-name string literals declared by Mesh(...) in this file.
        self.mesh_axes = set()
        self.mesh_lines = []
        #: names PartitionSpec is importable under in this file.
        self.pspec_aliases = {"PartitionSpec"}
        #: names the jax.random module travels under (import aliases).
        self.random_aliases = {"jrandom", "jran"}
        #: function names imported directly from jax.random.
        self.random_funcs = set()
        #: callgraph.ProjectContext, attached by the engine once every
        #: file in the invocation is parsed; GL006-GL009 read it.
        self.project = None

        self._collect_imports(tree)
        self._collect_jit(tree)
        self._collect_globals(tree)
        self._collect_mesh(tree)

    # -- pre-pass collectors ------------------------------------------

    def _collect_imports(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                module = node.module or ""
                for alias in node.names:
                    bound = alias.asname or alias.name
                    if alias.name == "PartitionSpec":
                        self.pspec_aliases.add(bound)
                    if alias.name == "random" and module == "jax":
                        self.random_aliases.add(bound)
                    if module == "jax.random":
                        self.random_funcs.add(bound)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "jax.random" and alias.asname:
                        self.random_aliases.add(alias.asname)

    def _collect_jit(self, tree):
        # Decorated defs.
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    info = self._decorator_jit_info(deco)
                    if info is not None:
                        info.node = node
                        self.jit_defs[node] = info
                        self.jit_names[node.name] = info
                        break
        # Call form, wherever it appears: `jax.jit(train_step, ...)` in
        # an assignment, a return statement, or any expression marks
        # the wrapped def's body as traced code. Assignment targets
        # additionally become known-jit call-site names.
        wrapped_names = {}
        for node in ast.walk(tree):
            info, wrapped = _jit_call_info(node)
            if info is None:
                continue
            if isinstance(wrapped, ast.Name):
                wrapped_names[wrapped.id] = info
            elif isinstance(wrapped, ast.Lambda):
                info.node = wrapped
                self.jit_defs[wrapped] = info
            parent = self.parents.get(node)
            if isinstance(parent, ast.Assign):
                for target in parent.targets:
                    if isinstance(target, ast.Name):
                        self.jit_names[target.id] = info
        # The plain defs that assignment-form jit calls wrapped: their
        # bodies are traced code too.
        if wrapped_names:
            for node in ast.walk(tree):
                if (isinstance(node, ast.FunctionDef)
                        and node.name in wrapped_names
                        and node not in self.jit_defs):
                    info = wrapped_names[node.name]
                    if info.node is None:
                        info.node = node
                    self.jit_defs[node] = info

    def _decorator_jit_info(self, deco):
        name = _terminal_name(deco)
        if name in _JIT_NAMES:
            return JitInfo()
        info, _ = _jit_call_info(deco)
        return info

    def _collect_globals(self, tree):
        for node in tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            mutable = isinstance(value, (ast.Dict, ast.List, ast.Set))
            if (isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Name)
                    and value.func.id in ("dict", "list", "set",
                                          "bytearray", "defaultdict")):
                mutable = True
            if not mutable:
                continue
            for target in targets:
                if isinstance(target, ast.Name):
                    self.mutable_globals.add(target.id)

    def _collect_mesh(self, tree):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _terminal_name(node.func) not in ("Mesh", "make_mesh"):
                continue
            candidates = list(node.args[1:2])
            candidates += [kw.value for kw in node.keywords
                           if kw.arg == "axis_names"]
            for cand in candidates:
                value = _literal(cand)
                if isinstance(value, str):
                    value = (value,)
                if isinstance(value, (tuple, list)):
                    axes = [v for v in value if isinstance(v, str)]
                    if axes:
                        self.mesh_axes.update(axes)
                        self.mesh_lines.append(node.lineno)

    # -- shared queries -----------------------------------------------

    def enclosing_jit(self, node):
        """The innermost jit-compiled def lexically containing `node`
        (the def itself excluded), or None. Nested plain defs inside a
        jit body still count as jit code: they are traced when called.
        """
        current = self.parents.get(node)
        while current is not None:
            if current in self.jit_defs:
                return current
            current = self.parents.get(current)
        return None

    def traced_params(self, def_node):
        """Positional/keyword parameter names of a jit def, minus the
        ones marked static and the instance receiver."""
        info = self.jit_defs[def_node]
        args = def_node.args
        ordered = [a.arg for a in args.posonlyargs + args.args]
        names = set(ordered + [a.arg for a in args.kwonlyargs])
        for index in info.static_argnums:
            if 0 <= index < len(ordered):
                names.discard(ordered[index])
        names -= info.static_argnames
        names.discard("self")
        names.discard("cls")
        return names

    def finding(self, node, rule, message):
        return Finding(self.path, node.lineno, node.col_offset, rule,
                       message)


# -- ordered scope events (GL003 / GL004 share this walker) -----------


def _scope_bodies(ctx):
    """Yields (body_statements,) for every straight-line scope: the
    module body and each function body. Nested defs are separate
    scopes (their statements are NOT merged into the parent's order).
    """
    yield ctx.tree.body
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.body


def _scope_events(body, ctx):
    """Flattens one scope body into an ordered event stream:
    ('load'|'store'|'donate'|'keyuse', name, node). Source order is
    approximated by statement order with assignment values visited
    before their targets — exactly what `x = step(x)` rebinding needs.
    """
    events = []

    def visit(node):
        if node is None:
            return
        if isinstance(node, ast.Name):
            kind = "store" if isinstance(node.ctx,
                                         (ast.Store, ast.Del)) else "load"
            events.append((kind, node.id, node))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            events.append(("store", node.name, node))
            return  # separate scope
        if isinstance(node, ast.Lambda):
            return  # separate scope
        if isinstance(node, ast.Call):
            visit(node.func)
            for arg in node.args:
                visit(arg)
            for kw in node.keywords:
                visit(kw.value)
            _call_events(node, ctx, events)
            return
        if isinstance(node, ast.Assign):
            visit(node.value)
            for target in node.targets:
                visit(target)
            return
        if isinstance(node, ast.AnnAssign):
            visit(node.value)
            visit(node.target)
            return
        if isinstance(node, ast.AugAssign):
            visit(node.value)
            # target is read-modify-write: load then store.
            if isinstance(node.target, ast.Name):
                events.append(("load", node.target.id, node.target))
                events.append(("store", node.target.id, node.target))
            else:
                visit(node.target)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            visit(node.iter)
            visit(node.target)
            for stmt in node.body + node.orelse:
                visit(stmt)
            return
        for child in ast.iter_child_nodes(node):
            visit(child)

    for stmt in body:
        visit(stmt)
    return events


def _call_events(node, ctx, events):
    """Appends donate/keyuse/keyuse_ip/escape events for one Call node
    (loads of its arguments were already emitted by the caller)."""
    func = node.func
    # Donation: a call to a known-jit callable with donate_argnums.
    if isinstance(func, ast.Name) and func.id in ctx.jit_names:
        info = ctx.jit_names[func.id]
        for pos in info.donate_argnums:
            if 0 <= pos < len(node.args):
                arg = node.args[pos]
                if isinstance(arg, ast.Name):
                    events.append(("donate", arg.id, node))
    # RNG key consumption: jax.random.<fn>(key, ...).
    if _is_random_call(func, ctx) and node.args:
        key = node.args[0]
        if isinstance(key, ast.Name):
            events.append(("keyuse", key.id, node))
    # Interprocedural facts: the call resolves to a function whose
    # summary says a parameter consumes a key / retains its argument.
    # Separate event kinds so GL004/GL003 keep their intraprocedural
    # jurisdiction and GL008/GL009 own the cross-call pairs.
    if ctx.project is not None:
        for arg in node.args:
            if not isinstance(arg, ast.Name):
                continue
            if ctx.project.consuming_key_param(ctx, node, arg.id):
                events.append(("keyuse_ip", arg.id, node))
            if ctx.project.retaining_param(ctx, node, arg.id):
                events.append(("escape", arg.id, node))


def _is_random_call(func, ctx):
    if isinstance(func, ast.Attribute):
        if func.attr == "PRNGKey" or func.attr == "key":
            return False  # creates keys, consumes nothing
        value = func.value
        if isinstance(value, ast.Attribute):  # jax.random.<fn>
            return (value.attr == "random"
                    and isinstance(value.value, ast.Name)
                    and value.value.id == "jax")
        if isinstance(value, ast.Name):      # random.<fn> / jrandom.<fn>
            return value.id in ctx.random_aliases
        return False
    if isinstance(func, ast.Name):           # from jax.random import fn
        return (func.id in ctx.random_funcs
                and func.id not in ("PRNGKey", "key"))
    return False


# -- the rules --------------------------------------------------------


class Rule:
    id = None
    title = None
    predicts = None  # the runtime counter this rule is the static half of

    def check(self, ctx):
        raise NotImplementedError


class HostSyncInJit(Rule):
    id = "GL001"
    title = "host-sync-in-jit"
    predicts = "transfer_stats().d2h_fetches"

    _MSG = ("host sync inside a jit-compiled function: {} forces a "
            "device->host transfer (or a trace-time concretization "
            "error) on every dispatch; compute on device and fetch "
            "once outside jit [predicts {} growth]")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.enclosing_jit(node) is None:
                continue
            label = self._host_sync_label(node)
            if label is not None:
                yield ctx.finding(node, self.id,
                                  self._MSG.format(label, self.predicts))

    @staticmethod
    def _host_sync_label(node):
        func = node.func
        if isinstance(func, ast.Name):
            if func.id == "float" and node.args:
                return "float(...)"
            if func.id == "print":
                return "print(...) (use jax.debug.print)"
        if isinstance(func, ast.Attribute):
            if func.attr == "item" and not node.args:
                return ".item()"
            if (func.attr in ("asarray", "array")
                    and _base_name(func) in _NUMPY_ALIASES):
                return "{}.{}(...)".format(_base_name(func), func.attr)
            if (func.attr == "device_get"
                    and _base_name(func) == "jax"):
                return "jax.device_get(...)"
        return None


class RetraceHazard(Rule):
    id = "GL002"
    title = "retrace-hazard"
    predicts = "compile_stats().n_traces"

    _ARG_MSG = ("{} passed as a traced argument to jit-compiled "
                "`{}` (no static_argnums/static_argnames): every "
                "distinct value mints a new trace — mark the argument "
                "static or move it into the array [predicts {} growth "
                "the runtime's on_retrace sentinel only catches at "
                "epoch 2]")
    _GLOBAL_MSG = ("jit-compiled function closes over mutable module "
                   "global `{}`: its value is baked in at trace time, "
                   "and later mutation either goes silently unseen or "
                   "forces a retrace [predicts {} growth]")

    def check(self, ctx):
        yield from self._call_site_hazards(ctx)
        yield from self._mutable_global_closures(ctx)

    def _call_site_hazards(self, ctx):
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)):
                continue
            info = ctx.jit_names.get(node.func.id)
            if info is None or info.has_statics:
                continue
            loop_vars = self._enclosing_loop_vars(ctx, node)
            for arg in node.args:
                label = self._hazard_label(arg, loop_vars)
                if label is not None:
                    yield ctx.finding(
                        arg, self.id,
                        self._ARG_MSG.format(label, node.func.id,
                                             self.predicts))

    @staticmethod
    def _hazard_label(arg, loop_vars):
        if (isinstance(arg, ast.Call)
                and isinstance(arg.func, ast.Name)
                and arg.func.id == "len"):
            return "`len(...)`-derived Python int"
        if isinstance(arg, ast.Dict):
            return "Python dict literal"
        if isinstance(arg, ast.Name) and arg.id in loop_vars:
            return "loop variable `{}`".format(arg.id)
        return None

    @staticmethod
    def _enclosing_loop_vars(ctx, node):
        names = set()
        current = ctx.parents.get(node)
        while current is not None:
            if isinstance(current, (ast.For, ast.AsyncFor)):
                for sub in ast.walk(current.target):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
            if isinstance(current, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                break
            current = ctx.parents.get(current)
        return names

    def _mutable_global_closures(self, ctx):
        if not ctx.mutable_globals:
            return
        for def_node, _ in ctx.jit_defs.items():
            if isinstance(def_node, ast.Lambda):
                continue
            local = self._local_bindings(def_node)
            seen = set()
            for node in ast.walk(def_node):
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and node.id in ctx.mutable_globals
                        and node.id not in local
                        and node.id not in seen):
                    seen.add(node.id)
                    yield ctx.finding(
                        node, self.id,
                        self._GLOBAL_MSG.format(node.id, self.predicts))

    @staticmethod
    def _local_bindings(def_node):
        args = def_node.args
        local = {a.arg for a in (args.posonlyargs + args.args
                                 + args.kwonlyargs)}
        if args.vararg:
            local.add(args.vararg.arg)
        if args.kwarg:
            local.add(args.kwarg.arg)
        for node in ast.walk(def_node):
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                local.add(node.id)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.ClassDef)):
                local.add(node.name)
        return local


class DonationAfterUse(Rule):
    id = "GL003"
    title = "donation-after-use"
    predicts = "donated-buffer UAF (jax 'donated buffers' warning)"

    _MSG = ("`{}` is read after being donated to jit-compiled `{}` at "
            "line {}: donate_argnums invalidates the caller's buffer, "
            "so this read sees freed or aliased memory — rebind the "
            "result (`{}` = ...) before reuse")

    def check(self, ctx):
        for body in _scope_bodies(ctx):
            donated = {}  # name -> (call node, callee name)
            for kind, name, node in _scope_events(body, ctx):
                if kind == "donate":
                    callee = node.func.id
                    donated[name] = (node, callee)
                elif kind == "store":
                    donated.pop(name, None)
                elif kind == "load" and name in donated:
                    call, callee = donated.pop(name)
                    yield ctx.finding(
                        node, self.id,
                        self._MSG.format(name, callee, call.lineno,
                                         name))


class RngKeyReuse(Rule):
    id = "GL004"
    title = "rng-key-reuse"
    predicts = "correlated randomness (no counter; silently wrong)"

    _MSG = ("RNG key `{}` flows into a second jax.random call (first "
            "consumed at line {}) without an intervening split: both "
            "draws see identical randomness — use "
            "`jax.random.split` and consume each subkey once")

    def check(self, ctx):
        for body in _scope_bodies(ctx):
            consumed = {}  # name -> first-use line
            for kind, name, node in _scope_events(body, ctx):
                if kind == "keyuse":
                    if name in consumed:
                        yield ctx.finding(
                            node, self.id,
                            self._MSG.format(name, consumed[name]))
                    else:
                        consumed[name] = node.lineno
                elif kind == "store":
                    consumed.pop(name, None)


class TracerControlFlow(Rule):
    id = "GL005"
    title = "tracer-control-flow"
    predicts = "compile_stats().n_traces (per-branch) or trace error"

    _MSG = ("`{}` branches on traced argument `{}` inside a "
            "jit-compiled function: tracing either fails "
            "(TracerBoolConversionError) or the argument must go "
            "static and every distinct value retraces — use "
            "jax.lax.cond / jax.lax.while_loop / jnp.where [predicts "
            "{}]")

    def check(self, ctx):
        for def_node in ctx.jit_defs:
            if isinstance(def_node, ast.Lambda):
                continue
            hazard_names = ctx.traced_params(def_node)
            if not hazard_names:
                continue
            for node in ast.walk(def_node):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                offender = self._traced_test_name(node.test,
                                                  hazard_names)
                if offender is not None:
                    keyword = ("if" if isinstance(node, ast.If)
                               else "while")
                    yield ctx.finding(
                        node, self.id,
                        self._MSG.format(keyword, offender,
                                         self.predicts))

    def _traced_test_name(self, test, hazard_names):
        """First hazard parameter whose VALUE the test depends on.
        Static facts about a traced arg are excluded: `x is None`,
        `isinstance(x, ...)`, `len(x)`, and attribute access like
        `x.ndim`/`cfg.remat` (shape/config metadata, known at trace
        time)."""
        found = []

        def collect(node):
            if isinstance(node, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in node.ops):
                return
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in _STATIC_CALLS):
                return
            if isinstance(node, ast.Attribute):
                if isinstance(node.value, ast.Name):
                    return
                collect(node.value)
                return
            if isinstance(node, ast.Name):
                if (isinstance(node.ctx, ast.Load)
                        and node.id in hazard_names):
                    found.append(node.id)
                return
            for child in ast.iter_child_nodes(node):
                collect(child)

        collect(test)
        return found[0] if found else None


class ShardingAxisMismatch(Rule):
    id = "GL006"
    title = "sharding-axis-mismatch"
    predicts = "mesh-resolution error at dispatch (after compile time)"

    _MSG = ("PartitionSpec axis {!r} is not declared by any mesh "
            "literal in {} (declared: {}): "
            "with_sharding_constraint would fail at dispatch, after "
            "the compile was already paid — fix the axis name or the "
            "mesh's axis_names")

    def check(self, ctx):
        # Axis names are checked against every Mesh literal the lint
        # invocation can see: the file's own meshes plus every other
        # linted module's (the common split is PartitionSpecs in
        # models/ against a Mesh built in parallel/sharding.py). A
        # file with no mesh in sight anywhere stays unchecked — the
        # mesh may live in code we were not asked to lint.
        project = ctx.project
        if project is not None and project.mesh_axes:
            known = set(project.mesh_axes)
            declared = project.declared_axes_label()
            scope = ("this file" if ctx.mesh_axes
                     else "any linted module")
        elif ctx.mesh_axes:
            known = ctx.mesh_axes
            declared = ", ".join(sorted(ctx.mesh_axes))
            scope = "this file"
        else:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _terminal_name(node.func)
            if name not in ctx.pspec_aliases:
                continue
            for arg in node.args:
                value = _literal(arg)
                axes = []
                if isinstance(value, str):
                    axes = [value]
                elif isinstance(value, (tuple, list)):
                    axes = [v for v in value if isinstance(v, str)]
                for axis in axes:
                    if axis not in known:
                        yield ctx.finding(
                            arg, self.id,
                            self._MSG.format(axis, scope, declared))


# -- interprocedural rules (read ctx.project) -------------------------


def _chain_label(chain):
    """'pkg.mod.f (line 3) -> pkg.mod.g (line 9: float(...))' for a
    host-sync chain; entries are (qualname, line[, label])."""
    parts = []
    for entry in chain:
        qualname, line = entry[0], entry[1]
        label = entry[2] if len(entry) > 2 else None
        if label:
            parts.append("{} (line {}: {})".format(qualname, line, label))
        else:
            parts.append("{} (line {})".format(qualname, line))
    return " -> ".join(parts)


class TransitiveHostSync(Rule):
    id = "GL007"
    title = "transitive-host-sync-in-jit"
    predicts = "transfer_stats().d2h_fetches"

    _MSG = ("call to `{}` inside a jit-compiled function reaches a "
            "host sync through its call chain: {} — hoist the sync "
            "out of the jitted region or return device values "
            "[predicts {} growth]")

    def check(self, ctx):
        if ctx.project is None:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.enclosing_jit(node) is None:
                continue
            if HostSyncInJit._host_sync_label(node) is not None:
                continue  # the direct form is GL001's finding
            chain = ctx.project.host_sync_chain(ctx, node.func)
            if chain:
                yield ctx.finding(
                    node, self.id,
                    self._MSG.format(_terminal_name(node.func),
                                     _chain_label(chain),
                                     self.predicts))


class RngKeyReuseAcrossCalls(Rule):
    id = "GL008"
    title = "rng-key-reuse-across-calls"
    predicts = "correlated randomness (no counter; silently wrong)"

    _MSG = ("RNG key `{}` is consumed twice (first at line {}, again "
            "here) and at least one consumption happens inside a "
            "callee: {} — both draws see identical randomness; "
            "`jax.random.split` before the call and pass a subkey")

    def check(self, ctx):
        project = ctx.project
        if project is None:
            return
        for body in _scope_bodies(ctx):
            consumed = {}  # name -> (kind, node) of the first use
            for kind, name, node in _scope_events(body, ctx):
                if kind in ("keyuse", "keyuse_ip"):
                    if name not in consumed:
                        consumed[name] = (kind, node)
                        continue
                    first_kind, first_node = consumed[name]
                    if "keyuse_ip" not in (kind, first_kind):
                        continue  # direct-direct pairs are GL004's
                    chain = (self._chain(project, ctx, node, name)
                             or self._chain(project, ctx, first_node,
                                            name) or [])
                    yield ctx.finding(
                        node, self.id,
                        self._MSG.format(name, first_node.lineno,
                                         _chain_label(chain)))
                elif kind == "store":
                    consumed.pop(name, None)

    @staticmethod
    def _chain(project, ctx, call_node, name):
        hit = project.consuming_key_param(ctx, call_node, name)
        if hit is None:
            return None
        callee, param = hit
        return project.key_chain(callee, param)


class DonationEscape(Rule):
    id = "GL009"
    title = "donation-escape"
    predicts = "donated-buffer UAF (jax 'donated buffers' warning)"

    _MSG = ("`{}` is donated to jit-compiled `{}` but a reference "
            "escaped at line {} into {} — the retained alias outlives "
            "the donation and will see freed or aliased memory; drop "
            "the retained reference or donate a copy")

    def check(self, ctx):
        project = ctx.project
        if project is None:
            return
        for body in _scope_bodies(ctx):
            escaped = {}  # name -> the escaping Call node
            for kind, name, node in _scope_events(body, ctx):
                if kind == "escape":
                    escaped.setdefault(name, node)
                elif kind == "store":
                    escaped.pop(name, None)
                elif kind == "donate" and name in escaped:
                    esc = escaped.pop(name)
                    hit = project.retaining_param(ctx, esc, name)
                    if hit is None:
                        continue
                    chain = project.retain_chain(*hit)
                    yield ctx.finding(
                        node, self.id,
                        self._MSG.format(name, node.func.id, esc.lineno,
                                         _chain_label(chain)))


ALL_RULES = [HostSyncInJit(), RetraceHazard(), DonationAfterUse(),
             RngKeyReuse(), TracerControlFlow(),
             ShardingAxisMismatch(), TransitiveHostSync(),
             RngKeyReuseAcrossCalls(), DonationEscape()]
