"""graftlint rules GL001-GL018.

Every rule is keyed to the runtime counter it predicts (PERF.md has the
table): the linter is the static half of the transfer/compile
accounting that `runtime.transfer_stats()` / `runtime.compile_stats()`
do at runtime. Rules are deliberately heuristic — they run on an AST,
with no types and no tracing — so each one is tuned to fire on the
unambiguous shape of its pitfall and stay silent otherwise; the escape
hatch for a deliberate pattern is a `# graftlint: disable=RULE` comment
on the flagged line.

Shared infrastructure: `FileContext` runs ONE pre-pass over the tree
collecting everything more than one rule needs — which functions are
jit-compiled (decorator forms, `functools.partial` forms, and
`g = jax.jit(f, ...)` assignment forms), their static/donated argument
positions, module-level mutable literals, mesh axis-name literals, and
the import aliases under which `PartitionSpec` and `jax.random` travel.

GL001-GL005 are intraprocedural and need only the FileContext. GL006
(cross-module mesh axes) and GL007-GL009 additionally read
`ctx.project` — a `callgraph.ProjectContext` over every file in the
lint invocation, attached by the engine before rules run — so facts
propagate through calls: a host sync two helpers below a jit body
(GL007), a key consumed inside a callee then reused at the call site
(GL008), a donated buffer retained by an earlier callee (GL009).

The graftseal family (this PR's jit-boundary/threading seal):
GL010 flags signature leaves a jit boundary carries but never reads
(the retrace shape the serving prefix-gather hit — dead per-slot
leaves binding one executable per slot count), using the callgraph's
`unread_params` to see through helper forwards; GL011 flags call
sites feeding unhashable values into static_argnums/static_argnames;
GL012 flags host-side branches and cache keys derived from an
argument's `.shape`/`.ndim` on a jit call path; GL013 checks lock
discipline per class — a field written under `with self._lock` in one
method but touched lock-free in a method reachable from a different
`threading.Thread` target, with `# graftlint: unlocked-ok` as the
sanction comment for documented single-writer fields.

The graftmesh family (GL014-GL018) cross-checks mesh-axis semantics
against the whole-program axis registry (`analysis/meshmap.py`, read
through `ctx.project.graftmesh()`): GL014 collectives over axes no
mesh literal declares, GL015 malformed PartitionSpecs (duplicate axis,
or longer than the annotated array's rank), GL016 shard_map bodies
that replicate an axis they shard without reducing over it, GL017
nested scopes re-pinning a value to a conflicting layout, GL018
statically-known dims not divisible by the mesh axis size sharding
them. All five honor the `# graftlint: axis-ok` sanction comment.
"""

import ast
import os

from cloud_tpu.analysis.engine import Finding

# Callables that make the wrapped function traced/compiled. `pjit` and
# `instrumented_jit` (cloud_tpu.parallel.runtime) behave like jax.jit
# for every rule here.
_JIT_NAMES = {"jit", "instrumented_jit", "pjit"}

# numpy's conventional import aliases: `np.asarray(x)` on a tracer
# inside jit is a concretization (host sync) hazard.
_NUMPY_ALIASES = {"np", "numpy", "onp"}

# Test-expression calls whose result is static even on traced args.
_STATIC_CALLS = {"len", "isinstance", "hasattr", "getattr", "callable",
                 "issubclass"}


def _terminal_name(node):
    """`jax.jit` -> 'jit', `runtime.instrumented_jit` ->
    'instrumented_jit', `jit` -> 'jit'; None for anything else."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _base_name(node):
    """`np.asarray` -> 'np' (the root Name of an attribute chain)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _literal(node):
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError, TypeError):
        return None


class JitInfo:
    """What we know about one jit-compiled callable."""

    __slots__ = ("static_argnums", "static_argnames", "donate_argnums",
                 "node", "bound")

    def __init__(self):
        self.static_argnums = set()
        self.static_argnames = set()
        self.donate_argnums = set()
        self.node = None  # the FunctionDef, when known
        #: True when the wrapped callable was a bound method
        #: (`jit(self._method)`): the def's `self` is already bound, so
        #: argnum indices are offset by one against the param list.
        self.bound = False

    @property
    def has_statics(self):
        return bool(self.static_argnums or self.static_argnames)

    def absorb_kwargs(self, call):
        """Reads static_argnums/static_argnames/donate_argnums literal
        keywords off a jit(...) / partial(jit, ...) call node."""
        for kw in call.keywords:
            value = _literal(kw.value)
            if value is None:
                continue
            if not isinstance(value, (tuple, list)):
                value = (value,)
            if kw.arg == "static_argnums":
                self.static_argnums |= {v for v in value
                                        if isinstance(v, int)}
            elif kw.arg == "static_argnames":
                self.static_argnames |= {v for v in value
                                         if isinstance(v, str)}
            elif kw.arg == "donate_argnums":
                self.donate_argnums |= {v for v in value
                                        if isinstance(v, int)}


def _jit_call_info(node):
    """If `node` is a Call that jit-compiles something, return
    (JitInfo, wrapped) where wrapped is the first positional argument
    (the function being compiled) or None. Handles `jax.jit(f, ...)`,
    `instrumented_jit(f, ...)` and `functools.partial(jax.jit, ...)`.
    """
    if not isinstance(node, ast.Call):
        return None, None
    name = _terminal_name(node.func)
    if name in _JIT_NAMES:
        info = JitInfo()
        info.absorb_kwargs(node)
        wrapped = node.args[0] if node.args else None
        return info, wrapped
    if name == "partial" and node.args:
        inner = _terminal_name(node.args[0])
        if inner in _JIT_NAMES:
            info = JitInfo()
            info.absorb_kwargs(node)
            return info, None  # partial(jit, ...) decorates the def below
    # Immediately-applied partial: `partial(jit, donate_argnums=...)(f)`
    # wraps f right there (the serving engine's executable-binding
    # idiom). The inner call must be the bare partial form (wrapped is
    # None) — `jit(f)(x)` is a dispatch, not a wrap.
    if isinstance(node.func, ast.Call):
        inner_info, inner_wrapped = _jit_call_info(node.func)
        if inner_info is not None and inner_wrapped is None:
            inner_info.absorb_kwargs(node)
            return inner_info, node.args[0] if node.args else None
    return None, None


class FileContext:
    """One shared pre-pass over the tree; rules read, never re-walk."""

    def __init__(self, tree, source, path):
        self.tree = tree
        self.source = source
        self.path = path
        self.parents = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

        #: FunctionDef/Lambda node -> JitInfo for jit-compiled defs.
        self.jit_defs = {}
        #: local callable name -> JitInfo (call sites: `g = jax.jit(f)`
        #: assignments AND decorated defs, callable by their own name).
        self.jit_names = {}
        #: instance attribute name -> JitInfo for the attribute form
        #: `self.tick = jit(self._tick_impl, ...)`; call sites look
        #: like `self.tick(...)`.
        self.jit_attr_names = {}
        #: module-level names bound to mutable literals ({} [] set()).
        self.mutable_globals = set()
        #: axis-name string literals declared by Mesh(...) in this file.
        self.mesh_axes = set()
        self.mesh_lines = []
        #: names PartitionSpec is importable under in this file.
        self.pspec_aliases = {"PartitionSpec"}
        #: names the jax.random module travels under (import aliases).
        self.random_aliases = {"jrandom", "jran"}
        #: function names imported directly from jax.random.
        self.random_funcs = set()
        #: callgraph.ProjectContext, attached by the engine once every
        #: file in the invocation is parsed; GL006-GL009 read it.
        self.project = None

        self._collect_imports(tree)
        self._collect_jit(tree)
        self._collect_globals(tree)
        self._collect_mesh(tree)

    # -- pre-pass collectors ------------------------------------------

    def _collect_imports(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                module = node.module or ""
                for alias in node.names:
                    bound = alias.asname or alias.name
                    if alias.name == "PartitionSpec":
                        self.pspec_aliases.add(bound)
                    if alias.name == "random" and module == "jax":
                        self.random_aliases.add(bound)
                    if module == "jax.random":
                        self.random_funcs.add(bound)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "jax.random" and alias.asname:
                        self.random_aliases.add(alias.asname)

    def _collect_jit(self, tree):
        # Decorated defs.
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    info = self._decorator_jit_info(deco)
                    if info is not None:
                        info.node = node
                        self.jit_defs[node] = info
                        self.jit_names[node.name] = info
                        break
        # Call form, wherever it appears: `jax.jit(train_step, ...)` in
        # an assignment, a return statement, or any expression marks
        # the wrapped def's body as traced code. Assignment targets
        # additionally become known-jit call-site names.
        wrapped_names = {}
        for node in ast.walk(tree):
            info, wrapped = _jit_call_info(node)
            if info is None:
                continue
            if isinstance(wrapped, ast.Name):
                wrapped_names[wrapped.id] = info
            elif isinstance(wrapped, ast.Lambda):
                info.node = wrapped
                self.jit_defs[wrapped] = info
            elif (isinstance(wrapped, ast.Attribute)
                  and isinstance(wrapped.value, ast.Name)
                  and wrapped.value.id == "self"):
                # Bound-method form: `jit(self._tick_impl, ...)` inside
                # a class. The wrapped def lives on the enclosing
                # ClassDef; `self` is pre-bound, so argnums shift.
                method = self._enclosing_class_method(node, wrapped.attr)
                if method is not None and method not in self.jit_defs:
                    info.bound = True
                    info.node = method
                    self.jit_defs[method] = info
            # Climb through single-argument wrapper calls
            # (`best_effort_donation(jit(...))`) to the binding site.
            parent = self.parents.get(node)
            while (isinstance(parent, ast.Call)
                   and len(parent.args) == 1 and parent.args[0] is node):
                node = parent
                parent = self.parents.get(node)
            if isinstance(parent, ast.Assign):
                for target in parent.targets:
                    if isinstance(target, ast.Name):
                        self.jit_names[target.id] = info
                    elif (isinstance(target, ast.Attribute)
                          and isinstance(target.value, ast.Name)
                          and target.value.id == "self"):
                        self.jit_attr_names[target.attr] = info
        # The plain defs that assignment-form jit calls wrapped: their
        # bodies are traced code too.
        if wrapped_names:
            for node in ast.walk(tree):
                if (isinstance(node, ast.FunctionDef)
                        and node.name in wrapped_names
                        and node not in self.jit_defs):
                    info = wrapped_names[node.name]
                    if info.node is None:
                        info.node = node
                    self.jit_defs[node] = info

    def _enclosing_class_method(self, node, name):
        """The FunctionDef named `name` on the ClassDef lexically
        containing `node`, or None."""
        current = self.parents.get(node)
        while current is not None:
            if isinstance(current, ast.ClassDef):
                for stmt in current.body:
                    if (isinstance(stmt, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                            and stmt.name == name):
                        return stmt
                return None
            current = self.parents.get(current)
        return None

    def _decorator_jit_info(self, deco):
        name = _terminal_name(deco)
        if name in _JIT_NAMES:
            return JitInfo()
        info, _ = _jit_call_info(deco)
        return info

    def _collect_globals(self, tree):
        for node in tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            mutable = isinstance(value, (ast.Dict, ast.List, ast.Set))
            if (isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Name)
                    and value.func.id in ("dict", "list", "set",
                                          "bytearray", "defaultdict")):
                mutable = True
            if not mutable:
                continue
            for target in targets:
                if isinstance(target, ast.Name):
                    self.mutable_globals.add(target.id)

    def _collect_mesh(self, tree):
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _terminal_name(node.func) not in ("Mesh", "make_mesh"):
                continue
            candidates = list(node.args[1:2])
            candidates += [kw.value for kw in node.keywords
                           if kw.arg == "axis_names"]
            for cand in candidates:
                value = _literal(cand)
                if isinstance(value, str):
                    value = (value,)
                if isinstance(value, (tuple, list)):
                    axes = [v for v in value if isinstance(v, str)]
                    if axes:
                        self.mesh_axes.update(axes)
                        self.mesh_lines.append(node.lineno)

    # -- shared queries -----------------------------------------------

    def enclosing_jit(self, node):
        """The innermost jit-compiled def lexically containing `node`
        (the def itself excluded), or None. Nested plain defs inside a
        jit body still count as jit code: they are traced when called.
        """
        current = self.parents.get(node)
        while current is not None:
            if current in self.jit_defs:
                return current
            current = self.parents.get(current)
        return None

    def traced_params(self, def_node):
        """Positional/keyword parameter names of a jit def, minus the
        ones marked static and the instance receiver."""
        info = self.jit_defs[def_node]
        args = def_node.args
        ordered = [a.arg for a in args.posonlyargs + args.args]
        names = set(ordered + [a.arg for a in args.kwonlyargs])
        # Bound-method wraps (`jit(self._m)`) number argnums against
        # the bound callable, which excludes the receiver.
        mapped = (ordered[1:] if info.bound and ordered
                  and ordered[0] in ("self", "cls") else ordered)
        for index in info.static_argnums:
            if 0 <= index < len(mapped):
                names.discard(mapped[index])
        names -= info.static_argnames
        names.discard("self")
        names.discard("cls")
        return names

    def finding(self, node, rule, message):
        return Finding(self.path, node.lineno, node.col_offset, rule,
                       message)


# -- ordered scope events (GL003 / GL004 share this walker) -----------


def _scope_bodies(ctx):
    """Yields (body_statements,) for every straight-line scope: the
    module body and each function body. Nested defs are separate
    scopes (their statements are NOT merged into the parent's order).
    """
    yield ctx.tree.body
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node.body


def _scope_events(body, ctx):
    """Flattens one scope body into an ordered event stream:
    ('load'|'store'|'donate'|'keyuse', name, node). Source order is
    approximated by statement order with assignment values visited
    before their targets — exactly what `x = step(x)` rebinding needs.
    """
    events = []

    def visit(node):
        if node is None:
            return
        if isinstance(node, ast.Name):
            kind = "store" if isinstance(node.ctx,
                                         (ast.Store, ast.Del)) else "load"
            events.append((kind, node.id, node))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            events.append(("store", node.name, node))
            return  # separate scope
        if isinstance(node, ast.Lambda):
            return  # separate scope
        if isinstance(node, ast.Call):
            visit(node.func)
            for arg in node.args:
                visit(arg)
            for kw in node.keywords:
                visit(kw.value)
            _call_events(node, ctx, events)
            return
        if isinstance(node, ast.Assign):
            visit(node.value)
            for target in node.targets:
                visit(target)
            return
        if isinstance(node, ast.AnnAssign):
            visit(node.value)
            visit(node.target)
            return
        if isinstance(node, ast.AugAssign):
            visit(node.value)
            # target is read-modify-write: load then store.
            if isinstance(node.target, ast.Name):
                events.append(("load", node.target.id, node.target))
                events.append(("store", node.target.id, node.target))
            else:
                visit(node.target)
            return
        if isinstance(node, (ast.For, ast.AsyncFor)):
            visit(node.iter)
            visit(node.target)
            for stmt in node.body + node.orelse:
                visit(stmt)
            return
        for child in ast.iter_child_nodes(node):
            visit(child)

    for stmt in body:
        visit(stmt)
    return events


def _call_events(node, ctx, events):
    """Appends donate/keyuse/keyuse_ip/escape events for one Call node
    (loads of its arguments were already emitted by the caller)."""
    func = node.func
    # Donation: a call to a known-jit callable with donate_argnums.
    if isinstance(func, ast.Name) and func.id in ctx.jit_names:
        info = ctx.jit_names[func.id]
        for pos in info.donate_argnums:
            if 0 <= pos < len(node.args):
                arg = node.args[pos]
                if isinstance(arg, ast.Name):
                    events.append(("donate", arg.id, node))
    # RNG key consumption: jax.random.<fn>(key, ...).
    if _is_random_call(func, ctx) and node.args:
        key = node.args[0]
        if isinstance(key, ast.Name):
            events.append(("keyuse", key.id, node))
    # Interprocedural facts: the call resolves to a function whose
    # summary says a parameter consumes a key / retains its argument.
    # Separate event kinds so GL004/GL003 keep their intraprocedural
    # jurisdiction and GL008/GL009 own the cross-call pairs.
    if ctx.project is not None:
        for arg in node.args:
            if not isinstance(arg, ast.Name):
                continue
            if ctx.project.consuming_key_param(ctx, node, arg.id):
                events.append(("keyuse_ip", arg.id, node))
            if ctx.project.retaining_param(ctx, node, arg.id):
                events.append(("escape", arg.id, node))


def _is_random_call(func, ctx):
    if isinstance(func, ast.Attribute):
        if func.attr == "PRNGKey" or func.attr == "key":
            return False  # creates keys, consumes nothing
        value = func.value
        if isinstance(value, ast.Attribute):  # jax.random.<fn>
            return (value.attr == "random"
                    and isinstance(value.value, ast.Name)
                    and value.value.id == "jax")
        if isinstance(value, ast.Name):      # random.<fn> / jrandom.<fn>
            return value.id in ctx.random_aliases
        return False
    if isinstance(func, ast.Name):           # from jax.random import fn
        return (func.id in ctx.random_funcs
                and func.id not in ("PRNGKey", "key"))
    return False


# -- the rules --------------------------------------------------------


class Rule:
    id = None
    title = None
    predicts = None  # the runtime counter this rule is the static half of

    def check(self, ctx):
        raise NotImplementedError


class HostSyncInJit(Rule):
    id = "GL001"
    title = "host-sync-in-jit"
    predicts = "transfer_stats().d2h_fetches"

    _MSG = ("host sync inside a jit-compiled function: {} forces a "
            "device->host transfer (or a trace-time concretization "
            "error) on every dispatch; compute on device and fetch "
            "once outside jit [predicts {} growth]")

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.enclosing_jit(node) is None:
                continue
            label = self._host_sync_label(node)
            if label is not None:
                yield ctx.finding(node, self.id,
                                  self._MSG.format(label, self.predicts))

    @staticmethod
    def _host_sync_label(node):
        func = node.func
        if isinstance(func, ast.Name):
            if func.id == "float" and node.args:
                return "float(...)"
            if func.id == "print":
                return "print(...) (use jax.debug.print)"
        if isinstance(func, ast.Attribute):
            if func.attr == "item" and not node.args:
                return ".item()"
            if (func.attr in ("asarray", "array")
                    and _base_name(func) in _NUMPY_ALIASES):
                return "{}.{}(...)".format(_base_name(func), func.attr)
            if (func.attr == "device_get"
                    and _base_name(func) == "jax"):
                return "jax.device_get(...)"
        return None


class RetraceHazard(Rule):
    id = "GL002"
    title = "retrace-hazard"
    predicts = "compile_stats().n_traces"

    _ARG_MSG = ("{} passed as a traced argument to jit-compiled "
                "`{}` (no static_argnums/static_argnames): every "
                "distinct value mints a new trace — mark the argument "
                "static or move it into the array [predicts {} growth "
                "the runtime's on_retrace sentinel only catches at "
                "epoch 2]")
    _GLOBAL_MSG = ("jit-compiled function closes over mutable module "
                   "global `{}`: its value is baked in at trace time, "
                   "and later mutation either goes silently unseen or "
                   "forces a retrace [predicts {} growth]")

    def check(self, ctx):
        yield from self._call_site_hazards(ctx)
        yield from self._mutable_global_closures(ctx)

    def _call_site_hazards(self, ctx):
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)):
                continue
            info = ctx.jit_names.get(node.func.id)
            if info is None or info.has_statics:
                continue
            loop_vars = self._enclosing_loop_vars(ctx, node)
            for arg in node.args:
                label = self._hazard_label(arg, loop_vars)
                if label is not None:
                    yield ctx.finding(
                        arg, self.id,
                        self._ARG_MSG.format(label, node.func.id,
                                             self.predicts))

    @staticmethod
    def _hazard_label(arg, loop_vars):
        if (isinstance(arg, ast.Call)
                and isinstance(arg.func, ast.Name)
                and arg.func.id == "len"):
            return "`len(...)`-derived Python int"
        if isinstance(arg, ast.Dict):
            return "Python dict literal"
        if isinstance(arg, ast.Name) and arg.id in loop_vars:
            return "loop variable `{}`".format(arg.id)
        return None

    @staticmethod
    def _enclosing_loop_vars(ctx, node):
        names = set()
        current = ctx.parents.get(node)
        while current is not None:
            if isinstance(current, (ast.For, ast.AsyncFor)):
                for sub in ast.walk(current.target):
                    if isinstance(sub, ast.Name):
                        names.add(sub.id)
            if isinstance(current, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                break
            current = ctx.parents.get(current)
        return names

    def _mutable_global_closures(self, ctx):
        if not ctx.mutable_globals:
            return
        for def_node, _ in ctx.jit_defs.items():
            if isinstance(def_node, ast.Lambda):
                continue
            local = self._local_bindings(def_node)
            seen = set()
            for node in ast.walk(def_node):
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and node.id in ctx.mutable_globals
                        and node.id not in local
                        and node.id not in seen):
                    seen.add(node.id)
                    yield ctx.finding(
                        node, self.id,
                        self._GLOBAL_MSG.format(node.id, self.predicts))

    @staticmethod
    def _local_bindings(def_node):
        args = def_node.args
        local = {a.arg for a in (args.posonlyargs + args.args
                                 + args.kwonlyargs)}
        if args.vararg:
            local.add(args.vararg.arg)
        if args.kwarg:
            local.add(args.kwarg.arg)
        for node in ast.walk(def_node):
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                local.add(node.id)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.ClassDef)):
                local.add(node.name)
        return local


class DonationAfterUse(Rule):
    id = "GL003"
    title = "donation-after-use"
    predicts = "donated-buffer UAF (jax 'donated buffers' warning)"

    _MSG = ("`{}` is read after being donated to jit-compiled `{}` at "
            "line {}: donate_argnums invalidates the caller's buffer, "
            "so this read sees freed or aliased memory — rebind the "
            "result (`{}` = ...) before reuse")

    def check(self, ctx):
        for body in _scope_bodies(ctx):
            donated = {}  # name -> (call node, callee name)
            for kind, name, node in _scope_events(body, ctx):
                if kind == "donate":
                    callee = node.func.id
                    donated[name] = (node, callee)
                elif kind == "store":
                    donated.pop(name, None)
                elif kind == "load" and name in donated:
                    call, callee = donated.pop(name)
                    yield ctx.finding(
                        node, self.id,
                        self._MSG.format(name, callee, call.lineno,
                                         name))


class RngKeyReuse(Rule):
    id = "GL004"
    title = "rng-key-reuse"
    predicts = "correlated randomness (no counter; silently wrong)"

    _MSG = ("RNG key `{}` flows into a second jax.random call (first "
            "consumed at line {}) without an intervening split: both "
            "draws see identical randomness — use "
            "`jax.random.split` and consume each subkey once")

    def check(self, ctx):
        for body in _scope_bodies(ctx):
            consumed = {}  # name -> first-use line
            for kind, name, node in _scope_events(body, ctx):
                if kind == "keyuse":
                    if name in consumed:
                        yield ctx.finding(
                            node, self.id,
                            self._MSG.format(name, consumed[name]))
                    else:
                        consumed[name] = node.lineno
                elif kind == "store":
                    consumed.pop(name, None)


class TracerControlFlow(Rule):
    id = "GL005"
    title = "tracer-control-flow"
    predicts = "compile_stats().n_traces (per-branch) or trace error"

    _MSG = ("`{}` branches on traced argument `{}` inside a "
            "jit-compiled function: tracing either fails "
            "(TracerBoolConversionError) or the argument must go "
            "static and every distinct value retraces — use "
            "jax.lax.cond / jax.lax.while_loop / jnp.where [predicts "
            "{}]")

    def check(self, ctx):
        for def_node in ctx.jit_defs:
            if isinstance(def_node, ast.Lambda):
                continue
            hazard_names = ctx.traced_params(def_node)
            if not hazard_names:
                continue
            for node in ast.walk(def_node):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                offender = self._traced_test_name(node.test,
                                                  hazard_names)
                if offender is not None:
                    keyword = ("if" if isinstance(node, ast.If)
                               else "while")
                    yield ctx.finding(
                        node, self.id,
                        self._MSG.format(keyword, offender,
                                         self.predicts))

    def _traced_test_name(self, test, hazard_names):
        """First hazard parameter whose VALUE the test depends on.
        Static facts about a traced arg are excluded: `x is None`,
        `isinstance(x, ...)`, `len(x)`, and attribute access like
        `x.ndim`/`cfg.remat` (shape/config metadata, known at trace
        time)."""
        found = []

        def collect(node):
            if isinstance(node, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot))
                    for op in node.ops):
                return
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in _STATIC_CALLS):
                return
            if isinstance(node, ast.Attribute):
                if isinstance(node.value, ast.Name):
                    return
                collect(node.value)
                return
            if isinstance(node, ast.Name):
                if (isinstance(node.ctx, ast.Load)
                        and node.id in hazard_names):
                    found.append(node.id)
                return
            for child in ast.iter_child_nodes(node):
                collect(child)

        collect(test)
        return found[0] if found else None


class ShardingAxisMismatch(Rule):
    id = "GL006"
    title = "sharding-axis-mismatch"
    predicts = "mesh-resolution error at dispatch (after compile time)"

    _MSG = ("PartitionSpec axis {!r} is not declared by any mesh "
            "literal in {} (declared: {}): "
            "with_sharding_constraint would fail at dispatch, after "
            "the compile was already paid — fix the axis name or the "
            "mesh's axis_names")

    def check(self, ctx):
        # Axis names are checked against every Mesh literal the lint
        # invocation can see: the file's own meshes plus every other
        # linted module's (the common split is PartitionSpecs in
        # models/ against a Mesh built in parallel/sharding.py). A
        # file with no mesh in sight anywhere stays unchecked — the
        # mesh may live in code we were not asked to lint.
        project = ctx.project
        if project is not None and project.mesh_axes:
            known = set(project.mesh_axes)
            declared = project.declared_axes_label()
            scope = ("this file" if ctx.mesh_axes
                     else "any linted module")
        elif ctx.mesh_axes:
            known = ctx.mesh_axes
            declared = ", ".join(sorted(ctx.mesh_axes))
            scope = "this file"
        else:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _terminal_name(node.func)
            if name not in ctx.pspec_aliases:
                continue
            for arg in node.args:
                value = _literal(arg)
                axes = []
                if isinstance(value, str):
                    axes = [value]
                elif isinstance(value, (tuple, list)):
                    axes = [v for v in value if isinstance(v, str)]
                for axis in axes:
                    if axis not in known:
                        yield ctx.finding(
                            arg, self.id,
                            self._MSG.format(axis, scope, declared))


# -- interprocedural rules (read ctx.project) -------------------------


def _chain_label(chain):
    """'pkg.mod.f (line 3) -> pkg.mod.g (line 9: float(...))' for a
    host-sync chain; entries are (qualname, line[, label])."""
    parts = []
    for entry in chain:
        qualname, line = entry[0], entry[1]
        label = entry[2] if len(entry) > 2 else None
        if label:
            parts.append("{} (line {}: {})".format(qualname, line, label))
        else:
            parts.append("{} (line {})".format(qualname, line))
    return " -> ".join(parts)


class TransitiveHostSync(Rule):
    id = "GL007"
    title = "transitive-host-sync-in-jit"
    predicts = "transfer_stats().d2h_fetches"

    _MSG = ("call to `{}` inside a jit-compiled function reaches a "
            "host sync through its call chain: {} — hoist the sync "
            "out of the jitted region or return device values "
            "[predicts {} growth]")

    def check(self, ctx):
        if ctx.project is None:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if ctx.enclosing_jit(node) is None:
                continue
            if HostSyncInJit._host_sync_label(node) is not None:
                continue  # the direct form is GL001's finding
            chain = ctx.project.host_sync_chain(ctx, node.func)
            if chain:
                yield ctx.finding(
                    node, self.id,
                    self._MSG.format(_terminal_name(node.func),
                                     _chain_label(chain),
                                     self.predicts))


class RngKeyReuseAcrossCalls(Rule):
    id = "GL008"
    title = "rng-key-reuse-across-calls"
    predicts = "correlated randomness (no counter; silently wrong)"

    _MSG = ("RNG key `{}` is consumed twice (first at line {}, again "
            "here) and at least one consumption happens inside a "
            "callee: {} — both draws see identical randomness; "
            "`jax.random.split` before the call and pass a subkey")

    def check(self, ctx):
        project = ctx.project
        if project is None:
            return
        for body in _scope_bodies(ctx):
            consumed = {}  # name -> (kind, node) of the first use
            for kind, name, node in _scope_events(body, ctx):
                if kind in ("keyuse", "keyuse_ip"):
                    if name not in consumed:
                        consumed[name] = (kind, node)
                        continue
                    first_kind, first_node = consumed[name]
                    if "keyuse_ip" not in (kind, first_kind):
                        continue  # direct-direct pairs are GL004's
                    chain = (self._chain(project, ctx, node, name)
                             or self._chain(project, ctx, first_node,
                                            name) or [])
                    yield ctx.finding(
                        node, self.id,
                        self._MSG.format(name, first_node.lineno,
                                         _chain_label(chain)))
                elif kind == "store":
                    consumed.pop(name, None)

    @staticmethod
    def _chain(project, ctx, call_node, name):
        hit = project.consuming_key_param(ctx, call_node, name)
        if hit is None:
            return None
        callee, param = hit
        return project.key_chain(callee, param)


class DonationEscape(Rule):
    id = "GL009"
    title = "donation-escape"
    predicts = "donated-buffer UAF (jax 'donated buffers' warning)"

    _MSG = ("`{}` is donated to jit-compiled `{}` but a reference "
            "escaped at line {} into {} — the retained alias outlives "
            "the donation and will see freed or aliased memory; drop "
            "the retained reference or donate a copy")

    def check(self, ctx):
        project = ctx.project
        if project is None:
            return
        for body in _scope_bodies(ctx):
            escaped = {}  # name -> the escaping Call node
            for kind, name, node in _scope_events(body, ctx):
                if kind == "escape":
                    escaped.setdefault(name, node)
                elif kind == "store":
                    escaped.pop(name, None)
                elif kind == "donate" and name in escaped:
                    esc = escaped.pop(name)
                    hit = project.retaining_param(ctx, esc, name)
                    if hit is None:
                        continue
                    chain = project.retain_chain(*hit)
                    yield ctx.finding(
                        node, self.id,
                        self._MSG.format(name, node.func.id, esc.lineno,
                                         _chain_label(chain)))


# -- graftseal rules: jit-boundary signature + lock discipline --------


def _ordered_params(def_node, info=None):
    """Positional parameter names a call site's args map onto, with the
    bound receiver stripped for `jit(self._method)` wraps."""
    args = def_node.args
    ordered = [a.arg for a in args.posonlyargs + args.args]
    if (info is not None and info.bound and ordered
            and ordered[0] in ("self", "cls")):
        return ordered[1:]
    return ordered


class DeadJitSignatureLeaf(Rule):
    id = "GL010"
    title = "dead-leaf-in-jit-signature"
    predicts = "compile_stats().n_traces"

    _PARAM_MSG = ("traced argument `{}` of jit-compiled `{}` is never "
                  "read by the traced body{}: the leaf still shapes "
                  "the executable's signature, so every distinct aval "
                  "it takes mints a fresh compile — drop the argument "
                  "or mark it static [predicts {} growth]")
    _LEAF_MSG = ("dict leaf {!r} passed into jit-compiled `{}` is "
                 "never subscripted by the traced body (it only reads "
                 "{}): the dead leaf widens the signature and every "
                 "distinct aval mints a fresh compile — drop it from "
                 "the call [predicts {} growth]")

    def check(self, ctx):
        yield from self._dead_params(ctx)
        yield from self._dead_dict_leaves(ctx)

    # -- whole-argument leaves ----------------------------------------

    def _dead_params(self, ctx):
        for def_node in ctx.jit_defs:
            if isinstance(def_node, ast.Lambda):
                continue
            traced = ctx.traced_params(def_node)
            if not traced:
                continue
            reads, forwards = self._classify(def_node)
            for param in sorted(traced):
                if param.startswith("_") or param in reads:
                    continue  # `_unused` is the rename-sanction
                fwd = forwards.get(param)
                if not fwd:
                    yield ctx.finding(
                        def_node, self.id,
                        self._PARAM_MSG.format(param, def_node.name, "",
                                               self.predicts))
                    continue
                chain = self._dead_forward_chain(ctx, fwd)
                if chain is not None:
                    yield ctx.finding(
                        def_node, self.id,
                        self._PARAM_MSG.format(
                            param, def_node.name,
                            " (forwarded to {}, which never reads "
                            "it)".format(chain), self.predicts))

    @staticmethod
    def _classify(def_node):
        """(reads, forwards) over the def body: params with a real read
        vs params only forwarded as plain positional call arguments —
        the same split callgraph.FunctionSummary makes, but usable on
        methods and nested defs the project call graph skips."""
        args = def_node.args
        params = {a.arg for a in (args.posonlyargs + args.args
                                  + args.kwonlyargs)}
        forwards = {}
        forward_ids = set()
        for node in ast.walk(def_node):
            if not isinstance(node, ast.Call):
                continue
            if any(isinstance(a, ast.Starred) for a in node.args):
                continue  # splats break positional mapping: real reads
            for pos, arg in enumerate(node.args):
                if isinstance(arg, ast.Name) and arg.id in params:
                    forward_ids.add(id(arg))
                    forwards.setdefault(arg.id, []).append((node, pos))
        reads = set()
        for node in ast.walk(def_node):
            if (isinstance(node, ast.Name) and node.id in params
                    and id(node) not in forward_ids):
                reads.add(node.id)
        return reads, forwards

    @staticmethod
    def _dead_forward_chain(ctx, forwards):
        """Qualname label when EVERY forward lands on a callee param
        the project fixpoint proved unread; None otherwise (method
        calls and other unresolvable callees count as reads)."""
        project = ctx.project
        if project is None:
            return None
        labels = []
        for call, pos in forwards:
            callee = project.resolve_call(ctx, call.func)
            if (callee is None or pos >= len(callee.params)
                    or callee.params[pos] not in callee.unread_params):
                return None
            labels.append("{}`{}`".format(
                "" if not labels else " and ", callee.qualname))
        return "".join(labels)

    # -- container leaves (the serving prefix-gather shape) ------------

    def _dead_dict_leaves(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            info, label = _jit_callee_info(ctx, node)
            if info is None or not isinstance(info.node,
                                              (ast.FunctionDef,
                                               ast.AsyncFunctionDef)):
                continue
            params = _ordered_params(info.node, info)
            for pos, arg in enumerate(node.args):
                if not isinstance(arg, ast.Dict) or pos >= len(params):
                    continue
                keys = [k.value for k in arg.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)]
                if len(keys) != len(arg.keys):
                    continue  # **spread or non-literal keys: opaque
                param = params[pos]
                if param not in ctx.traced_params(info.node):
                    continue
                used = self._subscripted_keys(ctx, info.node, param)
                if used is None:
                    continue  # whole-dict uses: every leaf may be live
                for key, key_node in zip(keys, arg.keys):
                    if key not in used:
                        yield ctx.finding(
                            key_node, self.id,
                            self._LEAF_MSG.format(
                                key, label,
                                ", ".join(sorted(used)) or "nothing",
                                self.predicts))

    @staticmethod
    def _subscripted_keys(ctx, def_node, param):
        """The set of literal keys `param` is subscripted with inside
        the def, or None when any use is not a literal subscript (the
        dict then escapes whole and no leaf is provably dead)."""
        used = set()
        for node in ast.walk(def_node):
            if not (isinstance(node, ast.Name) and node.id == param
                    and isinstance(node.ctx, ast.Load)):
                continue
            parent = ctx.parents.get(node)
            if (isinstance(parent, ast.Subscript)
                    and parent.value is node
                    and isinstance(parent.slice, ast.Constant)
                    and isinstance(parent.slice.value, str)):
                used.add(parent.slice.value)
            else:
                return None
        return used


def _jit_callee_info(ctx, call):
    """(JitInfo, human label) when `call` dispatches into a known jit
    callable — `tick(...)` via jit_names or `self.tick(...)` via the
    attribute form — else (None, None)."""
    func = call.func
    if isinstance(func, ast.Name) and func.id in ctx.jit_names:
        return ctx.jit_names[func.id], func.id
    if (isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
            and func.attr in ctx.jit_attr_names):
        return ctx.jit_attr_names[func.attr], "self." + func.attr
    return None, None


class UnhashableStaticArg(Rule):
    id = "GL011"
    title = "unhashable-static-arg"
    predicts = "ValueError at dispatch (static args are cache keys)"

    _MSG = ("static argument {} of jit-compiled `{}` receives {}: "
            "static args are hashed into the compile-cache key, so "
            "unhashable values raise at the first call (and mutable "
            "ones would silently alias cache entries) — pass a tuple "
            "or a frozen config instead")

    _BUILDERS = {"list", "dict", "set", "bytearray", "sorted"}
    _ARRAY_FUNCS = {"array", "asarray", "ones", "zeros", "arange",
                    "empty", "full"}
    _ARRAY_BASES = _NUMPY_ALIASES | {"jnp"}

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            info, label = _jit_callee_info(ctx, node)
            if info is None or not info.has_statics:
                continue
            for pos in info.static_argnums:
                if 0 <= pos < len(node.args):
                    bad = self._unhashable_label(node.args[pos])
                    if bad is not None:
                        yield ctx.finding(
                            node.args[pos], self.id,
                            self._MSG.format(pos, label, bad))
            for kw in node.keywords:
                if kw.arg in info.static_argnames:
                    bad = self._unhashable_label(kw.value)
                    if bad is not None:
                        yield ctx.finding(
                            kw.value, self.id,
                            self._MSG.format(repr(kw.arg), label, bad))

    @classmethod
    def _unhashable_label(cls, node):
        if isinstance(node, ast.List):
            return "a list literal"
        if isinstance(node, ast.Dict):
            return "a dict literal"
        if isinstance(node, ast.Set):
            return "a set literal"
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp)):
            return "a comprehension"
        if isinstance(node, ast.Call):
            name = _terminal_name(node.func)
            if (isinstance(node.func, ast.Name)
                    and name in cls._BUILDERS):
                return "a `{}(...)` value".format(name)
            if (name in cls._ARRAY_FUNCS
                    and _base_name(node.func) in cls._ARRAY_BASES):
                return "an ndarray (`{}.{}`)".format(
                    _base_name(node.func), name)
        return None


class RetraceProneCacheKey(Rule):
    id = "GL012"
    title = "retrace-prone-cache-key"
    predicts = "compile_stats().n_traces"

    _MSG = ("host-side {} on `{}.{}` in `{}`, which dispatches into "
            "jit: shape-keyed host control flow selects or mints one "
            "executable per distinct shape — bucket shapes explicitly "
            "(pow2 ladder) or fold the value into the traced "
            "signature [predicts {} growth]")

    def check(self, ctx):
        for def_node in ast.walk(ctx.tree):
            if not isinstance(def_node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                continue
            if (def_node in ctx.jit_defs
                    or ctx.enclosing_jit(def_node) is not None):
                continue  # traced code is GL005's jurisdiction
            if not self._dispatches_jit(ctx, def_node):
                continue
            args = def_node.args
            params = {a.arg for a in (args.posonlyargs + args.args
                                      + args.kwonlyargs)}
            params -= {"self", "cls"}
            if not params:
                continue
            seen = set()
            for node in ast.walk(def_node):
                if self._nearest_def(ctx, node) is not def_node:
                    continue
                if isinstance(node, (ast.If, ast.While)):
                    if (isinstance(node, ast.If) and not node.orelse
                            and all(isinstance(s, (ast.Raise, ast.Assert))
                                    for s in node.body)):
                        continue  # shape-validation guard: raising on a
                        # bad shape is the fix, not the hazard
                    kind, expr = "branch", node.test
                elif (isinstance(node, ast.Subscript)
                      and not self._subscripts_param(node, params)):
                    kind, expr = "cache key", node.slice
                else:
                    continue
                hit = self._shape_ref(expr, params)
                if hit is None or (node.lineno, hit) in seen:
                    continue
                seen.add((node.lineno, hit))
                param, attr = hit
                yield ctx.finding(
                    node, self.id,
                    self._MSG.format(kind, param, attr, def_node.name,
                                     self.predicts))

    @staticmethod
    def _dispatches_jit(ctx, def_node):
        for node in ast.walk(def_node):
            if not isinstance(node, ast.Call):
                continue
            info, _ = _jit_callee_info(ctx, node)
            if info is not None:
                return True
            if _terminal_name(node.func) in _JIT_NAMES:
                return True  # minting executables right here
        return False

    @staticmethod
    def _nearest_def(ctx, node):
        current = ctx.parents.get(node)
        while current is not None:
            if isinstance(current, (ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.Lambda)):
                return current
            current = ctx.parents.get(current)
        return None

    @staticmethod
    def _subscripts_param(node, params):
        """True for `x[...]` / `x.pages[...]` where x is a param: array
        indexing with shape arithmetic is normal host code — the
        hazard is shape-keyed lookup into *other* containers."""
        value = node.value
        while isinstance(value, ast.Attribute):
            value = value.value
        return isinstance(value, ast.Name) and value.id in params

    @staticmethod
    def _shape_ref(expr, params):
        """(param, 'shape'|'ndim') when the expression reads a shape
        fact off a parameter; None otherwise."""
        for node in ast.walk(expr):
            if (isinstance(node, ast.Attribute)
                    and node.attr in ("shape", "ndim")
                    and isinstance(node.value, ast.Name)
                    and node.value.id in params):
                return node.value.id, node.attr
        return None


class LockDiscipline(Rule):
    id = "GL013"
    title = "lock-discipline"
    predicts = "data race (no counter; torn state under interleaving)"

    _MSG = ("`self.{field}` is written under `self.{lock}` "
            "({writer} line {wline}) but {verb} here without it; "
            "`{method}` is reachable from thread root `{root}` while "
            "the locked writer runs from `{wroot}` — acquire "
            "`self.{lock}`, or sanction a documented single-writer "
            "field with `# graftlint: unlocked-ok` on this line")

    _LOCK_TYPES = {"Lock", "RLock", "Condition"}
    _MUTATORS = {"append", "appendleft", "extend", "insert", "add",
                 "remove", "discard", "pop", "popleft", "clear",
                 "update", "setdefault", "put"}
    _SANCTION = "graftlint: unlocked-ok"

    def check(self, ctx):
        sanctioned = {i + 1 for i, line
                      in enumerate(ctx.source.splitlines())
                      if self._SANCTION in line}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node, sanctioned)

    def _check_class(self, ctx, cls, sanctioned):
        methods = {d.name: d for d in cls.body
                   if isinstance(d, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))}
        locks = self._lock_fields(methods)
        targets = self._thread_targets(cls, methods)
        if not locks or not targets:
            return  # no lock or provably single-threaded: no discipline
        roots = self._roots(methods, targets)
        accesses, locked_writes = self._collect_accesses(
            ctx, methods, locks)
        flagged = set()
        for (lock, field), writers in sorted(locked_writes.items()):
            writer_roots = set()
            for method, _ in writers:
                writer_roots |= roots.get(method, set())
            wname, wnode = writers[0]
            for method, node, held, is_write in accesses.get(field, ()):
                if lock in held or node.lineno in sanctioned:
                    continue
                acc_roots = roots.get(method, set())
                pair = self._differing_roots(writer_roots, acc_roots)
                if pair is None or (field, node.lineno) in flagged:
                    continue
                flagged.add((field, node.lineno))
                yield ctx.finding(
                    node, self.id,
                    self._MSG.format(
                        field=field, lock=lock, writer=wname,
                        wline=wnode.lineno,
                        verb="written" if is_write else "read",
                        method=method, root=pair[1], wroot=pair[0]))

    # -- per-class facts -----------------------------------------------

    @classmethod
    def _lock_fields(cls, methods):
        locks = set()
        for method in methods.values():
            for node in ast.walk(method):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and _terminal_name(node.value.func)
                        in cls._LOCK_TYPES):
                    continue
                for target in node.targets:
                    if (isinstance(target, ast.Attribute)
                            and isinstance(target.value, ast.Name)
                            and target.value.id == "self"):
                        locks.add(target.attr)
        return locks

    @staticmethod
    def _thread_targets(cls_node, methods):
        targets = set()
        for node in ast.walk(cls_node):
            if not (isinstance(node, ast.Call)
                    and _terminal_name(node.func) == "Thread"):
                continue
            for kw in node.keywords:
                if (kw.arg == "target"
                        and isinstance(kw.value, ast.Attribute)
                        and isinstance(kw.value.value, ast.Name)
                        and kw.value.value.id == "self"
                        and kw.value.attr in methods):
                    targets.add(kw.value.attr)
        return targets

    @staticmethod
    def _roots(methods, targets):
        """method name -> set of thread roots that can reach it: each
        Thread target's name, plus 'caller' for the public API surface
        (any non-underscore method runs on whatever thread calls it).
        __init__ runs before the threads exist and is excluded."""
        edges = {}
        for name, method in methods.items():
            callees = set()
            for node in ast.walk(method):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and node.func.attr in methods):
                    callees.add(node.func.attr)
            edges[name] = callees
        roots = {}
        seeds = [(t, t) for t in sorted(targets)]
        seeds += [("caller", name) for name in methods
                  if not name.startswith("_")]
        for root, seed in seeds:
            stack = [seed]
            while stack:
                name = stack.pop()
                if name in ("__init__", "__del__"):
                    continue
                reached = roots.setdefault(name, set())
                if root in reached:
                    continue
                reached.add(root)
                stack.extend(edges.get(name, ()))
        return roots

    @classmethod
    def _collect_accesses(cls, ctx, methods, locks):
        """(accesses, locked_writes): every `self.<field>` touch per
        method with the lock set lexically held at that node, and the
        (lock, field) -> [(method, node)] map of guarded writes."""
        accesses = {}
        locked_writes = {}
        for name, method in methods.items():
            if name in ("__init__", "__del__"):
                continue  # construction precedes the threads
            for node in ast.walk(method):
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"):
                    continue
                field = node.attr
                if field in locks:
                    continue  # touching the lock object itself
                is_write = cls._is_write(ctx, node)
                held = cls._held_locks(ctx, node, method, locks)
                accesses.setdefault(field, []).append(
                    (name, node, held, is_write))
                if is_write:
                    for lock in held:
                        locked_writes.setdefault(
                            (lock, field), []).append((name, node))
        return accesses, locked_writes

    @classmethod
    def _is_write(cls, ctx, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            return True
        parent = ctx.parents.get(node)
        if isinstance(parent, ast.AugAssign) and parent.target is node:
            return True
        # Mutating container call: self.field.append(...) and friends.
        if (isinstance(parent, ast.Attribute)
                and parent.attr in cls._MUTATORS):
            grand = ctx.parents.get(parent)
            if isinstance(grand, ast.Call) and grand.func is parent:
                return True
        return False

    @staticmethod
    def _held_locks(ctx, node, method, locks):
        held = set()
        current = ctx.parents.get(node)
        while current is not None and current is not method:
            if isinstance(current, (ast.With, ast.AsyncWith)):
                for item in current.items:
                    expr = item.context_expr
                    if (isinstance(expr, ast.Attribute)
                            and isinstance(expr.value, ast.Name)
                            and expr.value.id == "self"
                            and expr.attr in locks):
                        held.add(expr.attr)
            current = ctx.parents.get(current)
        return held

    @staticmethod
    def _differing_roots(writer_roots, acc_roots):
        """(writer_root, access_root) with writer != access, preferring
        real thread names over the 'caller' pseudo-root; None when the
        two sides cannot run concurrently."""
        best = None
        for w in sorted(writer_roots):
            for a in sorted(acc_roots):
                if w == a:
                    continue
                pair = (w, a)
                if "caller" not in pair:
                    return pair
                best = best or pair
        return best


# -- graftmesh rules (GL014-GL018: read the project axis registry) ----
#
# All five share the `# graftlint: axis-ok` sanction comment (the GL013
# `unlocked-ok` discipline): append it, with a reason, to a flagged
# line whose axis handling is deliberate — e.g. an axis registered
# dynamically at runtime that the AST cannot see.

_AXIS_SANCTION = "graftlint: axis-ok"


def _axis_sanctioned_lines(ctx):
    cached = getattr(ctx, "_axis_sanctioned_lines", None)
    if cached is None:
        cached = {i + 1 for i, line in enumerate(ctx.source.splitlines())
                  if _AXIS_SANCTION in line}
        ctx._axis_sanctioned_lines = cached
    return cached


def _known_axes(ctx):
    """(known axis set, declared-label, scope-label) like GL006's
    resolution order: whole-project mesh literals first, file-local
    second, no opinion (None) when no mesh is in sight anywhere."""
    project = ctx.project
    if project is not None and project.mesh_axes:
        return (set(project.mesh_axes), project.declared_axes_label(),
                "this file" if ctx.mesh_axes else "any linted module")
    if ctx.mesh_axes:
        return (set(ctx.mesh_axes), ", ".join(sorted(ctx.mesh_axes)),
                "this file")
    return None, None, None


def _static_shape(node):
    """Literal shape tuple of an array-constructor Call
    (`jnp.zeros((2, 4))`, `jnp.full((8,), 0.0)`,
    `jax.ShapeDtypeStruct((2, 4), ...)`), or None. Unknown dims inside
    an otherwise-literal tuple come back as None entries."""
    if not isinstance(node, ast.Call):
        return None
    fname = _terminal_name(node.func)
    if fname not in ("zeros", "ones", "empty", "full",
                     "ShapeDtypeStruct"):
        return None
    cand = node.args[0] if node.args else None
    for kw in node.keywords:
        if kw.arg == "shape":
            cand = kw.value
    if cand is None:
        return None
    value = _literal(cand)
    if isinstance(value, int):
        return (value,)
    if isinstance(value, (tuple, list)):
        return tuple(v if isinstance(v, int) else None for v in value)
    return None


def _spec_call(node, ctx):
    """The P(...)/PartitionSpec(...) Call inside a sharding expression:
    the call itself, or the `spec` argument of a NamedSharding(...)
    wrapper. None for anything else (a variable, a Sharding object)."""
    if not isinstance(node, ast.Call):
        return None
    name = _terminal_name(node.func)
    if name in ctx.pspec_aliases:
        return node
    if name == "NamedSharding":
        cand = node.args[1] if len(node.args) > 1 else None
        for kw in node.keywords:
            if kw.arg == "spec":
                cand = kw.value
        if cand is not None:
            return _spec_call(cand, ctx)
    return None


def _paired_spec_shapes(ctx):
    """Yields (p_call, entries, shape) wherever a literal PartitionSpec
    is paired with a statically-known array shape:

    - `device_put(jnp.zeros((4, 8)), NamedSharding(mesh, P("dp")))`
    - `with_sharding_constraint(jnp.ones((4,)), P("dp"))`
    - `ShapeDtypeStruct((4, 8), dt, sharding=NamedSharding(m, P(...)))`
    - `shard_map(f, mesh=m, in_specs=(P("dp"),), ...)(jnp.zeros((6,)))`
      (specs mapped positionally onto the immediate call's arguments)
    """
    from cloud_tpu.analysis import meshmap

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal_name(node.func)
        if (name in ("device_put", "with_sharding_constraint")
                and len(node.args) >= 2):
            p_call = _spec_call(node.args[1], ctx)
            if p_call is not None:
                yield (p_call, meshmap.spec_entries(p_call),
                       _static_shape(node.args[0]))
        elif name == "ShapeDtypeStruct":
            for kw in node.keywords:
                if kw.arg == "sharding":
                    p_call = _spec_call(kw.value, ctx)
                    if p_call is not None:
                        yield (p_call, meshmap.spec_entries(p_call),
                               _static_shape(node))
        elif (isinstance(node.func, ast.Call)
              and meshmap.is_shard_map_call(node.func)):
            in_specs = None
            for kw in node.func.keywords:
                if kw.arg == "in_specs":
                    in_specs = kw.value
            if in_specs is None:
                continue
            spec_nodes = (list(in_specs.elts)
                          if isinstance(in_specs, ast.Tuple)
                          else [in_specs] * len(node.args))
            for spec_node, arg in zip(spec_nodes, node.args):
                p_call = _spec_call(spec_node, ctx)
                if p_call is not None:
                    yield (p_call, meshmap.spec_entries(p_call),
                           _static_shape(arg))


class UndeclaredCollectiveAxis(Rule):
    id = "GL014"
    title = "undeclared-collective-axis"
    predicts = "unbound axis-name error from deep inside the trace"

    _MSG = ("collective `{}` runs over mesh axis {!r}, which no mesh "
            "literal in {} declares (declared: {}) — the dispatch "
            "fails with an unbound-name error from deep inside the "
            "trace; fix the axis name, add it to the mesh's "
            "axis_names, or sanction a dynamically registered axis "
            "with `# graftlint: axis-ok`")

    def check(self, ctx):
        from cloud_tpu.analysis import meshmap

        known, declared, scope = _known_axes(ctx)
        if known is None:
            return  # no mesh in sight anywhere: the mesh may live
            # in code we were not asked to lint (GL006's contract)
        sanctioned = _axis_sanctioned_lines(ctx)
        for site in meshmap.file_sites(ctx)["collectives"]:
            if site["dynamic"] or site["line"] in sanctioned:
                continue  # parameter-passed axis names resolve at the
                # call site, not here (ring/ulysses/pipeline idiom)
            for axis in site["axes"]:
                if axis not in known:
                    yield Finding(
                        ctx.path, site["line"], site["col"], self.id,
                        self._MSG.format(site["op"], axis, scope,
                                         declared))


class MalformedPartitionSpec(Rule):
    id = "GL015"
    title = "malformed-partition-spec"
    predicts = "sharding-spec validation error at dispatch"

    _DUP_MSG = ("PartitionSpec mentions mesh axis {!r} twice — one "
                "array dimension set cannot be sharded over the same "
                "axis in two places; jax rejects the spec at dispatch, "
                "after the compile was already paid")
    _RANK_MSG = ("PartitionSpec has {} entries but the annotated array "
                 "has rank {} — the spec cannot be longer than the "
                 "array's rank; drop the extra entries (trailing "
                 "dimensions are replicated by default)")

    def check(self, ctx):
        from cloud_tpu.analysis import meshmap

        sanctioned = _axis_sanctioned_lines(ctx)
        # (a) one axis twice in one spec: purely local, always checked.
        for node in ast.walk(ctx.tree):
            if (not isinstance(node, ast.Call)
                    or _terminal_name(node.func) not in ctx.pspec_aliases
                    or node.lineno in sanctioned):
                continue
            entries = meshmap.spec_entries(node)
            seen = set()
            for axis in meshmap.entry_axes(entries):
                if axis in seen:
                    yield ctx.finding(node, self.id,
                                      self._DUP_MSG.format(axis))
                    break
                seen.add(axis)
        # (b) spec longer than the annotated array's rank.
        for p_call, entries, shape in _paired_spec_shapes(ctx):
            if shape is None or p_call.lineno in sanctioned:
                continue
            if len(entries) > len(shape):
                yield ctx.finding(
                    p_call, self.id,
                    self._RANK_MSG.format(len(entries), len(shape)))


class UnreducedShardMapLeak(Rule):
    id = "GL016"
    title = "unreduced-shard-leak"
    predicts = ("silent wrong numerics: the replicated output holds "
                "only one shard's partial value")

    _MSG = ("shard_map shards axis {!r} in `in_specs` but `out_specs` "
            "replicates it, and the mapped function `{}` applies no "
            "reducing collective (psum/pmean/pmax/pmin/psum_scatter/"
            "all_gather) over that axis — each device returns its own "
            "partial value and the \"replicated\" output is silently "
            "wrong; reduce over the axis before returning, keep it in "
            "out_specs, or sanction with `# graftlint: axis-ok`")

    #: Local-callee resolution depth when scanning the mapped function
    #: for reducing collectives (mirrors callgraph.MAX_CHAIN_DEPTH in
    #: spirit; shard_map bodies are shallow by construction).
    _MAX_DEPTH = 4

    def check(self, ctx):
        from cloud_tpu.analysis import meshmap

        sanctioned = _axis_sanctioned_lines(ctx)
        for node in ast.walk(ctx.tree):
            if (not isinstance(node, ast.Call)
                    or not meshmap.is_shard_map_call(node)
                    or node.lineno in sanctioned):
                continue
            kwargs = {kw.arg: kw.value for kw in node.keywords}
            if "in_specs" not in kwargs or "out_specs" not in kwargs:
                continue
            in_axes = self._spec_axes(ctx, node, kwargs["in_specs"],
                                      need_all=False)
            out_axes = self._spec_axes(ctx, node, kwargs["out_specs"],
                                       need_all=True)
            if in_axes is None or out_axes is None:
                continue  # unresolvable specs: no opinion
            leaked = in_axes - out_axes
            if not leaked:
                continue
            fn_node, fn_label = self._mapped_fn(ctx, node)
            if fn_node is None:
                continue  # body not visible: no opinion
            reduced = self._reduced_axes(ctx, fn_node, set(), 0)
            if reduced is None:
                continue  # a dynamic-axis reducing collective may
                # cover any axis: conservative silence
            for axis in sorted(leaked - reduced):
                yield ctx.finding(
                    node, self.id, self._MSG.format(axis, fn_label))

    def _spec_axes(self, ctx, call, spec_node, need_all):
        """Axis names a specs expression mentions, resolving direct
        P(...) calls, tuples of them, and single-assignment local
        names. Returns None when resolution is incomplete and
        `need_all` (out_specs: claiming an axis is ABSENT needs the
        whole expression) — for in_specs the known subset suffices."""
        from cloud_tpu.analysis import meshmap

        nodes = (list(spec_node.elts)
                 if isinstance(spec_node, (ast.Tuple, ast.List))
                 else [spec_node])
        axes, complete = set(), True
        for item in nodes:
            if isinstance(item, ast.Name):
                item = self._local_spec_binding(ctx, call, item.id)
            p_call = _spec_call(item, ctx) if item is not None else None
            if p_call is None:
                complete = False
                continue
            entries = meshmap.spec_entries(p_call)
            if meshmap.UNKNOWN in entries or any(
                    isinstance(e, tuple) and meshmap.UNKNOWN in e
                    for e in entries):
                complete = False
            axes.update(meshmap.entry_axes(entries))
        if need_all and not complete:
            return None
        return axes

    @staticmethod
    def _local_spec_binding(ctx, call, name):
        """The single P(...) Call a local name is bound to in the
        function enclosing `call` (or at module level); None when the
        name is rebound, a parameter, or not a spec call."""
        scope = ctx.parents.get(call)
        while scope is not None and not isinstance(
                scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scope = ctx.parents.get(scope)
        body_root = scope if scope is not None else ctx.tree
        bindings = []
        for node in ast.walk(body_root):
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == name
                    for t in node.targets):
                bindings.append(node.value)
        if len(bindings) == 1:
            return bindings[0]
        return None

    def _mapped_fn(self, ctx, call):
        """(AST node to scan for collectives, label) for the mapped
        function: a Lambda inline, a local def by name, or a
        functools.partial over one (ring/ulysses bind axis_name this
        way — scanning the underlying def keeps the rule's view of the
        body, with the partial's literal kwargs folded in)."""
        fn = call.args[0] if call.args else None
        if isinstance(fn, ast.Call) and _terminal_name(fn.func) == "partial":
            fn = fn.args[0] if fn.args else None
        if isinstance(fn, ast.Lambda):
            return fn, "<lambda>"
        if isinstance(fn, ast.Name):
            target = self._local_def(ctx, fn.id)
            if target is not None:
                return target, fn.id
        return None, None

    @staticmethod
    def _local_def(ctx, name):
        for node in ast.walk(ctx.tree):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node.name == name):
                return node
        return None

    def _reduced_axes(self, ctx, fn_node, visiting, depth):
        """Literal axis names the body (or a reachable local callee)
        reduces over. None means a reducing collective with a DYNAMIC
        axis was seen — it may cover any axis, so the caller must stay
        silent."""
        from cloud_tpu.analysis import meshmap

        if fn_node in visiting or depth > self._MAX_DEPTH:
            return set()
        visiting = visiting | {fn_node}
        reduced = set()
        for node in ast.walk(fn_node):
            if not isinstance(node, ast.Call):
                continue
            op = meshmap.collective_op(ctx, node)
            if op in meshmap.REDUCING_COLLECTIVES:
                axes, dynamic = meshmap.collective_axes(node, op)
                if dynamic:
                    return None
                reduced.update(axes)
            elif op is None and isinstance(node.func, ast.Name):
                callee = self._local_def(ctx, node.func.id)
                if callee is not None:
                    sub = self._reduced_axes(ctx, callee, visiting,
                                             depth + 1)
                    if sub is None:
                        return None
                    reduced |= sub
        return reduced


class ConflictingNestedSharding(Rule):
    id = "GL017"
    title = "conflicting-nested-sharding"
    predicts = ("resharding churn at scope boundaries (h2d/d2d "
                "transfers per entry, or a GSPMD conflict error)")

    _MSG = ("`{name}` is pinned to PartitionSpec({inner}) inside a "
            "nested {what} scope, but the enclosing scope already "
            "pinned it to PartitionSpec({outer}) (line {oline}) — "
            "nested scopes re-pinning the same value to a different "
            "layout force a reshard (or a GSPMD conflict) every time "
            "the inner scope runs; pick one layout, or sanction an "
            "intentional boundary reshard with `# graftlint: axis-ok`")

    _PIN_CALLS = ("with_sharding_constraint", "device_put")

    def check(self, ctx):
        from cloud_tpu.analysis import meshmap

        sanctioned = _axis_sanctioned_lines(ctx)
        pins = []
        for node in ast.walk(ctx.tree):
            if (not isinstance(node, ast.Call)
                    or _terminal_name(node.func) not in self._PIN_CALLS
                    or len(node.args) < 2
                    or not isinstance(node.args[0], ast.Name)):
                continue
            p_call = _spec_call(node.args[1], ctx)
            if p_call is None:
                continue
            entries = meshmap.spec_entries(p_call)
            if meshmap.UNKNOWN in entries:
                continue
            pins.append((node.args[0].id, entries, node,
                         self._scope_chain(ctx, node)))
        for name, entries, node, chain in pins:
            if node.lineno in sanctioned:
                continue
            for oname, oentries, onode, ochain in pins:
                if (oname != name or onode is node
                        or oentries == entries):
                    continue
                if (len(ochain) < len(chain)
                        and chain[:len(ochain)] == ochain):
                    what = self._inner_scope_kind(
                        ctx, chain[len(ochain):])
                    if what is None:
                        continue  # plain nested def: a different
                        # dynamic extent, not an enclosed scope
                    yield ctx.finding(node, self.id, self._MSG.format(
                        name=name,
                        inner=self._fmt(entries),
                        outer=self._fmt(oentries),
                        oline=onode.lineno, what=what))
                    break

    @staticmethod
    def _fmt(entries):
        return ", ".join(repr(e) if not isinstance(e, tuple)
                         else repr(tuple(e)) for e in entries)

    @classmethod
    def _scope_chain(cls, ctx, node):
        """Outermost-first tuple of enclosing scope nodes: function
        defs and `with <mesh>:` blocks."""
        chain = []
        current = ctx.parents.get(node)
        while current is not None:
            if isinstance(current, (ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.Lambda)):
                chain.append(current)
            elif (isinstance(current, (ast.With, ast.AsyncWith))
                  and cls._is_mesh_with(current)):
                chain.append(current)
            current = ctx.parents.get(current)
        return tuple(reversed(chain))

    @staticmethod
    def _is_mesh_with(node):
        """`with Mesh(...):` / `with make_mesh(...):` / `with mesh:` —
        the name heuristic ('mesh' / '*_mesh') covers the dominant
        idiom of entering a pre-built mesh context."""
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                if _terminal_name(expr.func) in ("Mesh", "make_mesh"):
                    return True
            name = _terminal_name(expr)
            if isinstance(name, str):
                lowered = name.lower()
                if lowered == "mesh" or lowered.endswith("_mesh"):
                    return True
        return False

    def _inner_scope_kind(self, ctx, extra):
        """What makes the inner pin a *different sharding scope*: a
        jit-compiled def or a with-mesh block among the scopes below
        the outer pin. A plain nested def is neither."""
        for scope in extra:
            if isinstance(scope, (ast.With, ast.AsyncWith)):
                return "with-mesh"
            if scope in ctx.jit_defs:
                return "jit"
        return None


class AxisDivisibility(Rule):
    id = "GL018"
    title = "axis-divisibility"
    predicts = "an opaque XLA sharding error at compile time"

    _MSG = ("dimension {dim} of shape {shape} has size {size}, which "
            "is not divisible by mesh axis {axes} (size {asize}, "
            "declared at {where}) — XLA rejects the uneven shard with "
            "an opaque partitioning error; pad the dimension, resize "
            "the mesh axis, or sanction with `# graftlint: axis-ok`")

    def check(self, ctx):
        project = ctx.project
        if project is None:
            return
        registry = project.graftmesh()
        sizes = registry.axis_sizes()
        if not sizes:
            return  # no statically sized mesh anywhere: no opinion
        where = {}
        for mesh in registry.meshes:
            for axis in mesh["axes"]:
                where.setdefault(axis, "{}:{}".format(
                    os.path.basename(mesh["path"]), mesh["line"]))
        sanctioned = _axis_sanctioned_lines(ctx)
        for p_call, entries, shape in _paired_spec_shapes(ctx):
            if shape is None or p_call.lineno in sanctioned:
                continue
            for i, entry in enumerate(entries):
                if i >= len(shape) or shape[i] is None:
                    continue
                axes = ((entry,) if isinstance(entry, str) else entry
                        if isinstance(entry, tuple) else ())
                total, names = 1, []
                for axis in axes:
                    if axis not in sizes:
                        total = None
                        break
                    total *= sizes[axis]
                    names.append(axis)
                if not names or total in (None, 0):
                    continue
                if shape[i] % total:
                    label = (repr(names[0]) if len(names) == 1
                             else repr(tuple(names)))
                    yield ctx.finding(p_call, self.id, self._MSG.format(
                        dim=i, shape=tuple(shape), size=shape[i],
                        axes=label, asize=total,
                        where=", ".join(where.get(a, "?")
                                        for a in names)))


ALL_RULES = [HostSyncInJit(), RetraceHazard(), DonationAfterUse(),
             RngKeyReuse(), TracerControlFlow(),
             ShardingAxisMismatch(), TransitiveHostSync(),
             RngKeyReuseAcrossCalls(), DonationEscape(),
             DeadJitSignatureLeaf(), UnhashableStaticArg(),
             RetraceProneCacheKey(), LockDiscipline(),
             UndeclaredCollectiveAxis(), MalformedPartitionSpec(),
             UnreducedShardMapLeak(), ConflictingNestedSharding(),
             AxisDivisibility()]
