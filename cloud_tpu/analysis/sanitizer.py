"""graftsan: runtime sanitizer with per-source-line attribution.

The static rules (GL001-GL009) predict runtime pathology from the AST;
the counters in `cloud_tpu.parallel.runtime` measure it. This module is
the bridge: under `sanitize()` every transfer/compile record and every
`jax.random` key consumption is attributed to the source line that
caused it, aggregated per line, and checked against the same invariants
the static rules encode — so a `d2h_fetches` regression arrives as
"trainer.py:2134 fetched inside the step loop", not a bare number.

Violations (ids mirror the GL numbering, GS-prefixed):

- GS001 d2h-in-step-loop — a device->host fetch while the recording
  thread's phase label is "step" (the Trainer marks its epoch step
  loops; boundary/async-reader/checkpoint fetches are sanctioned).
- GS002 retrace-after-warm — a trace recorded in the step phase after
  the first epoch finished: the runtime dual of GL002, attributing the
  retrace the Trainer's sentinel can only count.
- GS003 rng-key-reuse — a key with bit-identical contents consumed by
  two `jax.random` calls (the runtime dual of GL004/GL008). `fold_in`,
  `PRNGKey` and `key` are deliberately not watched: deriving fresh
  keys from a base key is the sanctioned pattern (e.g. the per-epoch
  `fold_in(PRNGKey(seed), epoch)` shuffle keys in training/data.py).
- GS004 donated-buffer-access — a fetch touched an array previously
  donated to an `instrumented_jit(donate_argnums=...)` call, tracked
  by weakref identity. jax's own failure for this is a bare "Array
  has been deleted" with no hint of WHERE the donation happened (and
  on backends that ignore donation there is no failure at all, just a
  silent portability bug); the finding carries the donation site.
- GS005 retrace-attribution — the runtime dual of GL010. When a trace
  fires after warmup (after `runtime.notify_warm_mark()` — the serving
  engine's `mark_warm()` — or after epoch 1), the InstrumentedJit
  diffs the offending call's aval signature against the warm table and
  its trace history and the finding names the exact leaf whose avals
  moved: "args[1]['page_table'] widened int32[4,16] -> int32[8,16]",
  attributed to the dispatching call site. Warmup traces are expected
  and record nothing.
- GS006 mesh-drift — the runtime dual of the graftmesh rules
  (GL014-GL018). Every InstrumentedJit records the concrete mesh +
  input shardings at the first observed dispatch per executable (aval
  signature); a later dispatch whose shardings differ means the jit
  boundary is silently resharding — a device transfer per call that no
  counter otherwise names — and the finding carries the exact leaf and
  BOTH layouts: "args[0] moved NamedSharding(..., PartitionSpec()) ->
  NamedSharding(..., PartitionSpec('dp',))". Unlike GS005 this arms
  immediately (the baseline IS the first dispatch), so it fires during
  warmup too — drift there costs the same transfer.

Enablement is scoped, never ambient: `with sanitize(mode="warn"):`
installs the runtime observer and the `jax.random` watchers and tears
both down on exit — with no active scope there are ZERO hooks: the
observer seam is a None check and `jax.random` holds its original
functions. `CLOUD_TPU_SANITIZE=1|warn|strict` asks the Trainer to wrap
each `fit()`/`evaluate()` in such a scope (`env_scope()`).

Findings are emitted through `utils/events.log_job_event` (JSONL, kind
"graftsan") and escalate like the preflight lint: warn logs, strict
raises `GraftsanError` at scope exit.
"""

import contextlib
import functools
import logging
import os
import sys
import threading

from cloud_tpu.parallel import runtime
from cloud_tpu.utils import events

logger = logging.getLogger("cloud_tpu")

#: Violation id -> (title, message template).
VIOLATIONS = {
    "GS001": ("d2h-in-step-loop",
              "device->host fetch ({} bytes) inside the step loop at "
              "{} — every such fetch is a tunnel round trip per step; "
              "coalesce into the epoch-boundary fetch"),
    "GS002": ("retrace-after-warm",
              "{} new trace(s) after epoch 1 at {} — the steady state "
              "should be fully warm; suspect a ragged tail batch, "
              "dtype drift, or a Python-value argument"),
    "GS003": ("rng-key-reuse",
              "RNG key with identical bits consumed twice: first at "
              "{}, again at {} — both draws see the same randomness; "
              "split and consume each subkey once"),
    "GS004": ("donated-buffer-access",
              "fetched an array that was donated at {} — donation "
              "invalidated that buffer; keep the jitted result (or "
              "drop the argument from donate_argnums) instead of "
              "re-reading the donated input"),
    "GS005": ("retrace-attribution",
              "post-warmup retrace of `{}` at {}: {} — the signature "
              "leaf(s) named moved between calls; pin the leaf's "
              "shape/dtype, pre-warm the new geometry, or drop a dead "
              "leaf from the signature (graftlint GL010)"),
    "GS006": ("mesh-drift",
              "input sharding of `{}` drifted at {}: {} — the jit "
              "boundary is silently resharding that leaf (a device "
              "transfer per dispatch); device_put the input into the "
              "first-dispatch layout once upstream, or make the new "
              "layout the one the executable is compiled for "
              "(graftmesh GL014-GL018)"),
}

#: jax.random functions whose first argument is a key they consume.
#: Creators (PRNGKey/key) and derivers (fold_in) are excluded — see
#: the module docstring.
_WATCHED_RANDOM = ("normal", "uniform", "bernoulli", "split",
                   "categorical", "randint", "permutation", "choice",
                   "gumbel", "truncated_normal", "exponential",
                   "shuffle", "laplace", "beta", "gamma", "poisson",
                   "dirichlet", "multivariate_normal")

_THIS_FILE = os.path.abspath(__file__)
_RUNTIME_FILE = os.path.abspath(runtime.__file__)
_SKIP_MARKERS = ("site-packages", "dist-packages",
                 os.sep + "jax" + os.sep, "importlib", "<frozen")


class GraftsanError(RuntimeError):
    """Raised at `sanitize(mode="strict")` scope exit when the run
    produced sanitizer findings. The message lists every finding with
    its attributed site."""


def _attribution_site(skip=2):
    """(path, line, function) of the innermost frame that is user or
    framework code — sanitizer/runtime internals, jax, and stdlib
    import machinery are walked past. Falls back to "<unknown>" when
    every frame is infrastructure (e.g. a pure-jax-internal event)."""
    try:
        frame = sys._getframe(skip)
    except ValueError:  # shallower stack than `skip`
        return "<unknown>", 0, "?"
    while frame is not None:
        path = frame.f_code.co_filename
        if not _is_infrastructure(path):
            return path, frame.f_lineno, frame.f_code.co_name
        frame = frame.f_back
    return "<unknown>", 0, "?"


def _is_infrastructure(path):
    abspath = os.path.abspath(path)
    if abspath in (_THIS_FILE, _RUNTIME_FILE):
        return True
    return any(marker in path for marker in _SKIP_MARKERS)


def _format_site(site):
    return "{}:{}".format(site[0], site[1])


def _key_fingerprint(key):
    """Canonical bytes of a PRNG key's bit content, or None for values
    we must not (tracers) or cannot (exotic dtypes) inspect. Typed key
    arrays go through `jax.random.key_data`; raw uint32 keys through
    numpy."""
    import jax
    import numpy as np

    try:
        if isinstance(key, jax.core.Tracer):
            return None
    except AttributeError:  # pragma: no cover - jax.core moved
        pass
    try:
        data = key
        if getattr(getattr(key, "dtype", None), "name", "").startswith(
                "key"):
            data = jax.random.key_data(key)
        arr = np.asarray(data)
    except Exception:
        return None
    if arr.dtype.kind not in "ui":
        return None
    return arr.tobytes()


class Sanitizer:
    """The observer `sanitize()` installs into the runtime seam.

    All state is guarded by one lock: events arrive from the training
    thread, the async metric-reader thread, and the checkpoint worker
    concurrently. Attribution walks the recording thread's own stack,
    so each event lands on the line that caused it regardless of which
    thread recorded."""

    def __init__(self, mode="warn", event_log=None):
        self.mode = mode
        self.event_log = event_log
        self._lock = threading.Lock()
        #: (path, line) -> {"d2h"/"h2d"/"traces"/"compiles"/
        #: "cache_hits"/"cache_misses"/"key_uses": count}
        self._site_counts = {}
        self._findings = []
        self._finding_index = {}   # (rule, site-string) -> finding
        self._epochs_done = 0
        self._warm_marked = False  # notify_warm_mark() arms GS005
        self._seen_keys = {}       # fingerprint -> first-use site str
        self._donated = {}         # id(array) -> (weakref, site str)

    # -- runtime observer interface ------------------------------------

    def on_d2h(self, nbytes, tree):
        site = _attribution_site()
        with self._lock:
            self._bump(site, "d2h")
            if runtime.current_phase() == "step":
                self._violation(
                    "GS001", site,
                    VIOLATIONS["GS001"][1].format(
                        nbytes, _format_site(site)))
            self._check_donated(tree, site)

    def on_h2d(self, transfers, nbytes):
        site = _attribution_site()
        with self._lock:
            self._bump(site, "h2d", transfers)

    def on_compile(self, n_traces, n_compiles, cache_hits):
        site = _attribution_site()
        with self._lock:
            self._bump(site, "traces", n_traces)
            self._bump(site, "compiles", n_compiles)
            self._bump(site, "cache_hits", cache_hits)
            if (n_traces and self._epochs_done >= 1
                    and runtime.current_phase() == "step"):
                self._violation(
                    "GS002", site,
                    VIOLATIONS["GS002"][1].format(
                        n_traces, _format_site(site)))

    def on_cache_miss(self):
        site = _attribution_site()
        with self._lock:
            self._bump(site, "cache_misses")

    def on_epoch(self, epoch):
        with self._lock:
            self._epochs_done = max(self._epochs_done, epoch + 1)

    def on_warm_mark(self):
        """Arms GS005: every executable the workload needs is compiled
        (the serving engine's `mark_warm()`), so any later trace is a
        bug with a name."""
        with self._lock:
            self._warm_marked = True

    def on_retrace(self, label, diffs):
        """One attributed retrace from an InstrumentedJit. `diffs` is
        a tuple of (leaf path, old aval, new aval) naming what moved,
        or None when no prior signature shared the call's tree shape.
        Silent until armed — warmup traces are the expected cost of
        building the warm table, not findings."""
        site = _attribution_site()
        with self._lock:
            if not (self._warm_marked or self._epochs_done >= 1):
                return
            if diffs:
                detail = "; ".join(
                    "{} widened {} -> {}".format(path, old, new)
                    for path, old, new in diffs)
            else:
                detail = ("new call structure (no prior signature "
                          "with this tree shape to diff)")
            self._violation(
                "GS005", site,
                VIOLATIONS["GS005"][1].format(
                    label, _format_site(site), detail))

    def on_mesh_drift(self, label, drifts):
        """One attributed jit-boundary resharding from an
        InstrumentedJit (GS006). `drifts` is a tuple of (leaf path,
        sharding at first dispatch, sharding now). No warm gate:
        unlike a retrace, the baseline is by definition the first
        dispatch, so every drift is a real extra transfer."""
        site = _attribution_site()
        with self._lock:
            detail = "; ".join(
                "{} moved {} -> {}".format(path, old, new)
                for path, old, new in drifts)
            self._violation(
                "GS006", site,
                VIOLATIONS["GS006"][1].format(
                    label, _format_site(site), detail))

    def on_donation(self, args):
        import jax
        import weakref

        site = _attribution_site()
        site_str = _format_site(site)
        with self._lock:
            # Prune dead entries so id() recycling cannot mis-attribute
            # a fresh array to a long-freed donation.
            dead = [k for k, (ref, _) in self._donated.items()
                    if ref() is None]
            for k in dead:
                del self._donated[k]
            for leaf in jax.tree_util.tree_leaves(args):
                if isinstance(leaf, jax.Array):
                    try:
                        self._donated[id(leaf)] = (weakref.ref(leaf),
                                                   site_str)
                    except TypeError:  # pragma: no cover - no weakref
                        pass

    # -- jax.random watcher interface ----------------------------------

    def on_key_use(self, key):
        fingerprint = _key_fingerprint(key)
        if fingerprint is None:
            return
        site = _attribution_site()
        with self._lock:
            self._bump(site, "key_uses")
            first = self._seen_keys.get(fingerprint)
            if first is None:
                self._seen_keys[fingerprint] = _format_site(site)
            else:
                self._violation(
                    "GS003", site,
                    VIOLATIONS["GS003"][1].format(
                        first, _format_site(site)))

    # -- bookkeeping ---------------------------------------------------

    def _bump(self, site, kind, count=1):
        if not count:
            return
        bucket = self._site_counts.setdefault((site[0], site[1]), {})
        bucket[kind] = bucket.get(kind, 0) + count

    def _check_donated(self, tree, site):
        import jax

        for leaf in jax.tree_util.tree_leaves(tree):
            if not isinstance(leaf, jax.Array):
                continue
            entry = self._donated.get(id(leaf))
            if entry is not None and entry[0]() is leaf:
                self._violation(
                    "GS004", site,
                    VIOLATIONS["GS004"][1].format(entry[1]))

    def _violation(self, rule, site, message):
        # Already holding self._lock. Dedupe per (rule, line): steady
        # repetition raises the count, not the noise.
        key = (rule, _format_site(site))
        existing = self._finding_index.get(key)
        if existing is not None:
            existing["count"] += 1
            return
        finding = {"rule": rule, "title": VIOLATIONS[rule][0],
                   "path": site[0], "line": site[1],
                   "message": message, "count": 1}
        self._finding_index[key] = finding
        self._findings.append(finding)
        if self.mode == "warn":
            logger.warning("graftsan %s %s: %s", rule,
                           VIOLATIONS[rule][0], message)

    # -- results -------------------------------------------------------

    def findings(self):
        """Copies of the accumulated findings (thread-safe snapshot)."""
        with self._lock:
            return [dict(f) for f in self._findings]

    def site_counts(self):
        """{"path:line": {kind: count}} aggregate attribution table."""
        with self._lock:
            return {_format_site(site): dict(counts)
                    for site, counts in self._site_counts.items()}

    def finalize(self):
        """Emits the JSONL event and escalates per mode. Called by
        `sanitize()` at scope exit (after hooks are removed)."""
        findings = self.findings()
        events.log_job_event(
            "graftsan",
            {"mode": self.mode, "findings": findings,
             "site_counts": self.site_counts()},
            path=self.event_log)
        if self.mode == "strict" and findings:
            raise GraftsanError(
                "graftsan: {} finding(s) in strict mode:\n{}".format(
                    len(findings),
                    "\n".join("  {} {} {}:{} {}".format(
                        f["rule"], f["title"], f["path"], f["line"],
                        f["message"]) for f in findings)))


# -- jax.random watchers ------------------------------------------------


def _install_random_watchers(san):
    """Wraps the consuming jax.random functions to report first-arg
    key fingerprints. Returns {name: original} for teardown."""
    import jax

    originals = {}
    for name in _WATCHED_RANDOM:
        original = getattr(jax.random, name, None)
        if original is None:
            continue

        def _make(fn):
            @functools.wraps(fn)
            def _watched(key, *args, **kwargs):
                san.on_key_use(key)
                return fn(key, *args, **kwargs)
            _watched.__graftsan_original__ = fn
            return _watched

        originals[name] = original
        setattr(jax.random, name, _make(original))
    return originals


def _remove_random_watchers(originals):
    import jax

    for name, original in originals.items():
        setattr(jax.random, name, original)


def random_watchers_installed():
    """True when any jax.random function is currently wrapped — the
    "zero hooks when disabled" invariant's introspection point."""
    import jax

    return any(
        hasattr(getattr(jax.random, name, None),
                "__graftsan_original__")
        for name in _WATCHED_RANDOM)


# -- public entry points ------------------------------------------------


@contextlib.contextmanager
def sanitize(mode="warn", event_log=None):
    """Scoped runtime sanitizing: observer + jax.random watchers.

    Args:
        mode: "warn" logs each finding as it first occurs and reports
            all of them at exit; "strict" additionally raises
            `GraftsanError` at scope exit when any finding accumulated.
        event_log: Optional JSONL path for the "graftsan" job event;
            defaults to the CLOUD_TPU_EVENT_LOG env contract (see
            `utils.events.log_job_event`).

    Yields:
        The `Sanitizer`, for introspection (`findings()`,
        `site_counts()`) while the scope is live.
    """
    if mode not in ("warn", "strict"):
        raise ValueError(
            "Invalid graftsan mode {!r}. Expected \"warn\" or "
            "\"strict\".".format(mode))
    san = Sanitizer(mode=mode, event_log=event_log)
    # add/remove (not set/restore): the sanitizer STACKS with other
    # runtime observers — graftscope telemetry keeps counting while a
    # sanitize scope is live, and vice versa.
    runtime.add_observer(san)
    originals = _install_random_watchers(san)
    try:
        yield san
    finally:
        _remove_random_watchers(originals)
        runtime.remove_observer(san)
        san.finalize()


def env_mode():
    """The CLOUD_TPU_SANITIZE env contract -> None | "warn" | "strict".

    Unset / "0" / "off" / "false" disable; "strict" escalates; any
    other truthy value (the documented spelling is "1" or "warn")
    means warn.
    """
    value = os.environ.get("CLOUD_TPU_SANITIZE", "").strip().lower()
    if value in ("", "0", "off", "false", "none"):
        return None
    return "strict" if value == "strict" else "warn"


def env_scope():
    """A context manager for library entry points (Trainer.fit/
    evaluate): a real `sanitize()` scope when CLOUD_TPU_SANITIZE asks
    for one and no sanitizer is already active, else a no-op. Nested
    fits under an explicit `sanitize()` reuse the outer scope instead
    of stacking. Only SANITIZERS suppress: another observer kind on
    the seam (graftscope telemetry) must not swallow the env ask."""
    mode = env_mode()
    if mode is None or any(isinstance(obs, Sanitizer)
                           for obs in runtime.observers()):
        return contextlib.nullcontext()
    return sanitize(mode=mode)
