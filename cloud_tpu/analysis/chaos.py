"""graftchaos: deterministic fault injection for graftguard and CI.

A chaos plan is a comma-separated list of `kind@step[:arg]` events,
usually supplied via `CLOUD_TPU_CHAOS`; each fires EXACTLY ONCE at a
configured global optimizer-step number, so a chaos run is fully
reproducible — the point is a deterministic rig for the recovery path
(training/resilience.py), not random monkey-testing. The fit loops
call `pre_dispatch(step, n_steps)` before every dispatch;
checkpoint.save calls `notify_checkpoint(path, step)` after every
committed write.

Kinds:

  `hang@N[:seconds]`   Hang on the host before dispatching step N
                       (default 3600 s), sleeping in 50 ms slices so
                       graftwatch's async raise lands promptly — the
                       watchdog converts the hang into a typed
                       `BackendUnavailable`, exactly like a real
                       wedged dispatch.
  `preempt@N`          Raise `resilience.Preemption` before step N —
                       the SIGTERM-grace-window interruption.
  `fetch@N`            Raise `resilience.DataStall` before step N — a
                       transient input-pipeline fetch error.
  `nan@N`              Raise `resilience.NaNLoss` before step N — the
                       rollback-with-fresh-rng path end to end.
  `corrupt@N`          Truncate the largest file of the FIRST
                       checkpoint saved at step >= N — a torn write
                       the digest check (or orbax itself) must catch
                       as `CheckpointCorrupt` on restore.

Serving kinds (graftstorm) reuse the same grammar with the step index
meaning the graftserve ENGINE TICK (post-warmup; the scheduler resets
its tick counter after warmup). They are consumed by the Scheduler's
tick loop via `pre_tick(tick)` — never by the training `pre_dispatch`
hook — and each describes WHAT breaks; serving/scheduler.py owns the
recovery:

  `slot_hang@T`           The lowest-index active slot at tick T stops
                          making progress; it drains via the evict
                          scatter and its request requeues.
  `prefill_fail@T`        The next prefill attempted at tick >= T
                          raises `serving.PrefillFailed` once; pages
                          release and the prefill retries.
  `slot_evict@T:S`        Slot S's pages are reclaimed at tick T (arg
                          = slot index); its request requeues.
  `pool_squeeze@T:P`      Up to P free KV pages are confiscated at
                          tick T (arg = page count) and returned after
                          a hold window — admission backpressure must
                          absorb the shrunken pool.

Example: `CLOUD_TPU_CHAOS="hang@12:30,corrupt@9"` hangs the host 30 s
before step 12 and tears the first checkpoint written at step >= 9 —
the chaos-smoke CI scenario; `"slot_hang@6,pool_squeeze@10:8"` is its
serving twin. Fired events emit "graftchaos" JSONL job events
(CLOUD_TPU_EVENT_LOG) so post-hoc assertions can line injected faults
up against graftguard's/graftstorm's responses.
"""

import logging
import os
import time

from cloud_tpu.training import resilience

logger = logging.getLogger("cloud_tpu")

#: Serving-scoped kinds: tick-indexed, consumed by Scheduler.pre_tick,
#: invisible to the training pre_dispatch hook.
SERVE_KINDS = ("slot_hang", "prefill_fail", "slot_evict",
               "pool_squeeze")

KINDS = ("hang", "preempt", "fetch", "nan", "corrupt") + SERVE_KINDS

#: Default hang duration, seconds — long enough that any sane
#: graftwatch deadline fires first.
DEFAULT_HANG_S = 3600.0


class ChaosEvent:
    """One `kind@step[:arg]` injection; fires at most once."""

    __slots__ = ("kind", "step", "arg", "fired")

    def __init__(self, kind, step, arg=None):
        self.kind = kind
        self.step = int(step)
        self.arg = arg
        self.fired = False

    def spec(self):
        return {"kind": self.kind, "step": self.step, "arg": self.arg,
                "fired": self.fired}

    def __repr__(self):
        return "ChaosEvent({}@{}{})".format(
            self.kind, self.step,
            ":{}".format(self.arg) if self.arg is not None else "")


def parse_spec(spec):
    """Parses a `kind@step[:arg],...` spec string into ChaosEvents."""
    events = []
    for item in str(spec).split(","):
        item = item.strip()
        if not item:
            continue
        kind, sep, rest = item.partition("@")
        kind = kind.strip()
        if not sep or kind not in KINDS:
            raise ValueError(
                "Malformed chaos event {!r}: expected kind@step[:arg] "
                "with kind in {}.".format(item, "/".join(KINDS)))
        step_text, _, arg_text = rest.partition(":")
        try:
            step = int(step_text)
            arg = float(arg_text) if arg_text else None
        except ValueError:
            raise ValueError(
                "Malformed chaos event {!r}: step must be an int and "
                "arg a float.".format(item))
        events.append(ChaosEvent(kind, step, arg))
    return events


def _log_event(event, extra=None):
    try:
        from cloud_tpu.utils import events as events_lib

        payload = event.spec()
        if extra:
            payload.update(extra)
        events_lib.log_job_event("graftchaos", payload)
    except Exception:
        logger.debug("graftchaos: job event export failed", exc_info=True)


class ChaosPlan:
    """A set of one-shot injections, checked against the live step
    counter by the fit loops and against committed checkpoint writes
    by checkpoint.save."""

    #: `pre_dispatch` step interpretations: "global" matches the fit
    #: loop's own step counter (single training run — the default);
    #: "cumulative" matches a plan-private dispatch index that only
    #: ever grows, so `preempt@N` means "the N-th dispatch THIS PLAN
    #: has seen" even when many short runs (graftsweep trials) each
    #: restart their step counter at 0. Replayed dispatches after a
    #: resume count too — the index tracks work offered, keeping the
    #: injection point a deterministic function of the spec alone.
    STEP_MODES = ("global", "cumulative")

    def __init__(self, events):
        self.events = list(events)
        self.step_mode = "global"
        self._dispatched = 0

    def set_step_mode(self, mode):
        if mode not in self.STEP_MODES:
            raise ValueError("step_mode must be one of {}; got {!r}."
                             .format(self.STEP_MODES, mode))
        self.step_mode = mode

    @classmethod
    def parse(cls, spec):
        return cls(parse_spec(spec))

    def remaining(self):
        """Specs of events that have not fired yet."""
        return [e.spec() for e in self.events if not e.fired]

    def pre_dispatch(self, step, n_steps=1):
        """Fires step-triggered events falling in [step, step + n_steps)
        — the window the NEXT dispatch will execute. A grouped or
        device-resident dispatch covers several steps per call, so the
        injection lands at the nearest dispatch boundary at or before
        its configured step (dispatch is the abort granularity).

        Under `step_mode == "cumulative"` the caller's step is ignored
        in favor of the plan's own dispatch index, which advances by
        `n_steps` per call — including the call an injection aborts:
        the window is claimed either way, so resume re-entries see
        fresh windows and the schedule stays deterministic."""
        if step is None:
            return
        if self.step_mode == "cumulative":
            step = self._dispatched
            self._dispatched += n_steps
        due = [e for e in self.events
               if not e.fired and e.kind != "corrupt"
               and e.kind not in SERVE_KINDS
               and step <= e.step < step + n_steps]
        for event in sorted(due, key=lambda e: e.step):
            event.fired = True
            self._fire(event)

    def pre_tick(self, tick):
        """Fires serving events whose configured tick has arrived
        (tick >= e.step — a tick loop that idles between requests must
        not skip past an injection) and RETURNS them: chaos describes
        the fault, the Scheduler owns the recovery, so serving kinds
        are handed back instead of raised here. One-shot like
        everything else in the plan."""
        if tick is None:
            return []
        due = [e for e in self.events
               if not e.fired and e.kind in SERVE_KINDS
               and tick >= e.step]
        due.sort(key=lambda e: e.step)
        for event in due:
            event.fired = True
            _log_event(event, extra={"tick": int(tick)})
            logger.warning(
                "graftchaos: injected %s at serve tick %d.",
                event.kind, tick)
        return due

    def _fire(self, event):
        _log_event(event)
        if event.kind == "hang":
            duration = DEFAULT_HANG_S if event.arg is None else event.arg
            logger.warning(
                "graftchaos: hanging %.1fs before step %d "
                "(graftwatch should convert this to BackendUnavailable).",
                duration, event.step)
            end = time.monotonic() + duration
            while time.monotonic() < end:
                # Sliced sleep: the watchdog delivers its typed fault
                # by async raise, which only lands between bytecode —
                # a single long sleep would absorb the whole hang.
                time.sleep(0.05)
            return
        message = "graftchaos: injected {} before step {}".format(
            event.kind, event.step)
        logger.warning("%s", message)
        if event.kind == "preempt":
            raise resilience.Preemption(message)
        if event.kind == "fetch":
            raise resilience.DataStall(
                message + " (transient fetch error)")
        if event.kind == "nan":
            raise resilience.NaNLoss(message)

    def notify_checkpoint(self, path, step):
        """Called by checkpoint.save after a committed write; fires any
        pending `corrupt` event whose threshold the save reached."""
        due = [e for e in self.events
               if not e.fired and e.kind == "corrupt" and step >= e.step]
        for event in due:
            if self._truncate(path):
                event.fired = True
                _log_event(event, extra={"path": str(path),
                                         "checkpoint_step": step})

    @staticmethod
    def _truncate(path):
        """Truncates the largest file under checkpoint `path` to half
        its size — a torn write. Returns False (event stays armed)
        when there is nothing truncatable yet (e.g. an async save
        still committing)."""
        candidates = []
        if os.path.isfile(path):
            candidates.append((os.path.getsize(path), path))
        elif os.path.isdir(path):
            for root, _, names in os.walk(path):
                for name in names:
                    target = os.path.join(root, name)
                    try:
                        candidates.append((os.path.getsize(target), target))
                    except OSError:
                        continue
        # Largest first, path as the deterministic tie-break.
        candidates = [c for c in sorted(candidates,
                                        key=lambda c: (-c[0], c[1]))
                      if c[0] > 0]
        if not candidates:
            return False
        size, target = candidates[0]
        with open(target, "r+b") as f:
            f.truncate(size // 2)
        logger.warning("graftchaos: truncated %s (%d -> %d bytes).",
                       target, size, size // 2)
        return True


# --------------------------------------------------------------------------
# Module singleton: one plan per process, surviving in-process retries
# (a fired event stays fired across graftguard re-entries).
# --------------------------------------------------------------------------

_plan = None
_env_checked = False


def install(spec):
    """Installs (or with a falsy spec, clears) the active plan.
    Replaces any existing plan and suppresses the one-time
    CLOUD_TPU_CHAOS auto-install."""
    global _plan, _env_checked
    _env_checked = True
    _plan = ChaosPlan.parse(spec) if spec else None
    return _plan


def uninstall():
    """Clears the active plan (test isolation) and re-arms the
    CLOUD_TPU_CHAOS auto-install."""
    global _plan, _env_checked
    _plan = None
    _env_checked = False


def active_plan():
    """The installed plan, auto-installing from CLOUD_TPU_CHAOS on the
    first ask (once — a consumed plan is not re-armed). None when
    chaos is off."""
    global _plan, _env_checked
    if _plan is None and not _env_checked:
        _env_checked = True
        spec = os.environ.get("CLOUD_TPU_CHAOS")
        if spec:
            _plan = ChaosPlan.parse(spec)
            logger.warning("graftchaos: active plan %s.",
                           [e.spec() for e in _plan.events])
    return _plan


def notify_checkpoint(path, step):
    """checkpoint.save's hook: forwards to the active plan, if any."""
    plan = _plan
    if plan is not None:
        plan.notify_checkpoint(path, step)
