"""graftlint: AST-based static analysis for JAX/TPU training code.

The runtime made transfers and compiles *counted* resources
(`runtime.transfer_stats()` / `runtime.compile_stats()`); this package
is the static complement — each rule predicts the runtime counter that
would regress if the pattern shipped, so a pitfall is caught before the
job is containerized instead of as a wall-clock pathology on the slice.

Three entry points share one engine:

- CLI:       python -m cloud_tpu.analysis.lint <paths> [--strict] [--format json]
- Preflight: `run(entry_point=..., lint="warn"|"strict"|"off")` lints the
             entry point before containerize (analysis/preflight.py).
- Self-run:  CI runs the linter over this repository itself; the tree
             stays graftlint-clean.

Pure `ast` + `tokenize` — the target is parsed, never imported.
"""

from cloud_tpu.analysis.engine import Finding
from cloud_tpu.analysis.engine import RULES
from cloud_tpu.analysis.engine import check_paths
from cloud_tpu.analysis.engine import check_source
from cloud_tpu.analysis.preflight import GraftlintError
from cloud_tpu.analysis.preflight import preflight_lint

__all__ = ["Finding", "RULES", "check_paths", "check_source",
           "GraftlintError", "preflight_lint"]
