"""graftlint: AST-based static analysis for JAX/TPU training code.

The runtime made transfers and compiles *counted* resources
(`runtime.transfer_stats()` / `runtime.compile_stats()`); this package
is the static complement — each rule predicts the runtime counter that
would regress if the pattern shipped, so a pitfall is caught before the
job is containerized instead of as a wall-clock pathology on the slice.

Three entry points share one engine:

- CLI:       python -m cloud_tpu.analysis.lint <paths> [--strict]
             [--format json|sarif] [--axes]
- Preflight: `run(entry_point=..., lint="warn"|"strict"|"off")` lints the
             entry point AND its first-level local imports before
             containerize (analysis/preflight.py).
- Self-run:  CI runs the linter over this repository itself; the tree
             stays graftlint-clean.

Pure `ast` + `tokenize` — the target is parsed, never imported. Rules
GL006-GL010 and GL014-GL018 are interprocedural: every file in one
invocation shares a `callgraph.ProjectContext`, so facts flow through
imports and calls — the graftmesh family (GL014-GL018) additionally
reads the whole-program mesh-axis registry (analysis/meshmap.py,
dumped by `lint --axes`).

The dynamic complement is graftsan (analysis/sanitizer.py): `with
sanitize():` — or `CLOUD_TPU_SANITIZE=1` around `Trainer.fit` — hooks
the runtime's transfer/compile records and `jax.random` key
consumption, attributes each event to its source line, and checks the
same invariants the static rules encode.
"""

from cloud_tpu.analysis.engine import Finding
from cloud_tpu.analysis.engine import RULES
from cloud_tpu.analysis.engine import check_paths
from cloud_tpu.analysis.engine import check_source
from cloud_tpu.analysis.preflight import GraftlintError
from cloud_tpu.analysis.preflight import preflight_lint
from cloud_tpu.analysis.sanitizer import GraftsanError
from cloud_tpu.analysis.sanitizer import Sanitizer
from cloud_tpu.analysis.sanitizer import sanitize

__all__ = ["Finding", "RULES", "check_paths", "check_source",
           "GraftlintError", "preflight_lint",
           "GraftsanError", "Sanitizer", "sanitize"]
