"""The graftlint engine: parse, build shared context, dispatch rules.

The engine never imports the code under analysis — everything is a pure
`ast` walk plus a `tokenize` pass for suppression comments. That keeps
the linter runnable on broken trees, on files with unavailable
dependencies, and inside the preflight path of `run()` where importing
user training code would execute it.

Suppression syntax (comment-level, mirrored on pylint's):

    x = float(loss)          # graftlint: disable=GL001
    key2 = reuse(key)        # graftlint: disable=GL004,GL001
    anything = hazard()      # graftlint: disable=all

    # graftlint: disable-file=GL005      <- anywhere in the file

`disable=` applies to findings reported on the comment's own line;
`disable-file=` disables the rule(s) for the whole file.
"""

import ast
import io
import os
import re
import tokenize

#: Rule id reserved for files the engine cannot parse at all. A syntax
#: error is a finding (not a crash) so `--strict` still gates on it.
PARSE_ERROR = "GL000"

_SUPPRESS_RE = re.compile(
    r"graftlint:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<codes>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*)")


class Finding:
    """One lint finding, stable across text and JSON output."""

    __slots__ = ("path", "line", "col", "rule", "message")

    def __init__(self, path, line, col, rule, message):
        self.path = path
        self.line = line
        self.col = col
        self.rule = rule
        self.message = message

    def sort_key(self):
        return (self.path, self.line, self.col, self.rule)

    def to_dict(self):
        return {"path": self.path, "line": self.line, "col": self.col,
                "rule": self.rule, "message": self.message}

    def format(self):
        return "{}:{}:{}: {} {}".format(
            self.path, self.line, self.col, self.rule, self.message)

    def __repr__(self):
        return "Finding({!r})".format(self.format())


def _suppressions(source):
    """-> (line -> set(codes), set(file_codes)); 'all' wildcard kept."""
    per_line = {}
    per_file = set()
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            match = _SUPPRESS_RE.search(tok.string)
            if not match:
                continue
            codes = {c.strip().upper() if c.strip().lower() != "all"
                     else "all"
                     for c in match.group("codes").split(",")}
            if match.group("file"):
                per_file |= codes
            else:
                per_line.setdefault(tok.start[0], set()).update(codes)
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # unparseable files are reported as GL000 by check_source
    return per_line, per_file


def _suppressed(finding, per_line, per_file):
    if "all" in per_file or finding.rule in per_file:
        return True
    codes = per_line.get(finding.line, ())
    return "all" in codes or finding.rule in codes


def _parse_context(source, path):
    """-> (FileContext, None) or (None, GL000 Finding)."""
    # Imported here, not at module top: rules imports engine for the
    # Finding type, and this lazy edge breaks the cycle.
    from cloud_tpu.analysis import rules

    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return None, Finding(path, exc.lineno or 1, exc.offset or 0,
                             PARSE_ERROR,
                             "could not parse file: {}".format(exc.msg))
    return rules.FileContext(tree, source, path), None


def _check_context(ctx, select):
    """Runs the (selected) rules over one FileContext, honouring the
    file's suppression comments. `ctx.project` must already be set."""
    per_line, per_file = _suppressions(ctx.source)
    findings = []
    for rule in RULES.values():
        if select is not None and rule.id not in select:
            continue
        for finding in rule.check(ctx):
            if not _suppressed(finding, per_line, per_file):
                findings.append(finding)
    return findings


def check_source(source, path="<string>", select=None):
    """Lints one source string -> sorted [Finding].

    select: optional iterable of rule ids to run (default: all).
    The interprocedural rules see a one-module project, so chains
    through helpers defined in the same source still resolve.
    """
    from cloud_tpu.analysis import callgraph

    ctx, error = _parse_context(source, path)
    if error is not None:
        return [error]
    ctx.project = callgraph.ProjectContext([ctx])
    return sorted(_check_context(ctx, select), key=Finding.sort_key)


def iter_python_files(paths):
    """Expands files/directories into a sorted list of .py files.

    Directories are walked recursively; hidden directories and
    `__pycache__` are skipped. Non-python files given explicitly raise
    ValueError (a typo'd path should not silently lint nothing).
    """
    out = []
    for path in paths:
        if os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(".")
                                 and d != "__pycache__")
                for name in sorted(files):
                    if name.endswith(".py"):
                        out.append(os.path.join(root, name))
        elif os.path.isfile(path):
            if not path.endswith(".py"):
                raise ValueError(
                    "graftlint only checks .py files; got {!r}".format(path))
            out.append(path)
        else:
            raise ValueError("No such file or directory: {!r}".format(path))
    return out


def build_project(paths):
    """Parses files/directories into one shared project.

    -> (callgraph.ProjectContext, [GL000 Findings], files_listed).
    Every parseable file's FileContext has `.project` attached. Both
    `check_paths` and the graftmesh `lint --axes` registry dump build
    their world through here, so the two always see the same modules.
    """
    from cloud_tpu.analysis import callgraph

    files = iter_python_files(paths)
    errors, contexts = [], []
    for filename in files:
        try:
            with open(filename, "r", encoding="utf-8") as handle:
                source = handle.read()
        except OSError as exc:
            # A file that vanished or lost read permission between
            # listing and reading (preflight races the user's editor)
            # degrades to a finding, not a crashed lint run.
            errors.append(Finding(
                filename, 0, 0, PARSE_ERROR,
                "unreadable: {}".format(exc)))
            continue
        ctx, error = _parse_context(source, filename)
        if error is not None:
            errors.append(error)
        else:
            contexts.append(ctx)
    project = callgraph.ProjectContext(contexts)
    for ctx in contexts:
        ctx.project = project
    return project, errors, len(files)


def check_paths(paths, select=None):
    """Lints files/directories -> (sorted [Finding], files_checked).

    All parseable files share ONE `callgraph.ProjectContext`, so the
    interprocedural rules (GL006-GL010, GL014-GL018) resolve imports
    and call chains across every file in the invocation — linting a
    package directory sees strictly more than linting its files one by
    one.
    """
    project, findings, files_checked = build_project(paths)
    for view in project.modules.values():
        findings.extend(_check_context(view.ctx, select))
    return sorted(findings, key=Finding.sort_key), files_checked


def _build_registry():
    from cloud_tpu.analysis import rules

    registry = {}
    for rule in rules.ALL_RULES:
        if rule.id in registry:
            raise ValueError("Duplicate rule id: {}".format(rule.id))
        registry[rule.id] = rule
    return registry


class _LazyRegistry(dict):
    """id -> rule, materialized on first access (breaks the
    engine<->rules import cycle without repeating the lazy import at
    every call site)."""

    _loaded = False

    def _ensure(self):
        if not self._loaded:
            self._loaded = True
            super().update(_build_registry())

    def __getitem__(self, key):
        self._ensure()
        return super().__getitem__(key)

    def __iter__(self):
        self._ensure()
        return super().__iter__()

    def __len__(self):
        self._ensure()
        return super().__len__()

    def keys(self):
        self._ensure()
        return super().keys()

    def values(self):
        self._ensure()
        return super().values()

    def items(self):
        self._ensure()
        return super().items()

    def __contains__(self, key):
        self._ensure()
        return super().__contains__(key)


#: Rule registry: id -> rule instance, in GL001..GL018 order.
RULES = _LazyRegistry()
