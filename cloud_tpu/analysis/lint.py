"""graftlint CLI: `python -m cloud_tpu.analysis.lint <paths>`.

Exit-code contract (CI gates on it):

    0  clean tree, or findings in default (warn) mode
    1  findings (or unparseable files) with --strict
    2  usage errors (argparse) / nonexistent paths

JSON output schema (test-pinned, `--format json`):

    {"version": 1,
     "files_checked": <int>,
     "counts": {"GL001": <int>, ...},        # only rules that fired
     "findings": [{"path": str, "line": int, "col": int,
                   "rule": str, "message": str}, ...]}    # sorted

`--format sarif` emits a SARIF 2.1.0 log (one run, driver "graftlint",
every registered rule in the rule table, findings as level "warning"
results with 1-based line/column physical locations) — the interchange
format code-scanning UIs (GitHub, VS Code SARIF viewer) ingest
directly; CI uploads it as the analysis artifact.

`--axes` skips the rules entirely and dumps the graftmesh axis
registry (analysis/meshmap.py) as JSON: every Mesh construction with
its axis names and statically-known sizes, every PartitionSpec /
NamedSharding, every shard_map in/out spec, and every collective with
its axis_name — each attributed to file:line and enclosing scope. CI
uploads it next to the SARIF artifact; with --strict an EMPTY registry
exits 1 (a silent meshmap walker breakage, not a clean tree).
"""

import argparse
import json
import sys

from cloud_tpu.analysis import engine

#: Bumped on any backwards-incompatible change to the JSON schema.
JSON_VERSION = 1


def _build_parser():
    parser = argparse.ArgumentParser(
        prog="python -m cloud_tpu.analysis.lint",
        description="graftlint: static analysis for JAX/TPU training "
                    "code (rules GL001-GL018; see --list-rules).")
    parser.add_argument("paths", nargs="*",
                        help=".py files and/or directories to lint")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text",
                        help="output format (default: text)")
    parser.add_argument("--strict", action="store_true",
                        help="exit 1 when there is any finding "
                             "(default: report and exit 0)")
    parser.add_argument("--select", default=None, metavar="IDS",
                        help="comma-separated rule ids to run, e.g. "
                             "GL001,GL004 (default: all)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument("--axes", action="store_true",
                        help="dump the graftmesh axis registry (every "
                             "Mesh/PartitionSpec/shard_map/collective "
                             "site) as JSON instead of linting; with "
                             "--strict an empty registry exits 1")
    return parser


def _list_rules(out):
    for rule in engine.RULES.values():
        out.write("{}  {:<24} predicts: {}\n".format(
            rule.id, rule.title, rule.predicts))


#: SARIF spec version emitted by --format sarif (schema is test-pinned).
SARIF_VERSION = "2.1.0"
_SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                 "master/Schemata/sarif-schema-2.1.0.json")


def to_sarif(findings, files_checked):
    """Findings -> a SARIF 2.1.0 log dict (one run, driver graftlint).

    Every registered rule (plus GL000, the parse-error pseudo-rule)
    appears in the driver's rule table whether or not it fired, so
    `ruleIndex` is stable across runs of the same linter version.
    SARIF columns/lines are 1-based; `Finding.col` is the 0-based ast
    col_offset.
    """
    rule_ids = [engine.PARSE_ERROR] + list(engine.RULES.keys())
    rules = [{"id": engine.PARSE_ERROR,
              "name": "parse-error",
              "shortDescription": {"text": "file does not parse"}}]
    for rule in engine.RULES.values():
        rules.append({
            "id": rule.id,
            "name": rule.title,
            "shortDescription": {"text": rule.title},
            "fullDescription": {
                "text": "predicts: {}".format(rule.predicts)},
        })
    results = []
    for f in findings:
        results.append({
            "ruleId": f.rule,
            "ruleIndex": rule_ids.index(f.rule),
            "level": "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": f.line,
                               "startColumn": f.col + 1},
                },
            }],
        })
    return {
        "$schema": _SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {"name": "graftlint",
                                "rules": rules}},
            "results": results,
            "properties": {"files_checked": files_checked},
        }],
    }


def run_lint(paths, select=None):
    """Library entry: -> (findings, files_checked). `select` is an
    iterable of rule ids or None for all."""
    return engine.check_paths(paths, select=select)


def main(argv=None, out=None):
    out = out or sys.stdout
    args = _build_parser().parse_args(argv)

    if args.list_rules:
        _list_rules(out)
        return 0
    if not args.paths:
        _build_parser().print_usage(sys.stderr)
        return 2

    if args.axes:
        from cloud_tpu.analysis import meshmap

        try:
            registry, errors = meshmap.registry_for_paths(args.paths)
        except ValueError as exc:
            sys.stderr.write("graftlint: {}\n".format(exc))
            return 2
        doc = registry.to_json()
        doc["parse_errors"] = [f.to_dict() for f in errors]
        out.write(json.dumps(doc, indent=2, sort_keys=True) + "\n")
        if args.strict and registry.is_empty():
            sys.stderr.write(
                "graftlint --axes --strict: EMPTY axis registry — no "
                "Mesh/PartitionSpec/shard_map/collective site found; "
                "either the paths hold no sharded code or the meshmap "
                "walker broke\n")
            return 1
        return 0

    select = None
    if args.select:
        select = {s.strip().upper() for s in args.select.split(",")
                  if s.strip()}
        unknown = select - set(engine.RULES.keys()) - {engine.PARSE_ERROR}
        if unknown:
            sys.stderr.write("graftlint: unknown rule id(s): {}\n".format(
                ", ".join(sorted(unknown))))
            return 2
        select |= {engine.PARSE_ERROR}  # parse errors always gate

    try:
        findings, files_checked = run_lint(args.paths, select=select)
    except ValueError as exc:
        sys.stderr.write("graftlint: {}\n".format(exc))
        return 2

    if args.format == "json":
        counts = {}
        for finding in findings:
            counts[finding.rule] = counts.get(finding.rule, 0) + 1
        doc = {"version": JSON_VERSION,
               "files_checked": files_checked,
               "counts": counts,
               "findings": [f.to_dict() for f in findings]}
        out.write(json.dumps(doc, indent=2, sort_keys=True) + "\n")
    elif args.format == "sarif":
        out.write(json.dumps(to_sarif(findings, files_checked),
                             indent=2, sort_keys=True) + "\n")
    else:
        for finding in findings:
            out.write(finding.format() + "\n")
        out.write("graftlint: {} finding(s) in {} file(s)\n".format(
            len(findings), files_checked))

    if findings and args.strict:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
