"""Package-wide module graph + call graph for interprocedural graftlint.

PR 4's rules are strictly intraprocedural: a `float()` two frames below
a jitted body, a PRNG key consumed inside a helper, or a PartitionSpec
checked against a Mesh declared in another file all sail through. This
module gives the rules the facts they need to see across calls — still
pure `ast`, targets parsed and never imported.

Three ingredients:

1. **Module graph.** Every linted file gets a dotted module name
   (derived by walking up `__init__.py` parents, so
   `cloud_tpu/parallel/runtime.py` -> `cloud_tpu.parallel.runtime`;
   loose scripts use their stem). Import statements — absolute,
   aliased, and relative — resolve to other linted modules when the
   target is in the same lint invocation, and to nothing otherwise
   (facts never cross into code we did not parse).

2. **Call graph.** Module-level `def`s are registered per module; a
   call `helper(...)`, `mod.helper(...)` or `from m import helper;
   helper(...)` resolves to its `FunctionSummary`. Methods and nested
   defs are deliberately unresolved — attribute dispatch on instances
   is untyped guesswork, and a wrong edge turns a heuristic lint into
   a noise source.

3. **Transitive summaries.** Per function, computed to fixpoint over
   the call graph (cycle-safe):
   - `host_sync`: the function directly performs a host sync
     (`float`/`.item()`/`np.asarray`/`print`/`jax.device_get`), or
     calls (transitively) one that does. `host_sync_chain` reproduces
     the full call chain for the finding message.
   - `key_params`: parameters the function consumes as PRNG keys —
     directly (first argument of a `jax.random.<fn>` call) or by
     passing them into a callee's key parameter.
   - `retained_params`: parameters the function stores somewhere that
     outlives the call (an attribute, a subscript, a declared global,
     or a `.append/.add/.insert` container call) — the escape facts
     GL009 needs to see a donated buffer leak through a helper.
   - `unread_params`: parameters neither the function nor any
     resolvable callee they are forwarded to ever reads — the
     dead-leaf facts GL010 uses to see through helpers at a jit
     boundary (decreasing fixpoint; unresolvable forwards count as
     reads).
"""

import ast
import os

# Mirrors rules._STATIC_CALLS conceptually: container-mutation method
# names that retain their argument beyond the call.
_RETAIN_METHODS = {"append", "add", "insert", "appendleft", "push",
                   "setdefault"}

#: Hard ceiling on call-chain depth for transitive facts. Real pitfalls
#: hide one or two frames down; past that the chain message is noise
#: and a pathological tree could make the DFS expensive.
MAX_CHAIN_DEPTH = 8


def module_name_for(path):
    """Dotted module name for a file path.

    Walks up while `__init__.py` siblings exist, so files inside a
    package get their importable name; loose files get their stem.
    `__init__.py` itself names the package.
    """
    path = os.path.abspath(path)
    directory, base = os.path.split(path)
    stem = base[:-3] if base.endswith(".py") else base
    parts = [] if stem == "__init__" else [stem]
    while os.path.isfile(os.path.join(directory, "__init__.py")):
        directory, pkg = os.path.split(directory)
        if not pkg:
            break
        parts.insert(0, pkg)
    return ".".join(parts) if parts else stem


class FunctionSummary:
    """Everything the interprocedural rules know about one def."""

    __slots__ = ("name", "qualname", "module", "node", "ctx",
                 "params", "direct_sync", "calls", "key_params",
                 "retained_params", "param_reads", "param_forwards",
                 "unread_params")

    def __init__(self, name, module, node, ctx):
        self.name = name
        self.module = module                  # ModuleView
        self.qualname = "{}.{}".format(module.name, name)
        self.node = node
        self.ctx = ctx
        args = node.args
        self.params = [a.arg for a in args.posonlyargs + args.args]
        #: (label, line) of a direct host-sync call in the body, or None.
        self.direct_sync = None
        #: [(call_node, callee_name_expr)] — resolved lazily.
        self.calls = []
        #: param name -> (line, via_summary, via_param): the consuming
        #: jax.random call's line (via None) or the line of the call
        #: that forwards the key into `via_summary`'s `via_param`.
        self.key_params = {}
        #: param name -> (line, how, via_summary, via_param) for params
        #: retained past the call; `how` is the human label, via fields
        #: follow the same convention as key_params.
        self.retained_params = {}
        #: param names with at least one "real" read in the body — any
        #: Load that is not a plain positional forward into a call
        #: (attribute access, arithmetic, return, store target, ...).
        self.param_reads = set()
        #: param name -> [(call_node, positional_index), ...] for plain
        #: positional forwards; the only way a param can be consumed
        #: without a real read.
        self.param_forwards = {}
        #: params never read by this function nor (transitively) by any
        #: resolvable callee they are forwarded to. Computed by a
        #: decreasing fixpoint in `_fixpoint_unread`; forwards into
        #: unresolvable callees conservatively count as reads.
        self.unread_params = set()

    def __repr__(self):
        return "FunctionSummary({})".format(self.qualname)


class ModuleView:
    """Per-file view of the project: name, context, import resolution."""

    __slots__ = ("path", "name", "ctx", "functions", "import_modules",
                 "from_imports")

    def __init__(self, path, name, ctx):
        self.path = path
        self.name = name
        self.ctx = ctx
        #: top-level def name -> FunctionSummary
        self.functions = {}
        #: local alias -> dotted module (import x.y as z; import x.y)
        self.import_modules = {}
        #: local name -> (dotted module, original name) for
        #: `from m import f [as g]` (f may itself be a submodule).
        self.from_imports = {}


class ProjectContext:
    """The cross-file fact base rules GL006-GL009 read.

    Built once per lint invocation from every parseable file in it.
    Single-file runs get a one-module project, so interprocedural
    rules still see helpers defined in the same file.
    """

    def __init__(self, contexts):
        #: path -> ModuleView
        self.modules = {}
        #: dotted name -> ModuleView (first wins on duplicates)
        self.by_name = {}
        #: axis name -> sorted list of declaring module paths
        self.mesh_axes = {}
        #: lazily built graftmesh AxisRegistry (see `graftmesh()`)
        self._graftmesh = None
        for ctx in contexts:
            view = ModuleView(ctx.path, module_name_for(ctx.path), ctx)
            self.modules[ctx.path] = view
            self.by_name.setdefault(view.name, view)
            for axis in ctx.mesh_axes:
                self.mesh_axes.setdefault(axis, []).append(ctx.path)
        for view in self.modules.values():
            self._collect_imports(view)
            self._collect_functions(view)
        self._summarize_direct_facts()
        self._fixpoint_key_and_retain()
        self._fixpoint_unread()

    # -- construction --------------------------------------------------

    def _collect_imports(self, view):
        for node in ast.walk(view.ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    # `import x.y` binds `x`; `import x.y as z` binds z
                    # to x.y itself.
                    target = alias.name if alias.asname else \
                        alias.name.split(".")[0]
                    view.import_modules[bound] = target
            elif isinstance(node, ast.ImportFrom):
                module = self._resolve_from(view, node)
                if module is None:
                    continue
                for alias in node.names:
                    bound = alias.asname or alias.name
                    view.from_imports[bound] = (module, alias.name)

    @staticmethod
    def _resolve_from(view, node):
        """Absolute dotted module for a `from ... import` statement.

        Relative imports resolve against the importing module's
        package (cycle-safe by construction: name resolution only, no
        recursion)."""
        if not node.level:
            return node.module
        parts = view.name.split(".")
        # level 1 strips the module segment, each extra level one
        # package; a too-deep relative import resolves to nothing.
        if node.level > len(parts):
            return None
        base = parts[:len(parts) - node.level]
        if node.module:
            base.append(node.module)
        return ".".join(base) if base else None

    def _collect_functions(self, view):
        for node in view.ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                view.functions[node.name] = FunctionSummary(
                    node.name, view, node, view.ctx)

    # -- resolution ----------------------------------------------------

    def view_for(self, ctx):
        return self.modules.get(ctx.path)

    def resolve_call(self, ctx, func):
        """FunctionSummary for a Call's func expression, or None.

        Handles `f(...)` (local def or from-import) and `mod.f(...)`
        (module alias or from-imported submodule). Anything else —
        methods, nested defs, chains — is unresolved on purpose.
        """
        view = self.view_for(ctx)
        if view is None:
            return None
        if isinstance(func, ast.Name):
            local = view.functions.get(func.id)
            if local is not None:
                return local
            origin = view.from_imports.get(func.id)
            if origin is not None:
                target = self.by_name.get(origin[0])
                if target is not None:
                    return target.functions.get(origin[1])
            return None
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)):
            base = func.value.id
            module = view.import_modules.get(base)
            if module is None:
                origin = view.from_imports.get(base)
                if origin is not None:
                    # `from cloud_tpu.parallel import runtime` — the
                    # bound name is a submodule.
                    module = "{}.{}".format(origin[0], origin[1])
            if module is None:
                return None
            target = self.by_name.get(module)
            if target is None:
                return None
            return target.functions.get(func.attr)
        return None

    # -- direct facts --------------------------------------------------

    def _summarize_direct_facts(self):
        from cloud_tpu.analysis import rules

        for view in self.modules.values():
            for summary in view.functions.values():
                self._scan_body(view, summary, rules)

    def _scan_body(self, view, summary, rules):
        params = set(summary.params)
        global_names = set()
        for node in ast.walk(summary.node):
            if isinstance(node, ast.Global):
                global_names.update(node.names)
        forward_ids = set()  # id() of Name nodes that are plain forwards
        for node in ast.walk(summary.node):
            if isinstance(node, ast.Call):
                label = rules.HostSyncInJit._host_sync_label(node)
                if label is not None and summary.direct_sync is None:
                    summary.direct_sync = (label, node.lineno)
                summary.calls.append(node)
                # Plain positional forwards: f(p) where p is a param.
                # A Starred earlier in the arg list breaks positional
                # mapping, so the whole call is treated as real reads.
                if not any(isinstance(a, ast.Starred) for a in node.args):
                    for pos, arg in enumerate(node.args):
                        if (isinstance(arg, ast.Name)
                                and arg.id in params):
                            forward_ids.add(id(arg))
                            summary.param_forwards.setdefault(
                                arg.id, []).append((node, pos))
                # Direct key consumption: jax.random.<fn>(param, ...).
                if (rules._is_random_call(node.func, view.ctx)
                        and node.args
                        and isinstance(node.args[0], ast.Name)
                        and node.args[0].id in params):
                    summary.key_params.setdefault(
                        node.args[0].id, (node.lineno, None, None))
                # Container retention: box.append(param) and friends.
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _RETAIN_METHODS):
                    for arg in node.args:
                        if isinstance(arg, ast.Name) and arg.id in params:
                            summary.retained_params.setdefault(
                                arg.id,
                                (node.lineno,
                                 ".{}()".format(node.func.attr),
                                 None, None))
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                value = node.value
                if not (isinstance(value, ast.Name)
                        and value.id in params):
                    continue
                for target in targets:
                    if isinstance(target, (ast.Attribute, ast.Subscript)):
                        summary.retained_params.setdefault(
                            value.id,
                            (node.lineno,
                             "attribute store" if isinstance(
                                 target, ast.Attribute)
                             else "subscript store",
                             None, None))
                    elif (isinstance(target, ast.Name)
                          and target.id in global_names):
                        summary.retained_params.setdefault(
                            value.id,
                            (node.lineno, "global store", None, None))
        # Second pass so forward_ids is complete: any param occurrence
        # that is not a plain positional forward is a real read
        # (Store/Del included — rebinding makes liveness murky, and a
        # conservative "read" only suppresses a finding).
        for node in ast.walk(summary.node):
            if (isinstance(node, ast.Name) and node.id in params
                    and id(node) not in forward_ids):
                summary.param_reads.add(node.id)

    # -- fixpoint propagation ------------------------------------------

    def _fixpoint_key_and_retain(self):
        """Propagates key consumption and retention through call args.

        A param flows into a callee when it appears as a plain Name in
        a resolvable call's positional args; the callee's fact at that
        position transfers back to the caller's param. Iterated to a
        fixpoint — the graphs are small and each pass only adds facts,
        so termination is by monotonicity.
        """
        changed = True
        passes = 0
        while changed and passes < 20:  # belt over the monotonic brace
            changed = False
            passes += 1
            for view in self.modules.values():
                for summary in view.functions.values():
                    params = set(summary.params)
                    for call in summary.calls:
                        callee = self.resolve_call(view.ctx, call.func)
                        if callee is None or callee is summary:
                            continue
                        for pos, arg in enumerate(call.args):
                            if not (isinstance(arg, ast.Name)
                                    and arg.id in params):
                                continue
                            if pos >= len(callee.params):
                                continue
                            callee_param = callee.params[pos]
                            if (callee_param in callee.key_params
                                    and arg.id not in summary.key_params):
                                summary.key_params[arg.id] = (
                                    call.lineno, callee, callee_param)
                                changed = True
                            if (callee_param in callee.retained_params
                                    and arg.id not in
                                    summary.retained_params):
                                summary.retained_params[arg.id] = (
                                    call.lineno,
                                    "via {}".format(callee.qualname),
                                    callee, callee_param)
                                changed = True

    def _fixpoint_unread(self):
        """Decreasing fixpoint for `unread_params`.

        Start optimistic: every param without a real read is
        candidate-unread. Each pass flips a candidate to "read" when
        any of its forwards lands somewhere we cannot prove dead — an
        unresolvable callee (methods, builtins, other packages), an
        arity mismatch, or a callee param that is itself read. Only
        unread->read flips happen, so termination is by monotonicity;
        self-recursive forwards correctly stay unread.
        """
        for view in self.modules.values():
            for summary in view.functions.values():
                summary.unread_params = (
                    set(summary.params) - summary.param_reads)
        changed = True
        passes = 0
        while changed and passes < 20:
            changed = False
            passes += 1
            for view in self.modules.values():
                for summary in view.functions.values():
                    for param in list(summary.unread_params):
                        forwards = summary.param_forwards.get(param, ())
                        if self._forward_is_read(view, summary, forwards):
                            summary.unread_params.discard(param)
                            changed = True

    def _forward_is_read(self, view, summary, forwards):
        for call, pos in forwards:
            callee = self.resolve_call(view.ctx, call.func)
            if callee is None:
                return True
            if pos >= len(callee.params):
                return True
            if callee.params[pos] not in callee.unread_params:
                return True
        return False

    def unread_chain(self, summary, param):
        """[(qualname, param), ...] from `summary` down through the
        forwards that keep `param` unread (depth-capped, cycle-safe).
        Length 1 means the function simply never touches the param."""
        chain = [(summary.qualname, param)]
        seen = {(summary.qualname, param)}
        for _ in range(MAX_CHAIN_DEPTH):
            nxt = None
            for call, pos in summary.param_forwards.get(param, ()):
                callee = self.resolve_call(summary.ctx, call.func)
                if (callee is not None and pos < len(callee.params)
                        and callee.params[pos] in callee.unread_params):
                    nxt = (callee, callee.params[pos])
                    break
            if nxt is None:
                break
            summary, param = nxt
            key = (summary.qualname, param)
            if key in seen:
                break
            seen.add(key)
            chain.append(key)
        return chain

    # -- chain reconstruction ------------------------------------------

    def consuming_key_param(self, ctx, call, name):
        """(callee, param) when the Call passes local `name` into a
        callee parameter known to consume it as a PRNG key; else None.
        """
        return self._param_fact(ctx, call, name, "key_params")

    def retaining_param(self, ctx, call, name):
        """(callee, param) when the Call passes local `name` into a
        callee parameter known to retain it past the call; else None."""
        return self._param_fact(ctx, call, name, "retained_params")

    def _param_fact(self, ctx, call, name, table):
        callee = self.resolve_call(ctx, call.func)
        if callee is None:
            return None
        for pos, arg in enumerate(call.args):
            if (isinstance(arg, ast.Name) and arg.id == name
                    and pos < len(callee.params)
                    and callee.params[pos] in getattr(callee, table)):
                return callee, callee.params[pos]
        return None

    def key_chain(self, summary, param):
        """[(qualname, line), ...] from `summary`'s `param` down to the
        jax.random call that consumes it (depth-capped, cycle-safe)."""
        return self._fact_chain(summary, param, "key_params")

    def retain_chain(self, summary, param):
        """[(qualname, line, how), ...] down to the direct retention."""
        chain = []
        for _ in range(MAX_CHAIN_DEPTH):
            fact = summary.retained_params.get(param)
            if fact is None:
                break
            line, how, via, via_param = fact
            chain.append((summary.qualname, line, how))
            if via is None:
                break
            summary, param = via, via_param
        return chain

    def _fact_chain(self, summary, param, table):
        chain = []
        for _ in range(MAX_CHAIN_DEPTH):
            fact = getattr(summary, table).get(param)
            if fact is None:
                break
            line, via, via_param = fact
            chain.append((summary.qualname, line))
            if via is None:
                break
            summary, param = via, via_param
        return chain

    # -- transitive host-sync chains -----------------------------------

    def host_sync_chain(self, ctx, func, _depth=0, _visiting=None):
        """Call chain from `func` (a Call's func expr in `ctx`) down to
        a host-sync primitive, or None.

        Returns [(qualname, line, label), ...] — one frame per hop,
        last frame carrying the primitive's label and line. Callees
        that are themselves jit-compiled are excluded: GL001 already
        flags the sync inside them, and double-reporting one pitfall
        under two rules would train people to suppress both.
        """
        summary = self.resolve_call(ctx, func)
        if summary is None:
            return None
        return self._chain_from(summary, _depth, _visiting or set())

    def _chain_from(self, summary, depth, visiting):
        if depth >= MAX_CHAIN_DEPTH or summary in visiting:
            return None
        if summary.node in summary.ctx.jit_defs:
            return None  # GL001's jurisdiction (see docstring)
        if summary.direct_sync is not None:
            label, line = summary.direct_sync
            return [(summary.qualname, line, label)]
        visiting = visiting | {summary}
        for call in summary.calls:
            sub = self.host_sync_chain(summary.ctx, call.func,
                                       depth + 1, visiting)
            if sub is not None:
                return [(summary.qualname, call.lineno, None)] + sub
        return None

    # -- mesh axes -----------------------------------------------------

    def mesh_axis_declared(self, axis):
        return axis in self.mesh_axes

    def declared_axes_label(self):
        """Human-readable 'axis (module.py), ...' summary for messages."""
        parts = []
        for axis in sorted(self.mesh_axes):
            paths = self.mesh_axes[axis]
            parts.append("{!r} ({})".format(
                axis, os.path.basename(paths[0])))
        return ", ".join(parts) if parts else "none"

    def graftmesh(self):
        """The graftmesh `AxisRegistry` over this project, built on
        first use and shared by every rule that reads it (GL014-GL018)
        and by `lint --axes`. Lazy import: meshmap imports rules,
        which already imports nothing from here at module scope, but
        keeping the edge out of import time makes the layering obvious
        and cycle-proof."""
        if self._graftmesh is None:
            from cloud_tpu.analysis import meshmap
            self._graftmesh = meshmap.build_registry(self)
        return self._graftmesh
