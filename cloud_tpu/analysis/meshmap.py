"""graftmesh: the whole-program mesh-axis registry.

ROADMAP item 1's missing fact base, built statically: one walk over a
lint invocation (the same `FileContext`s the rules see, shared through
`callgraph.ProjectContext`) inventories every site where mesh-axis
semantics enter the program —

- `Mesh(...)` / `jax.make_mesh(...)` constructions, with their axis
  names and, where the shape is a literal (`make_mesh((2, 4), ...)`,
  `devices.reshape(2, 4)`, `create_device_mesh((2, 4))`), the axis
  *sizes*;
- every `PartitionSpec` (under whatever alias the file imports it) and
  `NamedSharding` construction, with per-dimension entries;
- every `shard_map(...)` call with its `in_specs` / `out_specs`
  (matched by name, or by shape — any call carrying both spec
  keywords, which catches shard_map travelling as a parameter);
- every `jax.lax` collective (`psum`, `pmean`, `all_gather`,
  `ppermute`, `all_to_all`, `axis_index`, ...) with its `axis_name`,
  including whether the axis is a literal or flows in dynamically
  (a parameter — ring/ulysses/pipeline style); a dynamic axis whose
  parameter has a literal default (`axis="sp"`, `axis=DATA_AXIS`) is
  additionally surfaced as `default_axes`, a registry-only hint the
  rules never treat as a fact since callers can override defaults;

each attributed to file:line:col and to the enclosing function scope,
with a `[jit]` tag when the site sits inside a jit-compiled body.

The registry is the shared substrate of rules GL014-GL018 (read it via
`ctx.project.graftmesh()`) and of `python -m cloud_tpu.analysis.lint
--axes`, which dumps it as JSON — the starting `SpecLayout` the Plan
refactor (ROADMAP item 1) will consume. Like everything in graftlint
it is pure `ast`: the target is parsed, never imported, so dynamically
registered axes (a Mesh built from a variable axis tuple, e.g.
`runtime.initialize()`) appear as `"dynamic": true` mesh sites with no
axis names — the documented GL006 blind spot, now at least *visible*
in the inventory instead of silently absent.
"""

import ast

from cloud_tpu.analysis import rules as _rules

#: Schema version of the JSON document `lint --axes` emits.
REGISTRY_VERSION = 1

#: jax.lax collectives that take an axis_name (canonical names).
COLLECTIVES = frozenset((
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "pshuffle", "axis_index"))

#: The subset whose OUTPUT no longer varies over the axis: after one of
#: these, every device along the axis holds the same (reduced or fully
#: gathered) value, so replicating it in `out_specs` is sound. ppermute
#: / all_to_all / axis_index keep per-device variance and do NOT
#: discharge GL016.
REDUCING_COLLECTIVES = frozenset((
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather"))

#: Position of axis_name when passed positionally (default slot 1:
#: `psum(x, axis_name)`; `axis_index(axis_name)` takes it first).
_AXIS_ARG_INDEX = {"axis_index": 0}

#: Sentinel for a spec entry the AST cannot resolve to a literal.
UNKNOWN = "?"


def _unparse(node):
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - very old ast nodes only
        return "<expr>"


def lax_aliases(ctx):
    """local name -> canonical collective, for `from jax.lax import
    psum [as p]` style imports (cached on the FileContext)."""
    cached = getattr(ctx, "_graftmesh_lax_aliases", None)
    if cached is None:
        cached = {}
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.ImportFrom)
                    and node.module == "jax.lax"):
                for alias in node.names:
                    if alias.name in COLLECTIVES:
                        cached[alias.asname or alias.name] = alias.name
        ctx._graftmesh_lax_aliases = cached
    return cached


def collective_op(ctx, node):
    """Canonical collective name for a Call node, or None.

    `jax.lax.psum(...)` / `lax.psum(...)` match on the attribute chain;
    a bare `psum(...)` matches only when the file imported it from
    `jax.lax` — an unrelated local `all_gather` helper stays invisible.
    """
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    if isinstance(func, ast.Attribute):
        if (func.attr in COLLECTIVES
                and _rules._terminal_name(func.value) == "lax"):
            return func.attr
        return None
    if isinstance(func, ast.Name):
        return lax_aliases(ctx).get(func.id)
    return None


def collective_axis_expr(node, op):
    """The axis_name expression of a collective Call, or None."""
    index = _AXIS_ARG_INDEX.get(op, 1)
    cand = None
    for kw in node.keywords:
        if kw.arg == "axis_name":
            cand = kw.value
    if cand is None and len(node.args) > index:
        cand = node.args[index]
    return cand


def collective_axes(node, op):
    """(axes, dynamic) for a collective Call: the literal axis names it
    runs over, or ((), True) when the axis flows in as a non-literal
    (a parameter — the ring/ulysses/pipeline idiom). ((), False) means
    the call has no axis argument at all (malformed; jax would reject
    it, not our department)."""
    cand = collective_axis_expr(node, op)
    if cand is None:
        return (), False
    value = _rules._literal(cand)
    if isinstance(value, str):
        return (value,), False
    if (isinstance(value, (tuple, list)) and value
            and all(isinstance(v, str) for v in value)):
        return tuple(value), False
    return (), True


def is_shard_map_call(node):
    """A `shard_map(...)` call — by name, or by shape: any call
    carrying BOTH `in_specs` and `out_specs` keywords (catches the
    indirected form where shard_map itself travels as a parameter,
    e.g. ring_attention's `shard_map_fn(fn, mesh=..., in_specs=...,
    out_specs=...)`)."""
    if not isinstance(node, ast.Call):
        return False
    if _rules._terminal_name(node.func) == "shard_map":
        return True
    kws = {kw.arg for kw in node.keywords}
    return "in_specs" in kws and "out_specs" in kws


def _module_constants(ctx):
    """module-level `NAME = "literal"` string bindings (cached)."""
    cached = getattr(ctx, "_graftmesh_consts", None)
    if cached is None:
        cached = {}
        for node in ctx.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                value = _rules._literal(node.value)
                if isinstance(value, str):
                    cached[node.targets[0].id] = value
        ctx._graftmesh_consts = cached
    return cached


def resolve_default_axis(ctx, site_node, expr):
    """Best-effort resolution of a Name used as an axis to its
    *default* string: an enclosing def's parameter default (`axis=
    "sp"`, `axis=DATA_AXIS` through a module constant) or a
    module-level constant. Registry-only information: a caller can
    override a default, so rules never treat these as facts — the
    rollup reports them as `default_refs`."""
    if not isinstance(expr, ast.Name):
        return None
    name = expr.id
    consts = _module_constants(ctx)
    current = ctx.parents.get(site_node)
    while current is not None:
        if isinstance(current, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = current.args
            # A local rebinding makes the name's value untrackable.
            for node in ast.walk(current):
                if (isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == name
                        for t in node.targets)):
                    return None
            params = args.posonlyargs + args.args
            offset = len(params) - len(args.defaults)
            for i, param in enumerate(params):
                if param.arg != name:
                    continue
                if i < offset:
                    return None  # required param: truly dynamic
                return self_or_const(args.defaults[i - offset], consts)
            for param, default in zip(args.kwonlyargs, args.kw_defaults):
                if param.arg == name:
                    if default is None:
                        return None
                    return self_or_const(default, consts)
        current = ctx.parents.get(current)
    return consts.get(name)


def self_or_const(default, consts):
    """A default expression's string value: a literal, or one hop
    through a module constant Name."""
    value = _rules._literal(default)
    if isinstance(value, str):
        return value
    if isinstance(default, ast.Name):
        return consts.get(default.id)
    return None


def mesh_axis_names(node):
    """Literal axis-name tuple of a Mesh/make_mesh Call, or ()."""
    candidates = list(node.args[1:2])
    candidates += [kw.value for kw in node.keywords
                   if kw.arg == "axis_names"]
    for cand in candidates:
        value = _rules._literal(cand)
        if isinstance(value, str):
            value = (value,)
        if isinstance(value, (tuple, list)):
            axes = tuple(v for v in value if isinstance(v, str))
            if axes and len(axes) == len(value):
                return axes
    return ()


def _int_shape(value):
    if isinstance(value, int):
        return (value,)
    if isinstance(value, (tuple, list)):
        return tuple(v if isinstance(v, int) else None for v in value)
    return None


def mesh_axis_sizes(node, axes):
    """{axis -> size or None}: per-axis sizes when the mesh shape is a
    literal. Handles `make_mesh((2, 4), ...)`, `Mesh(x.reshape(2, 4),
    ...)` / `.reshape((2, 4))`, and `Mesh(create_device_mesh((2, 4)),
    ...)`; anything else (a device array variable — the dynamic mesh)
    maps every axis to None."""
    sizes = None
    name = _rules._terminal_name(node.func)
    if name == "make_mesh":
        cand = node.args[0] if node.args else None
        for kw in node.keywords:
            if kw.arg == "axis_shapes":
                cand = kw.value
        if cand is not None:
            sizes = _int_shape(_rules._literal(cand))
    elif name == "Mesh" and node.args:
        first = node.args[0]
        if isinstance(first, ast.Call):
            fname = _rules._terminal_name(first.func)
            if fname == "reshape":
                parts = [_rules._literal(a) for a in first.args]
                if len(parts) == 1 and isinstance(parts[0], (tuple, list)):
                    sizes = _int_shape(parts[0])
                else:
                    sizes = _int_shape(parts)
            elif fname == "create_device_mesh" and first.args:
                sizes = _int_shape(_rules._literal(first.args[0]))
    out = {}
    for i, axis in enumerate(axes):
        size = sizes[i] if sizes is not None and i < len(sizes) else None
        out[axis] = size if isinstance(size, int) and size > 0 else None
    return out


def spec_entry(arg):
    """One PartitionSpec argument -> its registry entry: an axis name
    string, a tuple of axis names, None (replicated dim), or UNKNOWN
    for a non-literal expression."""
    if isinstance(arg, ast.Constant) and arg.value is None:
        return None
    value = _rules._literal(arg)
    if isinstance(value, str):
        return value
    if isinstance(value, (tuple, list)):
        if value and all(isinstance(v, str) for v in value):
            return tuple(value)
        return UNKNOWN
    return UNKNOWN


def spec_entries(node):
    """Entries of a P(...)/PartitionSpec(...) Call node."""
    return tuple(spec_entry(arg) for arg in node.args)


def entry_axes(entries):
    """The axis-name strings an entry tuple mentions (UNKNOWN/None
    skipped)."""
    axes = []
    for entry in entries:
        if isinstance(entry, str) and entry != UNKNOWN:
            axes.append(entry)
        elif isinstance(entry, tuple):
            axes.extend(entry)
    return axes


def scope_label(ctx, node):
    """'outer.inner' chain of enclosing defs ('<module>' at top level),
    with ' [jit]' appended when the site is inside a jit-compiled
    body."""
    parts = []
    current = ctx.parents.get(node)
    while current is not None:
        if isinstance(current, (ast.FunctionDef, ast.AsyncFunctionDef)):
            parts.append(current.name)
        current = ctx.parents.get(current)
    label = ".".join(reversed(parts)) if parts else "<module>"
    if ctx.enclosing_jit(node) is not None:
        label += " [jit]"
    return label


def _json_entry(entry):
    return list(entry) if isinstance(entry, tuple) else entry


def file_sites(ctx):
    """Every mesh/spec/shard_map/collective site in one file, as
    JSON-ready dicts (cached on the FileContext — rules and the --axes
    dump share one walk)."""
    cached = getattr(ctx, "_graftmesh_sites", None)
    if cached is not None:
        return cached
    sites = {"meshes": [], "partition_specs": [], "named_shardings": [],
             "shard_maps": [], "collectives": []}

    def at(node, **extra):
        entry = {"path": ctx.path, "line": node.lineno,
                 "col": node.col_offset, "scope": scope_label(ctx, node)}
        entry.update(extra)
        return entry

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _rules._terminal_name(node.func)
        if name in ("Mesh", "make_mesh"):
            axes = mesh_axis_names(node)
            sites["meshes"].append(at(
                node, axes=list(axes),
                sizes=mesh_axis_sizes(node, axes),
                dynamic=not axes))
        elif name in ctx.pspec_aliases:
            entries = spec_entries(node)
            defaults = sorted({
                axis for axis in (
                    resolve_default_axis(ctx, node, arg)
                    for arg in node.args if isinstance(arg, ast.Name))
                if axis is not None})
            sites["partition_specs"].append(at(
                node, entries=[_json_entry(e) for e in entries],
                axes=sorted(set(entry_axes(entries))),
                default_axes=defaults))
        elif name == "NamedSharding":
            cand = node.args[1] if len(node.args) > 1 else None
            for kw in node.keywords:
                if kw.arg == "spec":
                    cand = kw.value
            sites["named_shardings"].append(at(
                node, spec=_unparse(cand) if cand is not None else None))
        elif is_shard_map_call(node):
            kwargs = {kw.arg: kw.value for kw in node.keywords}
            fn = node.args[0] if node.args else None
            sites["shard_maps"].append(at(
                node,
                fn=_unparse(fn) if fn is not None else None,
                in_specs=(_unparse(kwargs["in_specs"])
                          if "in_specs" in kwargs else None),
                out_specs=(_unparse(kwargs["out_specs"])
                           if "out_specs" in kwargs else None)))
        else:
            op = collective_op(ctx, node)
            if op is not None:
                axes, dynamic = collective_axes(node, op)
                default = None
                if dynamic:
                    default = resolve_default_axis(
                        ctx, node, collective_axis_expr(node, op))
                sites["collectives"].append(at(
                    node, op=op, axes=list(axes), dynamic=dynamic,
                    default_axes=[default] if default else []))
    ctx._graftmesh_sites = sites
    return sites


class AxisRegistry:
    """The aggregated whole-invocation inventory (one per
    ProjectContext; build via `project.graftmesh()`)."""

    _KINDS = ("meshes", "partition_specs", "named_shardings",
              "shard_maps", "collectives")

    def __init__(self, project):
        for kind in self._KINDS:
            setattr(self, kind, [])
        for path in sorted(project.modules):
            sites = file_sites(project.modules[path].ctx)
            for kind in self._KINDS:
                getattr(self, kind).extend(sites[kind])

    def declared_axes(self):
        """Axis names any mesh literal declares."""
        axes = set()
        for mesh in self.meshes:
            axes.update(mesh["axes"])
        return axes

    def axis_sizes(self):
        """axis -> size, only where every size-known mesh declaring the
        axis agrees (conflicting literals make the size unusable for
        divisibility reasoning, not a coin flip)."""
        sizes = {}
        for mesh in self.meshes:
            for axis, size in mesh["sizes"].items():
                if size is not None:
                    sizes.setdefault(axis, set()).add(size)
        return {axis: values.pop() for axis, values in sizes.items()
                if len(values) == 1}

    def axis_summary(self):
        """Per-axis rollup: declarations, agreed size, reference
        counts from specs and collectives."""
        summary = {}

        def row(axis):
            return summary.setdefault(axis, {
                "declared_at": [], "size": None,
                "partition_spec_refs": 0, "collective_refs": 0,
                "default_refs": 0})

        sizes = self.axis_sizes()
        for mesh in self.meshes:
            for axis in mesh["axes"]:
                row(axis)["declared_at"].append(
                    "{}:{}".format(mesh["path"], mesh["line"]))
        for spec in self.partition_specs:
            for axis in spec["axes"]:
                row(axis)["partition_spec_refs"] += 1
            for axis in spec["default_axes"]:
                row(axis)["default_refs"] += 1
        for coll in self.collectives:
            for axis in coll["axes"]:
                row(axis)["collective_refs"] += 1
            for axis in coll["default_axes"]:
                row(axis)["default_refs"] += 1
        for axis, size in sizes.items():
            row(axis)["size"] = size
        return {axis: summary[axis] for axis in sorted(summary)}

    def is_empty(self):
        return not any(getattr(self, kind) for kind in self._KINDS)

    def to_json(self):
        doc = {"version": REGISTRY_VERSION,
               "axes": self.axis_summary()}
        for kind in self._KINDS:
            doc[kind] = getattr(self, kind)
        return doc


def build_registry(project):
    return AxisRegistry(project)


def registry_for_paths(paths):
    """(AxisRegistry, [GL000 Findings]) over files/dirs — the
    `lint --axes` entry point."""
    from cloud_tpu.analysis import engine

    project, errors, _ = engine.build_project(paths)
    return build_registry(project), errors
