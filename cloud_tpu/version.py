"""Version of the cloud-tpu framework.

Mirrors the reference's single-constant version module
(reference: src/python/tensorflow_cloud/version.py:16), consumed by
packaging and by the client telemetry user-agent header
(cloud_tpu/utils/google_api_client.py).
"""

__version__ = "0.3.0.dev"
