"""cloud_tpu: a TPU-native cloud training framework.

Single import point (reference parity:
src/python/tensorflow_cloud/__init__.py:16-27):

    import cloud_tpu as ctc
    ctc.run(entry_point="train.py", chief_config=ctc.COMMON_MACHINE_CONFIGS["TPU_V5E_8"])
"""

from cloud_tpu.core.machine_config import AcceleratorType
from cloud_tpu.core.machine_config import COMMON_MACHINE_CONFIGS
from cloud_tpu.core.machine_config import MachineConfig
from cloud_tpu.core.run import remote
from cloud_tpu.core.run import run
from cloud_tpu.version import __version__

from cloud_tpu.tuner import (CloudOracle, CloudTuner,
                             DistributingCloudTuner, HyperParameters,
                             Objective)
