"""cloud_tpu: a TPU-native cloud training framework.

Single import point (reference parity:
src/python/tensorflow_cloud/__init__.py:16-27):

    import cloud_tpu as ctc
    ctc.run(entry_point="train.py", chief_config=ctc.COMMON_MACHINE_CONFIGS["TPU_V5E_8"])
"""

from cloud_tpu.core.machine_config import AcceleratorType
from cloud_tpu.core.machine_config import COMMON_MACHINE_CONFIGS
from cloud_tpu.core.machine_config import MachineConfig
from cloud_tpu.core.run import remote
from cloud_tpu.core.run import run
from cloud_tpu.version import __version__

# Tuner names resolve lazily (PEP 562, mirroring cloud_tpu.tuner's own
# lazy table): `import cloud_tpu` must not decide whether the process
# gets the hosted Vizier path or a local sweep.
_TUNER_NAMES = ("CloudOracle", "CloudTuner", "DistributingCloudTuner",
                "HyperParameters", "Objective", "Sweep", "RandomOracle",
                "GridOracle", "ASHA")


def __getattr__(name):
    if name in _TUNER_NAMES:
        import importlib

        value = getattr(importlib.import_module("cloud_tpu.tuner"), name)
        globals()[name] = value
        return value
    raise AttributeError(
        "module {!r} has no attribute {!r}".format(__name__, name))


def __dir__():
    return sorted(set(globals()) | set(_TUNER_NAMES))
