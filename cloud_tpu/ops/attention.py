"""Flash attention as a Pallas TPU kernel.

The compute-path counterpart the reference never had: its attention runs
wherever `tf.distribute` puts Keras layers (reference core/preprocess.py
picks a strategy, TF picks kernels). Here the hot op is a hand-written
TPU kernel: blockwise online-softmax attention that never materializes
the [S, S] score matrix in HBM, keeps the matmuls on the MXU in bf16/f32,
and streams K/V blocks through VMEM.

Design notes (see /opt/skills/guides/pallas_guide.md):
- Grid is (batch*heads, q_blocks, k_blocks) with the k dimension
  innermost; VMEM scratch (acc, m, l) carries the online-softmax state
  across k steps, and the output block is written on the last k step.
- m/l live in (block_q, 128) lane-broadcast scratch, and the saved
  logsumexp residual is materialized lane-broadcast ([BH, S, 128]) so the
  backward kernels can read it without cross-lane relayouts (Mosaic has
  no cheap (N,1)<->(1,N) transpose).
- Causal blocks strictly above the diagonal are skipped via `pl.when`.
- Backward = two kernels (dq over k-blocks; dk/dv over q-blocks), the
  standard FlashAttention-2 recomputation split, wired through
  `jax.custom_vjp`.
- Sequences are padded to a block multiple outside the custom_vjp, so
  autodiff of pad/slice handles the edges; padded keys are masked inside
  the kernel, padded dO rows are zero so they contribute nothing.

On non-TPU backends the kernels run in Pallas interpret mode (tests), so
the same code path is exercised everywhere.
"""

import functools
import math
import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128


class _Config(NamedTuple):
    causal: bool
    sm_scale: float
    block_q: int
    block_k: int
    kv_len: int  # true (unpadded) sequence length
    heads: int   # q heads, folded into the grid's leading batch*heads dim
    has_mask: bool  # per-example key mask streamed as [B, 1, S_pad] blocks
    interpret: bool
    kv_group: int = 1  # q heads per kv head (grouped-query attention)
    window: int = 0  # sliding-window width; 0 = full causal
    softcap: float = 0.0  # Gemma2-style tanh logit cap; 0 = off


def repeat_kv(k, num_heads):
    """Broadcast [B, S, H_kv, D] key/value heads to num_heads groups.

    GQA's compute-side expansion: each kv head serves
    num_heads // H_kv query heads. Prefer passing H_kv-width k/v
    straight to `flash_attention`/`mha_reference` (both take the
    grouped layout natively); this helper is for paths that need the
    materialized expansion (e.g. sharding heads across a mesh axis).
    """
    h_kv = k.shape[2]
    if num_heads == h_kv:
        return k
    if num_heads % h_kv:
        raise ValueError(
            "num_heads=%d must be a multiple of num_kv_heads=%d."
            % (num_heads, h_kv))
    return jnp.repeat(k, num_heads // h_kv, axis=2)


def mha_reference(q, k, v, causal=True, sm_scale=None, mask=None,
                  window=None, logit_softcap=None):
    """Pure-jnp multi-head attention, layout [B, S, H, D].

    The correctness oracle for the kernel and the fallback path for
    shapes/backends the kernel does not cover. Grouped-query attention:
    k/v may carry H_kv < H heads (H divisible by H_kv); they are
    broadcast to the q-head grouping here. window: sliding-window
    (Mistral-style) attention — row i attends keys (i-window, i];
    requires causal=True. logit_softcap: Gemma2-style tanh capping,
    logits -> cap * tanh(logits / cap), applied after the softmax scale
    and before any masking (the HF Gemma2 order).
    """
    head_dim = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(head_dim)
    if v.shape != k.shape:
        raise ValueError("k and v must have identical shapes; got "
                         "{} vs {}.".format(k.shape, v.shape))
    if window is not None and not causal:
        raise ValueError("window (sliding-window attention) requires "
                         "causal=True.")
    if k.shape[2] != q.shape[2]:
        heads, h_kv = q.shape[2], k.shape[2]
        if heads % h_kv:
            raise ValueError(
                "q heads {} must be a multiple of kv heads {}.".format(
                    heads, h_kv))
        k = jnp.repeat(k, heads // h_kv, axis=2)
        v = jnp.repeat(v, heads // h_kv, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * sm_scale
    logits = logits.astype(jnp.float32)
    if logit_softcap:
        cap = float(logit_softcap)
        logits = cap * jnp.tanh(logits / cap)
    seq_q, seq_k = q.shape[1], k.shape[1]
    if causal:
        allowed = jnp.tril(jnp.ones((seq_q, seq_k), dtype=bool))
        if window is not None:
            # Band: col in (row - window, row]. HF Mistral's convention
            # (sliding_window keys INCLUDING self are visible).
            row = jnp.arange(seq_q)[:, None]
            col = jnp.arange(seq_k)[None, :]
            allowed = allowed & (col > row - int(window))
        logits = jnp.where(allowed, logits, _NEG_INF)
    if mask is not None:
        logits = jnp.where(mask[:, None, None, :], logits, _NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    if causal or mask is not None:
        # Fully-masked rows output ZEROS (and zero grads) — the flash
        # convention, unified here (round 4) so the oracle and kernel
        # agree on every row and the sp strategies (ring zeros via its
        # lse sentinel; ulysses delegates to whichever local kernel the
        # backend picked) behave identically on any backend. Without
        # this, softmax over all-(-1e30) logits is a uniform average.
        all_masked = jnp.max(logits, axis=-1,
                             keepdims=True) <= _NEG_INF / 2
        weights = jnp.where(all_masked, 0.0, weights)
    weights = weights.astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _block_mask(config, qi, ki, mask_ref):
    """Combined validity mask for one (block_q, block_k) tile: global
    kv padding, causal structure, and (when present) the per-example
    key mask block."""
    block_q, block_k = config.block_q, config.block_k
    col = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = col < config.kv_len
    if config.causal:
        row = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        mask = mask & (col <= row)
        if config.window:
            # Sliding-window band: col in (row - window, row] — the HF
            # Mistral convention (window keys visible including self).
            mask = mask & (col > row - config.window)
    if mask_ref is not None:
        valid = mask_ref[...].reshape(1, block_k) != 0
        mask = mask & jnp.broadcast_to(valid, (block_q, block_k))
    return mask


def _tile_live(config, qi, ki):
    """Causal tile-skip condition: a (qi, ki) tile runs only if it
    intersects the visible region — at or below the diagonal, and
    (with a sliding window) not entirely below the band."""
    cond = (ki * config.block_k <= qi * config.block_q
            + config.block_q - 1)
    if config.window:
        cond = jnp.logical_and(
            cond, (ki + 1) * config.block_k - 1
            > qi * config.block_q - config.window)
    return cond


def _fwd_kernel(q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, config, num_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * config.sm_scale
        if config.softcap:
            # Gemma2 logit soft-capping, cap * tanh(s / cap) — before
            # masking (the HF order; masked entries go to -inf either
            # way, so the capped value never leaks).
            s = config.softcap * jnp.tanh(s / config.softcap)
        mask = _block_mask(config, qi, ki, mask_ref)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_curr = jnp.max(s, axis=-1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_curr)
        alpha = jnp.exp(m_prev - m_next)
        # Explicit zero where masked: exp(s - m) underflows to 0 for
        # normal rows, but a fully-masked row has m == s == -inf and
        # exp(0) == 1 would leak mass (such rows output 0 instead).
        p = jnp.where(mask, jnp.exp(s - m_next), 0.0)
        l_next = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_next, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_next, l_ref.shape)

    if config.causal:
        @pl.when(_tile_live(config, qi, ki))
        def _masked_step():
            _step()
    else:
        _step()

    @pl.when(ki == num_k - 1)
    def _finalize():
        l = l_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)
        lse = m_ref[:, :1] + jnp.log(safe_l)
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref[0].shape)


def _mask_spec(config, transposed=False):
    """BlockSpec for the [B, 1, S_pad] key-mask: one (1, 1, block_k)
    strip per k-block, indexed by the example this program serves.

    The mask rides with a singleton middle axis so the block's
    second-to-last dim (1) EQUALS the array dim — Mosaic requires the
    last two block dims be (divisible by 8, divisible by 128) or equal
    to the array dims, and a rank-2 [B, S_pad] layout with (1, block_k)
    blocks violates the sublane rule whenever B > 1 (caught by the
    round-4 on-TPU parity smoke; interpret mode never checks this)."""
    heads = config.heads
    if transposed:  # dk/dv grid: (b over B*H_kv, j, t)
        heads_kv = config.heads // config.kv_group
        return pl.BlockSpec((1, 1, config.block_k),
                            lambda b, j, t: (b // heads_kv, 0, j))
    return pl.BlockSpec((1, 1, config.block_k),
                        lambda b, i, j: (b // heads, 0, j))


def _maybe_mask(config, kernel):
    """Adapts a mask-taking kernel body to the unmasked arg list."""
    if config.has_mask:
        return kernel

    def adapted(q_ref, k_ref, v_ref, *rest):
        return kernel(q_ref, k_ref, v_ref, None, *rest)
    return adapted


def _flash_forward(config, q, k, v, kmask):
    """q: [B*H, S_pad, D]; k/v: [B*H_kv, S_pad, D] (H_kv = H/kv_group);
    kmask: [B, 1, S_pad] int32 or None ->
    (out [B*H, S_pad, D], lse [B*H, S_pad, 128]).

    GQA streams each kv head's blocks to its group of q-head programs
    via the index map (b // kv_group) — the H-wide expansion is never
    materialized in HBM."""
    bh, seq, head_dim = q.shape
    num_q = seq // config.block_q
    num_k = seq // config.block_k
    grid = (bh, num_q, num_k)
    group = config.kv_group
    kernel = _maybe_mask(
        config, functools.partial(_fwd_kernel, config=config, num_k=num_k))
    in_specs = [
        pl.BlockSpec((1, config.block_q, head_dim),
                     lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, config.block_k, head_dim),
                     lambda b, i, j: (b // group, j, 0)),
        pl.BlockSpec((1, config.block_k, head_dim),
                     lambda b, i, j: (b // group, j, 0)),
    ]
    inputs = [q, k, v]
    if config.has_mask:
        in_specs.append(_mask_spec(config))
        inputs.append(kmask)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, config.block_q, head_dim),
                         lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, config.block_q, _LANES),
                         lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, head_dim), q.dtype),
            jax.ShapeDtypeStruct((bh, seq, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((config.block_q, head_dim), jnp.float32),
            pltpu.VMEM((config.block_q, _LANES), jnp.float32),
            pltpu.VMEM((config.block_q, _LANES), jnp.float32),
        ],
        interpret=config.interpret,
    )(*inputs)
    return out, lse


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _attn_probs(config, qi, ki, q, k, lse_col, mask_ref):
    """Recomputes the (block_q, block_k) probability block.

    Returns (p, dcap): dcap is the softcap chain-rule factor
    d(cap*tanh(s/cap))/ds = 1 - tanh^2(s/cap) to fold into dS, or None
    when soft-capping is off.
    """
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * config.sm_scale
    dcap = None
    if config.softcap:
        t = jnp.tanh(s / config.softcap)
        dcap = 1.0 - t * t
        s = config.softcap * t
    mask = _block_mask(config, qi, ki, mask_ref)
    # Explicit zero (not just -inf logits): a fully-masked row carries
    # lse == -inf and exp(-inf - -inf) == 1 would fabricate mass.
    p = jnp.where(mask, jnp.exp(jnp.where(mask, s, _NEG_INF) - lse_col),
                  0.0)
    return p, dcap


def _dq_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_acc, *, config, num_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        p, dcap = _attn_probs(config, qi, ki, q, k, lse_ref[0][:, :1],
                              mask_ref)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1]) * config.sm_scale
        if dcap is not None:
            ds = ds * dcap
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if config.causal:
        @pl.when(_tile_live(config, qi, ki))
        def _masked_step():
            _step()
    else:
        _step()

    @pl.when(ki == num_k - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _dkdv_kernel(q_ref, k_ref, v_ref, mask_ref, do_ref, lse_ref, delta_ref,
                 dk_ref, dv_ref, dk_acc, dv_acc, *, config, num_q):
    """Grid (B*H_kv, num_k, kv_group*num_q): each kv head's dk/dv block
    accumulates over every q block of every q head in its group — the
    GQA sum over the group happens in the same VMEM accumulator that
    already sums over q blocks. t decomposes as g*num_q + i."""
    ki = pl.program_id(1)
    t = pl.program_id(2)
    qi = jax.lax.rem(t, num_q)

    @pl.when(t == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        p, dcap = _attn_probs(config, qi, ki, q, k, lse_ref[0][:, :1],
                              mask_ref)
        # dV += P^T dO   (contract over the q rows)
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1]) * config.sm_scale
        if dcap is not None:
            ds = ds * dcap
        # dK += dS^T Q
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if config.causal:
        @pl.when(_tile_live(config, qi, ki))
        def _masked_step():
            _step()
    else:
        _step()

    @pl.when(t == config.kv_group * num_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward(config, q, k, v, kmask, out, lse, g):
    bh, seq, head_dim = q.shape
    bh_kv = k.shape[0]
    num_q = seq // config.block_q
    num_k = seq // config.block_k
    group = config.kv_group

    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (bh, seq, _LANES))

    q_spec = pl.BlockSpec((1, config.block_q, head_dim),
                          lambda b, i, j: (b, i, 0))
    row_spec = pl.BlockSpec((1, config.block_q, _LANES),
                            lambda b, i, j: (b, i, 0))
    k_spec = pl.BlockSpec((1, config.block_k, head_dim),
                          lambda b, i, j: (b // group, j, 0))

    in_specs = [q_spec, k_spec, k_spec]
    inputs = [q, k, v]
    if config.has_mask:
        in_specs.append(_mask_spec(config))
        inputs.append(kmask)

    dq = pl.pallas_call(
        _maybe_mask(config, functools.partial(
            _dq_kernel, config=config, num_k=num_k)),
        grid=(bh, num_q, num_k),
        in_specs=in_specs + [q_spec, row_spec, row_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        scratch_shapes=[
            pltpu.VMEM((config.block_q, head_dim), jnp.float32)],
        interpret=config.interpret,
    )(*inputs, g, lse, delta)[0]

    # dk/dv: one program per kv head and k-block; the innermost dim t
    # fuses (group, q_blocks) so the group sum lands in the accumulator
    # (see _dkdv_kernel). Index maps lift t -> (q head b*group + t//num_q,
    # q block t%num_q).
    qT_spec = pl.BlockSpec(
        (1, config.block_q, head_dim),
        lambda b, j, t: (b * group + t // num_q, t % num_q, 0))
    rowT_spec = pl.BlockSpec(
        (1, config.block_q, _LANES),
        lambda b, j, t: (b * group + t // num_q, t % num_q, 0))
    kT_spec = pl.BlockSpec((1, config.block_k, head_dim),
                           lambda b, j, t: (b, j, 0))
    inT_specs = [qT_spec, kT_spec, kT_spec]
    if config.has_mask:
        inT_specs.append(_mask_spec(config, transposed=True))
    dk, dv = pl.pallas_call(
        _maybe_mask(config, functools.partial(
            _dkdv_kernel, config=config, num_q=num_q)),
        grid=(bh_kv, num_k, group * num_q),
        in_specs=inT_specs + [qT_spec, rowT_spec, rowT_spec],
        out_specs=[kT_spec, kT_spec],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((config.block_k, head_dim), jnp.float32),
            pltpu.VMEM((config.block_k, head_dim), jnp.float32),
        ],
        interpret=config.interpret,
    )(*inputs, g, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_attention(config, q, k, v):
    out, _ = _flash_forward(config, q, k, v, None)
    return out


def _flash_attention_fwd(config, q, k, v):
    out, lse = _flash_forward(config, q, k, v, None)
    return out, (q, k, v, out, lse)


def _flash_attention_bwd(config, residuals, g):
    q, k, v, out, lse = residuals
    return _flash_backward(config, q, k, v, None, out, lse, g)


_flash_attention.defvjp(_flash_attention_fwd, _flash_attention_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_attention_masked(config, q, k, v, kmask):
    out, _ = _flash_forward(config, q, k, v, kmask)
    return out


def _flash_attention_masked_fwd(config, q, k, v, kmask):
    out, lse = _flash_forward(config, q, k, v, kmask)
    return out, (q, k, v, kmask, out, lse)


def _flash_attention_masked_bwd(config, residuals, g):
    import numpy as np

    q, k, v, kmask, out, lse = residuals
    dq, dk, dv = _flash_backward(config, q, k, v, kmask, out, lse, g)
    # Integer mask: the cotangent is the symbolic zero, float0.
    return dq, dk, dv, np.zeros(kmask.shape, jax.dtypes.float0)


_flash_attention_masked.defvjp(_flash_attention_masked_fwd,
                               _flash_attention_masked_bwd)


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def flash_attention(q, k, v, causal=True, sm_scale=None, mask=None,
                    window=None, logit_softcap=None, block_q=None,
                    block_k=None, interpret: Optional[bool] = None):
    """Blockwise flash attention, layout [batch, seq, heads, head_dim].

    Args:
        q, k, v: [B, S, H, D] arrays (any float dtype; compute is f32 on
            the MXU, output in the input dtype). Grouped-query
            attention: k/v may carry H_kv < H heads (H divisible by
            H_kv) — each kv head serves H/H_kv consecutive q heads, and
            the kernel streams kv blocks per group instead of
            materializing the H-wide expansion in HBM.
        causal: Apply a causal (autoregressive) mask.
        window: Sliding-window (Mistral-style) attention — row i
            attends keys in (i-window, i]; requires causal=True. Tiles
            entirely below the band are skipped in the grid
            (_tile_live), so long-sequence cost scales with S*window,
            not S^2.
        sm_scale: Softmax temperature; default 1/sqrt(D).
        logit_softcap: Gemma2-style tanh logit capping — logits become
            cap * tanh(logits / cap) after the softmax scale and before
            masking (the HF Gemma2 order); the backward kernels fold
            the tanh derivative into dS. None/0 = off.
        mask: Optional [B, S] boolean key mask (True = attend). The
            padded-batch fast path: masked keys are excluded inside the
            kernel, so Keras-parity workloads with per-example padding
            never leave the flash path. Any pattern is supported, not
            just contiguous prefixes. Rows whose keys are ALL masked
            output zeros — and since round 4 `mha_reference` adopts the
            same convention, kernel and oracle agree on every row.
        block_q / block_k: Kernel tile sizes along the sequence. S is
            padded up to a multiple internally. Default (None) is 128,
            overridable process-wide via CLOUD_TPU_FLASH_BLOCK_Q /
            CLOUD_TPU_FLASH_BLOCK_K — the deployment hook for a
            `benchmarks/flash_autotune.py` pin, so a measured best
            config applies without touching call sites.
        interpret: Force Pallas interpret mode. Default: interpret
            everywhere except on real TPU backends.

    Returns:
        [B, S, H, D] attention output, differentiable w.r.t. q/k/v.
    """
    batch, seq, heads, head_dim = q.shape
    h_kv = k.shape[2]
    if v.shape != k.shape:
        raise ValueError("k and v must have identical shapes; got "
                         "{} vs {}.".format(k.shape, v.shape))
    if heads % h_kv:
        raise ValueError(
            "q heads {} must be a multiple of kv heads {}.".format(
                heads, h_kv))
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(head_dim)
    if window is not None and not causal:
        raise ValueError("window (sliding-window attention) requires "
                         "causal=True.")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_q is None:
        block_q = int(os.environ.get("CLOUD_TPU_FLASH_BLOCK_Q", 128))
    if block_k is None:
        block_k = int(os.environ.get("CLOUD_TPU_FLASH_BLOCK_K", 128))

    block = max(block_q, block_k)
    if block_q % min(block_q, block_k) or block_k % min(block_q, block_k):
        raise ValueError(
            "block_q={} and block_k={} must divide one another.".format(
                block_q, block_k))
    seq_pad = -(-seq // block) * block
    block_q = min(block_q, seq_pad)
    block_k = min(block_k, seq_pad)

    config = _Config(causal=bool(causal), sm_scale=float(sm_scale),
                     block_q=block_q, block_k=block_k, kv_len=seq,
                     heads=heads, has_mask=mask is not None,
                     interpret=bool(interpret),
                     kv_group=heads // h_kv,
                     window=int(window or 0),
                     softcap=float(logit_softcap or 0.0))

    def fold(x):
        n_heads = x.shape[2]
        x = jnp.transpose(x, (0, 2, 1, 3)).reshape(
            batch * n_heads, seq, head_dim)
        if seq_pad != seq:
            x = jnp.pad(x, ((0, 0), (0, seq_pad - seq), (0, 0)))
        return x

    if mask is None:
        out = _flash_attention(config, fold(q), fold(k), fold(v))
    else:
        if mask.shape != (batch, seq):
            raise ValueError(
                "mask must be [batch, seq] = {}; got {}.".format(
                    (batch, seq), mask.shape))
        kmask = mask.astype(jnp.int32)
        if seq_pad != seq:
            kmask = jnp.pad(kmask, ((0, 0), (0, seq_pad - seq)))
        # [B, 1, S_pad]: the singleton axis makes the (1, 1, block_k)
        # mask blocks legal under Mosaic's sublane rule (_mask_spec).
        kmask = kmask[:, None, :]
        out = _flash_attention_masked(config, fold(q), fold(k), fold(v),
                                      kmask)
    out = out[:, :seq].reshape(batch, heads, seq, head_dim)
    return jnp.transpose(out, (0, 2, 1, 3))


def attention(q, k, v, causal=True, sm_scale=None, mask=None,
              window=None, logit_softcap=None, impl="auto"):
    """Dispatching attention: pallas flash kernel or jnp reference.

    impl: "auto" picks the flash kernel on TPU (with or without a key
    mask — padded batches stay on the fast path), the jnp reference
    elsewhere; "flash"/"reference" force a path. window: sliding-window
    width; logit_softcap: Gemma2 tanh capping (both paths honor both).
    """
    kwargs = dict(causal=causal, sm_scale=sm_scale, mask=mask,
                  window=window, logit_softcap=logit_softcap)
    if impl == "flash":
        return flash_attention(q, k, v, **kwargs)
    if impl == "reference":
        return mha_reference(q, k, v, **kwargs)
    if impl != "auto":
        raise ValueError("Unknown attention impl: {!r}".format(impl))
    if jax.default_backend() == "tpu":
        return flash_attention(q, k, v, **kwargs)
    return mha_reference(q, k, v, **kwargs)
