"""Chunked LM-head cross-entropy: loss without materializing logits.

For a decoder LM the [B, S, V] logits tensor is usually the single
largest array in the step: batch 8 x seq 2048 x vocab 256k in f32 is
16 GiB — more than a v5e chip's HBM — while the loss itself only needs
one logsumexp and one gathered label logit per token. This op fuses the
output projection with the softmax cross-entropy, scanning the vocab in
chunks:

    forward:  per chunk c: logits_c = h @ W[:, c]  (an MXU matmul),
              folded into a running online logsumexp + the label logit
              for labels that land in the chunk. Peak extra memory is
              one [N, chunk] block.
    backward: recompute logits_c per chunk, form p_c = exp(logits_c -
              lse), accumulate dh += (p_c - onehot) @ W[:, c]^T and
              dW[:, c] = h^T (p_c - onehot), scaled by the cotangent.
              Same [N, chunk] peak; dW is the same size as W (it is the
              gradient).

The scan is `lax.scan` over chunk indices with `dynamic_slice` into W,
so XLA compiles one chunk program — compile time and HBM stay flat as
V grows. Numerics: accumulation in f32 regardless of input dtype
(matching optax.softmax_cross_entropy_with_integer_labels on the same
values).

No reference counterpart (the reference delegates losses to Keras);
this is TPU-first design for the long-context/big-vocab regime the
framework's TransformerLM targets.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def _num_chunks(vocab, chunk):
    return -(-vocab // chunk)


def _chunk_logits(hidden, weights, idx, chunk):
    """f32 logits for vocab chunk `idx`, sliced straight from W.

    No padded copy of W is ever made (padding would materialize a
    second [D, V] array — the very memory this op exists to avoid).
    Instead the slice start is clamped so the final chunk ends at V;
    columns the previous chunk already covered (the overlap a clamped
    start creates when chunk does not divide V) are masked out of the
    logsumexp/label accounting via `keep`.
    """
    vocab = weights.shape[1]
    unclamped = idx * chunk
    start = jnp.minimum(unclamped, vocab - chunk)
    w_c = lax.dynamic_slice(weights, (0, start),
                            (weights.shape[0], chunk))
    logits = jnp.einsum("nd,dc->nc", hidden, w_c,
                        preferred_element_type=jnp.float32)
    col = start + jnp.arange(chunk)
    keep = col >= unclamped
    return jnp.where(keep[None, :], logits, _NEG_INF), w_c, col, keep, \
        start


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def lm_head_loss(hidden, weights, labels, chunk=8192):
    """Per-token softmax cross-entropy of `hidden @ weights` vs labels.

    Args:
        hidden: [N, D] (flatten batch/seq dims first) activations.
        weights: [D, V] output projection (no bias).
        labels: [N] int32 target ids. Ids in [0, V) contribute their
            cross-entropy; ids OUTSIDE that range (e.g. the common -1
            ignore-index for padded tokens) produce loss 0 and zero
            gradient for that position — unlike the materializing optax
            oracle, which clips out-of-range gathers.
        chunk: vocab tile width (static); peak extra memory is one
            [N, chunk] f32 block. W is never copied/padded — the final
            chunk's slice is clamped and its overlap masked.

    Returns:
        [N] f32 per-token losses — identical (to f32 numerics) to
        `optax.softmax_cross_entropy_with_integer_labels(h @ W, labels)`
        for in-range labels, 0 for ignored positions.
    """
    loss, _ = _forward(hidden, weights, labels, chunk)
    return loss


def _forward(hidden, weights, labels, chunk):
    n = hidden.shape[0]
    vocab = weights.shape[1]
    chunk = min(chunk, vocab)
    num_chunks = _num_chunks(vocab, chunk)

    def step(carry, idx):
        m, s, label_logit = carry
        logits, _, col, keep, _ = _chunk_logits(hidden, weights, idx,
                                                chunk)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1)
        hit = (labels[:, None] == col[None, :]) & keep[None, :]
        label_logit = label_logit + jnp.sum(
            jnp.where(hit, logits, 0.0), axis=-1)
        return (m_new, s, label_logit), None

    init = (jnp.full((n,), _NEG_INF, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32))
    (m, s, label_logit), _ = lax.scan(step, init,
                                      jnp.arange(num_chunks))
    lse = m + jnp.log(s)
    # Ignore-index semantics: out-of-range labels (padding convention
    # -1) carry zero loss instead of a garbage `lse - 0` value.
    valid = (labels >= 0) & (labels < vocab)
    return jnp.where(valid, lse - label_logit, 0.0), lse


def _fwd(hidden, weights, labels, chunk):
    loss, lse = _forward(hidden, weights, labels, chunk)
    return loss, (hidden, weights, labels, lse)


def _bwd(chunk, residuals, g):
    hidden, weights, labels, lse = residuals
    vocab = weights.shape[1]
    chunk = min(chunk, vocab)
    num_chunks = _num_chunks(vocab, chunk)
    # Ignored positions (out-of-range labels) have zero cotangent: no
    # gradient flows from them, matching their zero loss.
    valid = (labels >= 0) & (labels < vocab)
    g = g.astype(jnp.float32) * valid.astype(jnp.float32)

    def step(carry, idx):
        dh, dw = carry
        logits, w_c, col, keep, start = _chunk_logits(hidden, weights,
                                                      idx, chunk)
        p = jnp.exp(logits - lse[:, None])  # 0 for overlap-masked cols
        onehot = ((labels[:, None] == col[None, :])
                  & keep[None, :]).astype(jnp.float32)
        dlogits = (p - onehot) * g[:, None]
        dh = dh + jnp.einsum("nc,dc->nd", dlogits, w_c,
                             preferred_element_type=jnp.float32)
        dw_c = jnp.einsum("nd,nc->dc", hidden, dlogits,
                          preferred_element_type=jnp.float32)
        # Accumulate (read-add-write): a clamped final chunk overlaps
        # the previous one, and its masked columns carry dlogits == 0 —
        # a plain update_slice would zero the overlap's earlier grads.
        prev = lax.dynamic_slice(dw, (0, start),
                                 (dw.shape[0], chunk))
        dw = lax.dynamic_update_slice(dw, prev + dw_c, (0, start))
        return (dh, dw), None

    init = (jnp.zeros(hidden.shape, jnp.float32),
            jnp.zeros(weights.shape, jnp.float32))
    (dh, dw), _ = lax.scan(step, init, jnp.arange(num_chunks))
    return (dh.astype(hidden.dtype), dw.astype(weights.dtype), None)


lm_head_loss.defvjp(_fwd, _bwd)


def lm_head_loss_reference(hidden, weights, labels):
    """Naive oracle: materializes the full logits."""
    import optax

    logits = jnp.einsum("nd,dv->nv", hidden, weights,
                        preferred_element_type=jnp.float32)
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)
